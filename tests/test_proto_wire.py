"""Binary protobuf wire tests (pb/wire.py + WEEDTPU_WIRE=proto): codec
conversion semantics, descriptor-artifact freshness, and a live cluster
round-trip where every control RPC rides real protobuf frames."""

import json
import os

import pytest

from seaweedfs_tpu.pb import FILER_SERVICE, MASTER_SERVICE, VOLUME_SERVICE, wire


@pytest.fixture(scope="module")
def codec():
    return wire.WireCodec()


def test_descriptor_artifact_is_fresh(codec):
    """contracts.desc must match what protoc emits for contracts.proto —
    a schema edit without regenerating the artifact would hand
    protoc-less deploys a stale wire."""
    import shutil

    if shutil.which("protoc") is None:
        pytest.skip("protoc not in image")
    with open(wire.DESC_PATH, "rb") as f:
        committed = f.read()
    assert committed == wire._descriptor_set_bytes(), (
        "contracts.desc is stale — run "
        "python -c 'from seaweedfs_tpu.pb import wire; "
        "wire.regenerate_descriptor_artifact()'"
    )


def test_codec_covers_every_registered_method(codec):
    """Every (service, method) the servers register must resolve to
    message classes — the binary wire may not silently skip one."""
    import re

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "seaweedfs_tpu")
    registered = set()
    for root, _, files in os.walk(pkg):
        for name in files:
            if name.endswith(".py"):
                with open(os.path.join(root, name), encoding="utf-8") as f:
                    registered.update(re.findall(r"\badd\(\s*\"(\w+)\"", f.read()))
    known = {m for (_s, m) in codec._methods}
    missing = registered - known
    assert not missing, f"registered methods without schema classes: {missing}"


def test_scalar_and_map_conversions(codec):
    req_cls, _ = codec.classes(VOLUME_SERVICE, "VolumeNeedleTs")
    msg = codec.to_message({"volume_id": 7, "needle_ids": [1, 2, 3]}, req_cls)
    assert codec.to_dict(req_cls.FromString(msg.SerializeToString())) == {
        "volume_id": 7,
        "needle_ids": [1, 2, 3],
    }
    # int-keyed maps accept the JSON habit of string keys
    _, resp_cls = codec.classes(VOLUME_SERVICE, "VolumeNeedleTs")
    m2 = codec.to_message({"ts": {"5": 123, 9: 456}}, resp_cls)
    out = codec.to_dict(resp_cls.FromString(m2.SerializeToString()))
    assert out["ts"] == {5: 123, 9: 456}
    # 64-bit values stay ints (proto3 JSON would stringify them)
    big = (1 << 62) + 3
    m3 = codec.to_message({"ts": {1: big}}, resp_cls)
    assert codec.to_dict(resp_cls.FromString(m3.SerializeToString()))["ts"][1] == big


def test_bytes_fields_carry_base64_strings(codec):
    import base64

    req_cls, _ = codec.classes(VOLUME_SERVICE, "WriteNeedle")
    payload = b"\x00\x01\xfe raw"
    d = {"fid": "3,17abcdef01", "data": base64.b64encode(payload).decode()}
    msg = codec.to_message(d, req_cls)
    assert msg.data == payload  # raw bytes on the wire, not b64 text
    back = codec.to_dict(req_cls.FromString(msg.SerializeToString()))
    assert base64.b64decode(back["data"]) == payload


def test_unknown_dict_key_raises(codec):
    req_cls, _ = codec.classes(MASTER_SERVICE, "Assign")
    with pytest.raises(ValueError, match="not a schema field"):
        codec.to_message({"count": 1, "typo_field": "x"}, req_cls)


def test_optional_presence_round_trips(codec):
    """copy_ecx_file: absent, explicit False, and explicit True are three
    distinct wire states — the .get(k, True) handler default depends on
    it."""
    req_cls, _ = codec.classes(VOLUME_SERVICE, "VolumeEcShardsCopy")
    base = {"volume_id": 1, "shard_ids": [0, 7], "source_data_node": "h:1"}
    for d, expect in (
        (base, None),
        ({**base, "copy_ecx_file": False}, False),
        ({**base, "copy_ecx_file": True}, True),
    ):
        out = codec.to_dict(
            req_cls.FromString(codec.to_message(d, req_cls).SerializeToString())
        )
        assert out.get("copy_ecx_file") is expect if expect is None else (
            out["copy_ecx_file"] is expect
        )
        # zero-valued shard id survives (senders always set repeated items)
        assert out["shard_ids"] == [0, 7]


def test_wrapper_messages_round_trip_bare_shapes(codec):
    """The topology dump's nested maps/lists keep their natural JSON
    shapes through the wrapper messages."""
    _, resp_cls = codec.classes(MASTER_SERVICE, "VolumeList")
    d = {
        "max_volume_id": 9,
        "volume_size_limit": 1 << 30,
        "data_centers": {
            "dc1": {"rackA": [{"url": "h:1", "grpc_port": 2, "volumes": [{"id": 4}]}]},
            "dc2": {},
        },
        "ec_volumes": {"7": {"0": ["h:1", "h:2"], "13": ["h:3"]}},
        "ec_collections": {"7": "buck"},
    }
    out = codec.to_dict(resp_cls.FromString(codec.to_message(d, resp_cls).SerializeToString()))
    assert out["data_centers"]["dc1"]["rackA"][0]["url"] == "h:1"
    assert out["data_centers"]["dc1"]["rackA"][0]["volumes"][0]["id"] == 4
    assert out["data_centers"]["dc2"] == {}
    assert out["ec_volumes"]["7"]["0"] == ["h:1", "h:2"]
    assert out["ec_collections"] == {"7": "buck"}


def test_request_frames_are_binary_not_json(codec):
    ser, _de = codec.request_serdes(MASTER_SERVICE, "Assign")
    raw = ser({"count": 3, "collection": "c"})
    with pytest.raises(ValueError):
        json.loads(raw)  # a JSON frame would parse


def test_cluster_round_trip_over_binary_wire(tmp_path, monkeypatch):
    """Full in-process stack with WEEDTPU_WIRE=proto: assign -> upload ->
    read -> filer namespace ops, every control RPC on protobuf frames."""
    monkeypatch.setenv("WEEDTPU_WIRE", "proto")

    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer import FilerServer
    from seaweedfs_tpu.filer.client import FilerClient

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "vol"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    fs = FilerServer(master.address)
    fs.start()
    try:
        mc = MasterClient(master.address)
        fid = mc.submit(b"protobuf wire payload").fid
        assert mc.read(fid) == b"protobuf wire payload"
        mc.close()
        fc = FilerClient(fs.grpc_address)
        from seaweedfs_tpu.filer.entry import Entry

        fc.create(Entry(path="/pw/dir", is_directory=True))
        fc.create(Entry(path="/pw/dir/a.txt"))
        assert [e.name for e in fc.list("/pw/dir")] == ["a.txt"]
        fc.kv_put("wirekey", b"\x00bin\xff")
        assert fc.kv_get("wirekey") == b"\x00bin\xff"
        fc.delete("/pw/dir", recursive=True)
        fc.close()
    finally:
        fs.stop()
        vs.stop()
        master.stop()
