"""Geometry-flexible codes + ec.convert — the conversion subsystem's
tier-1 contract.

Byte identity vs the decode->re-encode oracle is THE spec: for every
geometry pair and layout shape (tile-edge, odd, tiny, degraded source),
`convert_ec_files`'s staged output must equal `write_dat_file` +
`write_ec_files` on the target geometry, bit for bit — while moving far
fewer bytes (the BENCH_CONVERT gate) and never materializing a .dat.
Crash-resume (SIGKILL mid-conversion, journal watermark replay),
cut-over atomicity (a half-swapped volume refuses to mount, never
misreads), multi-geometry mounts, and the cluster RPC/shell wiring ride
along.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import signal
import subprocess
import sys

import numpy as np
import pytest

from seaweedfs_tpu.ec import convert, locate, stripe
from seaweedfs_tpu.ec.ec_volume import EcGeometryError, EcVolume
from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import (
    CODE_FAMILIES,
    Encoder,
    geometry_for,
    new_encoder,
)

L, S = 4096, 512  # scaled block geometry (the shell-test convention)
FAMILIES = ("cauchy_12_3", "merge_20_4")


def _enc(k=10, m=4, kind="vandermonde"):
    return Encoder(k, m, matrix_kind=kind, backend="numpy")


def _build_source(tmp_path, dat_bytes, seed=11, name="1"):
    os.makedirs(str(tmp_path), exist_ok=True)
    base = os.path.join(str(tmp_path), name)
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, dat_bytes, dtype=np.uint8).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    stripe.write_ec_files(
        base, large_block_size=L, small_block_size=S, buffer_size=S,
        encoder=_enc(),
    )
    return base, data


def _oracle(tmp_path, base, family, name="oracle"):
    """decode->re-encode reference shard set for `base` at `family`."""
    ob = os.path.join(str(tmp_path), name, "1")
    os.makedirs(os.path.dirname(ob), exist_ok=True)
    src_total = stripe.geometry_from_info(stripe.read_ec_info(base)).total_shards
    for s in stripe.find_local_shards(base, src_total):
        shutil.copy(stripe.shard_file_name(base, s), stripe.shard_file_name(ob, s))
    shutil.copy(base + ".eci", ob + ".eci")
    missing = [
        s for s in range(src_total)
        if not os.path.exists(stripe.shard_file_name(ob, s))
    ]
    if missing:
        stripe.rebuild_ec_files(ob, encoder=_enc())
    stripe.write_dat_file(ob)
    for s in range(src_total):
        os.unlink(stripe.shard_file_name(ob, s))
    geom = geometry_for(family)
    stripe.write_ec_files(
        ob, large_block_size=L, small_block_size=S, buffer_size=S,
        encoder=_enc(geom.data_shards, geom.parity_shards, geom.matrix_kind),
    )
    return ob


def _assert_staged_matches(base, ob, family):
    staged = convert.stage_base(base)
    for s in range(geometry_for(family).total_shards):
        a = open(stripe.shard_file_name(staged, s), "rb").read()
        b = open(stripe.shard_file_name(ob, s), "rb").read()
        assert a == b, f"{family} shard {s}: staged differs from oracle"


# -- registry + planner -------------------------------------------------------


def test_code_family_registry():
    assert set(FAMILIES) <= set(CODE_FAMILIES)
    legacy = geometry_for("rs_10_4")
    assert (legacy.data_shards, legacy.parity_shards) == (10, 4)
    wide = geometry_for("cauchy_12_3")
    assert wide.overhead < legacy.overhead  # the tiering point: cheaper
    assert geometry_for("merge_20_4").total_shards == 24
    with pytest.raises(ValueError, match="unknown code family"):
        geometry_for("nope_9_9")
    enc = new_encoder(family="cauchy_12_3", backend="numpy")
    assert (enc.data_shards, enc.parity_shards, enc.matrix_kind) == (
        12, 3, "cauchy",
    )
    assert enc.family == "cauchy_12_3"
    assert _enc().family == "rs_10_4"
    assert Encoder(7, 2, backend="numpy").family is None  # ad-hoc geometry


def test_conversion_matrix_maps_survivors_to_target_shards():
    """The planner's algebra for k-preserving pairs: M = G_tgt · Dec maps
    ANY k survivor source shards to the full target shard set — data
    rows pass through (identity block when survivors are the data
    shards), parity rows are projections."""
    src = _enc(10, 4, "vandermonde")
    tgt = _enc(10, 4, "cauchy")
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, (10, 257), dtype=np.uint8)
    src_shards = np.stack(src.encode(list(data)))
    tgt_shards = np.stack(tgt.encode(list(data)))
    # healthy survivors = the data shards: M's top block is the identity
    m = convert.conversion_matrix(src, tgt)
    assert np.array_equal(m[:10], np.eye(10, dtype=np.uint8))
    assert np.array_equal(gf8.gf_mat_vec(m, src_shards[:10]), tgt_shards)
    # degraded survivors (parity standing in for lost data): same output
    survivors = [0, 1, 2, 3, 4, 5, 6, 7, 10, 13]
    m2 = convert.conversion_matrix(src, tgt, survivors)
    assert np.array_equal(
        gf8.gf_mat_vec(m2, src_shards[survivors]), tgt_shards
    )
    # k-changing pairs have no whole-shard matrix — the streaming block
    # regroup owns them, and the planner says so instead of mis-mapping
    with pytest.raises(convert.ConversionError, match="k-changing"):
        conversion = _enc(12, 3, "cauchy")
        convert.conversion_matrix(src, conversion)


# -- byte identity across layouts --------------------------------------------


@pytest.mark.parametrize("family", FAMILIES)
@pytest.mark.parametrize(
    "dat_bytes",
    [
        3 * L * 10 + 5 * S * 10 + 137,  # large + small + odd tail
        2 * L * 10,                      # tile edge: exact large rows
        4 * S * 10,                      # small rows only, exact
        777,                             # tiny: single partial small row
    ],
    ids=["mixed-odd", "large-exact", "small-exact", "tiny"],
)
def test_convert_byte_identity_vs_oracle(tmp_path, family, dat_bytes):
    base, _ = _build_source(tmp_path, dat_bytes)
    res = convert.convert_ec_files(
        base, family, encoder=_enc(), buffer_size=S, journal_bytes=1 << 16
    )
    assert res["mode"] == "converted"
    assert res["reconstructed_bytes"] == 0
    ob = _oracle(tmp_path, base, family)
    _assert_staged_matches(base, ob, family)
    # accounting: moved (written) bytes match the staged set exactly, and
    # the oracle formula is what BASELINE.md states
    geom = geometry_for(family)
    n_l, n_s = stripe.stripe_layout(dat_bytes, L, S, geom.data_shards)
    shard_len = n_l * L + n_s * S
    assert res["bytes_written"] == geom.total_shards * shard_len
    acct = convert.reencode_oracle_bytes(base, family)
    assert acct["total"] == 3 * dat_bytes + geom.total_shards * shard_len
    if dat_bytes >= L * 10:
        # the 0.5x gate is a property of real volumes; a sub-row toy
        # volume is all zero padding and the identity contract carries it
        assert res["bytes_written"] <= 0.5 * acct["total"]


def test_convert_degraded_source_projects_survivors(tmp_path):
    """Missing source data shards reconstruct inline from survivors
    (parity included) — the conversion never needs a whole .dat, and the
    output is still byte-exact vs the oracle on the rebuilt volume."""
    base, _ = _build_source(tmp_path, 2 * L * 10 + 3 * S * 10 + 99)
    ob = _oracle(tmp_path, base, "cauchy_12_3")  # oracle BEFORE the damage
    os.unlink(stripe.shard_file_name(base, 0))
    os.unlink(stripe.shard_file_name(base, 7))
    res = convert.convert_ec_files(
        base, "cauchy_12_3", encoder=_enc(), buffer_size=S
    )
    assert res["reconstructed_bytes"] > 0
    _assert_staged_matches(base, ob, "cauchy_12_3")
    # too few survivors refuses loudly
    for s in (1, 2, 3):
        os.unlink(stripe.shard_file_name(base, s))
    convert.discard_staged(base, keep_journal=False)
    with pytest.raises(convert.ConversionError, match="cannot read source"):
        convert.convert_ec_files(base, "merge_20_4", encoder=_enc())


def test_convert_noop_and_unknown_family(tmp_path):
    base, _ = _build_source(tmp_path, 3 * S * 10)
    assert convert.convert_ec_files(base, "rs_10_4")["mode"] == "noop"
    with pytest.raises(ValueError, match="unknown code family"):
        convert.convert_ec_files(base, "bogus")
    # conversion of a legacy sidecar-less set refuses (no vouched layout)
    os.unlink(base + ".eci")
    with pytest.raises(convert.ConversionError, match="no .eci"):
        convert.convert_ec_files(base, "cauchy_12_3")


# -- crash-resume -------------------------------------------------------------

_CHILD = """
import sys
sys.path.insert(0, {root!r})
from seaweedfs_tpu.ec import convert, stripe
from seaweedfs_tpu.ops.rs_codec import Encoder
orig = stripe._encode_rows
calls = [0]
def hooked(*a, **k):
    calls[0] += 1
    if calls[0] > {after}:
        print("MIDWAY", flush=True)
        import time
        time.sleep(60)
    return orig(*a, **k)
stripe._encode_rows = hooked
convert.convert_ec_files(
    {base!r}, {family!r}, encoder=Encoder(10, 4, backend="numpy"),
    buffer_size={S}, journal_bytes=4096,
)
"""


def test_convert_sigkill_resume_byte_identity(tmp_path):
    """The chaos contract, deterministically: the converting process is
    SIGKILLed mid-stream (journal watermarks on disk, staged partials
    torn), the source keeps serving untouched, and a re-run RESUMES from
    the last watermark — never restarts — finishing byte-identical to
    the oracle."""
    base, data = _build_source(tmp_path, 6 * L * 10 + 2 * S * 10 + 55)
    src_files = {
        s: open(stripe.shard_file_name(base, s), "rb").read()
        for s in range(14)
    }
    child = _CHILD.format(
        root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        base=base, family="merge_20_4", S=S, after=2,
    )
    p = subprocess.Popen(
        [sys.executable, "-c", child],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert "MIDWAY" in p.stdout.readline()
    p.send_signal(signal.SIGKILL)
    p.wait()
    marks = [
        r for r in convert._Journal.read(convert.journal_path(base))
        if r.get("type") == "watermark"
    ]
    assert marks, "the kill must land after at least one journal watermark"
    # old geometry untouched mid-conversion: still serving, bit for bit
    for s, blob in src_files.items():
        assert open(stripe.shard_file_name(base, s), "rb").read() == blob
    res = convert.convert_ec_files(
        base, "merge_20_4", encoder=_enc(), buffer_size=S, journal_bytes=4096
    )
    assert res["mode"] == "resumed"
    ob = _oracle(tmp_path, base, "merge_20_4")
    _assert_staged_matches(base, ob, "merge_20_4")


def test_convert_torn_journal_tail_restarts_clean(tmp_path):
    base, _ = _build_source(tmp_path, 2 * L * 10 + S * 10)
    with open(convert.journal_path(base), "ab") as f:
        f.write(b'{"type": "begin", "src_fam')  # torn mid-record
    res = convert.convert_ec_files(
        base, "cauchy_12_3", encoder=_enc(), buffer_size=S
    )
    assert res["mode"] == "converted"  # garbage journal = fresh start
    _assert_staged_matches(
        base, _oracle(tmp_path, base, "cauchy_12_3"), "cauchy_12_3"
    )


def test_convert_rejects_source_drift_on_resume(tmp_path):
    """A journal from a DIFFERENT source state (the .eci CRC fingerprint
    disagrees) must not resume over it — fresh start instead."""
    base, _ = _build_source(tmp_path, 2 * L * 10 + S * 10)
    res = convert.convert_ec_files(
        base, "cauchy_12_3", encoder=_enc(), buffer_size=S, journal_bytes=512
    )
    assert res["mode"] == "converted"
    # mutate the source (recorded CRCs change) and convert again: the
    # stale journal must be discarded, not resumed
    with open(base + ".dat", "r+b") as f:
        f.seek(0)
        f.write(b"\xff" * 64)
    for s in range(14):
        os.unlink(stripe.shard_file_name(base, s))
    stripe.write_ec_files(
        base, large_block_size=L, small_block_size=S, buffer_size=S,
        encoder=_enc(),
    )
    res2 = convert.convert_ec_files(
        base, "cauchy_12_3", encoder=_enc(), buffer_size=S
    )
    assert res2["mode"] == "converted"
    _assert_staged_matches(
        base, _oracle(tmp_path, base, "cauchy_12_3", name="o2"), "cauchy_12_3"
    )


# -- cut-over + serving -------------------------------------------------------


def _mountable(base):
    open(base + ".idx", "wb").close()
    stripe.write_sorted_file_from_idx(base)


def _read_range(ev, data, off, size):
    ivs = locate.locate_data(ev.large, ev.small, ev.dat_file_size, off, size,
                             ev.data_shards)
    assert ev.read_intervals(ivs) == data[off : off + size]


def test_cutover_serves_through_standard_ec_volume_path(tmp_path):
    """The acceptance criterion: converted shards are readable through
    the STANDARD EcVolume path after cut-over — healthy interval reads,
    degraded reconstruction, CRC fsck, and rebuild all speak the new
    geometry; the old geometry serves until the swap."""
    base, data = _build_source(tmp_path, 3 * L * 10 + 2 * S * 10 + 201)
    _mountable(base)
    convert.convert_ec_files(base, "cauchy_12_3", encoder=_enc(), buffer_size=S)
    # pre-cutover: volume still mounts and reads as the OLD geometry
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        assert ev.total_shards == 14 and ev.data_shards == 10
        _read_range(ev, data, 0, 300)
    out = convert.cutover(base)
    assert out["mode"] == "cutover"
    assert sorted(stripe.find_local_shards(base)) == list(range(15))
    assert not os.path.exists(convert.journal_path(base))
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        assert ev.geometry.family == "cauchy_12_3"
        assert (ev.data_shards, ev.total_shards) == (12, 15)
        assert ev.encoder.data_shards == 12  # geometry sibling, not 10+4
        for off, size in [(0, 1), (L * 10 - 7, 300), (len(data) - 99, 99)]:
            _read_range(ev, data, off, size)
        fsck = ev.verify_local_shards()
        assert fsck is not None and all(fsck.values())
    # degraded read + rebuild on the NEW geometry
    os.unlink(stripe.shard_file_name(base, 3))
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        _read_range(ev, data, L * 3, 513)  # reconstructs through 12+3
    assert stripe.rebuild_ec_files(base) == [3]


def test_cutover_crash_midswap_refuses_then_recovers(tmp_path):
    """Crash between the .eci swap and the shard swaps: the volume
    REFUSES to mount (typed EcGeometryError — old shard files are longer
    than the new geometry's layout) instead of misreading, and
    finish_cutover completes the swap from the journal."""
    base, data = _build_source(tmp_path, 2 * L * 10 + 3 * S * 10)
    _mountable(base)
    convert.convert_ec_files(base, "merge_20_4", encoder=_enc(), buffer_size=S)
    staged = convert.stage_base(base)
    j = convert._Journal(convert.journal_path(base))
    j.append({"type": "cutover"})
    j.close()
    os.replace(staged + ".eci", base + ".eci")  # crash right here
    with pytest.raises(EcGeometryError):
        EcVolume(base, encoder=_enc(), warm_on_mount=False)
    out = convert.finish_cutover(base)
    assert out["mode"] == "cutover"
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        assert (ev.data_shards, ev.total_shards) == (20, 24)
        _read_range(ev, data, 0, 257)
        _read_range(ev, data, len(data) - 31, 31)


@pytest.mark.parametrize("reissue_family", ["merge_20_4", "cauchy_12_3"])
def test_reissued_convert_finishes_crashed_cutover(tmp_path, reissue_family):
    """Regression: a crash AFTER the .eci rename leaves the live sidecar
    recording the TARGET geometry. A re-issued convert_ec_files — the
    documented remedy — must finish the journaled swap, not (same
    family) return noop on the src==tgt comparison and strand the volume
    un-mountable forever, nor (different family) mistake the journal for
    source drift and discard the staged shards, which are the only
    complete copy of the new layout."""
    base, data = _build_source(tmp_path, 2 * L * 10 + 3 * S * 10)
    _mountable(base)
    convert.convert_ec_files(base, "merge_20_4", encoder=_enc(), buffer_size=S)
    staged = convert.stage_base(base)
    j = convert._Journal(convert.journal_path(base))
    j.append({"type": "cutover"})
    j.close()
    os.replace(staged + ".eci", base + ".eci")  # crash right here
    out = convert.convert_ec_files(base, reissue_family, encoder=_enc())
    assert out["mode"] == "cutover"
    assert not convert.pending_cutover(base)
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        assert (ev.data_shards, ev.total_shards) == (20, 24)
        _read_range(ev, data, 0, 257)
        _read_range(ev, data, len(data) - 31, 31)


def test_geometry_mismatch_raises_typed_error(tmp_path):
    """Satellite: a wrong-geometry shard set is caught at mount by a
    typed error, not by CRC luck."""
    base, _ = _build_source(tmp_path, 2 * S * 10)
    _mountable(base)
    # stray shard id past the recorded geometry
    open(stripe.shard_file_name(base, 17), "wb").write(b"x")
    with pytest.raises(EcGeometryError) as ei:
        EcVolume(base, encoder=_enc(), warm_on_mount=False)
    assert ei.value.details["stray_shards"] == [17]
    os.unlink(stripe.shard_file_name(base, 17))
    # over-length shard (longer than the recorded layout allows)
    with open(stripe.shard_file_name(base, 4), "ab") as f:
        f.write(b"\0" * 64)
    with pytest.raises(EcGeometryError) as ei:
        EcVolume(base, encoder=_enc(), warm_on_mount=False)
    assert 4 in ei.value.details["over_length"]
    # truncation is NOT a geometry error (scrub territory): mount serves
    with open(stripe.shard_file_name(base, 4), "r+b") as f:
        f.truncate(os.path.getsize(stripe.shard_file_name(base, 0)) - 10)
    with EcVolume(base, encoder=_enc(), warm_on_mount=False) as ev:
        assert 4 in ev.shard_ids


def test_multi_geometry_mounts_coexist(tmp_path):
    """Two volumes of different geometry mounted side by side, each
    decoding through its own .eci-recorded code."""
    base_a, data_a = _build_source(tmp_path / "a", 2 * L * 10 + 3 * S * 10, seed=1)
    base_b, data_b = _build_source(tmp_path / "b", L * 10 + 5 * S * 10, seed=2)
    for b in (base_a, base_b):
        _mountable(b)
    convert.convert_ec_files(base_b, "merge_20_4", encoder=_enc(), buffer_size=S)
    convert.cutover(base_b)
    shared = _enc()  # ONE store-style encoder handed to both mounts
    with EcVolume(base_a, encoder=shared, warm_on_mount=False) as ev_a, \
         EcVolume(base_b, encoder=shared, warm_on_mount=False) as ev_b:
        assert ev_a.total_shards == 14 and ev_b.total_shards == 24
        assert ev_a.encoder is shared  # matching geometry: reused as-is
        assert ev_b.encoder.data_shards == 20
        _read_range(ev_a, data_a, 123, 456)
        _read_range(ev_b, data_b, 123, 456)


# -- .eci geometry record -----------------------------------------------------


def test_eci_records_geometry_with_legacy_default(tmp_path):
    base, _ = _build_source(tmp_path, 2 * S * 10)
    info = stripe.read_ec_info(base)
    # legacy default geometry stays IMPLICIT (byte-compat with every
    # pre-geometry writer); the read path supplies it
    assert "data_shards" not in info
    geom = stripe.geometry_from_info(info)
    assert (geom.family, geom.data_shards) == ("rs_10_4", 10)
    assert stripe.geometry_from_info(None).family == "rs_10_4"
    # non-default geometry is recorded explicitly
    convert.convert_ec_files(base, "cauchy_12_3", encoder=_enc(), buffer_size=S)
    staged_info = stripe.read_ec_info(convert.stage_base(base))
    assert staged_info["family"] == "cauchy_12_3"
    assert staged_info["data_shards"] == 12
    assert len(staged_info["shard_crc32"]) == 15
    # malformed geometry keys refuse rather than misread
    with pytest.raises(ValueError, match="unusable geometry"):
        stripe.geometry_from_info({"data_shards": 0, "parity_shards": 4})


def test_encoder_for_info_builds_same_backend_sibling():
    enc = _enc()
    assert stripe.encoder_for_info(None, enc) is enc
    sib = stripe.encoder_for_info(
        {"data_shards": 12, "parity_shards": 3, "matrix_kind": "cauchy"}, enc
    )
    assert (sib.data_shards, sib.backend) == (12, "numpy")


# -- cluster wiring -----------------------------------------------------------


@pytest.fixture
def cluster(tmp_path):
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.shell import CommandEnv

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)], master.address, heartbeat_interval=0.3,
            rack=f"rack{i % 2}", max_volume_count=50,
        )
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    env = CommandEnv(master.address)
    yield master, servers, client, env
    env.close()
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()


def _run_shell(env, line):
    from seaweedfs_tpu.shell import run_command

    out = io.StringIO()
    run_command(env, line, out)
    return out.getvalue()


def test_ec_convert_shell_e2e(cluster):
    """Full cluster pass: upload -> ec.encode (spread across nodes) ->
    ec.convert -family cauchy_12_3 (survivors pulled to the converter,
    conversion + verified cut-over, stale old-geometry shards dropped)
    -> every blob still readable through the standard degraded path ->
    master topology sees the 15-shard geometry."""
    master, servers, client, env = cluster
    payloads = []
    for i in range(12):
        res = client.submit(os.urandom(600 + i))
        payloads.append((res.fid, client.read(res.fid)))
    vid = int(payloads[0][0].split(",", 1)[0])
    _run_shell(env, "lock")
    out = _run_shell(
        env, f"ec.encode -volumeId {vid} -largeBlockSize {L} -smallBlockSize {S}"
    )
    assert f"ec.encode volume {vid}" in out
    out = _run_shell(env, f"ec.convert -volumeId {vid} -family cauchy_12_3")
    assert "rs_10_4 -> cauchy_12_3" in out and "cut over" in out
    # the master's shard map now carries the 15-shard geometry
    spread = {}
    for n in env.topology_nodes():
        for e in n.get("ec_shards", []):
            if int(e["volume_id"]) == vid:
                from seaweedfs_tpu.ec.shard_bits import ShardBits

                spread[n["url"]] = ShardBits(e.get("shard_bits", 0)).shard_ids()
    assert sorted(s for sids in spread.values() for s in sids) == list(range(15))
    for fid, payload in payloads:
        assert client.read(fid) == payload, f"{fid} corrupted by conversion"
    # geometry-aware ec.rebuild: lose shard 14 — an id the legacy
    # range(14) scan could never see — and prove the shell detects and
    # rebuilds it on the converted volume
    import time as time_mod

    from seaweedfs_tpu.ec.shard_bits import ShardBits
    from seaweedfs_tpu.shell import grpc_addr

    holder_url = next(u for u, sids in spread.items() if 14 in sids)
    holder = next(n for n in env.topology_nodes() if n["url"] == holder_url)
    env.vs_call(
        grpc_addr(holder),
        "VolumeEcShardsDelete",
        {"volume_id": vid, "collection": "", "shard_ids": [14]},
    )
    deadline = time_mod.time() + 15
    while time_mod.time() < deadline:
        held = {
            s
            for n in env.topology_nodes()
            for e in n.get("ec_shards", [])
            if int(e["volume_id"]) == vid
            for s in ShardBits(e.get("shard_bits", 0)).shard_ids()
        }
        if 14 not in held:
            break
        time_mod.sleep(0.2)
    assert 14 not in held, "heartbeat never dropped the deleted shard"
    out = _run_shell(env, "ec.rebuild")
    assert "rebuilt [14]" in out, out
    for fid, payload in payloads:
        assert client.read(fid) == payload, f"{fid} corrupted by rebuild"


def test_ec_convert_rpc_resume_and_counters(cluster, tmp_path):
    """RPC-level: a staged (nocutover) conversion leaves the old geometry
    serving; re-invoking completes cut-over from the journal; the
    convert byte counters land at the dispatch seam."""
    from seaweedfs_tpu import stats
    from seaweedfs_tpu.shell import grpc_addr

    master, servers, client, env = cluster
    res = client.submit(b"x" * 5000)
    vid = int(res.fid.split(",", 1)[0])
    payload = client.read(res.fid)
    _run_shell(env, "lock")
    _run_shell(
        env, f"ec.encode -volumeId {vid} -largeBlockSize {L} -smallBlockSize {S}"
    )
    before = stats.EcConvertBytes.labels("written").value
    out = _run_shell(env, f"ec.convert -volumeId {vid} -family merge_20_4 -nocutover")
    assert "merge_20_4 (converted)" in out
    assert stats.EcConvertBytes.labels("written").value > before
    assert client.read(res.fid) == payload  # old geometry still serving
    # the staged set + journal live on the converter the shell picked —
    # its URL is in the command output ("... (converted) on <url>: ...")
    converter_url = re.search(r" on ([^\s:]+:\d+): read ", out).group(1)
    holder = next(
        n for n in env.topology_nodes() if n["url"] == converter_url
    )
    # second call: nothing to re-encode (journal says staged) + cutover
    resp = env.vs_call(
        grpc_addr(holder),
        "VolumeEcShardsConvert",
        {"volume_id": vid, "target_family": "merge_20_4", "cutover": True},
        timeout=120,
    )
    assert resp["mode"] in ("resumed", "converted")
    assert resp["shard_ids"] == list(range(24))
    assert client.read(res.fid) == payload


# -- bench smoke (the tier-1 byte-accounting gate) ----------------------------


def test_bench_convert_smoke_gate(tmp_path):
    """BENCH_MODE=convert at smoke scale: deterministic byte accounting,
    ratio <= 0.5 for BOTH geometry pairs, staged output byte-identical
    to the oracle, measured oracle I/O == the stated formula."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    out = bench._measure_convert(
        str(tmp_path), dat_bytes=2 << 20, large=128 << 10, small=16 << 10,
        buffer_size=16 << 10, encoder=_enc(),
    )
    assert out["ok"], json.dumps(out, indent=1)
    for fam in FAMILIES:
        pair = out["pairs"][fam]
        assert pair["match"] is True
        assert pair["moved_over_reencode"] <= 0.5
        assert pair["oracle_total_measured"] == pair["oracle_total_bytes"]


def test_convert_knobs_registered():
    from seaweedfs_tpu.utils import config

    for name in (
        "WEEDTPU_CONVERT_BATCH",
        "WEEDTPU_CONVERT_JOURNAL_MB",
        "WEEDTPU_CONVERT_VERIFY",
    ):
        assert name in config.ENV_REGISTRY
        assert config.env(name) is not None
