"""Distributed (remote-survivor) rebuild tests: the network-overlapped
`ec.rebuild` path end to end — byte-identity against the serial oracle with
survivors split across two in-process volume servers, per-holder failover
mid-rebuild without a pipeline restart, drain+unlink exception safety when
too few holders survive (mirroring tests/test_stream_pipeline.py), the
CRC-framed bulk slab stream, single-flight shard-location lookups, and the
tier-1 `ec_rebuild_remote` bench smoke."""

import os
import shutil
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.pb import VOLUME_SERVICE

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL = 16384, 4096
VID = 9


def _build_ec_volume(dirpath: str, size: int = 400_000, seed: int = 3):
    """Write a full 14-shard EC volume (plus .ecx/.eci) under `dirpath`;
    returns (base_path, {shard: golden_bytes})."""
    base = os.path.join(dirpath, str(VID))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


def _move_shards(src_base: str, dst_base: str, shard_ids, with_index=True):
    for s in shard_ids:
        os.replace(stripe.shard_file_name(src_base, s), stripe.shard_file_name(dst_base, s))
    if with_index:
        for ext in (".ecx", ".eci"):
            if os.path.exists(src_base + ext) and not os.path.exists(dst_base + ext):
                shutil.copy(src_base + ext, dst_base + ext)


def _wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def cluster3(tmp_path):
    """master + 3 volume servers (target + two potential holders)."""
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
        vs.start()
        servers.append(vs)
    yield master, servers
    for vs in servers:
        vs.stop()
    master.stop()


# -- end to end: byte-identity with survivors split across two servers --------


def test_remote_rebuild_byte_identical_split_survivors(cluster3, tmp_path):
    """Survivors split across the target (7-9 local) and a peer (0-6
    remote); parity 10-13 lost cluster-wide. The distributed rebuild must
    produce byte-identical files to the golden shards AND to
    `rebuild_ec_files_serial` run on the same survivor set."""
    master, (target, peer, _spare) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, golden = _build_ec_volume(str(stage))
    base_peer = peer._base_path_for(VID)
    base_target = target._base_path_for(VID)
    for s in (10, 11, 12, 13):
        os.unlink(stripe.shard_file_name(base_stage, s))
    _move_shards(base_stage, base_peer, range(0, 7))
    _move_shards(base_stage, base_target, range(7, 10))
    with rpc.RpcClient(peer.grpc_address) as pc:
        pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    with rpc.RpcClient(target.grpc_address) as tc:
        tc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 10,
        msg="10 survivor shards registered",
    )

    with rpc.RpcClient(target.grpc_address) as tc:
        resp = tc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsRebuild",
            {"volume_id": VID, "remote": True},
            timeout=120,
        )
    assert resp["rebuilt_shard_ids"] == [10, 11, 12, 13]
    assert resp["local_survivors"] == [7, 8, 9]
    assert resp["remote_survivors"] == [0, 1, 2, 3, 4, 5, 6]
    for s in (10, 11, 12, 13):
        with open(stripe.shard_file_name(base_target, s), "rb") as f:
            assert f.read() == golden[s], f"rebuilt shard {s} differs from golden"

    # direct file-compare against the serial oracle on the SAME survivor set
    oracle = tmp_path / "oracle"
    oracle.mkdir()
    base_oracle = os.path.join(str(oracle), str(VID))
    for s in range(DATA_SHARDS_COUNT):
        with open(stripe.shard_file_name(base_oracle, s), "wb") as f:
            f.write(golden[s])
    assert stripe.rebuild_ec_files_serial(base_oracle, encoder=ENC) == [10, 11, 12, 13]
    for s in (10, 11, 12, 13):
        with open(stripe.shard_file_name(base_oracle, s), "rb") as f1, open(
            stripe.shard_file_name(base_target, s), "rb"
        ) as f2:
            assert f1.read() == f2.read(), f"shard {s}: remote != serial oracle"

    # the regenerated set mounts and serves
    with rpc.RpcClient(target.grpc_address) as tc:
        tc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsMount",
            {"volume_id": VID, "shard_ids": [10, 11, 12, 13]},
        )
        st = tc.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": VID})
    assert set(st["shard_ids"]) >= {7, 8, 9, 10, 11, 12, 13}


def test_remote_rebuild_holder_failover_mid_rebuild(cluster3, tmp_path):
    """Kill one survivor holder mid-rebuild (its slab RPC starts failing):
    the remaining slabs must fail over to the alternate holder without
    restarting the pipeline, and the output must stay byte-identical."""
    master, (target, holder_a, holder_b) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, golden = _build_ec_volume(str(stage))
    for s in (10, 11, 12, 13):
        os.unlink(stripe.shard_file_name(base_stage, s))
    # BOTH holders carry all 10 survivors (replicated shard placement)
    base_a = holder_a._base_path_for(VID)
    base_b = holder_b._base_path_for(VID)
    for s in range(DATA_SHARDS_COUNT):
        shutil.copy(stripe.shard_file_name(base_stage, s), stripe.shard_file_name(base_a, s))
        shutil.copy(stripe.shard_file_name(base_stage, s), stripe.shard_file_name(base_b, s))
    for ext in (".ecx", ".eci"):
        shutil.copy(base_stage + ext, base_a + ext)
        shutil.copy(base_stage + ext, base_b + ext)
    for vs in (holder_a, holder_b):
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: all(
            len(addrs) == 2 for addrs in master.topology.lookup_ec_shards(VID).values()
        )
        and len(master.topology.lookup_ec_shards(VID)) == 10,
        msg="both holders registered for all survivors",
    )

    # holder A "dies" mid-rebuild: its slab RPC serves 2 windows then fails
    served = {"n": 0}
    orig = holder_a._rpc_ec_slab_read

    def dying_slab_read(req, ctx):
        served["n"] += 1
        if served["n"] > 2:
            raise rpc.RpcFault("holder killed mid-rebuild")
        yield from orig(req, ctx)

    holder_a._rpc_ec_slab_read = dying_slab_read
    svc = holder_a._grpc._services[VOLUME_SERVICE]
    svc.add(
        "VolumeEcShardSlabRead", dying_slab_read, kind="unary_stream", resp_format="bytes"
    )
    # the target must try A first for every shard or the kill is untested
    orig_lookup = target._lookup_shard_locations
    a_addr = holder_a.grpc_address

    def a_first(vid):
        locs = orig_lookup(vid)
        return {
            sid: sorted(addrs, key=lambda a: a != a_addr) for sid, addrs in locs.items()
        }

    target._lookup_shard_locations = a_first

    with rpc.RpcClient(target.grpc_address) as tc:
        resp = tc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsRebuild",
            # trace_mode off: this test pins the SLAB failover path (the
            # trace path's failure handling is tests/test_trace_repair.py)
            {"volume_id": VID, "remote": True, "trace_mode": "off"},
            timeout=120,
        )
    assert resp["rebuilt_shard_ids"] == [10, 11, 12, 13]
    assert resp["failed_over"], "holder A died but no failover was recorded"
    assert all(f.endswith(a_addr) for f in resp["failed_over"])
    base_target = target._base_path_for(VID)
    for s in (10, 11, 12, 13):
        with open(stripe.shard_file_name(base_target, s), "rb") as f:
            assert f.read() == golden[s], f"shard {s} wrong after failover"


def test_remote_rebuild_too_few_survivors_faults(cluster3, tmp_path):
    """Fewer than DATA_SHARDS survivors reachable anywhere -> typed fault,
    no partial output files on the target."""
    master, (target, peer, _spare) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, _ = _build_ec_volume(str(stage))
    base_peer = peer._base_path_for(VID)
    _move_shards(base_stage, base_peer, range(0, 9))  # only 9 survivors
    with rpc.RpcClient(peer.grpc_address) as pc:
        pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 9,
        msg="9 shards registered",
    )
    import grpc as _grpc

    with rpc.RpcClient(target.grpc_address) as tc:
        with pytest.raises(_grpc.RpcError, match="cannot rebuild"):
            tc.call(
                VOLUME_SERVICE,
                "VolumeEcShardsRebuild",
                {"volume_id": VID, "remote": True},
                timeout=60,
            )
    base_target = target._base_path_for(VID)
    assert stripe.find_local_shards(base_target) == []


def test_remote_rebuild_truncated_local_survivor_faults(cluster3, tmp_path):
    """The remote path mirrors the local survivors-agree-on-length
    preflight: a truncated local survivor must fault the rebuild up front,
    not zero-fill into silently-wrong shards."""
    master, (target, peer, _spare) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, _ = _build_ec_volume(str(stage))
    for s in (10, 11, 12, 13):
        os.unlink(stripe.shard_file_name(base_stage, s))
    base_peer = peer._base_path_for(VID)
    base_target = target._base_path_for(VID)
    _move_shards(base_stage, base_peer, range(0, 7))
    _move_shards(base_stage, base_target, range(7, 10))
    p = stripe.shard_file_name(base_target, 8)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    for vs in (peer, target):
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 10,
        msg="10 shards registered",
    )
    import grpc as _grpc

    with rpc.RpcClient(target.grpc_address) as tc:
        with pytest.raises(_grpc.RpcError, match="disagree"):
            tc.call(
                VOLUME_SERVICE,
                "VolumeEcShardsRebuild",
                {"volume_id": VID, "remote": True},
                timeout=60,
            )
    assert not os.path.exists(stripe.shard_file_name(base_target, 10))


def test_remote_rebuild_truncated_remote_shard_faults(cluster3, tmp_path):
    """A truncated shard hiding behind healthy siblings on the SAME remote
    holder must also fail the preflight: VolumeStatus reports per-shard
    file sizes, not just the holder's max."""
    master, (target, peer, _spare) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, _ = _build_ec_volume(str(stage))
    for s in (10, 11, 12, 13):
        os.unlink(stripe.shard_file_name(base_stage, s))
    base_peer = peer._base_path_for(VID)
    _move_shards(base_stage, base_peer, range(0, 10))
    with rpc.RpcClient(peer.grpc_address) as pc:
        pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    # truncate AFTER mount: the holder's max-based shard_size still reads
    # full, only the per-shard report can expose it
    p = stripe.shard_file_name(base_peer, 3)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 10,
        msg="10 shards registered",
    )
    import grpc as _grpc

    with rpc.RpcClient(target.grpc_address) as tc:
        with pytest.raises(_grpc.RpcError, match="disagree"):
            tc.call(
                VOLUME_SERVICE,
                "VolumeEcShardsRebuild",
                {"volume_id": VID, "remote": True},
                timeout=60,
            )
    assert not os.path.exists(
        stripe.shard_file_name(target._base_path_for(VID), 10)
    )


# -- pipeline-level: deterministic failover + exception safety ----------------


def _local_fetch_for(base: str, shard_id: int):
    """A fetch(addr, offset, size) that reads the real shard file —
    the transport stub for RemoteSlabSource unit tests."""

    def fetch(addr: str, offset: int, size: int) -> bytes:
        with open(stripe.shard_file_name(base, shard_id), "rb") as f:
            f.seek(offset)
            return f.read(size)

    return fetch


def _make_local_volume(tmp_path, size=400_000):
    base = os.path.join(str(tmp_path), "v")
    rng = np.random.default_rng(5)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=ENC
    )
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    return base, golden


def test_slab_source_failover_is_mid_pipeline(tmp_path):
    """RemoteSlabSource: the primary holder dies after one window; later
    windows re-route to the alternate holder with the batch pipeline (and
    its earlier output) intact — output byte-identical to the serial path."""
    base, golden = _make_local_volume(tmp_path)
    missing = [0, 5, 11, 13]
    for s in missing:
        os.unlink(stripe.shard_file_name(base, s))
    present = [s for s in range(TOTAL_SHARDS_COUNT) if s not in missing]
    calls = {"dead": 0, "live": 0}
    sources = {}
    for s in present:
        real = _local_fetch_for(base, s)

        def fetch(addr, offset, size, _real=real):
            calls[addr] += 1
            if addr == "dead" and calls["dead"] > 3:
                raise IOError("holder gone")
            return _real(addr, offset, size)

        sources[s] = stripe.RemoteSlabSource(s, ["dead", "live"], fetch)
    shard_size = len(golden[1])
    try:
        rebuilt = stripe.rebuild_ec_files_from_sources(
            base,
            sources,
            shard_size,
            encoder=ENC,
            buffer_size=8192,
            max_batch_bytes=10 * 2 * 8192,  # several windows -> mid-stream kill
        )
    finally:
        for src in sources.values():
            src.close()
    assert rebuilt == sorted(missing)
    assert calls["live"] > 0, "no window was served by the failover holder"
    assert any(src.failovers == ["dead"] for src in sources.values())
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s], f"shard {s} differs after failover"


def test_from_sources_drains_and_unlinks_when_holders_die(tmp_path):
    """All holders of one survivor die mid-rebuild with no alternate: the
    pipeline must drain inflight device work and unlink the partial shard
    files, leaving survivors untouched (test_stream_pipeline mirror)."""
    base, golden = _make_local_volume(tmp_path)
    missing = [10, 11, 12, 13]
    for s in missing:
        os.unlink(stripe.shard_file_name(base, s))
    present = [s for s in range(TOTAL_SHARDS_COUNT) if s not in missing]
    calls = {"n": 0}
    sources = {}
    for s in present:
        real = _local_fetch_for(base, s)

        def fetch(addr, offset, size, _real=real):
            calls["n"] += 1
            if calls["n"] > 12:  # past the first window fan-out: all die
                raise IOError("cluster lost")
            return _real(addr, offset, size)

        sources[s] = stripe.RemoteSlabSource(s, ["only"], fetch)
    try:
        with pytest.raises(IOError, match="no reachable holder"):
            stripe.rebuild_ec_files_from_sources(
                base,
                sources,
                len(golden[1]),
                encoder=ENC,
                buffer_size=8192,
                max_batch_bytes=10 * 2 * 8192,
            )
    finally:
        for src in sources.values():
            src.close()
    for s in missing:
        assert not os.path.exists(stripe.shard_file_name(base, s)), f"partial {s} leaked"
    for s in present:
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s], f"survivor {s} damaged"


def test_from_sources_matches_local_rebuild(tmp_path):
    """LocalSlabSource through the generalized pipeline == the classic
    rebuild_ec_files on the same files (the refactor's identity check)."""
    base, golden = _make_local_volume(tmp_path, size=123_457)
    missing = [2, 12]
    for s in missing:
        os.unlink(stripe.shard_file_name(base, s))
    assert stripe.rebuild_ec_files(base, encoder=ENC, buffer_size=8192) == missing
    for s in missing:
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s]


# -- transport: CRC-framed slab stream ----------------------------------------


def test_crc_frame_roundtrip_and_mismatch():
    chunk = os.urandom(1000)
    assert rpc.crc_unframe(rpc.crc_frame(chunk)) == chunk
    framed = bytearray(rpc.crc_frame(chunk))
    framed[7] ^= 0xFF  # flip a payload bit
    with pytest.raises(IOError, match="CRC mismatch"):
        rpc.crc_unframe(bytes(framed))
    with pytest.raises(IOError, match="short CRC frame"):
        rpc.crc_unframe(b"\x00")


def test_slab_read_rpc_streams_crc_chunks_and_eof(cluster3, tmp_path):
    """VolumeEcShardSlabRead: bounded CRC-framed chunks for the requested
    window; a window past EOF ends the stream short (client zero-fills)."""
    master, (_target, peer, _spare) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, golden = _build_ec_volume(str(stage))
    base_peer = peer._base_path_for(VID)
    _move_shards(base_stage, base_peer, range(TOTAL_SHARDS_COUNT))
    with rpc.RpcClient(peer.grpc_address) as pc:
        pc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
        frames = list(
            pc.stream(
                VOLUME_SERVICE,
                "VolumeEcShardSlabRead",
                {
                    "volume_id": VID,
                    "shard_id": 3,
                    "offset": 100,
                    "size": 30_000,
                    "chunk_size": 64 * 1024,  # server clamps to its floor
                },
                timeout=30,
            )
        )
        got = b"".join(rpc.crc_unframe(f) for f in frames)
        assert got == golden[3][100 : 100 + 30_000]
        # EOF semantics: ask far past the end -> short stream, no error
        shard_len = len(golden[3])
        frames = list(
            pc.stream(
                VOLUME_SERVICE,
                "VolumeEcShardSlabRead",
                {
                    "volume_id": VID,
                    "shard_id": 3,
                    "offset": shard_len - 100,
                    "size": 10_000,
                },
                timeout=30,
            )
        )
        got = b"".join(rpc.crc_unframe(f) for f in frames)
        assert got == golden[3][-100:]


# -- single-flight shard-location lookups -------------------------------------


def test_lookup_shard_locations_single_flight(cluster3):
    """A burst of concurrent cache misses for one vid pays exactly ONE
    master LookupEcVolume round-trip."""
    master, (vs, peer, _spare) = cluster3
    master.topology.ec_locations[88] = {sid: {peer.url} for sid in range(14)}
    calls = {"n": 0}
    real_query = vs._master_query

    def slow_counting_query(method, req, timeout=5.0):
        if method == "LookupEcVolume":
            calls["n"] += 1
            time.sleep(0.1)  # widen the race window
        return real_query(method, req, timeout)

    vs._master_query = slow_counting_query
    results = []
    errs = []

    def one():
        try:
            results.append(vs._lookup_shard_locations(88))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=one) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert not errs
    assert len(results) == 8
    assert calls["n"] == 1, f"single-flight broken: {calls['n']} master lookups"
    assert all(set(r) == set(range(14)) for r in results)


def test_lookup_single_flight_leader_failure_wakes_waiters(cluster3):
    """A failed leader lookup must not strand waiters: they retry and
    either succeed themselves or raise their own error (no deadlock)."""
    master, (vs, peer, _spare) = cluster3
    master.topology.ec_locations[99] = {0: {peer.url}}
    state = {"n": 0}
    real_query = vs._master_query

    def first_fails(method, req, timeout=5.0):
        if method == "LookupEcVolume":
            state["n"] += 1
            if state["n"] == 1:
                time.sleep(0.05)
                raise RuntimeError("master hiccup")
        return real_query(method, req, timeout)

    vs._master_query = first_fails
    outcomes = []

    def one():
        try:
            outcomes.append(vs._lookup_shard_locations(99))
        except Exception as e:  # noqa: BLE001
            outcomes.append(e)

    threads = [threading.Thread(target=one) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert len(outcomes) == 4
    assert any(isinstance(o, dict) for o in outcomes), "no caller recovered"


# -- ec_volume satellite: abandoned fetches are cancelled+drained -------------


def test_gather_survivors_cancels_pending_on_raise(tmp_path):
    """An exception mid-fan-out must cancel/drain the still-pending remote
    futures (no hung-peer thread keeps a buffer or unobserved error)."""
    from seaweedfs_tpu.ec.ec_volume import EcVolume

    base, _ = _make_local_volume(tmp_path, size=60_000)
    with open(base + ".idx", "wb"):
        pass
    stripe.write_sorted_file_from_idx(base)
    # keep ONE local shard: too few to reconstruct locally, so the fan-out
    # must go remote for the rest
    for s in range(1, TOTAL_SHARDS_COUNT):
        os.unlink(stripe.shard_file_name(base, s))
    release = threading.Event()

    def hanging_reader(shard_id, offset, size):
        release.wait(5)  # a hung peer
        return None

    with EcVolume(
        base,
        encoder=ENC,
        large_block_size=LARGE,
        small_block_size=SMALL,
        warm_on_mount=False,
        shard_size=60_000,
        remote_reader=hanging_reader,
        recover_fetch_deadline=0.3,
    ) as ev:
        with pytest.raises(IOError, match="surviving shards"):
            ev._gather_survivors(1, 0, 100)
        release.set()


# -- operator path: ec.rebuild -remote ----------------------------------------


def test_shell_ec_rebuild_remote(cluster3, tmp_path):
    """`ec.rebuild -remote` end to end: the shell picks the
    fullest-shard-count node as rebuild target, the target streams the
    survivors it lacks, and the regenerated shard is mounted and
    topology-visible — no bulk survivor pre-copy RPCs."""
    import io

    from seaweedfs_tpu.shell import CommandEnv, run_command

    master, (srv0, srv1, srv2) = cluster3
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, golden = _build_ec_volume(str(stage))
    base1 = srv1._base_path_for(VID)
    base2 = srv2._base_path_for(VID)
    _move_shards(base_stage, base1, range(0, 7))
    _move_shards(base_stage, base2, range(7, 13))
    os.unlink(stripe.shard_file_name(base_stage, 13))  # shard 13 lost
    for vs in (srv1, srv2):
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 13,
        msg="13 shards registered",
    )
    env = CommandEnv(master.address)
    try:
        out = io.StringIO()
        run_command(env, "lock", out)
        run_command(env, "ec.rebuild -remote", out)
        text = out.getvalue()
    finally:
        env.close()
    assert "rebuilt [13]" in text, text
    # the rebuilder was the 7-shard holder and now serves shard 13 too
    rebuilt_base = srv1._base_path_for(VID)
    with open(stripe.shard_file_name(rebuilt_base, 13), "rb") as f:
        assert f.read() == golden[13]
    _wait_for(
        lambda: 13 in master.topology.lookup_ec_shards(VID),
        msg="rebuilt shard in topology",
    )


# -- tier-1 CI smoke: the bench harness on tiny shards ------------------------


def test_bench_rebuild_remote_smoke(tmp_path):
    """Fast CPU smoke of bench.py's ec_rebuild_remote harness (tiny shards,
    two in-process servers): the distributed rebuild must complete, match
    golden bytes, and report the overlap metrics — wired into tier-1 like
    kernel_sweep --smoke, without asserting timing ratios (1-core CI)."""
    import bench

    out = bench._measure_rebuild_remote(
        str(tmp_path),
        dat_bytes=1 << 20,
        large=65536,
        small=16384,
        buffer_size=16384,
        max_batch_bytes=10 * 2 * 16384,
        delay_ms=0,
    )
    assert out["ok"], out
    assert out["match"] is True
    assert out["rebuilt_shard_ids"] == [10, 11, 12, 13]
    assert out["remote_survivors"] == list(range(10))
    for key in (
        "remote_rebuild_gbps",
        "local_rebuild_gbps",
        "overlap_efficiency",
        "pipelined_vs_serial_fetch_then_decode",
    ):
        assert isinstance(out.get(key), float), f"missing metric {key}: {out}"
