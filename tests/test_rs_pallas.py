"""Fused Pallas kernel tests (interpret mode on the CPU mesh): byte equality
with the XLA path and the host golden path across shapes, padding edges, and
the Encoder(backend="pallas") integration."""

import numpy as np
import pytest

import jax.numpy as jnp

from seaweedfs_tpu.ops import gf8, rs_jax, rs_pallas
from seaweedfs_tpu.ops.rs_codec import Encoder


@pytest.fixture(scope="module")
def parity_bits():
    return rs_jax.lifted_matrix(gf8.parity_matrix(10, 4))


@pytest.mark.parametrize("mxu", rs_pallas.VARIANTS)
@pytest.mark.parametrize(
    "shape",
    [
        (10, 128),
        (10, 100),  # sub-tile, needs padding
        (10, 8192),  # exactly one default tile
        (2, 10, 8321),  # batched, ragged
        (1, 10, 3 * 8192),
    ],
)
def test_fused_matches_xla(parity_bits, shape, mxu):
    """EVERY staged kernel variant (int8/bf16/u8/mplane/dma) must be
    byte-exact vs the XLA path across tile-edge and odd-size shapes."""
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=shape, dtype=np.uint8)
    got = np.asarray(rs_pallas.gf_apply_fused(parity_bits, jnp.asarray(data), mxu=mxu))
    want = np.asarray(rs_jax.gf_apply(parity_bits, jnp.asarray(data)))
    assert np.array_equal(got, want)


def test_every_variant_in_lowering_proof_shapes():
    """Each staged variant must be registered in tpu_lowering.PROOF_SHAPES
    — a variant outside the proof would hit Mosaic for the first time
    inside a scarce tunnel-alive window."""
    from seaweedfs_tpu.ops import tpu_lowering

    proven = {s.get("mxu", "int8") for s in tpu_lowering.PROOF_SHAPES}
    assert proven >= set(rs_pallas.VARIANTS), (
        f"variants missing from PROOF_SHAPES: {set(rs_pallas.VARIANTS) - proven}"
    )


@pytest.mark.parametrize("mxu", rs_pallas.VARIANTS)
def test_variant_reconstruction_matrix(parity_bits, mxu):
    """Every variant must also serve arbitrary decode matrices (the
    rebuild path) — not just the 4x10 parity shape."""
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix

    lost = (1, 6, 12, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, size=(10, 500), dtype=np.uint8)
    enc = Encoder(10, 4, backend="numpy")
    shards = np.stack(enc.encode(list(data)))
    got = np.asarray(rs_pallas.apply_matrix(recon, shards[list(surv)], mxu=mxu))
    assert np.array_equal(got, shards[list(lost)])


def test_dma_chunk_divides_every_tile():
    for t in rs_pallas._TILE_STEPS:
        assert t % rs_pallas._dma_chunk(t) == 0
    assert rs_pallas._dma_chunk(8448) == 256  # non-2048-multiple width


def test_fused_reconstruction_matrix(parity_bits):
    """The kernel must work for arbitrary (R, C) matrices, not just 4x10."""
    from seaweedfs_tpu.ops.rs_codec import _reconstruction_matrix

    lost = (1, 6, 12, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, size=(10, 500), dtype=np.uint8)
    enc = Encoder(10, 4, backend="numpy")
    shards = np.stack(enc.encode(list(data)))
    got = np.asarray(rs_pallas.apply_matrix(recon, shards[list(surv)]))
    assert np.array_equal(got, shards[list(lost)])


def test_encoder_pallas_backend_roundtrip():
    rng = np.random.default_rng(9)
    enc = Encoder(10, 4, backend="pallas")
    gold = Encoder(10, 4, backend="numpy")
    data = [rng.integers(0, 256, size=1000, dtype=np.uint8) for _ in range(10)]
    a = enc.encode([d.copy() for d in data])
    b = gold.encode([d.copy() for d in data])
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
    lost = [0, 5, 11, 13]
    holes = [None if i in lost else a[i].copy() for i in range(14)]
    rec = enc.reconstruct(holes)
    for i in range(14):
        assert np.array_equal(rec[i], a[i])


def test_zero_length(parity_bits):
    data = np.zeros((10, 0), dtype=np.uint8)
    out = np.asarray(rs_pallas.gf_apply_fused(parity_bits, jnp.asarray(data)))
    assert out.shape == (4, 0)
