"""GF(2^8) math core tests — golden-checked against an independent bitwise
(Russian-peasant) field implementation, plus the algebraic properties the EC
path depends on (systematic generator, MDS-ness of every 10-of-14 selection)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf8


def peasant_mul(a: int, b: int) -> int:
    """Independent GF(2^8) multiply: shift-and-xor with poly 0x11D."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
        b >>= 1
    return r


def test_mul_table_matches_peasant():
    rng = np.random.default_rng(0)
    for _ in range(2000):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert gf8.gf_mul(a, b) == peasant_mul(a, b)
    # exhaustive on a stratified slice
    for a in range(0, 256, 7):
        for b in range(256):
            assert gf8.gf_mul(a, b) == peasant_mul(a, b)


def test_field_axioms():
    rng = np.random.default_rng(1)
    for _ in range(500):
        a, b, c = (int(x) for x in rng.integers(1, 256, size=3))
        assert gf8.gf_mul(a, b) == gf8.gf_mul(b, a)
        assert gf8.gf_mul(a, gf8.gf_mul(b, c)) == gf8.gf_mul(gf8.gf_mul(a, b), c)
        assert gf8.gf_mul(a, gf8.gf_inv(a)) == 1
        assert gf8.gf_div(gf8.gf_mul(a, b), b) == a
        # distributivity over XOR (field addition)
        assert gf8.gf_mul(a, b ^ c) == gf8.gf_mul(a, b) ^ gf8.gf_mul(a, c)


def test_gf_exp():
    for a in range(256):
        assert gf8.gf_exp(a, 0) == 1
        assert gf8.gf_exp(a, 1) == a
        assert gf8.gf_exp(a, 2) == gf8.gf_mul(a, a)
    assert gf8.gf_exp(0, 5) == 0


def test_mat_inv_random():
    rng = np.random.default_rng(2)
    n_done = 0
    while n_done < 20:
        m = rng.integers(0, 256, size=(10, 10)).astype(np.uint8)
        try:
            inv = gf8.gf_mat_inv(m)
        except ValueError:
            continue
        prod = gf8.gf_mat_mul(m, inv)
        assert np.array_equal(prod, np.eye(10, dtype=np.uint8))
        n_done += 1


def test_build_matrix_systematic():
    for kind_fn in (gf8.build_matrix, gf8.build_matrix_cauchy):
        g = kind_fn(10, 14)
        assert g.shape == (14, 10)
        assert np.array_equal(g[:10], np.eye(10, dtype=np.uint8))


def test_generator_is_mds_for_10_4():
    """Every 10-of-14 row selection must be invertible — this is exactly the
    'any 10 surviving shards reconstruct the volume' guarantee."""
    for g in (gf8.build_matrix(10, 14), gf8.build_matrix_cauchy(10, 14)):
        for rows in itertools.combinations(range(14), 10):
            gf8.gf_mat_inv(g[list(rows), :])  # raises if singular


def test_bit_lift_matches_table_mul():
    rng = np.random.default_rng(3)
    for _ in range(200):
        c, x = int(rng.integers(256)), int(rng.integers(256))
        a = gf8.gf_const_to_bits(c)
        xbits = np.array([(x >> j) & 1 for j in range(8)], dtype=np.uint8)
        ybits = (a @ xbits) & 1
        y = int(sum(int(ybits[i]) << i for i in range(8)))
        assert y == gf8.gf_mul(c, x)


def test_matrix_bit_lift_matches_gf_matvec():
    rng = np.random.default_rng(4)
    m = rng.integers(0, 256, size=(4, 10)).astype(np.uint8)
    data = rng.integers(0, 256, size=(10, 64)).astype(np.uint8)
    want = gf8.gf_mat_vec(m, data)
    b = gf8.gf_matrix_to_bits(m)
    bits = np.zeros((80, 64), dtype=np.uint8)
    for d in range(10):
        for j in range(8):
            bits[d * 8 + j] = (data[d] >> j) & 1
    out_bits = (b.astype(np.int32) @ bits.astype(np.int32)) & 1
    got = np.zeros((4, 64), dtype=np.uint8)
    for r in range(4):
        for i in range(8):
            got[r] |= (out_bits[r * 8 + i] << i).astype(np.uint8)
    assert np.array_equal(got, want)


def test_gf_mat_vec_matches_scalar():
    rng = np.random.default_rng(5)
    m = rng.integers(0, 256, size=(3, 5)).astype(np.uint8)
    x = rng.integers(0, 256, size=(5, 17)).astype(np.uint8)
    got = gf8.gf_mat_vec(m, x)
    for i in range(3):
        for n in range(17):
            acc = 0
            for l in range(5):
                acc ^= peasant_mul(int(m[i, l]), int(x[l, n]))
            assert acc == got[i, n]
