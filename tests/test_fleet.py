"""Fleet repair scheduler + failure-domain placement tests.

Covers the PR's acceptance surface in-process: the redundancy-ranked
priority queue (2-missing strictly before 1-missing under concurrent
enqueue/completion, re-rank on a second failure mid-storm), the
placement invariant property tests (no domain holds more than m shards
across random topologies), the width-packed multi-volume batch rebuild
pipeline's byte-identity, master lookup annotation, the heartbeat
unreachable-peers report plumbing, and the tier-1 smoke: scheduler ->
batched rebuild -> remount after a holder death, with the dispatch
order asserted from the RepairStatus event log.
"""

import os
import random
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import placement, stripe
from seaweedfs_tpu.ec.fleet import RepairQueue, RepairScheduler
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.pb import MASTER_SERVICE, VOLUME_SERVICE, Heartbeat

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL = 16384, 4096


def _wait_for(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


# -- RepairQueue --------------------------------------------------------------


def test_queue_orders_by_redundancy_then_size_then_exposure():
    q = RepairQueue()
    q.update(1, RepairQueue.priority(1, 500, 1, 1))
    q.update(2, RepairQueue.priority(2, 10, 0, 2))   # least redundant: first
    q.update(3, RepairQueue.priority(1, 900, 0, 3))  # bigger 1-missing
    q.update(4, RepairQueue.priority(1, 500, 3, 4))  # same size, more exposed
    order = []
    while True:
        got = q.pop()
        if got is None:
            break
        order.append(got[0])
    assert order == [2, 3, 4, 1]


def test_queue_concurrent_enqueue_pops_2_missing_strictly_first():
    q = RepairQueue()
    rng = random.Random(11)
    items = [(vid, rng.choice([1, 2]), rng.randrange(1, 1000)) for vid in range(400)]

    def push(chunk):
        for vid, missing, size in chunk:
            q.update(vid, RepairQueue.priority(missing, size, 0, vid))

    threads = [
        threading.Thread(target=push, args=(items[i::8],)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    popped = []
    while True:
        got = q.pop()
        if got is None:
            break
        popped.append(-got[1][0])  # missing count
    assert len(popped) == 400
    # every 2-missing strictly before any 1-missing
    assert popped == sorted(popped, reverse=True)


def test_queue_rerank_on_second_failure_mid_storm():
    q = RepairQueue()
    q.update(7, RepairQueue.priority(1, 100, 0, 7))
    q.update(8, RepairQueue.priority(2, 100, 0, 8))
    # volume 7 loses a SECOND shard while queued: re-rank ahead of pops
    q.update(7, RepairQueue.priority(2, 100, 0, 7))
    first, second = q.pop(), q.pop()
    assert {first[0], second[0]} == {7, 8}
    assert -first[1][0] == 2 and -second[1][0] == 2
    assert q.pop() is None  # the stale 1-missing entry was skipped, not served


def test_queue_discard_and_completion():
    q = RepairQueue()
    q.update(1, RepairQueue.priority(1, 1, 0, 1))
    q.discard(1)
    assert q.pop() is None and len(q) == 0


# -- placement properties -----------------------------------------------------


def _random_nodes(rng, n_nodes, n_racks, n_dcs=1):
    return [
        {
            "url": f"n{i}:80",
            "data_center": f"dc{rng.randrange(n_dcs)}",
            "rack": f"r{rng.randrange(n_racks)}",
        }
        for i in range(n_nodes)
    ]


def test_plan_spread_invariant_across_random_topologies():
    rng = random.Random(5)
    for trial in range(60):
        n_racks = rng.randrange(1, 8)
        nodes = _random_nodes(rng, rng.randrange(1, 12), n_racks)
        total, parity = rng.choice([(14, 4), (15, 3), (24, 4)])
        alloc = placement.plan_spread(nodes, total, parity)
        # every shard assigned exactly once
        all_sids = sorted(s for sids in alloc.values() for s in sids)
        assert all_sids == list(range(total))
        racks = {placement.domain_of(n) for n in nodes}
        per_dom: dict = {}
        by_url = {n["url"]: n for n in nodes}
        for url, sids in alloc.items():
            dom = placement.domain_of(by_url[url])
            per_dom[dom] = per_dom.get(dom, 0) + len(sids)
        feasible_cap = max(parity, -(-total // len(racks)))
        assert max(per_dom.values()) <= feasible_cap, (
            f"trial {trial}: domain over cap: {per_dom} vs {feasible_cap}"
        )
        if len(racks) * parity >= total:
            # enough racks: the HARD invariant must hold, no relaxation
            assert max(per_dom.values()) <= parity


def test_stripe_violations_detects_and_clears():
    domains = {"a:1": ("dc", "r1"), "b:1": ("dc", "r1"), "c:1": ("dc", "r2")}
    holders = {s: ["a:1"] for s in range(5)}  # 5 shards on rack r1 > m=4
    v = placement.stripe_violations(holders, domains, 4)
    assert len(v) == 1 and v[0][0] == ("dc", "r1") and len(v[0][1]) == 5
    # replicating one of them onto another rack removes the exposure
    holders[0] = ["a:1", "c:1"]
    v = placement.stripe_violations(holders, domains, 4)
    assert not v


def test_pick_rebuild_target_respects_domain_cap():
    nodes = [
        {"url": f"n{i}:80", "data_center": "dc", "rack": f"r{i % 4}"}
        for i in range(8)
    ]
    domains = {n["url"]: placement.domain_of(n) for n in nodes}
    # rack r0 already holds 3 shards; a 2-missing rebuild there would
    # push it to 5 > 4, so the target must come from another rack
    holders = {0: ["n0:80"], 1: ["n4:80"], 2: ["n0:80"], 3: ["n0:80"]}
    target = placement.pick_rebuild_target(
        nodes, holders, domains, missing=[12, 13], parity=4
    )
    assert domains[target["url"]] != ("dc", "r0")


def test_plan_parity_targets_excludes_owner_and_caps_domains():
    rng = random.Random(9)
    for _ in range(30):
        nodes = _random_nodes(rng, rng.randrange(2, 10), rng.randrange(1, 6))
        owner = nodes[0]["url"]
        targets = placement.plan_parity_targets(nodes, owner, 10, 14)
        assert all(n["url"] != owner for n in targets.values())
        assert set(targets) <= set(range(10, 14))
        per_dom: dict = {}
        for n in targets.values():
            d = placement.domain_of(n)
            per_dom[d] = per_dom.get(d, 0) + 1
        if per_dom:
            assert max(per_dom.values()) <= 4


def test_fix_placement_moves_restores_invariant():
    """ec.balance -fixPlacement planning: a rack holding 6 shards of one
    stripe sheds exactly the excess onto racks with headroom, and the
    plan leaves zero violations."""
    from seaweedfs_tpu.shell.command_ec import fix_placement_moves

    by_url = {
        "a:1": {"url": "a:1", "data_center": "dc", "rack": "r0"},
        "b:1": {"url": "b:1", "data_center": "dc", "rack": "r0"},
        "c:1": {"url": "c:1", "data_center": "dc", "rack": "r1"},
        "d:1": {"url": "d:1", "data_center": "dc", "rack": "r2"},
        "e:1": {"url": "e:1", "data_center": "dc", "rack": "r3"},
    }
    placement_map = {
        "a:1": {7: {0, 1, 2}},
        "b:1": {7: {3, 4, 5}},   # rack r0 holds 6 of stripe 7 — 2 over cap
        "c:1": {7: {6, 7, 8, 9}},
        "d:1": {7: {10, 11, 12}},
        "e:1": {7: {13}},
    }
    moves = fix_placement_moves(placement_map, by_url, lambda vid: 4)
    assert len(moves) == 2
    for vid, sid, src, dst in moves:
        assert by_url[src]["rack"] == "r0" and by_url[dst]["rack"] != "r0"
    # the mutated map (the planner updates it in place) is violation-free
    domains = {u: placement.domain_of(n) for u, n in by_url.items()}
    holders: dict = {}
    for u, per in placement_map.items():
        for s in per.get(7, ()):
            holders.setdefault(s, []).append(u)
    assert not placement.stripe_violations(holders, domains, 4)


# -- width-packed multi-volume batch rebuild ----------------------------------


def _build_volume(dirpath, vid, size, seed):
    base = os.path.join(dirpath, str(vid))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


def test_rebuild_batch_width_packs_and_matches_serial(tmp_path):
    """Three volumes across two missing signatures: heterogeneous fusion
    (the default) runs the whole cohort as ONE block-diagonal dispatch,
    batches pack columns across volume boundaries (sizes chosen to not
    align), and every rebuilt byte matches the encode-time golden."""
    specs = [
        (21, 333_000, [12, 13]),
        (22, 150_000, [12, 13]),  # same signature as 21 -> same group
        (23, 200_000, [3]),       # different signature -> its own group
    ]
    jobs, goldens = [], {}
    for vid, size, missing in specs:
        base, golden = _build_volume(str(tmp_path), vid, size, seed=vid)
        goldens[base] = (golden, missing)
        for s in missing:
            os.unlink(stripe.shard_file_name(base, s))
        present = [s for s in range(TOTAL_SHARDS_COUNT) if s not in missing]
        jobs.append(
            {
                "base": base,
                "sources": {
                    s: stripe.LocalSlabSource(stripe.shard_file_name(base, s))
                    for s in present
                },
                "shard_size": len(golden[0]),
                "missing": missing,
                "encoder": ENC,
            }
        )
    try:
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=16384, max_batch_bytes=163840
        )
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()
    assert not res["errors"], res["errors"]
    assert res["dispatch_groups"] == 1  # heterogeneous fusion: one dispatch
    assert res["signature_groups"] == 2
    assert res["volumes_fused"] == 3
    assert res["block_order"] == [j["base"] for j in jobs]
    for base, (golden, missing) in goldens.items():
        assert sorted(res["rebuilt"][base]) == sorted(missing)
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                assert f.read() == golden[s], f"{base} shard {s} differs"


def test_rebuild_batch_group_failure_unlinks_partials(tmp_path):
    base, golden = _build_volume(str(tmp_path), 31, 120_000, seed=31)
    os.unlink(stripe.shard_file_name(base, 13))

    class Dying(stripe.SlabSource):
        def __init__(self, path):
            self._inner = stripe.LocalSlabSource(path)
            self._calls = 0

        def read_into(self, offset, out):
            self._calls += 1
            if self._calls > 1:
                raise IOError("holder died")
            self._inner.read_into(offset, out)

        def close(self):
            self._inner.close()

    sources = {
        s: (
            Dying(stripe.shard_file_name(base, s))
            if s == 0
            else stripe.LocalSlabSource(stripe.shard_file_name(base, s))
        )
        for s in range(13)
    }
    try:
        res = stripe.rebuild_ec_files_batch(
            [
                {
                    "base": base,
                    "sources": sources,
                    "shard_size": len(golden[0]),
                    "missing": [13],
                    "encoder": ENC,
                }
            ],
            buffer_size=4096,
            max_batch_bytes=8192,
        )
    finally:
        for src in sources.values():
            src.close()
    assert base in res["errors"]
    assert not os.path.exists(stripe.shard_file_name(base, 13))


# -- scheduler unit (no live cluster) -----------------------------------------


def _hb(url_port, grpc_port, rack, ec=None):
    return Heartbeat(
        ip="127.0.0.1",
        port=url_port,
        grpc_port=grpc_port,
        rack=rack,
        data_center="dc",
        max_volume_count=30,
        ec_shards=[e for e in (ec or [])],
    )


def _ec_info(vid, sids, shard_size=1000):
    from seaweedfs_tpu.ec.shard_bits import EcVolumeInfo, ShardBits

    return EcVolumeInfo(
        volume_id=vid,
        shard_bits=ShardBits.from_ids(sids),
        shard_size=shard_size,
        data_shards=10,
        total_shards=14,
    ).to_dict()


@pytest.fixture
def quiet_master():
    m = MasterServer(port=0, reap_interval=3600, http_port=None)
    # scheduler attached manually (env default is off): loops NOT started,
    # scan()/status() driven synchronously by the tests
    m.repair = RepairScheduler(
        m, max_inflight=1, batch=4, scan_interval=60.0, settle=0.0,
        dead_after=0.2,
    )
    m.topology.on_ec_shrink = m.repair.kick
    yield m
    m._server.stop()


def test_scan_enumerates_and_ranks_after_holder_death(quiet_master):
    m = quiet_master
    # three holders; n1 holds 1 shard of vid 5 and 2 shards of vid 6
    m.topology.process_heartbeat(
        _hb(8001, 9001, "r1", ec=[_ec_info(5, [13]), _ec_info(6, [12, 13], 9000)])
    )
    m.topology.process_heartbeat(
        _hb(8002, 9002, "r2", ec=[_ec_info(5, list(range(7))), _ec_info(6, list(range(7)))])
    )
    m.topology.process_heartbeat(
        _hb(8003, 9003, "r3", ec=[_ec_info(5, list(range(7, 13))), _ec_info(6, list(range(7, 12)))])
    )
    assert m.repair.scan() == 0  # everything fully replicated: no entries
    m.topology.unregister_node("127.0.0.1:8001")
    changed = m.repair.scan()
    assert changed == 2
    first = m.repair.queue.pop()
    second = m.repair.queue.pop()
    assert first[0] == 6 and -first[1][0] == 2  # 2-missing strictly first
    assert second[0] == 5 and -second[1][0] == 1
    hist = m.repair.status()["redundancy_histogram"]
    assert hist.get("1") == 1 and hist.get("2") == 1


def test_scan_marks_unrecoverable_stripes_lost(quiet_master):
    m = quiet_master
    m.topology.process_heartbeat(_hb(8001, 9001, "r1", ec=[_ec_info(9, list(range(9)))]))
    m.repair.scan()  # 5 missing > m=4: lost, never queued
    assert len(m.repair.queue) == 0
    events = m.repair.status()["events"]
    assert any(e["state"] == "lost" and e["volume_id"] == 9 for e in events)


def test_reports_plus_heartbeat_silence_confirm_death(quiet_master):
    m = quiet_master
    m.topology.process_heartbeat(_hb(8001, 9001, "r1", ec=[_ec_info(5, [13])]))
    m.topology.process_heartbeat(
        _hb(8002, 9002, "r2", ec=[_ec_info(5, list(range(13)))])
    )
    # fresh heartbeat + report: NOT dead (one slow reporter isn't a death)
    m.repair.note_reports("127.0.0.1:8002", ["127.0.0.1:9001"])
    m.repair.scan()
    assert len(m.repair.queue) == 0
    # silence past dead_after + standing report: dead for repair purposes
    with m.topology._lock:
        m.topology.nodes["127.0.0.1:8001"].last_seen -= 1.0
    m.repair.scan()
    assert len(m.repair.queue) == 1
    assert "127.0.0.1:9001" in m.repair.status()["suspects"]


def test_master_lookup_annotates_rack_and_dc(quiet_master):
    m = quiet_master
    m.topology.process_heartbeat(_hb(8001, 9001, "rackA", ec=[_ec_info(5, [0])]))
    resp = m._rpc_lookup_ec({"volume_id": 5}, None)
    loc = resp["shard_id_locations"][0]["locations"][0]
    assert loc["rack"] == "rackA" and loc["data_center"] == "dc"


def test_repair_status_rpc_disabled_shape():
    m = MasterServer(port=0, reap_interval=3600, http_port=None)
    try:
        st = m._rpc_repair_status({}, None)
        assert st["enabled"] is False and st["queue_depth"] == 0
    finally:
        m._server.stop()


# -- tier-1 smoke: scheduler -> batched rebuild -> remount --------------------


@pytest.fixture
def repair_cluster(tmp_path, monkeypatch):
    """master WITH the live scheduler + 3 rack-labeled volume servers."""
    monkeypatch.setenv("WEEDTPU_REPAIR", "on")
    monkeypatch.setenv("WEEDTPU_REPAIR_MAX_INFLIGHT", "1")
    monkeypatch.setenv("WEEDTPU_REPAIR_SETTLE_S", "0.3")
    monkeypatch.setenv("WEEDTPU_REPAIR_SCAN_S", "0.5")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)], master.address, heartbeat_interval=0.3, rack=f"r{i}"
        )
        vs.start()
        servers.append(vs)
    yield master, servers, tmp_path
    for vs in servers:
        try:
            vs.stop()
        except Exception:
            pass
    master.stop()


def test_scheduler_end_to_end_two_missing_first(repair_cluster, tmp_path):
    """Kill the holder of {1 shard of volume A, 2 shards of volume B}:
    the scheduler must dispatch B's repair before A's, the batched
    rebuild must regenerate + remount every missing shard on survivors,
    and the master registry must converge back to full coverage."""
    master, servers, root = repair_cluster
    victim, s1, s2 = servers
    layout = {
        21: {0: [13], 1: list(range(7)), 2: [s for s in range(7, 13)]},
        22: {0: [12, 13], 1: list(range(7)), 2: [s for s in range(7, 12)]},
    }
    for vid in layout:
        base, _ = _build_volume(str(tmp_path), vid, 140_000, seed=vid)
        for i, vs in enumerate(servers):
            dst = os.path.join(vs.store.locations[0].directory, str(vid))
            for s in layout[vid][i]:
                os.replace(
                    stripe.shard_file_name(base, s), stripe.shard_file_name(dst, s)
                )
            for ext in (".ecx", ".eci"):
                import shutil

                shutil.copy(base + ext, dst + ext)
            vs.store.mount_ec_volume(vid, dst)
            vs.heartbeat_once()
    _wait_for(
        lambda: all(
            len(master.topology.lookup_ec_shards(v)) == 14 for v in (21, 22)
        ),
        msg="registry complete",
    )
    victim.stop()  # LeaveCluster -> unregister -> on_ec_shrink kick
    _wait_for(
        lambda: all(
            len(master.topology.lookup_ec_shards(v)) == 14 for v in (21, 22)
        ),
        timeout=60.0,
        msg="scheduler repaired both volumes",
    )
    st = master.repair.status()
    dispatched = [e for e in st["events"] if e["state"] == "dispatched"]
    assert {e["volume_id"] for e in dispatched} >= {21, 22}
    b_first = min(e["seq"] for e in dispatched if e["volume_id"] == 22)
    a_first = min(e["seq"] for e in dispatched if e["volume_id"] == 21)
    assert b_first < a_first, (
        f"2-missing volume 22 must begin repair before 1-missing 21: {dispatched}"
    )
    by_vid = {e["volume_id"]: e["missing"] for e in dispatched}
    assert by_vid[22] == 2 and by_vid[21] == 1
    assert any(e["state"] == "done" for e in st["events"])
    # fusion observability: each dispatched batch left an occupancy record
    # with 2-before-1 preserved as in-batch block order (block_missing
    # non-increasing) and the whole batch fused to ONE decode dispatch
    assert st["batches"], "no per-batch occupancy records"
    for b in st["batches"]:
        assert b["dispatch_groups"] == 1
        assert b["volumes"] == len(b["block_order"]) == len(b["block_missing"])
        assert b["block_missing"] == sorted(b["block_missing"], reverse=True)
        assert b["wall_s"] > 0 and b["age_s"] >= 0
    assert st["fused_volumes_total"] == sum(b["volumes"] for b in st["batches"])
    assert {v for b in st["batches"] for v in b["block_order"]} >= {21, 22}
    # rebuilt bytes are REAL: every shard of both volumes reads somewhere
    for vid in (21, 22):
        holders = master.topology.lookup_ec_shards(vid)
        assert sorted(holders) == list(range(14))


def test_batch_rpc_rebuilds_multiple_volumes_one_call(repair_cluster, tmp_path):
    """Direct VolumeEcShardsRebuildBatch: two same-signature volumes in
    one RPC fuse into one dispatch group on the target."""
    master, servers, _ = repair_cluster
    _, s1, s2 = servers
    for vid in (31, 32):
        base, _ = _build_volume(str(tmp_path), vid, 90_000, seed=vid)
        dst = os.path.join(s1.store.locations[0].directory, str(vid))
        for s in range(13):  # shard 13 missing everywhere
            os.replace(
                stripe.shard_file_name(base, s), stripe.shard_file_name(dst, s)
            )
        for ext in (".ecx", ".eci"):
            import shutil

            shutil.copy(base + ext, dst + ext)
        s1.store.mount_ec_volume(vid, dst)
    s1.heartbeat_once()
    with rpc.RpcClient(s2.grpc_address) as c:
        resp = c.call(
            VOLUME_SERVICE,
            "VolumeEcShardsRebuildBatch",
            {"volumes": [{"volume_id": 31}, {"volume_id": 32}]},
            timeout=120,
        )
    assert resp["dispatch_groups"] == 1
    assert sorted(r["volume_id"] for r in resp["results"]) == [31, 32]
    for r in resp["results"]:
        assert r["error"] == "" and r["rebuilt_shard_ids"] == [13]
    assert resp["wire_bytes"] > 0
    # remounted + heartbeated: the registry sees the new holder
    _wait_for(
        lambda: all(
            13 in master.topology.lookup_ec_shards(v) for v in (31, 32)
        ),
        msg="rebuilt shards registered",
    )


def test_inline_spread_owner_never_hosts_all_14(tmp_path, monkeypatch):
    """PR 8 residual e2e: with WEEDTPU_INLINE_EC_SPREAD=on, parity rows
    stream to placement-planned holders DURING inline encode; the
    auto-seal commits them remotely (CRC-verified, mounted there) and
    the owner is born hosting only its data shards. A degraded read that
    needs a spread parity shard reconstructs byte-exact."""
    monkeypatch.setenv("WEEDTPU_INLINE_EC", "on")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SPREAD", "on")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_LARGE_BLOCK", "4096")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SMALL_BLOCK", "512")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SEAL_BYTES", "150000")
    from seaweedfs_tpu import stats as _stats
    from seaweedfs_tpu.storage.file_id import FileId

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    try:
        for i in range(3):
            d = tmp_path / f"srv{i}"
            d.mkdir()
            vs = VolumeServer(
                [str(d)], master.address, heartbeat_interval=0.3, rack=f"r{i}"
            )
            vs.start()
            servers.append(vs)
        owner = servers[0]
        _wait_for(lambda: len(master.topology.nodes) == 3, msg="cluster formed")
        vid = 41
        spread_before = _stats.InlineEcSpreadBytes.value
        with rpc.RpcClient(owner.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeCreate", {"volume_id": vid})
            rng = np.random.default_rng(41)
            blobs = {}
            import base64 as _b64

            for k in range(1, 9):
                payload = rng.integers(0, 256, 20_000, dtype=np.uint8).tobytes()
                fid = str(FileId(vid, k, 0x1234))
                c.call(
                    VOLUME_SERVICE, "WriteNeedle",
                    {"fid": fid, "data": _b64.b64encode(payload).decode()},
                    timeout=30,
                )
                blobs[fid] = payload
        _wait_for(
            lambda: owner.store.get_ec_volume(vid) is not None,
            timeout=60.0,
            msg="auto-seal mounted the EC volume",
        )
        ev = owner.store.get_ec_volume(vid)
        # the owner hosts ONLY its data shards: every parity shard was
        # committed at its planned holder
        assert set(ev.shard_ids) == set(range(10)), ev.shard_ids
        _wait_for(
            lambda: sorted(master.topology.lookup_ec_shards(vid)) == list(range(14)),
            msg="spread parity registered",
        )
        remote_parity = {
            s
            for i, vs in enumerate(servers[1:], start=1)
            for s in (vs.store.get_ec_volume(vid).shard_ids
                      if vs.store.get_ec_volume(vid) else [])
        }
        assert remote_parity == {10, 11, 12, 13}
        # parity bytes moved DURING encode, not only at seal
        assert _stats.InlineEcSpreadBytes.value > spread_before
        # degraded read through a spread parity shard: drop a local data
        # shard, reconstruction must pull parity from the remote holders
        with rpc.RpcClient(owner.grpc_address) as c:
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsDelete",
                {"volume_id": vid, "shard_ids": [0]},
            )
            c.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
            for fid, want in blobs.items():
                got = c.call(
                    VOLUME_SERVICE, "ReadNeedle",
                    {"volume_id": vid,
                     "needle_id": FileId.parse(fid).key},
                    timeout=60,
                )
                import base64 as _b64

                assert _b64.b64decode(got["data"]) == want
    finally:
        for vs in servers:
            try:
                vs.stop()
            except Exception:
                pass
        master.stop()


def test_unreachable_peer_report_rides_heartbeat(repair_cluster):
    master, servers, _ = repair_cluster
    vs = servers[0]
    for _ in range(int(os.environ.get("WEEDTPU_REPAIR_REPORT_FAILURES", "3"))):
        vs._note_peer_failure("127.0.0.1:59999")
    assert "127.0.0.1:59999" in vs._unreachable_peers()
    vs.heartbeat_once()
    # the master folded the report into the scheduler's suspect table
    assert "127.0.0.1:59999" in master.repair.status()["suspects"]
    vs._note_peer_success("127.0.0.1:59999")
    assert "127.0.0.1:59999" not in vs._unreachable_peers()
