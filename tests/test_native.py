"""Native C++ runtime lib (libweedtpu.so) vs pure-Python/numpy goldens."""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.utils import native


def test_crc32c_native_matches_python():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    lib = native.load()
    if lib is None:
        pytest.skip("native lib unavailable")
    got = lib.weedtpu_crc32c(0, data, len(data))
    # pure-python reference
    tbl = native._py_table()
    c = 0xFFFFFFFF
    for b in data[:1000]:
        c = (c >> 8) ^ tbl[(c ^ b) & 0xFF]
    want_1k = c ^ 0xFFFFFFFF
    assert lib.weedtpu_crc32c(0, data[:1000], 1000) == want_1k
    assert native.crc32c(data) == got


def test_gf_matrix_apply_native_matches_gf8():
    if native.load() is None:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(1)
    rows, cols, length = 4, 10, 4096
    matrix = rng.integers(0, 256, size=(rows, cols), dtype=np.uint8)
    inputs = [rng.integers(0, 256, size=length, dtype=np.uint8) for _ in range(cols)]
    outs = native.gf_matrix_apply_native(matrix, [i.tobytes() for i in inputs], length)
    assert outs is not None
    want = gf8.gf_mat_vec(matrix, np.stack(inputs))
    for r in range(rows):
        np.testing.assert_array_equal(np.asarray(outs[r]), want[r])


def test_gf_matrix_apply_mt_matches_single_thread():
    """The multithreaded split (WithAutoGoroutines analog) must be
    byte-identical to the single-core path at sizes that actually split,
    including the 64B-alignment remainder."""
    import numpy as np

    from seaweedfs_tpu.ops import gf8
    from seaweedfs_tpu.utils import native

    if native.load() is None:
        import pytest

        pytest.skip("native library unavailable")
    pm = gf8.parity_matrix(10, 4)
    rng = np.random.default_rng(7)
    for n in (1 << 20, (1 << 20) + 37):  # odd tail exercises the remainder
        ins = [rng.integers(0, 256, n, dtype=np.uint8).tobytes() for _ in range(10)]
        st = native.gf_matrix_apply_native(pm, ins, n, threads=1)
        for threads in (0, 2, 3, 8):
            mt = native.gf_matrix_apply_native(pm, ins, n, threads=threads)
            assert all((a == b).all() for a, b in zip(st, mt)), threads


def test_gf_matrix_apply_batch_matches_per_stack():
    """The batched entry point (per-element pointers, one pool) must be
    byte-identical to per-stack applies."""
    import numpy as np

    from seaweedfs_tpu.ops import gf8
    from seaweedfs_tpu.utils import native

    if native.load() is None or not native.has_mt():
        import pytest

        pytest.skip("native library unavailable")
    pm = gf8.parity_matrix(10, 4)
    rng = np.random.default_rng(31)
    shards = rng.integers(0, 256, (5, 10, 4097), dtype=np.uint8)
    got = native.gf_matrix_apply_batch_native(pm, shards)
    assert got is not None and got.shape == (5, 4, 4097)
    for b in range(5):
        want = native.gf_matrix_apply_native(
            pm, [bytes(shards[b, c]) for c in range(10)], 4097
        )
        assert all(np.array_equal(got[b, r], want[r]) for r in range(4))


# -- sanitizer coverage for the width-parallel XOR executor -------------------


def _sanitizer_cxx(flag: str):
    """First compiler on the image that can BUILD AND RUN a -fsanitize
    binary (having the flag is not enough — the runtime library or the
    kernel's ASLR mode can still refuse), else None -> skip."""
    import os
    import shutil
    import subprocess
    import tempfile

    probe = (
        "#include <thread>\n"
        "int x=0;\n"
        "int main(){ std::thread t([]{ x=1; }); t.join(); return x-1; }\n"
    )
    for cxx in ("clang++", "g++"):
        if shutil.which(cxx) is None:
            continue
        with tempfile.TemporaryDirectory() as td:
            src = os.path.join(td, "p.cc")
            binp = os.path.join(td, "p")
            with open(src, "w") as f:
                f.write(probe)
            try:
                r = subprocess.run(
                    [cxx, f"-fsanitize={flag}", "-O1", "-g", "-pthread",
                     "-o", binp, src],
                    capture_output=True, timeout=120,
                )
                if r.returncode != 0:
                    continue
                if subprocess.run([binp], capture_output=True, timeout=60).returncode == 0:
                    return cxx
            except (OSError, subprocess.TimeoutExpired):
                continue
    return None


@pytest.mark.slow
@pytest.mark.parametrize("flag,target,binary", [
    ("thread", "tsan", "xs_tsan"),
    ("address", "asan", "xs_asan"),
])
def test_xorsched_apply_blocks_under_sanitizer(tmp_path, flag, target, binary):
    """weedtpu_xor_schedule_apply_blocks under ThreadSanitizer (and ASan)
    across thread counts: the pool drains a flat tile list off one atomic
    counter with no other synchronization — any missed happens-before
    edge shows up here, not as a corrupted rebuild in production. The
    driver also cross-checks every parallel result against the byte-level
    XOR oracle."""
    import os
    import subprocess

    cxx = _sanitizer_cxx(flag)
    if cxx is None:
        pytest.skip(f"no {flag}-sanitizer-capable C++ compiler on this image")
    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
    )
    build = subprocess.run(
        ["make", "-C", native_dir, target, f"BUILD={tmp_path}", f"SAN_CXX={cxx}"],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, f"sanitizer build failed:\n{build.stderr}"
    run = subprocess.run(
        [os.path.join(str(tmp_path), binary), "1", "2", "4", "8"],
        capture_output=True, text=True, timeout=300,
    )
    assert run.returncode == 0, (
        f"{flag} sanitizer run failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}"
    )
    assert "all clean" in run.stdout
