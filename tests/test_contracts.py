"""Wire-contract schema tests: pb/contracts.proto is the normative pin for
every RPC (SURVEY §2.6 / VERDICT r3 missing #7) — it must stay a valid
proto3 file AND cover every method the servers actually register."""

import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = os.path.join(REPO, "seaweedfs_tpu", "pb", "contracts.proto")


def test_contracts_proto_is_valid_proto3():
    if shutil.which("protoc") is None:
        pytest.skip("protoc not in image")
    proc = subprocess.run(
        [
            "protoc",
            f"--proto_path={os.path.dirname(PROTO)}",
            "--descriptor_set_out=/dev/null",
            PROTO,
        ],
        capture_output=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stderr.decode()


def test_every_registered_rpc_method_is_in_the_schema():
    """Grep every svc.add("Method", ...) registration in the package and
    demand an `rpc Method(` line in contracts.proto — schema drift fails
    the build instead of rotting silently."""
    with open(PROTO, encoding="utf-8") as f:
        schema = f.read()
    declared = set(re.findall(r"\brpc\s+(\w+)\(", schema))

    registered = set()
    pkg = os.path.join(REPO, "seaweedfs_tpu")
    for root, _, files in os.walk(pkg):
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(root, name), encoding="utf-8") as f:
                src = f.read()
            # matches both `svc.add("M", ...)` and the `add = svc.add` alias
            # style (`add("M", ...)`) used by the filer and volume servers
            registered.update(re.findall(r"\badd\(\s*\"(\w+)\"", src))

    assert len(registered) > 40, (
        f"extraction looks broken: only {len(registered)} methods found"
    )
    missing = registered - declared
    assert not missing, f"RPC methods registered but absent from contracts.proto: {sorted(missing)}"
