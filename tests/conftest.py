"""Test harness config: run all tests on CPU with 8 virtual devices so
multi-chip sharding paths are exercised without TPU hardware (SURVEY.md §4:
the `xla_force_host_platform_device_count` fake-backend strategy).

Must run before jax initializes, hence env mutation at import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
