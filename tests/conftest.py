"""Test harness config: run all tests on CPU with 8 virtual devices so
multi-chip sharding paths are exercised without TPU hardware (SURVEY.md §4:
the `xla_force_host_platform_device_count` fake-backend strategy).

Must run before jax initializes, hence env mutation at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the global axon/TPU default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (interpreter start) calls
# jax.config.update("jax_platforms", "axon,cpu"), which outranks the env var —
# push it back to cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
