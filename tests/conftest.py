"""Test harness config: run all tests on CPU with 8 virtual devices so
multi-chip sharding paths are exercised without TPU hardware (SURVEY.md §4:
the `xla_force_host_platform_device_count` fake-backend strategy).

Must run before jax initializes, hence env mutation at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the global axon/TPU default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Opt-in instrumented-lock mode (WEEDTPU_LOCK_OBSERVE=1): wrap
# threading.Lock/RLock BEFORE anything else imports, so every lock the
# package creates carries its creation site and the session records the
# actual acquisition-order graph. pytest_sessionfinish asserts the
# package's observed graph is acyclic — the dynamic half of weedlint's
# lock-discipline family.
from seaweedfs_tpu.utils import config as _weedtpu_config  # noqa: E402

_LOCK_RECORDER = None
if _weedtpu_config.env("WEEDTPU_LOCK_OBSERVE"):
    from seaweedfs_tpu.analysis import lockrec as _lockrec

    _LOCK_RECORDER = _lockrec.install()

# Opt-in filesystem-op recorder (WEEDTPU_FS_OBSERVE=<dir>): interpose the
# weedsafe recording shims over open/os.fsync/rename/unlink for paths
# under the named directory — the dynamic half of the durability family.
# The replay tests install their own scoped recorders; this session-level
# hook exists to capture traces from ad-hoc runs for offline inspection.
_FS_RECORDER = None
_fs_observe_root = _weedtpu_config.env("WEEDTPU_FS_OBSERVE")
if _fs_observe_root:
    from seaweedfs_tpu.analysis import fsrec as _fsrec

    _FS_RECORDER = _fsrec.install(_fs_observe_root)

# The axon sitecustomize (interpreter start) calls
# jax.config.update("jax_platforms", "axon,cpu"), which outranks the env var —
# push it back to cpu before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')"
    )


@pytest.fixture(autouse=True)
def _reset_holder_suspicion():
    """Holder suspicion is process-wide and keyed by peer address; test
    servers reuse ephemeral ports, so suspicion leaking forward would make
    a later test's healthy peer read as wedged."""
    yield
    from seaweedfs_tpu.ec import suspicion

    suspicion.GLOBAL.reset()


@pytest.fixture(autouse=True)
def _reset_read_cache(monkeypatch):
    """The decoded-interval cache is process-wide and DEFAULT-ON in
    production; tests run it default-OFF so the hundreds of existing
    degraded-read tests keep measuring real decodes (repeat reads of one
    needle would otherwise collapse to cache hits and invalidate their
    latency/decode-count assertions). Cache-specific tests (and the
    weedload smoke) opt back in with monkeypatch.setenv; the cache itself
    is emptied after every test either way."""
    monkeypatch.setenv("WEEDTPU_READ_CACHE_MB", "0")
    yield
    from seaweedfs_tpu.ec import read_planner

    read_planner.CACHE.clear()


def pytest_sessionfinish(session, exitstatus):
    """Instrumented-lock gate: the tier-1 run's OBSERVED lock-order graph
    (package locks only — jax/stdlib internals order their own locks)
    must be acyclic, or the session fails even with every test green."""
    if _FS_RECORDER is not None:
        fs_out = _weedtpu_config.env("WEEDTPU_FS_OBSERVE_OUT")
        if fs_out:
            _FS_RECORDER.trace().dump(fs_out)
    if _LOCK_RECORDER is None:
        return
    out_path = _weedtpu_config.env("WEEDTPU_LOCK_OBSERVE_OUT")
    if out_path:
        _LOCK_RECORDER.dump(out_path)
    report = _LOCK_RECORDER.report(only_containing="seaweedfs_tpu")
    print(f"\n{report}")
    if _LOCK_RECORDER.cycles(only_containing="seaweedfs_tpu"):
        session.exitstatus = 1
