"""Decoded-interval read cache (PR 16): byte identity against the uncached
path, coalesce-leader publishing, the stats-purity rule (hits never feed
the reconstruct histogram or the hedge/EWMA machinery), and the
no-stale-bytes guarantee for every invalidation event — quarantine, shard
remount, inline-ingest delta update, and the unmount/convert cut-over seam
(Store.mount/unmount route through EcVolume.close)."""

import os
import shutil
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.ec import ingest, read_planner, stripe
from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.read_planner import CACHE
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types

LARGE = 1024
SMALL = 64
ENC = Encoder(10, 4, backend="numpy")


@pytest.fixture()
def volume(tmp_path):
    """Synthetic volume: blob records at 8-aligned offsets + matching index
    (same construction as test_ec_volume)."""
    rng = np.random.default_rng(23)
    base = str(tmp_path / "v9")
    records = {}
    offset = types.NEEDLE_PADDING_SIZE
    blobs = [b"\x03" + bytes(7)]
    for nid in [3, 10, 42, 999]:
        body = int(rng.integers(1, 300))
        total = types.actual_size(body, version=3)
        rec = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
        records[nid] = (offset, body, rec)
        blobs.append(rec)
        offset += total
    with open(base + ".dat", "wb") as f:
        f.write(b"".join(blobs))
    idx_mod.write_entries(
        [(nid, types.offset_to_bytes(off), size) for nid, (off, size, _) in records.items()],
        base + ".idx",
    )
    stripe.write_ec_files(base, large_block_size=LARGE, small_block_size=SMALL, buffer_size=64, encoder=ENC)
    stripe.write_sorted_file_from_idx(base)
    return base, records


def open_vol(base, **kw):
    kw.setdefault("encoder", ENC)
    kw.setdefault("warm_on_mount", False)
    return EcVolume(base, large_block_size=LARGE, small_block_size=SMALL, **kw)


def enable_cache(monkeypatch, mb="64", ttl="0"):
    monkeypatch.setenv("WEEDTPU_READ_CACHE_MB", mb)
    monkeypatch.setenv("WEEDTPU_READ_CACHE_TTL_S", ttl)


def drop_shards(base, shards):
    for s in shards:
        os.remove(stripe.shard_file_name(base, s))


def test_cached_reads_byte_identical_to_uncached(volume, monkeypatch):
    """The acceptance bar: uncached (cache off) vs cold decode-and-publish
    vs warm cache hit must produce identical bytes for every needle."""
    base, records = volume
    drop_shards(base, [0, 13])
    with open_vol(base) as ev:
        uncached = {nid: ev.read_needle_blob(nid) for nid in records}
    enable_cache(monkeypatch)
    with open_vol(base) as ev:
        h0, m0 = stats.ReadCacheHits.value, stats.ReadCacheMisses.value
        cold = {nid: ev.read_needle_blob(nid) for nid in records}
        assert stats.ReadCacheHits.value == h0, "cold pass must not hit"
        assert stats.ReadCacheMisses.value > m0
        warm = {nid: ev.read_needle_blob(nid) for nid in records}
        assert stats.ReadCacheHits.value > h0, "warm pass must hit"
    for nid, (off, size, rec) in records.items():
        assert uncached[nid][: len(rec)] == rec
        assert cold[nid] == uncached[nid], f"needle {nid}: cold != uncached"
        assert warm[nid] == uncached[nid], f"needle {nid}: warm != uncached"


def test_coalesce_leader_publishes_into_cache(volume, monkeypatch):
    """N concurrent degraded reads of one interval: the coalesce leader's
    single decode lands in the cache, and a LATER read is served from it
    byte-identically with zero additional decodes."""
    base, records = volume
    with open(stripe.shard_file_name(base, 0), "rb") as f:
        golden0 = f.read()
    drop_shards(base, [0])
    enable_cache(monkeypatch)
    with open_vol(base, recover_fetch_parallelism=16) as ev:
        decodes = []
        real = ev.encoder.reconstruct

        def counting(shards, wanted=None, **kw):
            decodes.append(1)
            return real(shards, wanted=wanted, **kw)

        monkeypatch.setattr(ev.encoder, "reconstruct", counting)
        results, barrier = [], threading.Barrier(5)
        lock = threading.Lock()

        def one():
            barrier.wait()
            out = ev._recover_interval(0, 0, 64).tobytes()
            with lock:
                results.append(out)

        threads = [threading.Thread(target=one) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20)
        assert len(results) == 5
        assert all(r == golden0[:64] for r in results)
        assert CACHE.snapshot()["entries"] >= 1, "leader did not publish"
        n_decodes = len(decodes)
        # the read ladder now serves the interval from the cache: no new
        # decode, bytes identical to the leader's
        late = ev._read_present(0, 0, 64)
        assert late is not None and late.tobytes() == golden0[:64]
        assert len(decodes) == n_decodes


def test_cache_hits_feed_no_decode_or_hedge_stats(volume, monkeypatch):
    """Stats purity: a hit returns before the fan-out, so repeated hot
    reads move ONLY the hit counter — never the reconstruct/degraded
    histograms, the hedge counters, or the coalesce counter."""
    base, records = volume
    drop_shards(base, [0, 1])
    enable_cache(monkeypatch)
    monkeypatch.setenv("WEEDTPU_HEDGE_READS", "1")
    with open_vol(base) as ev:
        warm = {nid: ev.read_needle_blob(nid) for nid in records}  # decode once
        rec0 = stats.EcReconstructSeconds.labels().total
        deg0 = stats.DegradedReadSeconds.labels().total
        hed0 = stats.HedgeFired.value
        coa0 = stats.CoalescedReads.value
        h0 = stats.ReadCacheHits.value
        for _ in range(3):
            for nid in records:
                assert ev.read_needle_blob(nid) == warm[nid]
        assert stats.ReadCacheHits.value > h0
        assert stats.EcReconstructSeconds.labels().total == rec0, "hit observed a decode"
        assert stats.DegradedReadSeconds.labels().total == deg0, "hit observed degraded latency"
        assert stats.HedgeFired.value == hed0, "hit fired a hedge"
        assert stats.CoalescedReads.value == coa0


def test_quarantine_flushes_volume_entries(volume, monkeypatch):
    base, records = volume
    drop_shards(base, [0])
    enable_cache(monkeypatch)
    with open_vol(base) as ev:
        for nid in records:
            ev.read_needle_blob(nid)
        assert CACHE.snapshot()["entries"] >= 1
        inv0 = stats.ReadCacheInvalidations.value
        ev.quarantine_shard(5, "corrupt")
        assert CACHE.snapshot()["entries"] == 0, "quarantine left stale intervals"
        assert stats.ReadCacheInvalidations.value > inv0
        # reads still serve, by re-decoding — never from the flushed cache
        rec0 = stats.EcReconstructSeconds.labels().total
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
        assert stats.EcReconstructSeconds.labels().total > rec0


def test_shard_remount_flushes_that_shard(volume, monkeypatch):
    """mount_local_shard is the repair path's remount-after-rebuild: the
    rebuilt file is authoritative, cached decodes of that shard must go."""
    base, records = volume
    shutil.copy(stripe.shard_file_name(base, 0), base + ".ec00.save")
    drop_shards(base, [0])
    enable_cache(monkeypatch)
    with open_vol(base) as ev:
        ev._read_shard_interval(0, 0, 64)  # decode + publish for shard 0
        assert any(k[1] == 0 for k in CACHE._entries), "no shard-0 entry cached"
        shutil.copy(base + ".ec00.save", stripe.shard_file_name(base, 0))
        assert ev.mount_local_shard(0)
        assert not any(k[1] == 0 for k in CACHE._entries), (
            "remount left stale shard-0 intervals"
        )


def test_unmount_and_remount_cutover_serves_fresh_bytes(tmp_path, monkeypatch):
    """The close() seam (Store.mount_ec_volume remount / unmount — the
    same seam ec.convert's cut-over routes through): re-encode the volume
    with DIFFERENT contents under the same base, remount, and prove the
    read serves the new bytes, not the cached decode of the old ones."""
    enable_cache(monkeypatch)
    nid = 77

    def build(seed):
        base = str(tmp_path / "v5")
        for f in os.listdir(tmp_path):
            if f.startswith("v5"):
                os.remove(tmp_path / f)
        rng = np.random.default_rng(seed)
        body = 200
        total = types.actual_size(body, version=3)
        rec = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
        with open(base + ".dat", "wb") as f:
            f.write(b"\x03" + bytes(7) + rec)
        idx_mod.write_entries(
            [(nid, types.offset_to_bytes(types.NEEDLE_PADDING_SIZE), body)],
            base + ".idx",
        )
        stripe.write_ec_files(base, large_block_size=LARGE, small_block_size=SMALL, buffer_size=64, encoder=ENC)
        stripe.write_sorted_file_from_idx(base)
        os.remove(stripe.shard_file_name(base, 0))  # force a degraded read
        return base, rec

    base, old_rec = build(1)
    ev = open_vol(base)
    assert ev.read_needle_blob(nid)[: len(old_rec)] == old_rec
    assert CACHE.snapshot()["entries"] >= 1
    ev.close()  # the unmount/cut-over seam
    assert CACHE.snapshot()["entries"] == 0, "close() left stale intervals"

    base, new_rec = build(2)
    assert new_rec != old_rec
    with open_vol(base) as ev2:
        got = ev2.read_needle_blob(nid)
        assert got[: len(new_rec)] == new_rec, "stale pre-cut-over bytes served"


def test_inline_delta_update_flushes_volume(tmp_path, monkeypatch):
    """The PR-12 seam: an inline-ingest overwrite folds a delta into the
    encoded rows — cached decodes of this base describe the old bytes and
    must be flushed (and the generation bump must block a concurrent
    publish that gathered pre-delta survivors)."""
    from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT

    enable_cache(monkeypatch)
    base = os.path.join(str(tmp_path), "v", "7")
    os.makedirs(os.path.dirname(base), exist_ok=True)
    data = np.random.default_rng(3).integers(
        0, 256, LARGE * DATA_SHARDS_COUNT * 2 + 777, dtype=np.uint8
    ).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    b = ingest.InlineStripeBuilder(base, ENC, LARGE, SMALL, buffer_size=64)
    b.poll()
    assert b.rows_done == 2
    # a decoded interval for this base sits cached (as if a degraded read
    # of a spread-ahead shard had happened)
    gen = CACHE.generation(base)
    CACHE.put(base, 0, 0, 16, b"x" * 16, gen)
    assert CACHE.snapshot()["entries"] == 1
    new = b"\x5a" * 64

    def mutate():
        with open(base + ".dat", "r+b") as f:
            f.write(new)

    assert b.overwrite(0, data[:64], new, mutate=mutate) == 64
    assert CACHE.snapshot()["entries"] == 0, "delta update left stale intervals"
    # the generation moved: a decode that started before the delta (its
    # snapshot is `gen`) must be refused
    assert not CACHE.put(base, 1, 0, 16, b"y" * 16, gen)
    assert CACHE.put(base, 1, 0, 16, b"y" * 16, CACHE.generation(base))
    b.abort()


def test_lru_bound_and_ttl(monkeypatch):
    """The WEEDTPU_READ_CACHE_MB budget evicts LRU-first and the TTL ages
    entries out (the decode-once-per-epoch bound)."""
    enable_cache(monkeypatch, mb=str(4096 / (1 << 20)))  # 4 KiB budget
    ev0 = stats.ReadCacheEvictions.value
    gen = CACHE.generation("b")
    for i in range(8):
        assert CACHE.put("b", 0, i * 1024, 1024, bytes(1024), gen)
    snap = CACHE.snapshot()
    assert snap["bytes"] <= 4096 and snap["entries"] == 4
    assert stats.ReadCacheEvictions.value - ev0 == 4
    assert CACHE.get("b", 0, 0, 1024) is None          # evicted (oldest)
    assert CACHE.get("b", 0, 7 * 1024, 1024) is not None  # newest survived

    monkeypatch.setenv("WEEDTPU_READ_CACHE_TTL_S", "0.05")
    time.sleep(0.06)
    assert CACHE.get("b", 0, 7 * 1024, 1024) is None, "TTL did not expire entry"
    assert stats.ReadCacheEvictions.value - ev0 == 5


def test_cache_disabled_is_fully_bypassed(volume, monkeypatch):
    """WEEDTPU_READ_CACHE_MB=0 (the tests' default): no lookups, no
    publishes, no counters — the pre-PR-16 read path exactly."""
    base, records = volume
    drop_shards(base, [0])
    h0, m0 = stats.ReadCacheHits.value, stats.ReadCacheMisses.value
    with open_vol(base) as ev:
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
    assert CACHE.snapshot() == {"entries": 0, "bytes": 0}
    assert (stats.ReadCacheHits.value, stats.ReadCacheMisses.value) == (h0, m0)
