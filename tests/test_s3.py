"""S3 gateway tests — bucket/object/multipart lifecycle against a real
master + volume + filer + s3 stack on loopback, driven by raw HTTP with
an independent SigV4 signer (the reference's test/s3 black-box pattern,
SURVEY.md §4)."""

import hashlib
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.s3api import Iam, Identity, S3ApiServer, sign_request

AK, SK = "testAccessKey1", "testSecretKey1"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("s3stack")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp / "vol").mkdir()
    # per-bucket collections (a volume set per bucket) need headroom on
    # the single test volume server
    vs = VolumeServer(
        [str(tmp / "vol")], master.address, heartbeat_interval=0.4,
        max_volume_count=200,
    )
    vs.start()
    fs = FilerServer(master.address, chunk_size=1024 * 1024)
    fs.start()
    s3 = S3ApiServer(
        fs.url,
        fs.grpc_address,
        iam=Iam([Identity("tester", AK, SK)]),
    )
    s3.start()
    yield s3
    s3.stop()
    fs.stop()
    vs.stop()
    master.stop()


def _req(s3, method, path, body=b"", headers=None, sign=True, query=""):
    url = f"http://{s3.url}{path}" + (f"?{query}" if query else "")
    h = dict(headers or {})
    if sign:
        h = {**sign_request(AK, SK, method, url, body, extra_headers=h)}
    req = urllib.request.Request(url, data=body if body else None, method=method, headers=h)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.headers, r.read()  # HTTPMessage: case-insensitive
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _xml(body):
    return ET.fromstring(body)


def test_bucket_lifecycle(stack):
    s3 = stack
    code, _, _ = _req(s3, "PUT", "/bkt1")
    assert code == 200
    code, _, body = _req(s3, "GET", "/")
    assert code == 200 and b"bkt1" in body
    # duplicate create
    code, _, body = _req(s3, "PUT", "/bkt1")
    assert code == 409
    code, _, _ = _req(s3, "HEAD", "/bkt1")
    assert code == 200
    code, _, _ = _req(s3, "DELETE", "/bkt1")
    assert code == 204
    code, _, _ = _req(s3, "HEAD", "/bkt1")
    assert code == 404


def test_object_put_get_delete(stack):
    s3 = stack
    _req(s3, "PUT", "/objs")
    payload = os.urandom(3 * 1024 * 1024)  # 3 chunks through the filer
    code, headers, _ = _req(
        s3, "PUT", "/objs/dir/data.bin", payload,
        {"Content-Type": "application/x-test", "x-amz-meta-tag": "v1"},
    )
    assert code == 200 and headers["ETag"]
    code, headers, got = _req(s3, "GET", "/objs/dir/data.bin")
    assert code == 200 and got == payload
    assert headers["Content-Type"] == "application/x-test"
    assert headers.get("x-amz-meta-tag") == "v1"
    # range
    code, headers, got = _req(
        s3, "GET", "/objs/dir/data.bin", headers={"Range": "bytes=100-199"}
    )
    assert code == 206 and got == payload[100:200]
    # head
    code, headers, _ = _req(s3, "HEAD", "/objs/dir/data.bin")
    assert code == 200 and int(headers["Content-Length"]) == len(payload)
    # missing key
    code, _, body = _req(s3, "GET", "/objs/missing.bin")
    assert code == 404 and b"NoSuchKey" in body
    # delete is idempotent
    assert _req(s3, "DELETE", "/objs/dir/data.bin")[0] == 204
    assert _req(s3, "DELETE", "/objs/dir/data.bin")[0] == 204
    assert _req(s3, "GET", "/objs/dir/data.bin")[0] == 404


def test_object_key_needing_percent_encoding(stack):
    """Signer and verifier must canonicalize encoded paths identically."""
    s3 = stack
    _req(s3, "PUT", "/enc")
    code, _, _ = _req(s3, "PUT", "/enc/sp%20ace%2Bplus.txt", b"odd key")
    assert code == 200
    code, _, got = _req(s3, "GET", "/enc/sp%20ace%2Bplus.txt")
    assert code == 200 and got == b"odd key"


def test_copy_object(stack):
    s3 = stack
    _req(s3, "PUT", "/cpy")
    _req(s3, "PUT", "/cpy/src.txt", b"copy me")
    code, _, body = _req(
        s3, "PUT", "/cpy/dst.txt", headers={"x-amz-copy-source": "/cpy/src.txt"}
    )
    assert code == 200 and b"CopyObjectResult" in body
    # delete source; copy must survive (fresh needles)
    _req(s3, "DELETE", "/cpy/src.txt")
    code, _, got = _req(s3, "GET", "/cpy/dst.txt")
    assert code == 200 and got == b"copy me"


def test_list_objects_v2(stack):
    s3 = stack
    _req(s3, "PUT", "/lst")
    for k in ("a/1.txt", "a/2.txt", "b/3.txt", "top.txt"):
        _req(s3, "PUT", f"/lst/{k}", b"x")
    # flat listing
    code, _, body = _req(s3, "GET", "/lst", query="list-type=2")
    root = _xml(body)
    ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
    keys = [e.text for e in root.findall("s3:Contents/s3:Key", ns)]
    assert set(keys) == {"a/1.txt", "a/2.txt", "b/3.txt", "top.txt"}
    # delimiter grouping
    code, _, body = _req(s3, "GET", "/lst", query="list-type=2&delimiter=%2F")
    root = _xml(body)
    keys = [e.text for e in root.findall("s3:Contents/s3:Key", ns)]
    prefixes = [e.text for e in root.findall("s3:CommonPrefixes/s3:Prefix", ns)]
    assert keys == ["top.txt"] and set(prefixes) == {"a/", "b/"}
    # prefix
    code, _, body = _req(s3, "GET", "/lst", query="list-type=2&prefix=a%2F")
    root = _xml(body)
    keys = [e.text for e in root.findall("s3:Contents/s3:Key", ns)]
    assert keys == ["a/1.txt", "a/2.txt"]
    # pagination
    code, _, body = _req(s3, "GET", "/lst", query="list-type=2&max-keys=2")
    root = _xml(body)
    assert root.find("s3:IsTruncated", ns).text == "true"
    token = root.find("s3:NextContinuationToken", ns).text
    code, _, body = _req(
        s3, "GET", "/lst",
        query=f"list-type=2&max-keys=10&continuation-token={urllib.parse.quote(token)}",
    )
    root = _xml(body)
    page2 = [e.text for e in root.findall("s3:Contents/s3:Key", ns)]
    assert len(page2) == 2 and root.find("s3:IsTruncated", ns).text == "false"


def test_list_objects_delimiter_pagination_dedup(stack):
    """CommonPrefixes must not repeat across pages when the continuation
    token lands inside a prefix group."""
    s3 = stack
    ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
    _req(s3, "PUT", "/pagi")
    for k in ("a/1", "a/2", "b/1", "c"):
        _req(s3, "PUT", f"/pagi/{k}", b"x")
    seen_prefixes, seen_keys, token = [], [], ""
    for _ in range(10):
        q = "list-type=2&delimiter=%2F&max-keys=1"
        if token:
            q += f"&continuation-token={urllib.parse.quote(token)}"
        _, _, body = _req(s3, "GET", "/pagi", query=q)
        root = _xml(body)
        seen_prefixes += [e.text for e in root.findall("s3:CommonPrefixes/s3:Prefix", ns)]
        seen_keys += [e.text for e in root.findall("s3:Contents/s3:Key", ns)]
        if root.find("s3:IsTruncated", ns).text != "true":
            break
        token = root.find("s3:NextContinuationToken", ns).text
    assert seen_keys == ["c"]
    assert seen_prefixes == ["a/", "b/"]  # no duplicates across pages


def test_delete_objects_bulk(stack):
    s3 = stack
    _req(s3, "PUT", "/bulk")
    for k in ("x1", "x2", "x3"):
        _req(s3, "PUT", f"/bulk/{k}", b"d")
    body = (
        b'<Delete><Object><Key>x1</Key></Object>'
        b'<Object><Key>x3</Key></Object></Delete>'
    )
    code, _, resp = _req(s3, "POST", "/bulk", body, query="delete=")
    assert code == 200 and b"<Deleted>" in resp
    code, _, body = _req(s3, "GET", "/bulk", query="list-type=2")
    keys = [e.text for e in _xml(body).findall(
        "{http://s3.amazonaws.com/doc/2006-03-01/}Contents/"
        "{http://s3.amazonaws.com/doc/2006-03-01/}Key")]
    assert keys == ["x2"]


def test_multipart_upload(stack):
    s3 = stack
    _req(s3, "PUT", "/mp")
    code, _, body = _req(s3, "POST", "/mp/big.bin", query="uploads=")
    assert code == 200
    upload_id = _xml(body).find(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId").text
    parts = [os.urandom(1024 * 1024 + 7), os.urandom(512 * 1024), os.urandom(99)]
    etags = []
    for i, p in enumerate(parts, start=1):
        code, headers, _ = _req(
            s3, "PUT", "/mp/big.bin", p,
            query=f"partNumber={i}&uploadId={upload_id}",
        )
        assert code == 200
        assert headers["ETag"].strip('"') == hashlib.md5(p).hexdigest()
        etags.append(headers["ETag"])
    # list parts
    code, _, body = _req(s3, "GET", "/mp/big.bin", query=f"uploadId={upload_id}")
    assert code == 200 and body.count(b"<Part>") == 3
    # complete validates the client's part list: wrong ETag rejected
    bad = ("<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
           "<ETag>deadbeef</ETag></Part></CompleteMultipartUpload>").encode()
    code, _, body = _req(s3, "POST", "/mp/big.bin", bad,
                         query=f"uploadId={upload_id}")
    assert code == 400 and b"InvalidPart" in body
    # out-of-order part list rejected
    ooo = ("<CompleteMultipartUpload>"
           f"<Part><PartNumber>2</PartNumber><ETag>{etags[1]}</ETag></Part>"
           f"<Part><PartNumber>1</PartNumber><ETag>{etags[0]}</ETag></Part>"
           "</CompleteMultipartUpload>").encode()
    code, _, body = _req(s3, "POST", "/mp/big.bin", ooo,
                         query=f"uploadId={upload_id}")
    assert code == 400 and b"InvalidPartOrder" in body
    payload = "".join(
        f"<Part><PartNumber>{i}</PartNumber><ETag>{t}</ETag></Part>"
        for i, t in enumerate(etags, start=1)
    )
    code, _, body = _req(
        s3, "POST", "/mp/big.bin",
        f"<CompleteMultipartUpload>{payload}</CompleteMultipartUpload>".encode(),
        query=f"uploadId={upload_id}")
    assert code == 200 and b"CompleteMultipartUploadResult" in body
    code, headers, got = _req(s3, "GET", "/mp/big.bin")
    assert code == 200 and got == b"".join(parts)
    assert headers["ETag"].endswith('-3"')
    # staging dir is gone
    assert stack.filer.lookup(f"/buckets/.uploads/mp/{upload_id}") is None


def test_multipart_abort(stack):
    s3 = stack
    _req(s3, "PUT", "/mpab")
    _, _, body = _req(s3, "POST", "/mpab/f.bin", query="uploads=")
    upload_id = _xml(body).find(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId").text
    _req(s3, "PUT", "/mpab/f.bin", b"part", query=f"partNumber=1&uploadId={upload_id}")
    code, _, _ = _req(s3, "DELETE", "/mpab/f.bin", query=f"uploadId={upload_id}")
    assert code == 204
    code, _, _ = _req(s3, "PUT", "/mpab/f.bin", b"p2",
                      query=f"partNumber=2&uploadId={upload_id}")
    assert code == 404


def test_auth_required(stack):
    s3 = stack
    # unsigned request rejected
    code, _, body = _req(s3, "GET", "/", sign=False)
    assert code == 403
    # bad secret rejected
    url = f"http://{s3.url}/"
    h = sign_request(AK, "wrongSecret", "GET", url, b"")
    req = urllib.request.Request(url, headers=h)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 403


def test_action_scoping(stack):
    s3 = stack
    s3.iam.add(Identity("ro", "roKey", "roSecret", ["Read", "List"]))
    _req(s3, "PUT", "/scoped")
    _req(s3, "PUT", "/scoped/f.txt", b"data")
    url = f"http://{s3.url}/scoped/f.txt"
    h = sign_request("roKey", "roSecret", "GET", url, b"")
    with urllib.request.urlopen(urllib.request.Request(url, headers=h), timeout=10) as r:
        assert r.read() == b"data"
    h = sign_request("roKey", "roSecret", "PUT", url, b"nope")
    req = urllib.request.Request(url, data=b"nope", method="PUT", headers=h)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 403
    s3.iam.remove("roKey")


def test_path_traversal_rejected(stack):
    s3 = stack
    _req(s3, "PUT", "/trav")
    _req(s3, "PUT", "/trav/secret.txt", b"top secret")
    # '.'/'..'/empty segments anywhere in bucket or key -> 400, never
    # resolved through the filer's path normalization
    for path in ("/trav/../trav/secret.txt", "/trav/a/../secret.txt",
                 "/../buckets/trav/secret.txt", "/trav/..", "/trav/./x"):
        code, _, body = _req(s3, "GET", path)
        assert code == 400 and b"InvalidArgument" in body, path
    code, _, _ = _req(s3, "PUT", "/trav/a//b", b"d")
    assert code == 400
    # dot-prefixed buckets (the .uploads staging area) are unreachable
    code, _, _ = _req(s3, "GET", "/.uploads", query="list-type=2")
    assert code == 400
    # bulk delete validates keys from the XML body as well
    xml_body = b'<Delete><Object><Key>../other/x</Key></Object></Delete>'
    code, _, resp = _req(s3, "POST", "/trav", xml_body, query="delete=")
    assert code == 200 and b"<Error>" in resp and b"<Deleted>" not in resp
    # copy-source traversal rejected
    code, _, _ = _req(s3, "PUT", "/trav/copy.txt",
                      headers={"x-amz-copy-source": "/trav/../trav/secret.txt"})
    assert code == 400
    # the original object is still readable through the legitimate path
    code, _, got = _req(s3, "GET", "/trav/secret.txt")
    assert code == 200 and got == b"top secret"


def test_content_sha256_required(stack):
    s3 = stack
    _req(s3, "PUT", "/shabkt")
    url = f"http://{s3.url}/shabkt/f.txt"
    body = b"payload"
    # a signed request whose x-amz-content-sha256 header is stripped (and
    # removed from SignedHeaders) must be rejected, not verified against
    # the empty-payload hash
    h = sign_request(AK, SK, "PUT", url, body)
    h.pop("x-amz-content-sha256")
    signed = [s for s in ("host", "x-amz-date") ]
    # re-sign without the header so only its absence is under test
    from seaweedfs_tpu.s3api import auth as auth_mod
    amz_date = h["x-amz-date"]
    sig = auth_mod._signature(SK, "PUT", "/shabkt/f.txt", "", h, signed,
                              "UNSIGNED-PAYLOAD", amz_date, "us-east-1", "s3")
    scope = f"{amz_date[:8]}/us-east-1/s3/aws4_request"
    h["authorization"] = (f"AWS4-HMAC-SHA256 Credential={AK}/{scope}, "
                          f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    req = urllib.request.Request(url, data=body, method="PUT", headers=h)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 403
    assert b"MissingSecurityHeader" in ei.value.read()


def test_upload_id_traversal_rejected(stack):
    s3 = stack
    _req(s3, "PUT", "/victim")
    _req(s3, "PUT", "/victim/data.txt", b"keep me")
    _req(s3, "PUT", "/mine")
    # an attacker with Write on their own bucket must not reach outside
    # the staging area via a crafted uploadId (Abort recursively deletes
    # the target path)
    evil = urllib.parse.quote("../../victim", safe="")
    for method, query in (
        ("DELETE", f"uploadId={evil}"),
        ("GET", f"uploadId={evil}"),
        ("POST", f"uploadId={evil}"),
        ("PUT", f"partNumber=1&uploadId={evil}"),
    ):
        code, _, body = _req(s3, method, "/mine/x", b"<x/>" if method == "POST" else b"",
                             query=query)
        assert code == 404 and b"NoSuchUpload" in body, (method, code, body)
    # victim bucket untouched
    code, _, got = _req(s3, "GET", "/victim/data.txt")
    assert code == 200 and got == b"keep me"


def test_copy_object_with_declared_body(stack):
    s3 = stack
    _req(s3, "PUT", "/cpbody")
    _req(s3, "PUT", "/cpbody/src.txt", b"copy payload")
    # a legally-signed copy request may declare a non-empty body that the
    # server ignores; auth must not re-verify the signature against b""
    code, _, _ = _req(s3, "PUT", "/cpbody/dst.txt", b"ignored-body",
                      headers={"x-amz-copy-source": "/cpbody/src.txt"})
    assert code == 200
    code, _, got = _req(s3, "GET", "/cpbody/dst.txt")
    assert code == 200 and got == b"copy payload"


def test_host_binding_enforced(stack):
    s3 = stack
    _req(s3, "PUT", "/hostbkt")
    # a request signed for some other endpoint's host must not verify
    url = f"http://{s3.url}/hostbkt"
    h = sign_request(AK, SK, "GET", f"http://other.example:9999/hostbkt", b"")
    req = urllib.request.Request(url, method="GET", headers=h)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            code = r.status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 403


def test_presigned_url_get_and_put(stack):
    """Query-string SigV4 (presigned URLs): a bare urllib client with no
    credentials reads/writes through a signed link until it expires."""
    from seaweedfs_tpu.s3api.auth import presign_url

    s3 = stack
    _req(s3, "PUT", "/presign-bkt")
    _req(s3, "PUT", "/presign-bkt/hello.txt", b"presigned world")

    url = presign_url(AK, SK, "GET", f"http://{s3.url}/presign-bkt/hello.txt", expires=60)
    with urllib.request.urlopen(url, timeout=10) as r:  # NO auth headers
        assert r.read() == b"presigned world"

    # presigned PUT uploads without credentials
    purl = presign_url(AK, SK, "PUT", f"http://{s3.url}/presign-bkt/up.bin", expires=60)
    req = urllib.request.Request(purl, data=b"via-presign", method="PUT")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status in (200, 201)
    url2 = presign_url(AK, SK, "GET", f"http://{s3.url}/presign-bkt/up.bin")
    with urllib.request.urlopen(url2, timeout=10) as r:
        assert r.read() == b"via-presign"

    # tampering with the signature is rejected
    bad = url.replace("X-Amz-Signature=", "X-Amz-Signature=0")
    code, _, body = _raw_get(bad)
    assert code == 403 and b"SignatureDoesNotMatch" in body

    # a link signed by an unknown key is rejected
    code, _, body = _raw_get(
        presign_url("nobody", "nosecret", "GET", f"http://{s3.url}/presign-bkt/hello.txt")
    )
    assert code == 403 and b"InvalidAccessKeyId" in body

    # method is part of the signature: a GET link cannot DELETE
    del_try = urllib.request.Request(url, method="DELETE")
    try:
        urllib.request.urlopen(del_try, timeout=10)
        raise AssertionError("GET link performed a DELETE")
    except urllib.error.HTTPError as e:
        assert e.code == 403


def test_presigned_url_expiry(stack, monkeypatch):
    from seaweedfs_tpu.s3api import auth as auth_mod
    from seaweedfs_tpu.s3api.auth import presign_url

    s3 = stack
    _req(s3, "PUT", "/presign-exp")
    _req(s3, "PUT", "/presign-exp/f.txt", b"x")
    url = presign_url(AK, SK, "GET", f"http://{s3.url}/presign-exp/f.txt", expires=1)
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
    real_time = auth_mod.time.time
    monkeypatch.setattr(auth_mod.time, "time", lambda: real_time() + 5)
    code, _, body = _raw_get(url)
    assert code == 403, "expired presigned link must be refused"
    # out-of-range X-Amz-Expires is malformed
    monkeypatch.undo()
    giant = presign_url(AK, SK, "GET", f"http://{s3.url}/presign-exp/f.txt",
                        expires=8 * 24 * 3600)
    code, _, body = _raw_get(giant)
    assert code in (400, 403)


def _raw_get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def test_object_tagging(stack):
    s3 = stack
    _req(s3, "PUT", "/tagbkt")
    # tags via the x-amz-tagging PUT header
    code, _, _ = _req(
        s3, "PUT", "/tagbkt/tagged.txt", b"data",
        {"x-amz-tagging": "env=prod&team=storage"},
    )
    assert code == 200
    code, headers, _ = _req(s3, "GET", "/tagbkt/tagged.txt")
    assert code == 200 and headers.get("x-amz-tagging-count") == "2"
    assert headers.get("x-amz-tagging") is None  # tags never leak as a header
    code, _, body = _req(s3, "GET", "/tagbkt/tagged.txt", query="tagging")
    root = _xml(body)
    ns = root.tag[: root.tag.index("}") + 1]
    tags = {
        t.find(f"{ns}Key").text: t.find(f"{ns}Value").text
        for t in root.findall(f"{ns}TagSet/{ns}Tag")
    }
    assert tags == {"env": "prod", "team": "storage"}

    # PutObjectTagging replaces the whole set
    new = (
        b'<Tagging xmlns="http://s3.amazonaws.com/doc/2006-03-01/"><TagSet>'
        b"<Tag><Key>tier</Key><Value>cold</Value></Tag>"
        b"</TagSet></Tagging>"
    )
    code, _, _ = _req(s3, "PUT", "/tagbkt/tagged.txt", new, query="tagging")
    assert code == 200
    code, _, body = _req(s3, "GET", "/tagbkt/tagged.txt", query="tagging")
    assert b"tier" in body and b"env" not in body
    code, headers, _ = _req(s3, "HEAD", "/tagbkt/tagged.txt")
    assert headers.get("x-amz-tagging-count") == "1"

    # validation: >10 tags and duplicate keys are rejected
    many = "&".join(f"k{i}=v" for i in range(11))
    code, _, _ = _req(
        s3, "PUT", "/tagbkt/toomany.txt", b"x", {"x-amz-tagging": many}
    )
    assert code == 400
    dup = (
        b"<Tagging><TagSet>"
        b"<Tag><Key>a</Key><Value>1</Value></Tag>"
        b"<Tag><Key>a</Key><Value>2</Value></Tag>"
        b"</TagSet></Tagging>"
    )
    code, _, body = _req(s3, "PUT", "/tagbkt/tagged.txt", dup, query="tagging")
    assert code == 400 and b"InvalidTag" in body

    # DeleteObjectTagging clears; GET tagging then returns an empty set
    code, _, _ = _req(s3, "DELETE", "/tagbkt/tagged.txt", query="tagging")
    assert code == 204
    code, _, body = _req(s3, "GET", "/tagbkt/tagged.txt", query="tagging")
    assert code == 200 and b"<Tag>" not in body
    code, headers, _ = _req(s3, "GET", "/tagbkt/tagged.txt")
    assert headers.get("x-amz-tagging-count") is None
    # tagging a missing key 404s
    code, _, body = _req(s3, "GET", "/tagbkt/ghost.txt", query="tagging")
    assert code == 404 and b"NoSuchKey" in body


def test_object_tagging_blank_value(stack):
    """A tag with an empty value is legal in S3 and must survive the
    round-trip (parse_qsl drops blank values unless told otherwise)."""
    s3 = stack
    _req(s3, "PUT", "/blankbkt")
    code, _, _ = _req(
        s3, "PUT", "/blankbkt/o", b"x", {"x-amz-tagging": "flag=&k=v"}
    )
    assert code == 200
    code, headers, _ = _req(s3, "HEAD", "/blankbkt/o")
    assert headers.get("x-amz-tagging-count") == "2"
    code, _, body = _req(s3, "GET", "/blankbkt/o", query="tagging")
    assert b"flag" in body


def test_bucket_collection_mapping_and_reclaim(stack):
    """Objects land in a collection named after their bucket, and bucket
    deletion drops those volumes cluster-wide (per-bucket collections)."""
    s3 = stack
    _req(s3, "PUT", "/collbkt")
    code, _, _ = _req(s3, "PUT", "/collbkt/obj1", b"d" * 2048)
    assert code == 200
    # the chunk's volume carries the bucket collection
    entry = s3.filer.lookup("/buckets/collbkt/obj1")
    assert entry.attributes.collection == "collbkt"
    import time as _time

    _time.sleep(0.8)  # heartbeat registers the new volume's collection
    # delete the object then the bucket; the collection's volumes drop
    _req(s3, "DELETE", "/collbkt/obj1")
    code, _, _ = _req(s3, "DELETE", "/collbkt")
    assert code == 204
    _time.sleep(0.8)
    from seaweedfs_tpu import rpc as _rpc

    # discover the master through the filer config and check the topology
    cfg = s3.filer.configuration()
    with _rpc.RpcClient(cfg["masters"][0]) as c:
        topo = c.call("weedtpu.Master", "VolumeList", {})
    colls = {
        v.get("collection")
        for racks in topo["data_centers"].values()
        for nodes in racks.values()
        for n in nodes
        for v in n.get("volumes", [])
    }
    assert "collbkt" not in colls, colls


def test_multipart_parts_inherit_bucket_collection(stack):
    """Multipart part needles must land in the bucket's collection (they
    are spliced verbatim into the final object) or the bucket's
    collection drop could never reclaim multipart objects."""
    s3 = stack
    _req(s3, "PUT", "/mpcoll")
    code, _, body = _req(s3, "POST", "/mpcoll/big.bin", query="uploads")
    assert code == 200
    upload_id = _xml(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId"
    )
    part = os.urandom(1024)
    code, headers, _ = _req(
        s3, "PUT", "/mpcoll/big.bin", part,
        query=f"partNumber=1&uploadId={upload_id}",
    )
    assert code == 200
    etag = headers["ETag"]
    staged = s3.filer.list(f"/buckets/.uploads/mpcoll/{upload_id}", limit=10)
    assert staged and staged[0].attributes.collection == "mpcoll"
    complete = (
        "<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
        f"<ETag>{etag}</ETag></Part></CompleteMultipartUpload>"
    ).encode()
    code, _, _ = _req(
        s3, "POST", "/mpcoll/big.bin", complete, query=f"uploadId={upload_id}"
    )
    assert code == 200
    entry = s3.filer.lookup("/buckets/mpcoll/big.bin")
    vid = int(entry.chunks[0].fid.split(",", 1)[0])
    # the final object's needles sit in a collection-mpcoll volume
    cfg = s3.filer.configuration()
    from seaweedfs_tpu import rpc as _rpc

    import time as _time

    _time.sleep(0.8)
    with _rpc.RpcClient(cfg["masters"][0]) as c:
        topo = c.call("weedtpu.Master", "VolumeList", {})
    vol = next(
        v
        for racks in topo["data_centers"].values()
        for nodes in racks.values()
        for n in nodes
        for v in n.get("volumes", [])
        if int(v["id"]) == vid
    )
    assert vol.get("collection") == "mpcoll"


def test_delete_collection_guards_default_and_rules(stack):
    """DeleteCollection must refuse names that would destroy non-bucket
    data: the filer default collection and fs.configure-pinned ones."""
    import grpc as _grpc
    import pytest as _pytest

    s3 = stack
    fs_client = s3.filer
    # simulate a filer default collection collision
    # (the stack's filer has no default; use an fs.configure rule)
    fs_client.set_filer_conf("/media/", collection="mediacoll")
    try:
        with _pytest.raises(_grpc.RpcError) as ei:
            fs_client.delete_collection("mediacoll")
        assert ei.value.code() == _grpc.StatusCode.FAILED_PRECONDITION
    finally:
        fs_client.set_filer_conf("/media/", delete=True)


def test_bucket_delete_cleans_staged_uploads(stack):
    """Deleting a bucket must also clear its multipart staging area —
    otherwise the collection drop leaves staged entries pointing at dead
    volumes and a later Complete splices dead fids."""
    s3 = stack
    _req(s3, "PUT", "/stagebkt")
    code, _, body = _req(s3, "POST", "/stagebkt/pending.bin", query="uploads")
    upload_id = _xml(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId"
    )
    _req(s3, "PUT", "/stagebkt/pending.bin", b"p" * 256,
         query=f"partNumber=1&uploadId={upload_id}")
    assert s3.filer.lookup(f"/buckets/.uploads/stagebkt/{upload_id}")
    code, _, _ = _req(s3, "DELETE", "/stagebkt")
    assert code == 204
    assert s3.filer.lookup("/buckets/.uploads/stagebkt") is None


def test_conditional_get_and_bucket_location(stack):
    s3 = stack
    _req(s3, "PUT", "/condbkt")
    code, _, body = _req(s3, "GET", "/condbkt", query="location")
    assert code == 200 and b"LocationConstraint" in body
    code, _, _ = _req(s3, "GET", "/ghostbkt", query="location")
    assert code == 404
    code, headers, _ = _req(s3, "PUT", "/condbkt/c.txt", b"cache me")
    etag = headers["ETag"].strip('"')
    # If-None-Match with the current etag -> 304 with no body
    code, headers, body = _req(
        s3, "GET", "/condbkt/c.txt", headers={"If-None-Match": f'"{etag}"'}
    )
    assert code == 304 and body == b""
    code, _, body = _req(
        s3, "GET", "/condbkt/c.txt", headers={"If-None-Match": '"stale"'}
    )
    assert code == 200 and body == b"cache me"
    # If-Modified-Since in the future -> 304; far past -> 200
    code, _, _ = _req(
        s3, "GET", "/condbkt/c.txt",
        headers={"If-Modified-Since": "Tue, 01 Jan 2030 00:00:00 GMT"},
    )
    assert code == 304
    code, _, body = _req(
        s3, "GET", "/condbkt/c.txt",
        headers={"If-Modified-Since": "Mon, 01 Jan 2001 00:00:00 GMT"},
    )
    assert code == 200 and body == b"cache me"


def test_upload_part_copy_and_acl(stack):
    s3 = stack
    _req(s3, "PUT", "/upcbkt")
    src_data = os.urandom(5000)
    _req(s3, "PUT", "/upcbkt/src.bin", src_data)
    # initiate, then copy a RANGE of the source as part 1 and body as part 2
    code, _, body = _req(s3, "POST", "/upcbkt/assembled.bin", query="uploads")
    upload_id = _xml(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId"
    )
    code, _, body = _req(
        s3, "PUT", "/upcbkt/assembled.bin",
        query=f"partNumber=1&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upcbkt/src.bin",
                 "x-amz-copy-source-range": "bytes=0-2047"},
    )
    assert code == 200 and b"CopyPartResult" in body, body
    etag1 = _xml(body).findtext("{http://s3.amazonaws.com/doc/2006-03-01/}ETag")
    tail = os.urandom(100)
    code, headers, _ = _req(
        s3, "PUT", "/upcbkt/assembled.bin", tail,
        query=f"partNumber=2&uploadId={upload_id}",
    )
    etag2 = headers["ETag"]
    complete = (
        "<CompleteMultipartUpload>"
        f"<Part><PartNumber>1</PartNumber><ETag>{etag1}</ETag></Part>"
        f"<Part><PartNumber>2</PartNumber><ETag>{etag2}</ETag></Part>"
        "</CompleteMultipartUpload>"
    ).encode()
    code, _, _ = _req(
        s3, "POST", "/upcbkt/assembled.bin", complete,
        query=f"uploadId={upload_id}",
    )
    assert code == 200
    code, _, got = _req(s3, "GET", "/upcbkt/assembled.bin")
    assert code == 200 and got == src_data[:2048] + tail
    # missing copy-source object -> 404
    code, _, _ = _req(
        s3, "PUT", "/upcbkt/assembled.bin",
        query=f"partNumber=3&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upcbkt/ghost.bin"},
    )
    assert code == 404
    # a directory as copy-source must 404, never serve the JSON listing
    _req(s3, "PUT", "/upcbkt/dir/nested.bin", b"nested")
    code, _, _ = _req(
        s3, "PUT", "/upcbkt/assembled.bin",
        query=f"partNumber=3&uploadId={upload_id}",
        headers={"x-amz-copy-source": "/upcbkt/dir"},
    )
    assert code == 404
    # an identity WITHOUT Read on the source bucket gets 403 (the copy
    # auth path, exercised directly against the resolution helper)
    from seaweedfs_tpu.s3api.auth import Identity as _Id

    class _Rec:
        def __init__(self):
            self.replies = []
        def _error(self, code, *a):
            self.replies.append(code)
    rec = _Rec()
    limited = _Id("limited", "k", "s", actions=["Write:upcbkt"])
    from seaweedfs_tpu.s3api import server as s3server

    out = s3server._Handler._resolve_copy_source.__get__(rec, _Rec)
    rec.s3 = s3  # consulted for the source bucket's policy (none here)
    rec._policy_verdict = s3server._Handler._policy_verdict.__get__(rec, _Rec)
    rec._is_anonymous = s3server._Handler._is_anonymous
    assert out("/upcbkt/src.bin", limited) is None
    assert rec.replies == [403]
    # ACL endpoints: canned responses, never 501
    for path, q in (("/upcbkt", "acl"), ("/upcbkt/src.bin", "acl")):
        code, _, body = _req(s3, "GET", path, query=q)
        assert code == 200 and b"FULL_CONTROL" in body, (path, body)
    code, _, _ = _req(s3, "PUT", "/upcbkt/src.bin", query="acl",
                      headers={"x-amz-acl": "private"})
    assert code == 200
    code, _, _ = _req(s3, "GET", "/upcbkt/ghost.bin", query="acl")
    assert code == 404


def test_bucket_policy_engine(stack):
    """Resource policies with IAM evaluation order: explicit Deny beats an
    identity allow, Allow grants anonymous principals (public-read), no
    match falls back to identity grants; Get/Put/DeleteBucketPolicy
    endpoints round-trip the document."""
    import json as _json

    s3 = stack
    assert _req(s3, "PUT", "/polbkt")[0] == 200
    assert _req(s3, "PUT", "/polbkt/pub/hello.txt", b"public bytes")[0] == 200
    assert _req(s3, "PUT", "/polbkt/secret/s.txt", b"secret bytes")[0] == 200

    # before any policy: anonymous reads are refused, policy GET is a 404
    code, _, body = _req(s3, "GET", "/polbkt/pub/hello.txt", sign=False)
    assert code == 403
    code, _, body = _req(s3, "GET", "/polbkt", query="policy")
    assert code == 404 and b"NoSuchBucketPolicy" in body

    # malformed documents are rejected with MalformedPolicy
    code, _, body = _req(s3, "PUT", "/polbkt", b"{not json", query="policy")
    assert code == 400 and b"MalformedPolicy" in body
    bad = _json.dumps({"Statement": [{"Effect": "Allow", "Principal": "*",
                                      "Action": "s3:GetObject",
                                      "Resource": "arn:aws:s3:::otherbucket/*"}]})
    code, _, body = _req(s3, "PUT", "/polbkt", bad.encode(), query="policy")
    assert code == 400 and b"MalformedPolicy" in body

    # public-read on /pub/* + explicit deny on /secret/* for everyone
    doc = {
        "Version": "2012-10-17",
        "Statement": [
            {"Effect": "Allow", "Principal": "*", "Action": "s3:GetObject",
             "Resource": "arn:aws:s3:::polbkt/pub/*"},
            {"Effect": "Deny", "Principal": "*", "Action": "s3:GetObject",
             "Resource": "arn:aws:s3:::polbkt/secret/*"},
        ],
    }
    code, _, _ = _req(s3, "PUT", "/polbkt", _json.dumps(doc).encode(), query="policy")
    assert code == 204

    # anonymous: granted exactly where the Allow says, nowhere else
    code, _, body = _req(s3, "GET", "/polbkt/pub/hello.txt", sign=False)
    assert code == 200 and body == b"public bytes"
    assert _req(s3, "GET", "/polbkt/other.txt", sign=False)[0] == 403
    assert _req(s3, "PUT", "/polbkt/pub/nope.txt", b"x", sign=False)[0] == 403
    assert _req(s3, "GET", "/polbkt", sign=False)[0] == 403  # list not granted

    # explicit deny overrides the signed admin identity's grant
    code, _, body = _req(s3, "GET", "/polbkt/secret/s.txt")
    assert code == 403 and b"bucket policy" in body
    # ...but only for the denied action: the same identity still writes
    assert _req(s3, "PUT", "/polbkt/secret/new.txt", b"w")[0] == 200
    # and undenied objects still read fine
    assert _req(s3, "GET", "/polbkt/pub/hello.txt")[0] == 200

    # round-trip the stored document
    code, _, body = _req(s3, "GET", "/polbkt", query="policy")
    assert code == 200 and _json.loads(body) == doc

    # lockout safety: even a blanket deny cannot take the policy
    # endpoints away from an admin identity
    nuke = {"Statement": [{"Effect": "Deny", "Principal": "*", "Action": "s3:*",
                           "Resource": ["arn:aws:s3:::polbkt",
                                        "arn:aws:s3:::polbkt/*"]}]}
    assert _req(s3, "PUT", "/polbkt", _json.dumps(nuke).encode(), query="policy")[0] == 204
    assert _req(s3, "GET", "/polbkt/pub/hello.txt")[0] == 403  # deny bites
    assert _req(s3, "DELETE", "/polbkt", query="policy")[0] == 204  # escape hatch
    assert _req(s3, "GET", "/polbkt/pub/hello.txt")[0] == 200
    assert _req(s3, "GET", "/polbkt", query="policy")[0] == 404
    # anonymous grant gone with the policy
    assert _req(s3, "GET", "/polbkt/pub/hello.txt", sign=False)[0] == 403


def test_bucket_policy_principal_scoping(stack):
    """Principal lists scope statements to named identities; others keep
    their identity-grant behavior; anonymous never matches a named
    principal."""
    import json as _json

    s3 = stack
    assert _req(s3, "PUT", "/pribkt")[0] == 200
    assert _req(s3, "PUT", "/pribkt/a.txt", b"data")[0] == 200
    doc = {
        "Statement": [
            {"Effect": "Deny", "Principal": {"AWS": ["arn:aws:iam:::user/tester"]},
             "Action": "s3:GetObject", "Resource": "arn:aws:s3:::pribkt/*"},
        ]
    }
    assert _req(s3, "PUT", "/pribkt", _json.dumps(doc).encode(), query="policy")[0] == 204
    # the named identity ("tester" is the stack's admin) is denied
    assert _req(s3, "GET", "/pribkt/a.txt")[0] == 403
    # anonymous does NOT match the named principal; falls through to
    # identity grants and fails there (no credentials)
    assert _req(s3, "GET", "/pribkt/a.txt", sign=False)[0] == 403
    assert _req(s3, "DELETE", "/pribkt", query="policy")[0] == 204
    assert _req(s3, "GET", "/pribkt/a.txt")[0] == 200


def test_policy_evaluator_unit():
    """Wildcard/principal/precedence semantics of the evaluator proper."""
    import pytest as _pytest

    from seaweedfs_tpu.s3api import policy as P

    def ev(doc, **kw):
        kw.setdefault("identity_name", "alice")
        kw.setdefault("access_key", "AKALICE")
        kw.setdefault("anonymous", False)
        return P.evaluate(doc, **kw)

    allow_all = {"Statement": [{"Effect": "Allow", "Principal": "*",
                                "Action": "s3:*", "Resource": "arn:aws:s3:::b/*"}]}
    assert ev(allow_all, action="s3:GetObject", resource="arn:aws:s3:::b/x") is True
    # action matching is case-insensitive; resource matching is not a
    # prefix match — 'b/*' does not cover the bucket ARN itself
    assert ev(allow_all, action="S3:GETOBJECT", resource="arn:aws:s3:::b/x") is True
    assert ev(allow_all, action="s3:ListBucket", resource="arn:aws:s3:::b") is None
    # deny wins over a matching allow regardless of statement order
    doc = {"Statement": [
        {"Effect": "Deny", "Principal": "*", "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::b/priv/*"},
        {"Effect": "Allow", "Principal": "*", "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::b/*"},
    ]}
    assert ev(doc, action="s3:GetObject", resource="arn:aws:s3:::b/priv/x") is False
    assert ev(doc, action="s3:GetObject", resource="arn:aws:s3:::b/pub/x") is True
    # principal forms: bare name, access key, ARN suffix; anonymous only *
    named = {"Statement": [{"Effect": "Allow",
                            "Principal": {"AWS": "arn:aws:iam:::user/alice"},
                            "Action": "s3:GetObject",
                            "Resource": "arn:aws:s3:::b/*"}]}
    assert ev(named, action="s3:GetObject", resource="arn:aws:s3:::b/x") is True
    assert ev(named, identity_name="bob", access_key="AKBOB",
              action="s3:GetObject", resource="arn:aws:s3:::b/x") is None
    assert ev(named, anonymous=True, identity_name="anonymous", access_key="",
              action="s3:GetObject", resource="arn:aws:s3:::b/x") is None
    # '?' wildcard and bracket-literal safety
    q = {"Statement": [{"Effect": "Allow", "Principal": "*",
                        "Action": "s3:GetObject",
                        "Resource": "arn:aws:s3:::b/v?/[data]/*"}]}
    assert ev(q, action="s3:GetObject", resource="arn:aws:s3:::b/v1/[data]/f") is True
    assert ev(q, action="s3:GetObject", resource="arn:aws:s3:::b/v12/[data]/f") is None
    # parse errors
    for raw in (b"nope", b"{}", b'{"Statement": []}',
                b'{"Statement": [{"Effect": "Maybe"}]}'):
        with _pytest.raises(P.PolicyError):
            P.parse_policy(raw, "b")
    with _pytest.raises(P.PolicyError):
        P.parse_policy(
            b'{"Statement": [{"Effect": "Allow", "Principal": "*",'
            b'"Action": "s3:GetObject", "Resource": "arn:aws:s3:::other/*"}]}',
            "b",
        )


def test_object_versioning_lifecycle(stack):
    """The VERDICT's SDK-shaped flow: enable versioning, put 2 versions,
    list them, get the old one by id, delete (marker appears), read old
    versions through the marker, remove the marker (restore)."""
    s3 = stack
    assert _req(s3, "PUT", "/verbkt")[0] == 200
    # pre-versioning object: becomes the 'null' version later
    assert _req(s3, "PUT", "/verbkt/doc.txt", b"v0 pre-versioning")[0] == 200

    # enable
    cfg = (b'<VersioningConfiguration xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
           b"<Status>Enabled</Status></VersioningConfiguration>")
    assert _req(s3, "PUT", "/verbkt", cfg, query="versioning")[0] == 200
    code, _, body = _req(s3, "GET", "/verbkt", query="versioning")
    assert code == 200 and b"<Status>Enabled</Status>" in body

    # two puts -> two version ids
    code, h1, _ = _req(s3, "PUT", "/verbkt/doc.txt", b"version one")
    vid1 = h1.get("x-amz-version-id")
    assert code == 200 and vid1
    code, h2, _ = _req(s3, "PUT", "/verbkt/doc.txt", b"version two!")
    vid2 = h2.get("x-amz-version-id")
    assert code == 200 and vid2 and vid2 != vid1

    # latest read; version-id reads; null version still reachable
    code, h, body = _req(s3, "GET", "/verbkt/doc.txt")
    assert code == 200 and body == b"version two!"
    assert h.get("x-amz-version-id") == vid2
    code, _, body = _req(s3, "GET", f"/verbkt/doc.txt", query=f"versionId={vid1}")
    assert code == 200 and body == b"version one"
    code, _, body = _req(s3, "GET", "/verbkt/doc.txt", query="versionId=null")
    assert code == 200 and body == b"v0 pre-versioning"
    code, _, body = _req(s3, "GET", "/verbkt/doc.txt", query="versionId=" + "0" * 24)
    assert code == 404 and b"NoSuchVersion" in body
    # versionId is path material: anything outside the minted-id/null
    # grammar (e.g. a '..' traversal at another bucket's objects) is 400
    for evil in ("nonexistent", "..%2F..%2Fother%2Fsecret.txt", "a/../b"):
        code, _, body = _req(s3, "GET", "/verbkt/doc.txt", query=f"versionId={evil}")
        assert code == 400, evil
        code, _, _ = _req(s3, "DELETE", "/verbkt/doc.txt", query=f"versionId={evil}")
        assert code == 400, evil

    # list versions: newest first, IsLatest on the head
    code, _, body = _req(s3, "GET", "/verbkt", query="versions")
    assert code == 200
    tree = _xml(body)
    ns = tree.tag[: tree.tag.index("}") + 1]
    vers = tree.findall(f"{ns}Version")
    assert [v.find(f"{ns}VersionId").text for v in vers] == [vid2, vid1, "null"]
    assert [v.find(f"{ns}IsLatest").text for v in vers] == ["true", "false", "false"]

    # plain delete -> marker; key 404s but versions still read
    code, h, _ = _req(s3, "DELETE", "/verbkt/doc.txt")
    assert code == 204 and h.get("x-amz-delete-marker") == "true"
    marker_vid = h.get("x-amz-version-id")
    assert marker_vid
    code, h, _ = _req(s3, "GET", "/verbkt/doc.txt")
    assert code == 404 and h.get("x-amz-delete-marker") == "true"
    code, _, body = _req(s3, "GET", f"/verbkt/doc.txt", query=f"versionId={vid2}")
    assert code == 200 and body == b"version two!"
    # marker shows in the listing as the latest
    code, _, body = _req(s3, "GET", "/verbkt", query="versions")
    tree = _xml(body)
    dms = tree.findall(f"{ns}DeleteMarker")
    assert len(dms) == 1 and dms[0].find(f"{ns}IsLatest").text == "true"
    assert dms[0].find(f"{ns}VersionId").text == marker_vid
    # GET of the marker version itself is 405
    assert _req(s3, "GET", f"/verbkt/doc.txt", query=f"versionId={marker_vid}")[0] == 405

    # deleting the marker restores the newest real version
    code, h, _ = _req(s3, "DELETE", f"/verbkt/doc.txt", query=f"versionId={marker_vid}")
    assert code == 204 and h.get("x-amz-delete-marker") == "true"
    code, _, body = _req(s3, "GET", "/verbkt/doc.txt")
    assert code == 200 and body == b"version two!"

    # permanent delete of the latest promotes the next-newest
    code, _, _ = _req(s3, "DELETE", f"/verbkt/doc.txt", query=f"versionId={vid2}")
    assert code == 204
    code, _, body = _req(s3, "GET", "/verbkt/doc.txt")
    assert code == 200 and body == b"version one"
    code, _, body = _req(s3, "GET", "/verbkt", query="versions")
    tree = _xml(body)
    vers = tree.findall(f"{ns}Version")
    assert [v.find(f"{ns}VersionId").text for v in vers] == [vid1, "null"]

    # versioned keys stay invisible to plain listings' archives
    code, _, body = _req(s3, "GET", "/verbkt")
    assert body.count(b"<Key>doc.txt</Key>") == 1 and b".s3versions" not in body

    # reserved suffix refused everywhere
    assert _req(s3, "PUT", "/verbkt/evil.s3versions", b"x")[0] == 400
    assert _req(s3, "PUT", "/verbkt/a.s3versions/b", b"x")[0] == 400


def test_versioning_suspended_and_bulk_markers(stack):
    """Suspended buckets overwrite the 'null' version in place but keep
    the archive readable; bulk DeleteObjects plants markers when enabled."""
    s3 = stack
    assert _req(s3, "PUT", "/susbkt")[0] == 200
    cfg_on = (b"<VersioningConfiguration><Status>Enabled</Status>"
              b"</VersioningConfiguration>")
    cfg_off = (b"<VersioningConfiguration><Status>Suspended</Status>"
               b"</VersioningConfiguration>")
    assert _req(s3, "PUT", "/susbkt", cfg_on, query="versioning")[0] == 200
    code, h, _ = _req(s3, "PUT", "/susbkt/f.txt", b"enabled-era")
    vid_real = h.get("x-amz-version-id")
    assert vid_real and vid_real != "null"
    assert _req(s3, "PUT", "/susbkt", cfg_off, query="versioning")[0] == 200
    # suspended puts carry the null id and replace each other
    code, h, _ = _req(s3, "PUT", "/susbkt/f.txt", b"null one")
    assert h.get("x-amz-version-id") == "null"
    code, h, _ = _req(s3, "PUT", "/susbkt/f.txt", b"null two")
    assert h.get("x-amz-version-id") == "null"
    code, _, body = _req(s3, "GET", "/susbkt/f.txt")
    assert body == b"null two"
    # the enabled-era version survived the suspended overwrites
    code, _, body = _req(s3, "GET", "/susbkt/f.txt", query=f"versionId={vid_real}")
    assert code == 200 and body == b"enabled-era"
    code, _, body = _req(s3, "GET", "/susbkt", query="versions")
    tree = _xml(body)
    ns = tree.tag[: tree.tag.index("}") + 1]
    vids = [v.find(f"{ns}VersionId").text for v in tree.findall(f"{ns}Version")]
    assert vids == ["null", vid_real]

    # bulk delete on an Enabled bucket reports the marker per key
    assert _req(s3, "PUT", "/susbkt", cfg_on, query="versioning")[0] == 200
    payload = (b"<Delete><Object><Key>f.txt</Key></Object></Delete>")
    code, _, body = _req(s3, "POST", "/susbkt", payload, query="delete")
    assert code == 200 and b"<DeleteMarker>true</DeleteMarker>" in body
    assert _req(s3, "GET", "/susbkt/f.txt")[0] == 404
    # both old versions still listed beneath the marker
    code, _, body = _req(s3, "GET", "/susbkt", query="versions")
    tree = _xml(body)
    assert len(tree.findall(f"{ns}Version")) == 2
    assert len(tree.findall(f"{ns}DeleteMarker")) == 1


def test_multipart_upload_versioned_bucket(stack):
    """CompleteMultipartUpload on a versioned bucket mints a version id
    and archives the previous latest instead of destroying it."""
    s3 = stack
    assert _req(s3, "PUT", "/mpver")[0] == 200
    cfg = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
    assert _req(s3, "PUT", "/mpver", cfg, query="versioning")[0] == 200
    code, h, _ = _req(s3, "PUT", "/mpver/big.bin", b"old small version")
    old_vid = h.get("x-amz-version-id")
    code, _, body = _req(s3, "POST", "/mpver/big.bin", query="uploads=")
    upload_id = _xml(body).find(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId").text
    part = os.urandom(256 * 1024)
    code, headers, _ = _req(
        s3, "PUT", "/mpver/big.bin", part,
        query=f"partNumber=1&uploadId={upload_id}",
    )
    etag = headers["ETag"]
    done = (f"<CompleteMultipartUpload><Part><PartNumber>1</PartNumber>"
            f"<ETag>{etag}</ETag></Part></CompleteMultipartUpload>").encode()
    code, h, _ = _req(s3, "POST", "/mpver/big.bin", done, query=f"uploadId={upload_id}")
    new_vid = h.get("x-amz-version-id")
    assert code == 200 and new_vid and new_vid != old_vid
    code, _, body = _req(s3, "GET", "/mpver/big.bin")
    assert code == 200 and body == part
    code, _, body = _req(s3, "GET", "/mpver/big.bin", query=f"versionId={old_vid}")
    assert code == 200 and body == b"old small version"


def test_policy_binds_copy_source_and_bulk_delete(stack):
    """A policy-denied object must not leak through CopyObject, and
    per-prefix s3:DeleteObject denies must bind inside bulk DeleteObjects
    (both bypass the plain per-request resource check)."""
    import json as _json

    s3 = stack
    assert _req(s3, "PUT", "/srcb")[0] == 200
    assert _req(s3, "PUT", "/dstb")[0] == 200
    assert _req(s3, "PUT", "/srcb/secret/x.txt", b"classified")[0] == 200
    assert _req(s3, "PUT", "/srcb/keep/y.txt", b"precious")[0] == 200
    doc = {"Statement": [
        {"Effect": "Deny", "Principal": "*", "Action": "s3:GetObject",
         "Resource": "arn:aws:s3:::srcb/secret/*"},
        {"Effect": "Deny", "Principal": "*", "Action": "s3:DeleteObject",
         "Resource": "arn:aws:s3:::srcb/keep/*"},
    ]}
    assert _req(s3, "PUT", "/srcb", _json.dumps(doc).encode(), query="policy")[0] == 204
    # CopyObject with a denied source: 403, nothing written
    code, _, body = _req(s3, "PUT", "/dstb/stolen.txt",
                         headers={"x-amz-copy-source": "/srcb/secret/x.txt"})
    assert code == 403 and b"source bucket policy" in body
    assert _req(s3, "GET", "/dstb/stolen.txt")[0] == 404
    # UploadPartCopy rides the same resolver
    code, _, body = _req(s3, "POST", "/dstb/big.bin", query="uploads=")
    upload_id = _xml(body).find(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId").text
    code, _, _ = _req(s3, "PUT", "/dstb/big.bin",
                      query=f"partNumber=1&uploadId={upload_id}",
                      headers={"x-amz-copy-source": "/srcb/secret/x.txt"})
    assert code == 403
    # bulk delete: the protected prefix survives, the rest deletes
    payload = (b"<Delete><Object><Key>keep/y.txt</Key></Object>"
               b"<Object><Key>secret/x.txt</Key></Object></Delete>")
    code, _, body = _req(s3, "POST", "/srcb", payload, query="delete")
    assert code == 200
    assert b"<Code>AccessDenied</Code>" in body and b"keep/y.txt" in body
    assert _req(s3, "GET", "/srcb/keep/y.txt")[0] == 200  # survived
    # the unprotected key really went (GetObject denied -> check via list)
    code, _, listing = _req(s3, "GET", "/srcb")
    assert b"secret/x.txt" not in listing
    assert _req(s3, "DELETE", "/srcb", query="policy")[0] == 204


def test_versioning_suspended_delete_removes_null(stack):
    """DELETE (no versionId) on a Suspended bucket removes the 'null'
    version and leaves a null marker — the key must read 404, not serve
    the supposedly deleted bytes."""
    s3 = stack
    assert _req(s3, "PUT", "/susdel")[0] == 200
    cfg = (b"<VersioningConfiguration><Status>Suspended</Status>"
           b"</VersioningConfiguration>")
    assert _req(s3, "PUT", "/susdel", cfg, query="versioning")[0] == 200
    assert _req(s3, "PUT", "/susdel/f.txt", b"null bytes")[0] == 200
    code, h, _ = _req(s3, "DELETE", "/susdel/f.txt")
    assert code == 204 and h.get("x-amz-delete-marker") == "true"
    assert h.get("x-amz-version-id") == "null"
    assert _req(s3, "GET", "/susdel/f.txt")[0] == 404
    code, _, body = _req(s3, "GET", "/susdel", query="versions")
    tree = _xml(body)
    ns = tree.tag[: tree.tag.index("}") + 1]
    assert len(tree.findall(f"{ns}Version")) == 0
    assert len(tree.findall(f"{ns}DeleteMarker")) == 1


def test_policy_rejects_unsupported_statement_fields(stack):
    """A Condition the evaluator does not implement must be rejected at
    PUT time — silently ignoring it would turn a conditional Allow into
    an unconditional public grant."""
    import json as _json

    s3 = stack
    assert _req(s3, "PUT", "/uncond")[0] == 200
    doc = {"Statement": [{"Effect": "Allow", "Principal": "*",
                          "Action": "s3:GetObject",
                          "Resource": "arn:aws:s3:::uncond/*",
                          "Condition": {"IpAddress": {"aws:SourceIp": "10.0.0.0/8"}}}]}
    code, _, body = _req(s3, "PUT", "/uncond", _json.dumps(doc).encode(), query="policy")
    assert code == 400 and b"Condition" in body
    assert _req(s3, "GET", "/uncond", query="policy")[0] == 404  # nothing stored


def test_copy_object_from_specific_version(stack):
    """x-amz-copy-source with ?versionId addresses an archived version;
    markers and bogus ids answer 404/400; the reply names the source
    version (x-amz-copy-source-version-id)."""
    s3 = stack
    assert _req(s3, "PUT", "/cpver")[0] == 200
    assert _req(s3, "PUT", "/cpdst")[0] == 200
    cfg = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
    assert _req(s3, "PUT", "/cpver", cfg, query="versioning")[0] == 200
    _, h1, _ = _req(s3, "PUT", "/cpver/f.txt", b"old version bytes")
    vid1 = h1.get("x-amz-version-id")
    _, h2, _ = _req(s3, "PUT", "/cpver/f.txt", b"new version bytes")
    assert h2.get("x-amz-version-id") != vid1
    # copy the OLD version into another bucket
    code, ch, body = _req(
        s3, "PUT", "/cpdst/restored.txt",
        headers={"x-amz-copy-source": f"/cpver/f.txt?versionId={vid1}"},
    )
    assert code == 200 and ch.get("x-amz-copy-source-version-id") == vid1
    assert _req(s3, "GET", "/cpdst/restored.txt")[2] == b"old version bytes"
    # a delete marker version has no bytes to copy
    _, dh, _ = _req(s3, "DELETE", "/cpver/f.txt")
    marker = dh.get("x-amz-version-id")
    code, _, body = _req(
        s3, "PUT", "/cpdst/nope.txt",
        headers={"x-amz-copy-source": f"/cpver/f.txt?versionId={marker}"},
    )
    assert code == 400 and b"delete marker" in body  # AWS: InvalidRequest
    # malformed version ids are rejected as path material
    code, _, _ = _req(
        s3, "PUT", "/cpdst/nope2.txt",
        headers={"x-amz-copy-source": "/cpver/f.txt?versionId=../../evil"},
    )
    assert code == 400
    # UploadPartCopy names the source version in its reply too
    code, _, body = _req(s3, "POST", "/cpdst/mp.bin", query="uploads=")
    upload_id = _xml(body).find(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId").text
    code, ph, body = _req(
        s3, "PUT", "/cpdst/mp.bin",
        query=f"partNumber=1&uploadId={upload_id}",
        headers={"x-amz-copy-source": f"/cpver/f.txt?versionId={vid1}"},
    )
    assert code == 200 and ph.get("x-amz-copy-source-version-id") == vid1
    assert b"CopyPartResult" in body
    _req(s3, "DELETE", "/cpdst/mp.bin", query=f"uploadId={upload_id}")


def test_delete_object_prunes_empty_folders(stack):
    """Deleting the last object under a nested prefix removes the empty
    folder husks, so an emptied bucket can actually be deleted (AWS has
    no real folders)."""
    s3 = stack
    assert _req(s3, "PUT", "/prune")[0] == 200
    assert _req(s3, "PUT", "/prune/a/b/c/deep.txt", b"x")[0] == 200
    assert _req(s3, "PUT", "/prune/a/side.txt", b"y")[0] == 200
    assert _req(s3, "DELETE", "/prune/a/b/c/deep.txt")[0] == 204
    # /a survives (side.txt), /a/b and /a/b/c are pruned
    code, _, body = _req(s3, "GET", "/prune", query="list-type=2")
    assert b"side.txt" in body and b"a/b" not in body
    assert _req(s3, "DELETE", "/prune/a/side.txt")[0] == 204
    code, _, _ = _req(s3, "DELETE", "/prune")
    assert code == 204  # fully prunable: bucket delete succeeds


def test_versioned_bucket_fully_emptied_is_deletable(stack):
    """Permanently deleting every version and marker of every key must
    leave a versioned bucket deletable (archive dirs and folder husks
    pruned)."""
    s3 = stack
    assert _req(s3, "PUT", "/vprune")[0] == 200
    cfg = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
    assert _req(s3, "PUT", "/vprune", cfg, query="versioning")[0] == 200
    _, h1, _ = _req(s3, "PUT", "/vprune/a/b/f.txt", b"v1")
    vid1 = h1.get("x-amz-version-id")
    _, dh, _ = _req(s3, "DELETE", "/vprune/a/b/f.txt")  # marker
    marker = dh.get("x-amz-version-id")
    assert _req(s3, "DELETE", "/vprune/a/b/f.txt", query=f"versionId={marker}")[0] == 204
    # marker gone re-exposed v1 at the plain path; now delete it for good
    assert _req(s3, "DELETE", "/vprune/a/b/f.txt", query=f"versionId={vid1}")[0] == 204
    code, _, body = _req(s3, "GET", "/vprune", query="versions")
    tree = _xml(body)
    ns = tree.tag[: tree.tag.index("}") + 1]
    assert not tree.findall(f"{ns}Version") and not tree.findall(f"{ns}DeleteMarker")
    assert _req(s3, "DELETE", "/vprune")[0] == 204  # no husks left


def test_version_granular_policy_actions(stack):
    """Versioned requests authorize under the s3:*Version action names:
    a public-read grant of s3:GetObject must NOT expose ?versionId reads,
    s3:ListBucket must not expose ?versions listings, and a Deny written
    against s3:DeleteObjectVersion actually matches."""
    import json as _json

    s3 = stack
    assert _req(s3, "PUT", "/vact")[0] == 200
    cfg = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
    assert _req(s3, "PUT", "/vact", cfg, query="versioning")[0] == 200
    _, h1, _ = _req(s3, "PUT", "/vact/doc.txt", b"old version")
    vid1 = h1.get("x-amz-version-id")
    _, h2, _ = _req(s3, "PUT", "/vact/doc.txt", b"new version")
    assert vid1 and h2.get("x-amz-version-id")

    # public-read: base actions only
    doc = {"Statement": [
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:GetObject", "s3:ListBucket"],
         "Resource": ["arn:aws:s3:::vact", "arn:aws:s3:::vact/*"]},
    ]}
    assert _req(s3, "PUT", "/vact", _json.dumps(doc).encode(), query="policy")[0] == 204
    code, _, body = _req(s3, "GET", "/vact/doc.txt", sign=False)
    assert code == 200 and body == b"new version"
    assert _req(s3, "GET", "/vact", sign=False)[0] == 200
    # ...but historical versions and version listings stay closed
    assert _req(s3, "GET", "/vact/doc.txt", sign=False, query=f"versionId={vid1}")[0] == 403
    assert _req(s3, "GET", "/vact", sign=False, query="versions")[0] == 403
    # a BLANK ?versionId= is served as the current object, so it must
    # authorize under the base name (and not smuggle past a base Deny)
    code, _, body = _req(s3, "GET", "/vact/doc.txt", sign=False, query="versionId=")
    assert code == 200 and body == b"new version"
    # duplicate versionId keys: authorization and serving must agree on
    # the SAME (first) value — a trailing blank copy must not downgrade
    # the action name to s3:GetObject while the handler serves <vid1>
    code, _, _ = _req(
        s3, "GET", "/vact/doc.txt", sign=False, query=f"versionId={vid1}&versionId="
    )
    assert code == 403

    # granting the *Version names opens exactly those
    doc["Statement"].append(
        {"Effect": "Allow", "Principal": "*",
         "Action": ["s3:GetObjectVersion", "s3:ListBucketVersions"],
         "Resource": ["arn:aws:s3:::vact", "arn:aws:s3:::vact/*"]})
    assert _req(s3, "PUT", "/vact", _json.dumps(doc).encode(), query="policy")[0] == 204
    code, _, body = _req(s3, "GET", "/vact/doc.txt", sign=False, query=f"versionId={vid1}")
    assert code == 200 and body == b"old version"
    assert _req(s3, "GET", "/vact", sign=False, query="versions")[0] == 200

    # a Deny on s3:DeleteObjectVersion binds the signed identity's
    # permanent versioned deletes but not its logical (marker) delete
    deny = {"Statement": [
        {"Effect": "Deny", "Principal": "*", "Action": "s3:DeleteObjectVersion",
         "Resource": "arn:aws:s3:::vact/*"},
    ]}
    assert _req(s3, "PUT", "/vact", _json.dumps(deny).encode(), query="policy")[0] == 204
    assert _req(s3, "DELETE", "/vact/doc.txt", query=f"versionId={vid1}")[0] == 403
    code, dh, _ = _req(s3, "DELETE", "/vact/doc.txt")  # marker: s3:DeleteObject
    assert code == 204 and dh.get("x-amz-delete-marker") == "true"
    assert _req(s3, "DELETE", "/vact", query="policy")[0] == 204


def test_version_archive_pagination(stack, monkeypatch):
    """A key with more versions than one filer page must keep its NEWEST
    versions visible: promotion after a permanent delete of the latest must
    pick the true next-newest (the one-shot limited listing used to drop
    it), and ListObjectVersions must show every record."""
    from seaweedfs_tpu.s3api import server as s3server

    monkeypatch.setattr(s3server._Handler, "_VERSION_PAGE", 3)
    s3 = stack
    assert _req(s3, "PUT", "/vpage")[0] == 200
    cfg = b"<VersioningConfiguration><Status>Enabled</Status></VersioningConfiguration>"
    assert _req(s3, "PUT", "/vpage", cfg, query="versioning")[0] == 200
    vids = []
    for i in range(8):
        _, h, _ = _req(s3, "PUT", "/vpage/k.txt", f"content {i}".encode())
        vids.append(h.get("x-amz-version-id"))
    assert all(vids) and len(set(vids)) == 8
    # permanent delete of the live latest: promotion must resurrect
    # version 6 (newest archived), which lives beyond the first page
    assert _req(s3, "DELETE", "/vpage/k.txt", query=f"versionId={vids[7]}")[0] == 204
    code, _, body = _req(s3, "GET", "/vpage/k.txt")
    assert code == 200 and body == b"content 6"
    # the listing walks every page: all 7 remaining versions show
    code, _, body = _req(s3, "GET", "/vpage", query="versions")
    tree = _xml(body)
    ns = tree.tag[: tree.tag.index("}") + 1]
    listed = {v.find(f"{ns}VersionId").text for v in tree.findall(f"{ns}Version")}
    assert listed == set(vids[:7])


def test_policy_and_versioning_caches_stay_bounded(stack):
    """Unauthenticated probes of nonexistent buckets must not grow the
    policy/versioning caches, expired entries are evicted on insert, and
    the size cap holds."""
    s3 = stack
    assert _req(s3, "PUT", "/cachebkt")[0] == 200
    for i in range(50):
        assert s3.get_bucket_policy(f"no-such-bucket-{i}") is None
        assert s3.get_bucket_versioning(f"no-such-bucket-{i}") == ""
    assert not any(k.startswith("no-such-") for k in s3._policy_cache)
    assert not any(k.startswith("no-such-") for k in s3._versioning_cache)
    # expired entries go on the next insert (the TTL used to only gate reuse)
    s3._policy_cache["stale-entry"] = (0.0, None)
    s3.get_bucket_policy("cachebkt")
    assert "stale-entry" not in s3._policy_cache
    assert "cachebkt" in s3._policy_cache
    # cap: a flood past _CACHE_MAX resets rather than grows
    import time as _time

    cache = {}
    now = _time.monotonic()
    for i in range(type(s3)._CACHE_MAX + 10):
        type(s3)._cache_put(cache, f"b{i}", None, now)
    assert len(cache) <= type(s3)._CACHE_MAX + 1
