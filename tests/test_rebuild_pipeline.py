"""Pipelined batched rebuild tests (the repair-path mirror of the encode
pipeline): `rebuild_ec_files` must stay byte-identical to the serial golden
path across geometries, every loss-pattern count (data/parity/mixed), and
non-multiple tail chunks — while issuing ONE device dispatch per batch.
`Encoder.reconstruct_batch`/`reconstruct_lazy` must match the per-call
`reconstruct` oracle, and `EcVolume.read_intervals`' batched degraded
recovery must match per-interval recovery."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder

ENC = Encoder(10, 4, backend="numpy")

# 1-4 missing shards: data-only, parity-only, and mixed patterns
LOSS_PATTERNS = [
    [2],
    [12],
    [0, 9],
    [11, 13],
    [3, 12],
    [0, 1, 2],
    [1, 10, 13],
    [0, 1, 2, 3],
    [10, 11, 12, 13],
    [0, 5, 11, 13],
]


def _make_volume(tmp_path, size, large=16384, small=4096, seed=1):
    base = os.path.join(str(tmp_path), "v")
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    stripe.write_ec_files(
        base, large_block_size=large, small_block_size=small, encoder=ENC
    )
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    return base, golden


def _check_rebuild(base, golden, lost, enc, **kw):
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    rebuilt = stripe.rebuild_ec_files(base, encoder=enc, **kw)
    assert rebuilt == sorted(lost)
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s], f"shard {s} differs after losing {lost}"


@pytest.mark.parametrize("lost", LOSS_PATTERNS)
def test_batched_rebuild_matches_serial_golden(tmp_path, lost):
    """Every loss-pattern count, against shards produced (and re-derivable)
    by the serial path — the pre-change byte-identity contract."""
    base, golden = _make_volume(tmp_path, size=655_360)
    _check_rebuild(base, golden, lost, ENC, buffer_size=8192, max_batch_bytes=10 * 3 * 8192)
    # and the serial oracle itself reproduces the same bytes
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    assert stripe.rebuild_ec_files_serial(base, encoder=ENC, buffer_size=8192) == sorted(lost)
    for s in lost:
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s]


@pytest.mark.parametrize(
    "size",
    [
        1,  # tiny: single zero-padded small row
        123_457,  # prime-ish: small-row tail, shard not a buffer multiple
        163_840 * 10 + 7,  # just past one large row
    ],
)
def test_batched_rebuild_tail_geometries(tmp_path, size):
    """Non-multiple tails: the zero-padded tail chunk must trim back to the
    exact shard length (large/small two-tier geometry included)."""
    base, golden = _make_volume(tmp_path, size=size)
    _check_rebuild(
        base, golden, [0, 5, 11, 13], ENC, buffer_size=8192, max_batch_bytes=10 * 4 * 8192
    )


@pytest.mark.parametrize("backend", ["jax"])
def test_batched_rebuild_device_backend_matches(tmp_path, backend):
    base, golden = _make_volume(tmp_path, size=200_000)
    enc = Encoder(10, 4, backend=backend)
    _check_rebuild(base, golden, [1, 6, 12], enc, buffer_size=8192)


def test_rebuild_one_dispatch_per_batch(tmp_path):
    """The acceptance criterion: dispatches scale with batches (ceil of
    chunks / batch-cap), never with chunks — now as flat (survivors, width)
    slabs, one wide matmul per batch."""
    base, golden = _make_volume(tmp_path, size=655_360)  # shard = 65536 B
    calls = []
    orig = Encoder.reconstruct_lazy

    class Counting(Encoder):
        def reconstruct_lazy(self, stack, survivors, wanted, **kw):
            calls.append(stack.shape)
            return orig(self, stack, survivors, wanted, **kw)

    enc = Counting(10, 4, backend="numpy")
    # 8 chunks of 8 KiB per shard; cap = 3 chunks/batch -> 3 dispatches
    _check_rebuild(
        base, golden, [0, 13], enc, buffer_size=8192, max_batch_bytes=3 * 10 * 8192
    )
    assert len(calls) == 3, f"want 3 batch dispatches for 8 chunks, got {calls}"
    assert [c for c in calls] == [(10, 3 * 8192), (10, 3 * 8192), (10, 2 * 8192)]


def test_rebuild_too_few_survivors_raises(tmp_path):
    base, _ = _make_volume(tmp_path, size=65_536)
    for s in range(5):
        os.unlink(stripe.shard_file_name(base, s))
    with pytest.raises(ValueError, match="cannot rebuild"):
        stripe.rebuild_ec_files(base, encoder=ENC)


def test_rebuild_truncated_survivor_raises(tmp_path):
    base, _ = _make_volume(tmp_path, size=65_536)
    os.unlink(stripe.shard_file_name(base, 3))
    p = stripe.shard_file_name(base, 7)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(IOError, match="disagree"):
        stripe.rebuild_ec_files(base, encoder=ENC)


# -- codec-level batched reconstruct -----------------------------------------


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("lost", [[0], [13], [0, 5, 11, 13]])
def test_reconstruct_batch_matches_oracle(backend, lost):
    rng = np.random.default_rng(3)
    full = ENC.encode([rng.integers(0, 256, 777, dtype=np.uint8) for _ in range(10)])
    survivors = [i for i in range(14) if i not in lost][:10]
    stack = np.stack([[full[s] for s in survivors] for _ in range(4)])
    enc = Encoder(10, 4, backend=backend)
    out = enc.reconstruct_batch(stack, survivors, lost)
    assert out.shape == (4, len(lost), 777)
    for b in range(4):
        for k, w in enumerate(lost):
            np.testing.assert_array_equal(out[b, k], full[w], err_msg=f"shard {w}")
    # the lazy form materializes to the same bytes
    np.testing.assert_array_equal(
        np.asarray(enc.reconstruct_lazy(stack, survivors, lost)), out
    )
    # bucketed form (pads to the serving buckets on device backends)
    np.testing.assert_array_equal(
        enc.reconstruct_batch(stack, survivors, lost, bucketed=True), out
    )


def test_reconstruct_batch_validates():
    stack = np.zeros((2, 10, 16), dtype=np.uint8)
    with pytest.raises(ValueError, match="distinct"):
        ENC.reconstruct_batch(stack, [0] * 10, [13])
    with pytest.raises(ValueError, match="at least one"):
        ENC.reconstruct_batch(stack, list(range(10)), [])
    with pytest.raises(ValueError, match="out of range"):
        ENC.reconstruct_batch(stack, list(range(10)), [14])
    with pytest.raises(ValueError, match="want"):
        ENC.reconstruct_batch(np.zeros((10, 16), np.uint8), list(range(10)), [13])


# -- EcVolume batched degraded-interval recovery ------------------------------


def test_read_intervals_batched_recovery_matches_per_interval(tmp_path):
    """A degraded volume's read_intervals (batched) must return exactly the
    bytes the per-interval recover ladder returns, and fuse the recovery of
    same-shard intervals into ONE reconstruct_batch call."""
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types

    large, small = 1024, 64
    rng = np.random.default_rng(17)
    base = str(tmp_path / "vol")
    records = {}
    offset = types.NEEDLE_PADDING_SIZE
    blobs = [b"\x03" + bytes(7)]
    for nid in range(1, 40):
        # big enough that many records span a full small row (10 x 64 B),
        # so one needle's intervals revisit the same (possibly missing)
        # shard — the case the batched recovery fuses
        body = int(rng.integers(100, 1800))
        total = types.actual_size(body, version=3)
        rec = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
        records[nid] = (offset, body, rec)
        blobs.append(rec)
        offset += total
    with open(base + ".dat", "wb") as f:
        f.write(b"".join(blobs))
    idx_mod.write_entries(
        [(nid, types.offset_to_bytes(off), sz) for nid, (off, sz, _) in records.items()],
        base + ".idx",
    )
    stripe.write_ec_files(
        base, large_block_size=large, small_block_size=small, buffer_size=64, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    for s in (0, 4, 11):
        os.remove(stripe.shard_file_name(base, s))

    batch_calls = []
    orig_batch = Encoder.reconstruct_batch

    class Counting(Encoder):
        def reconstruct_batch(self, stack, survivors, wanted, bucketed=False):
            batch_calls.append(stack.shape[0])
            return orig_batch(self, stack, survivors, wanted, bucketed)

    enc = Counting(10, 4, backend="numpy")
    with EcVolume(
        base, encoder=enc, large_block_size=large, small_block_size=small,
        warm_on_mount=False,
    ) as ev:
        multi = 0
        for nid, (off, sz, rec) in records.items():
            _, _, intervals = ev.locate_needle(nid)
            got = ev.read_intervals(intervals)
            assert got[: len(rec)] == rec, f"needle {nid}"
            # oracle: the per-interval single-recover ladder
            per = b"".join(
                ev._read_shard_interval(
                    *iv.to_shard_id_and_offset(large, small), iv.size
                ).tobytes()
                for iv in intervals
            )
            assert got == per, f"needle {nid}: batched != per-interval"
            on_missing = [
                iv.to_shard_id_and_offset(large, small)[0]
                for iv in intervals
                if iv.to_shard_id_and_offset(large, small)[0] in (0, 4, 11)
            ]
            if len(on_missing) > len(set(on_missing)):
                multi += 1  # >=2 intervals miss the SAME shard
        assert multi > 0, "fixture must exercise multi-interval degraded reads"
    assert any(b > 1 for b in batch_calls), (
        f"no multi-interval recovery was batched: {batch_calls}"
    )
