"""TLS/mTLS cluster tests (weed/security/tls.go analog, VERDICT r3 #7):
self-signed CA + leaf, process-wide activation, then a real master +
volume-server cluster doing assign/upload/read/delete with the gRPC
control plane on mTLS and the HTTP data path on HTTPS."""

import ssl
import urllib.request

import grpc
import pytest

from seaweedfs_tpu import rpc
from seaweedfs_tpu.security import tls

# cert generation needs the cryptography package; when the image lacks it
# (this one does) the whole suite must SKIP, not error at fixture setup —
# an optional dependency is not a test failure
pytest.importorskip("cryptography", reason="cryptography not installed in image")


@pytest.fixture()
def certs(tmp_path):
    paths = tls.generate_self_signed(str(tmp_path / "certs"))
    yield paths
    tls.reset()


def test_generate_self_signed_material(certs):
    for p in certs.values():
        pem = open(p, "rb").read()
        assert b"BEGIN" in pem


def test_rpc_over_mtls_and_plaintext_rejected(certs):
    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        override_authority="weedtpu-cluster",
    )
    server = rpc.RpcServer(port=0)
    svc = rpc.Service("weedtpu.Test")
    svc.add("Echo", lambda req, ctx: {"echo": req.get("x")})
    server.add_service(svc)
    server.start()
    try:
        with rpc.RpcClient(f"127.0.0.1:{server.port}") as c:
            assert c.call("weedtpu.Test", "Echo", {"x": 42}, timeout=10) == {"echo": 42}
        # a plaintext client must NOT get through a TLS server
        tls.reset()
        with rpc.RpcClient(f"127.0.0.1:{server.port}") as c:
            with pytest.raises(grpc.RpcError):
                c.call("weedtpu.Test", "Echo", {"x": 1}, timeout=3)
    finally:
        server.stop()


def test_mtls_rejects_unauthenticated_client(certs, tmp_path):
    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        override_authority="weedtpu-cluster",
    )
    server = rpc.RpcServer(port=0)
    svc = rpc.Service("weedtpu.Test")
    svc.add("Echo", lambda req, ctx: {"ok": True})
    server.add_service(svc)
    server.start()
    try:
        # client trusts the CA but presents NO certificate: the mTLS
        # handshake must fail
        creds = grpc.ssl_channel_credentials(
            root_certificates=open(certs["ca"], "rb").read()
        )
        ch = grpc.secure_channel(
            f"127.0.0.1:{server.port}",
            creds,
            options=[("grpc.ssl_target_name_override", "weedtpu-cluster")],
        )
        stub = ch.unary_unary(
            "/weedtpu.Test/Echo",
            request_serializer=lambda o: b"{}",
            response_deserializer=lambda b: b,
        )
        with pytest.raises(grpc.RpcError):
            stub({}, timeout=3)
        ch.close()
    finally:
        server.stop()


def test_plaintext_probe_does_not_block_https_server(certs, tmp_path):
    """The TLS handshake runs in the per-connection worker, not accept():
    an idle/plaintext probe must not park the server's accept loop."""
    import socket

    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        https=True, override_authority="weedtpu-cluster",
    )
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
    vs.start()
    try:
        # park a raw TCP connection that never handshakes
        probe = socket.create_connection((vs.host, vs.port), timeout=5)
        try:
            import time

            t0 = time.monotonic()
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.load_verify_locations(certs["ca"])
            ctx.load_cert_chain(certs["cert"], certs["key"])
            ctx.check_hostname = False
            # a real HTTPS request on a second connection must go through
            # promptly while the probe is still parked
            with urllib.request.urlopen(
                f"https://{vs.host}:{vs.port}/status", timeout=10, context=ctx
            ) as r:
                assert r.status == 200
            assert time.monotonic() - t0 < 5, "probe blocked the accept loop"
        finally:
            probe.close()
    finally:
        vs.stop()
        master.stop()


def test_filer_and_gateway_paths_over_tls(certs, tmp_path):
    """The filer's chunked upload (filer -> master assign -> volume POST)
    and read-back ride HTTPS end to end — the converted gateway/filer
    urlopen sites, not just the raw volume data path."""
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer import FilerServer

    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        https=True, override_authority="weedtpu-cluster",
    )
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address, chunk_size=1024, log_dir=str(tmp_path / "meta"))
    fs.start()
    try:
        import os

        payload = os.urandom(5000)  # > chunk_size: multi-chunk upload
        req = urllib.request.Request(
            f"https://{fs.url}/dir/blob.bin", data=payload, method="PUT"
        )
        with tls.urlopen(req, timeout=30) as r:
            assert r.status in (200, 201)
        with tls.urlopen(f"https://{fs.url}/dir/blob.bin", timeout=30) as r:
            assert r.read() == payload
    finally:
        fs.stop()
        vs.stop()
        master.stop()


def test_configure_rejects_cert_without_key(certs):
    with pytest.raises(ValueError, match="must be set together"):
        tls.configure(certs["ca"], certs["cert"], "")


def test_https_without_ca_fails_closed():
    """[https] enabled with no [grpc] ca must error, not silently serve
    plaintext."""
    with pytest.raises(ValueError, match="requires"):
        tls.configure_from_conf({"https": {"enabled": True}})


def test_https_mtls_rejects_anonymous_data_client(certs, tmp_path):
    """require_client_auth on the data path is enforced by the handshake:
    a CA-trusting client with NO certificate is refused."""
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        https=True, override_authority="weedtpu-cluster",
    )
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "v"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
    vs.start()
    try:
        anon = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        anon.load_verify_locations(certs["ca"])
        anon.check_hostname = False
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"https://{vs.host}:{vs.port}/status", timeout=5, context=anon
            )
    finally:
        vs.stop()
        master.stop()


def test_cluster_e2e_over_tls(certs, tmp_path):
    """The §3.1 write/read stack with every hop encrypted: heartbeats,
    assign, replication fan-out, reads, deletes."""
    from seaweedfs_tpu.cluster.client import ClusterError, MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    tls.configure(
        certs["ca"], certs["cert"], certs["key"],
        https=True,
        override_authority="weedtpu-cluster",
    )
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    try:
        for i in range(2):
            d = tmp_path / f"srv{i}"
            d.mkdir()
            vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
            vs.start()
            servers.append(vs)
        client = MasterClient(master.address)
        import os

        payload = os.urandom(30_000)
        res = client.submit(payload, replication="001")
        assert client.read(res.fid) == payload

        # the data path is genuinely TLS: a plain-HTTP GET must fail
        vid = int(res.fid.split(",")[0])
        holder = next(s for s in servers if s.store.get_volume(vid) is not None)
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://{holder.url}/{res.fid}", timeout=3)
        # and an HTTPS GET with the cluster CA succeeds
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(certs["ca"])
        ctx.load_cert_chain(certs["cert"], certs["key"])
        ctx.check_hostname = False
        with urllib.request.urlopen(
            f"https://{holder.url}/{res.fid}", timeout=10, context=ctx
        ) as r:
            assert r.read() == payload

        assert client.delete(res.fid)
        with pytest.raises(ClusterError):
            client.read(res.fid)
        client.close()
    finally:
        for vs in servers:
            vs.stop()
        master.stop()
