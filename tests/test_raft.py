"""Master HA tests: raft leader election, failover, redirects, and id
watermark continuity (the reference's master quorum behavior, SURVEY.md
§1/§2.1 "Master" row)."""

import time

import pytest

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer

FAST = (0.25, 0.5)  # election timeout range for tests


def _wait_for_leader(masters, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [m for m in masters if m.raft is not None and m.raft.is_leader]
        if len(leaders) == 1:
            # all followers agree on it
            agreed = all(
                m.raft.leader == leaders[0].address
                for m in masters
                if m is not leaders[0]
            )
            if agreed:
                return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no stable leader elected")


@pytest.fixture
def quorum(tmp_path):
    """Three masters forming a raft quorum on loopback."""
    masters = [
        MasterServer(port=0, reap_interval=3600, election_timeout=FAST)
        for _ in range(3)
    ]
    addresses = [m.address for m in masters]
    from seaweedfs_tpu.cluster.raft import RaftNode

    for m in masters:
        m.raft = RaftNode(
            me=m.address,
            peers=addresses,
            server=m._server,
            state_dir=str(tmp_path),
            election_timeout=FAST,
            payload_fn=m._raft_payload,
            apply_fn=m._raft_apply,
            on_leader=m._on_become_leader,
        )
    for m in masters:
        m.start()
    yield masters
    for m in masters:
        try:
            m.stop()
        except Exception:
            pass


def test_single_leader_elected(quorum):
    leader = _wait_for_leader(quorum)
    states = sorted(m.raft.state for m in quorum)
    assert states == ["follower", "follower", "leader"]
    assert leader.is_leader


def test_leader_failover_and_term_increase(quorum):
    leader = _wait_for_leader(quorum)
    old_term = leader.raft.term
    leader.stop()
    rest = [m for m in quorum if m is not leader]
    new_leader = _wait_for_leader(rest)
    assert new_leader is not leader
    assert new_leader.raft.term > old_term


def test_assign_redirect_and_failover(quorum, tmp_path):
    leader = _wait_for_leader(quorum)
    follower = next(m for m in quorum if m is not leader)
    d = tmp_path / "vol"
    d.mkdir()
    vs = VolumeServer(
        [str(d)],
        ",".join(m.address for m in quorum),
        heartbeat_interval=0.3,
    )
    vs.start()
    try:
        # client pointed ONLY at a follower: redirect must find the leader
        client = MasterClient(follower.address)
        a1 = client.assign()
        assert a1.fid
        client.upload(a1.fid, b"ha payload")
        assert client.read(a1.fid) == b"ha payload"
        client.close()
        # kill the leader; a quorum-aware client keeps working
        leader.stop()
        survivors = [m for m in quorum if m is not leader]
        _wait_for_leader(survivors)
        client = MasterClient(",".join(m.address for m in survivors))
        deadline = time.monotonic() + 10
        a2 = None
        while time.monotonic() < deadline:
            try:
                a2 = client.assign()
                break
            except Exception:
                time.sleep(0.2)
        assert a2 is not None and a2.fid
        # watermark continuity: the new fid never collides with the old
        assert a2.fid != a1.fid
        key1 = int(a1.fid.split(",")[1][:-8] or "0", 16)
        key2 = int(a2.fid.split(",")[1][:-8] or "0", 16)
        assert key2 > key1  # floored past the old leader's lease
        client.upload(a2.fid, b"after failover")
        assert client.read(a2.fid) == b"after failover"
        client.close()
    finally:
        vs.stop()


def test_partitioned_leader_steps_down(quorum):
    """A leader that cannot reach a quorum must stop claiming leadership
    (split-brain guard: a stale leader would keep allocating ids)."""
    leader = _wait_for_leader(quorum)
    # simulate partition: cut the leader's raft clients to its peers
    for c in leader.raft._clients.values():
        c.close()
    leader.raft._clients.clear()
    leader.raft.peers = ["127.0.0.1:1"]  # unreachable blackhole
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and leader.raft.is_leader:
        time.sleep(0.05)
    assert not leader.raft.is_leader
    assert not leader.is_leader  # Assign would now redirect


def test_raft_term_persistence(tmp_path):
    """A restarted node must come back with its persisted term."""
    from seaweedfs_tpu import rpc as rpc_mod
    from seaweedfs_tpu.cluster.raft import RaftNode

    server = rpc_mod.RpcServer(port=0)
    node = RaftNode(
        me="127.0.0.1:1",
        peers=["127.0.0.1:1"],
        server=server,
        state_dir=str(tmp_path),
        election_timeout=FAST,
    )
    node.term = 42
    node.voted_for = "127.0.0.1:9"
    node._save_state()
    server2 = rpc_mod.RpcServer(port=0)
    node2 = RaftNode(
        me="127.0.0.1:1",
        peers=["127.0.0.1:1"],
        server=server2,
        state_dir=str(tmp_path),
        election_timeout=FAST,
    )
    assert node2.term == 42 and node2.voted_for == "127.0.0.1:9"


def test_admin_lock_lease_requires_quorum_ack():
    """A lease the quorum never acked must not be handed to the client:
    the grant is rolled back and the RPC fails (VERDICT r3 weak #7 — a
    token a client holds must be visible to any future leader)."""
    from seaweedfs_tpu import rpc

    m = MasterServer(port=0, reap_interval=3600)

    class FakeRaft:
        is_leader = True
        leader = None
        acks = False

        def replicate_now(self):
            return self.acks

    try:
        m.raft = FakeRaft()
        with pytest.raises(rpc.RpcFault, match="not acknowledged by a master quorum"):
            m._rpc_lease_admin_token(
                {"lock_name": "admin", "previous_token": 0, "client_name": "a"}, None
            )
        assert m._admin_locks == {}, "failed lease must be rolled back"
        # quorum back: the lease goes through and is guarded
        m.raft.acks = True
        resp = m._rpc_lease_admin_token(
            {"lock_name": "admin", "previous_token": 0, "client_name": "a"}, None
        )
        tok = int(resp["token"])
        assert tok
        with pytest.raises(rpc.RpcFault, match="held by a"):
            m._rpc_lease_admin_token(
                {"lock_name": "admin", "previous_token": 0, "client_name": "b"}, None
            )
        # a quorum outage during RENEWAL must restore the prior lease, not
        # wipe it (the holder still owns the lock until TTL)
        m.raft.acks = False
        with pytest.raises(rpc.RpcFault, match="not acknowledged"):
            m._rpc_lease_admin_token(
                {"lock_name": "admin", "previous_token": tok, "client_name": "a"}, None
            )
        assert m._admin_locks["admin"][0] == tok
    finally:
        m.raft = None
        m._server.stop()


def test_admin_lock_apply_is_seq_gated():
    """A stale/reordered payload (lower lock_seq) must never roll the lock
    table back — only fresher payloads are adopted."""
    m = MasterServer(port=0, reap_interval=3600)
    try:
        fresh = {"max_volume_id": 0, "sequence": 0, "lock_seq": 5,
                 "admin_locks": {"admin": {"token": 42, "ttl_s": 30.0, "client": "holder"}}}
        stale = {"max_volume_id": 0, "sequence": 0, "lock_seq": 3, "admin_locks": {}}
        m._raft_apply(fresh)
        assert m._admin_locks["admin"][0] == 42
        m._raft_apply(stale)  # must be ignored
        assert m._admin_locks["admin"][0] == 42, "stale payload rolled back the table"
        newer = {"max_volume_id": 0, "sequence": 0, "lock_seq": 6, "admin_locks": {}}
        m._raft_apply(newer)  # a genuine release propagates
        assert "admin" not in m._admin_locks
    finally:
        m._server.stop()


def test_admin_lock_survives_leader_failover(quorum):
    """End-to-end: the shell's lock stays exclusive across a leader crash —
    the intruder is refused (replicated lease OR takeover grace) while the
    holder's renewal keeps working against the new leader."""
    from seaweedfs_tpu.shell import CommandEnv

    leader = _wait_for_leader(quorum)
    addresses = ",".join(m.address for m in quorum)
    env = CommandEnv(addresses, client_name="holder")
    env.lock()
    assert env.is_locked

    leader.stop()
    survivors = [m for m in quorum if m is not leader]
    _wait_for_leader(survivors)

    intruder = CommandEnv(addresses, client_name="intruder")
    try:
        with pytest.raises(Exception, match="held by"):
            intruder.lock()
        # holder's renewal keeps working against the new leader
        assert env._renew_once(), "holder lost the lock across failover"
        assert env.is_locked
        with pytest.raises(Exception, match="held by"):
            intruder.lock()
    finally:
        intruder.close()
        env._renew_stop and env._renew_stop.set()
        env.close()


def test_follower_http_names_leader_in_json(quorum):
    """The HTTP facade on a raft follower must answer leader-only calls
    with the reference's failover shape — {"error": ..., "Leader": addr} —
    not an opaque 412 (r4 advisor finding): curl-level HA clients read
    the Leader field to retry against the right master."""
    import json as _json
    import urllib.request

    leader = _wait_for_leader(quorum)
    follower = next(m for m in quorum if m is not leader)
    base = f"http://{follower.host}:{follower.http_port}"

    # /vol/grow raises the leader-only fault
    with urllib.request.urlopen(base + "/vol/grow?count=1", timeout=10) as r:
        assert r.status == 200
        d = _json.loads(r.read())
    assert d["Leader"] == leader.address and "not the raft leader" in d["error"]

    # /dir/assign answers through the Assign dict shape: same fields
    with urllib.request.urlopen(base + "/dir/assign?count=1", timeout=10) as r:
        d = _json.loads(r.read())
    assert d["Leader"] == leader.address and "not the raft leader" in d["error"]
