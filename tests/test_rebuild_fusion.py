"""Heterogeneous rebuild fusion: block-diagonal decode + fused batches.

Covers the fusion PR's acceptance surface without a live cluster: the
`Encoder.reconstruct_block` block-diagonal decode (byte-identity vs the
gf8 golden across backends, mixed geometries, tile-edge and odd widths,
overlap/bounds rejection), the `xorsched.apply_blocks` multi-program
executor (zero-copy caller outputs, thread-count variants, validation),
the heterogeneous `rebuild_ec_files_batch` path (mixed 10+4/12+3/20+4
storm byte-identical to the serial per-volume oracle, 2-missing and
1-missing in ONE batch, mid-batch failure unlinking only that block's
partials), the per-block schedule-cache keying under a mixed-signature
storm, the fusion fields on the wire contract, and the deterministic
`BENCH_MODE=rebuild_batch --smoke` tier-1 gate.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ops import gf8, xorsched
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.utils import native

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LARGE, SMALL = 16384, 4096

# encode with numpy-backend encoders so schedule-cache assertions below
# see ONLY the decode compiles; matrices are identical across backends
B10 = Encoder(10, 4, backend="numpy")
B12 = Encoder(12, 3, backend="numpy", matrix_kind="cauchy")
B20 = Encoder(20, 4, backend="numpy", matrix_kind="cauchy")


def _backends():
    out = ["numpy", "xorsched"]
    if native.load() is not None:
        out.append("native")
    return out


def _block(enc, missing, col_start, width):
    survivors = [
        s for s in range(enc.total_shards) if s not in missing
    ][: enc.data_shards]
    return {
        "encoder": enc,
        "survivors": survivors,
        "wanted": list(missing),
        "col_start": col_start,
        "width": width,
    }


# -- Encoder.reconstruct_block -------------------------------------------------


@pytest.mark.parametrize("backend", _backends())
def test_reconstruct_block_mixed_geometries_byte_exact(backend):
    """Three signature blocks (10+4 2-missing, 12+3 1-missing, 20+4
    2-missing) packed side by side, widths chosen to land on tile edges
    and odd remainders — the fused result must equal each block's gf8
    golden decode, rows past a block's wanted count unconstrained."""
    e10 = Encoder(10, 4, backend=backend)
    e12 = Encoder(12, 3, backend=backend, matrix_kind="cauchy")
    e20 = Encoder(20, 4, backend=backend, matrix_kind="cauchy")
    widths = [513, 7, 4096]  # odd, sub-tile, exact-tile
    blocks, col = [], 0
    for enc, missing, w in zip(
        (e10, e12, e20), ([12, 13], [5], [20, 23]), widths
    ):
        blocks.append(_block(enc, missing, col, w))
        col += w
    rng = np.random.default_rng(3)
    staging = rng.integers(0, 256, size=(20, col), dtype=np.uint8)
    out = np.asarray(e10.reconstruct_block(staging, blocks))
    assert out.shape == (2, col) and out.dtype == np.uint8
    for b in blocks:
        enc = b["encoder"]
        m = enc.reconstruction_matrix(b["survivors"], b["wanted"])
        sub = staging[: enc.data_shards, b["col_start"]:b["col_start"] + b["width"]]
        golden = gf8.gf_mat_vec(m, sub)
        got = out[: len(b["wanted"]), b["col_start"]:b["col_start"] + b["width"]]
        assert (got == golden).all(), f"{enc.data_shards}+ block differs"


def test_reconstruct_block_rejects_overlap_bounds_and_empty():
    e10 = Encoder(10, 4, backend="numpy")
    staging = np.zeros((10, 100), dtype=np.uint8)
    with pytest.raises(ValueError):
        e10.reconstruct_block(staging, [])
    with pytest.raises(ValueError):
        e10.reconstruct_block(
            staging,
            [_block(e10, [13], 0, 60), _block(e10, [12], 50, 50)],  # overlap
        )
    with pytest.raises(ValueError):
        e10.reconstruct_block(staging, [_block(e10, [13], 60, 50)])  # past end


# -- xorsched.apply_blocks -----------------------------------------------------


def test_apply_blocks_matches_apply_per_block_and_threads():
    """Two different programs over different widths (tile edge, odd,
    tiny) through one apply_blocks call — equal to per-program apply for
    every thread setting, including caller-supplied zero-copy outputs."""
    e10 = Encoder(10, 4, backend="numpy")
    e12 = Encoder(12, 3, backend="numpy", matrix_kind="cauchy")
    m1 = e10.reconstruction_matrix(list(range(10)), [12, 13])
    m2 = e12.reconstruction_matrix(list(range(12)), [14])
    p1, p2 = xorsched.get_schedule(m1), xorsched.get_schedule(m2)
    rng = np.random.default_rng(11)
    for width1, width2 in [(p1.tile_sym, 3), (p1.tile_sym + 1, 513)]:
        in1 = list(rng.integers(0, 256, size=(10, width1), dtype=np.uint8))
        in2 = list(rng.integers(0, 256, size=(12, width2), dtype=np.uint8))
        want1 = np.stack(xorsched.apply(p1, in1))
        want2 = np.stack(xorsched.apply(p2, in2))
        for threads in (None, 1, 2, 0):
            got = xorsched.apply_blocks([p1, p2], [in1, in2], threads=threads)
            assert (np.stack(got[0]) == want1).all()
            assert (np.stack(got[1]) == want2).all()
        # zero-copy: rows of caller arrays are filled in place
        buf1 = np.zeros((2, width1), dtype=np.uint8)
        buf2 = np.zeros((1, width2), dtype=np.uint8)
        xorsched.apply_blocks(
            [p1, p2], [in1, in2],
            outputs_per_block=[list(buf1), list(buf2)], threads=2,
        )
        assert (buf1 == want1).all() and (buf2 == want2).all()


def test_apply_blocks_validates_outputs():
    e10 = Encoder(10, 4, backend="numpy")
    m = e10.reconstruction_matrix(list(range(10)), [13])
    p = xorsched.get_schedule(m)
    ins = [np.zeros(64, dtype=np.uint8)] * 10
    with pytest.raises(ValueError):
        xorsched.apply_blocks([p], [ins], outputs_per_block=[[np.zeros(63, dtype=np.uint8)]])
    with pytest.raises(ValueError):
        xorsched.apply_blocks([p], [ins], outputs_per_block=[[np.zeros(64, dtype=np.uint16)]])
    with pytest.raises(ValueError):
        xorsched.apply_blocks(
            [p], [ins],
            outputs_per_block=[[np.zeros((64, 2), dtype=np.uint8)[:, 0]]],
        )


# -- heterogeneous rebuild_ec_files_batch -------------------------------------


def _build_volume(dirpath, vid, size, enc, seed):
    base = os.path.join(dirpath, str(vid))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=enc
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(enc.total_shards):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


def _storm_jobs(tmp_path, specs, job_encoders=None):
    jobs, goldens = [], {}
    for i, (vid, size, missing, enc) in enumerate(specs):
        base, golden = _build_volume(str(tmp_path), vid, size, enc, seed=vid)
        goldens[base] = (golden, missing, enc)
        for s in missing:
            os.unlink(stripe.shard_file_name(base, s))
        present = [s for s in range(enc.total_shards) if s not in missing]
        jobs.append({
            "base": base,
            "sources": {
                s: stripe.LocalSlabSource(stripe.shard_file_name(base, s))
                for s in present
            },
            "shard_size": len(golden[0]),
            "missing": missing,
            "encoder": (job_encoders or {}).get(i, enc),
        })
    return jobs, goldens


MIXED_SPECS = [
    (41, 123_457, [12, 13], B10),  # 2-missing, odd size
    (42, 88_001, [3], B10),        # 1-missing, same geometry
    (43, 97_003, [0, 12], B12),    # converted geometry, 2-missing
    (44, 64_005, [20, 23], B20),   # converted geometry, 2-missing
    (45, 71_999, [7], B20),        # 1-missing
]


def test_batch_mixed_signatures_one_dispatch_matches_serial(tmp_path):
    """The acceptance storm in miniature: 10+4 with converted 12+3 and
    20+4 geometries, 2-missing and 1-missing in ONE batch, odd sizes so
    column spans hit tile edges. The fused single dispatch must leave
    every volume byte-identical to what `rebuild_ec_files_serial`
    produces for it alone."""
    jobs, goldens = _storm_jobs(tmp_path, MIXED_SPECS)
    try:
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=16384, max_batch_bytes=163_840
        )
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()
    assert not res["errors"], res["errors"]
    assert res["dispatch_groups"] == 1
    assert res["signature_groups"] == len(MIXED_SPECS)  # all distinct here
    assert res["volumes_fused"] == len(MIXED_SPECS)
    for base, (golden, missing, enc) in goldens.items():
        assert sorted(res["rebuilt"][base]) == sorted(missing)
        fused_bytes = {}
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                fused_bytes[s] = f.read()
            assert fused_bytes[s] == golden[s], f"{base} shard {s} vs golden"
            os.unlink(stripe.shard_file_name(base, s))
        assert sorted(stripe.rebuild_ec_files_serial(base, encoder=enc)) == (
            sorted(missing)
        )
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                assert f.read() == fused_bytes[s], (
                    f"{base} shard {s}: fused differs from serial oracle"
                )


def test_batch_mid_failure_unlinks_only_failed_block(tmp_path):
    """A survivor of ONE signature group dies mid-pipeline: that group's
    partials are unlinked and reported, while every other block of the
    same fused batch completes byte-exact — group-scoped isolation."""

    class Dying(stripe.SlabSource):
        def __init__(self, path):
            self._inner = stripe.LocalSlabSource(path)
            self._calls = 0

        def read_into(self, offset, out):
            self._calls += 1
            if self._calls > 1:
                raise IOError("holder died")
            self._inner.read_into(offset, out)

        def close(self):
            self._inner.close()

    specs = [
        (51, 90_000, [13], B10),
        (52, 80_000, [12, 13], B10),   # this group's survivor dies
        (53, 70_000, [0, 12], B12),
    ]
    jobs, goldens = _storm_jobs(tmp_path, specs)
    dying_base = jobs[1]["base"]
    jobs[1]["sources"][0].close()
    jobs[1]["sources"][0] = Dying(stripe.shard_file_name(dying_base, 0))
    try:
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=4096, max_batch_bytes=81_920
        )
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()
    assert res["dispatch_groups"] == 1
    assert list(res["errors"]) == [dying_base]
    for s in (12, 13):
        assert not os.path.exists(stripe.shard_file_name(dying_base, s))
    for base, (golden, missing, _) in goldens.items():
        if base == dying_base:
            continue
        assert sorted(res["rebuilt"][base]) == sorted(missing)
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                assert f.read() == golden[s]


def test_fuse_off_restores_per_signature_dispatches(tmp_path):
    """WEEDTPU_REBUILD_FUSE=off (here: fuse=False) is the PR 16 baseline:
    one dispatch per signature group, same bytes."""
    jobs, goldens = _storm_jobs(tmp_path, MIXED_SPECS)
    try:
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=16384, max_batch_bytes=163_840, fuse=False
        )
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()
    assert not res["errors"], res["errors"]
    assert res["dispatch_groups"] == res["signature_groups"] == len(MIXED_SPECS)
    for base, (golden, missing, _) in goldens.items():
        for s in missing:
            with open(stripe.shard_file_name(base, s), "rb") as f:
                assert f.read() == golden[s]


def test_schedule_cache_keys_per_block_under_mixed_storm(tmp_path):
    """The small-fix satellite: the fused dispatch compiles ONE schedule
    per block sub-matrix (keyed individually in the LRU), not one giant
    composite program — so a re-run of the same storm is all hits and a
    storm sharing signatures re-uses entries across volumes."""
    job_encoders = {
        0: Encoder(10, 4, backend="xorsched"),
        1: Encoder(10, 4, backend="xorsched"),
        2: Encoder(12, 3, backend="xorsched", matrix_kind="cauchy"),
        3: Encoder(20, 4, backend="xorsched", matrix_kind="cauchy"),
        4: Encoder(20, 4, backend="xorsched", matrix_kind="cauchy"),
    }
    jobs, _ = _storm_jobs(tmp_path, MIXED_SPECS, job_encoders)
    n_sigs = len(MIXED_SPECS)
    xorsched.clear_schedule_cache()
    try:
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=16384, max_batch_bytes=163_840
        )
        assert not res["errors"] and res["dispatch_groups"] == 1
        info = xorsched.schedule_cache_info()
        assert info["size"] == n_sigs, info  # one entry PER BLOCK matrix
        assert info["misses"] == n_sigs, info
        first_hits = info["hits"]
        # identical storm again: every block schedule is a cache hit
        for job, (_, _, missing, _) in zip(jobs, MIXED_SPECS):
            for s in missing:
                os.unlink(stripe.shard_file_name(job["base"], s))
        res = stripe.rebuild_ec_files_batch(
            jobs, buffer_size=16384, max_batch_bytes=163_840
        )
        assert not res["errors"] and res["dispatch_groups"] == 1
        info = xorsched.schedule_cache_info()
        assert info["misses"] == n_sigs, info  # no recompiles
        assert info["size"] == n_sigs, info
        assert info["hits"] > first_hits, info
    finally:
        for job in jobs:
            for src in job["sources"].values():
                src.close()


# -- wire contract -------------------------------------------------------------


def test_wire_roundtrips_fusion_fields():
    from seaweedfs_tpu.pb import wire

    c = wire.codec()
    _, resp_cls = c.classes("weedtpu.VolumeServer", "VolumeEcShardsRebuildBatch")
    d = {
        "results": [], "dispatch_groups": 1, "wire_bytes": 9,
        "signature_groups": 3, "volumes_fused": 5, "block_order": [7, 9, 8],
    }
    assert c.to_dict(c.to_message(d, resp_cls)) == d
    _, status_cls = c.classes("weedtpu.Master", "RepairStatus")
    batch = {
        "target": "127.0.0.1:8080", "volumes": 4, "signature_groups": 2,
        "dispatch_groups": 1, "block_order": [5, 6, 7, 8],
        "block_missing": [2, 2, 1, 1], "wall_s": 0.25, "age_s": 3.5,
    }
    st = {"enabled": True, "batches": [batch], "fused_volumes_total": 12}
    got = c.to_dict(c.to_message(st, status_cls))
    assert got["batches"] == [batch]
    assert got["fused_volumes_total"] == 12


# -- bench smoke (tier-1 gate) -------------------------------------------------


def test_bench_rebuild_batch_smoke_deterministic():
    """`BENCH_MODE=rebuild_batch bench.py --smoke`: deterministic byte
    accounting + the homogeneous-vs-heterogeneous dispatch-count assert,
    no timing fields, no timestamp."""
    env = dict(os.environ, BENCH_MODE="rebuild_batch", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        timeout=300,
    )
    out = None
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        if line.strip().startswith("{"):
            out = json.loads(line)
            break
    assert out is not None, "no JSON from the smoke child"
    assert out["ok"] is True
    assert "when" not in out, "smoke output must be timestamp-free"
    assert out["fused"]["dispatch_groups"] == 1
    assert out["unfused"]["dispatch_groups"] == out["storm"]["signatures"] > 1
    assert out["homogeneous_fused"]["dispatch_groups"] == 1
    assert out["homogeneous_unfused"]["dispatch_groups"] == 1
    assert out["verify"]["fused_bytes_match"] is True
    assert out["verify"]["unfused_bytes_match"] is True
    assert out["rebuilt_bytes"] > 0
