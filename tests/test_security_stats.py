"""Security (JWT guard) + metrics tests — weed/security and weed/stats
analogs (SURVEY.md §2.1, §5). The guarded-cluster test runs a real
master+volume pair with a signing key: unauthorized writes/deletes must
401 while the assign->upload flow (and replica fan-out) works."""

import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.security import Guard
from seaweedfs_tpu.security.jwt import (
    JwtError,
    check_file_token,
    decode_jwt,
    encode_jwt,
    mint_file_token,
)

KEY = b"test-signing-key"


# -- jwt unit ----------------------------------------------------------------


def test_jwt_roundtrip_and_tamper():
    tok = encode_jwt(KEY, {"fid": "3,0102deadbeef"}, expires_seconds=60)
    claims = decode_jwt(KEY, tok)
    assert claims["fid"] == "3,0102deadbeef"
    assert claims["exp"] > time.time()
    with pytest.raises(JwtError, match="bad signature"):
        decode_jwt(b"other-key", tok)
    h, p, s = tok.split(".")
    with pytest.raises(JwtError):
        decode_jwt(KEY, h + "." + p + ".AAAA")
    with pytest.raises(JwtError, match="malformed"):
        decode_jwt(KEY, "not-a-token")


def test_jwt_expiry():
    tok = encode_jwt(KEY, {"fid": "1,ab"}, expires_seconds=-5)
    with pytest.raises(JwtError, match="expired"):
        decode_jwt(KEY, tok)


def test_file_token_checks():
    tok = mint_file_token(KEY, "7,aa11", expires_seconds=60)
    assert check_file_token(KEY, tok, "7,aa11")
    assert not check_file_token(KEY, tok, "7,aa12")  # other fid
    assert not check_file_token(KEY, "", "7,aa11")  # missing token
    assert check_file_token(None, "", "7,aa11")  # auth disabled
    assert mint_file_token(None, "7,aa11") == ""


def test_guard_white_list():
    g = Guard(signing_key=KEY, white_list=["10.0.0.9"])
    assert g.secured
    assert g.check_write("1,ab", "", remote_ip="10.0.0.9")
    assert not g.check_write("1,ab", "", remote_ip="10.0.0.7")
    # whitelist-ONLY mode must deny non-members, not degrade to auth-off
    g2 = Guard(white_list=["10.0.0.9"])
    assert g2.secured
    assert g2.check_write("1,ab", "", remote_ip="10.0.0.9")
    assert not g2.check_write("1,ab", "", remote_ip="10.0.0.7")


# -- guarded cluster e2e ------------------------------------------------------


@pytest.fixture
def secured_cluster(tmp_path):
    guard = Guard(signing_key=KEY)
    master = MasterServer(port=0, reap_interval=3600, guard=guard)
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)], master.address, heartbeat_interval=0.3, guard=guard
        )
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    yield master, servers, client
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()


def test_secured_write_flow(secured_cluster):
    master, servers, client = secured_cluster
    a = client.assign(replication="001")
    assert a.auth, "secured master must return an auth token on assign"
    payload = b"locked down payload"
    client.upload(a.fid, payload, auth=a.auth)  # replica fan-out included
    assert client.read(a.fid) == payload
    # both replicas actually hold it (fan-out hop minted its own token)
    held = sum(
        1
        for vs in servers
        if _direct_read(vs.url, a.fid) == payload
    )
    assert held == 2

    # un-authenticated write to a fresh fid: 401
    b = client.assign()
    with pytest.raises(Exception) as ei:
        client.upload(b.fid, b"no token")
    assert "401" in str(ei.value)
    # token for fid A does not authorize fid B
    with pytest.raises(Exception) as ei:
        client.upload(b.fid, b"wrong token", auth=a.auth)
    assert "401" in str(ei.value)
    # un-authenticated delete: 401 surfaces as not-deleted
    req = urllib.request.Request(f"http://{servers[0].url}/{a.fid}", method="DELETE")
    with pytest.raises(urllib.error.HTTPError) as he:
        urllib.request.urlopen(req, timeout=10)
    assert he.value.code == 401
    assert client.read(a.fid) == payload  # still there

    # a trusted client configured with the shared key self-mints delete tokens
    trusted = MasterClient(client.master_address, signing_key=KEY)
    try:
        assert trusted.delete(a.fid)
        with pytest.raises(Exception):
            trusted.read(a.fid)
    finally:
        trusted.close()


def _direct_read(url, fid):
    try:
        with urllib.request.urlopen(f"http://{url}/{fid}", timeout=10) as r:
            return r.read()
    except urllib.error.HTTPError:
        return None


# -- metrics -----------------------------------------------------------------


def test_metrics_exposition_and_counters(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "srv"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    client = MasterClient(master.address)
    try:
        hb_before = stats.MasterReceivedHeartbeatCounter.value
        vs.heartbeat_once()
        res = client.submit(b"metrics payload")
        assert client.read(res.fid) == b"metrics payload"
        body = urllib.request.urlopen(f"http://{vs.url}/metrics", timeout=10).read().decode()
        assert "# TYPE weedtpu_volume_request_total counter" in body
        assert 'weedtpu_volume_request_total{type="post"}' in body
        assert 'weedtpu_volume_request_total{type="get"}' in body
        assert "# TYPE weedtpu_ec_reconstruct_seconds histogram" in body
        assert "weedtpu_ec_reconstruct_seconds_bucket" in body
        assert stats.MasterReceivedHeartbeatCounter.value > hb_before
        assert stats.MasterAssignCounter.value >= 1
    finally:
        client.close()
        vs.stop()
        master.stop()


def test_histogram_quantile():
    h = stats.Histogram("t_q_seconds", "test", buckets=(0.001, 0.01, 0.1, 1.0))
    for _ in range(50):
        h.observe(0.005)
    for _ in range(50):
        h.observe(0.05)
    assert h.quantile(0.25) == 0.01
    assert h.quantile(0.9) == 0.1


def test_standalone_metrics_server():
    srv = stats.start_metrics_server(0)
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5).read()
        assert b"weedtpu_master_assign_total" in body
    finally:
        srv.shutdown()


def test_scaffold_and_config(tmp_path, monkeypatch):
    from seaweedfs_tpu.utils import config as cfg

    text = cfg.scaffold("security")
    assert "[jwt.signing]" in text
    p = tmp_path / "security.toml"
    p.write_text(text.replace('key = ""', 'key = "abc"', 1))
    monkeypatch.setattr(cfg, "SEARCH_PATHS", [str(tmp_path)])
    conf = cfg.load_configuration("security")
    assert cfg.get_nested(conf, "jwt.signing.key") == "abc"
    assert cfg.get_nested(conf, "jwt.signing.read.key") == ""
    assert cfg.get_nested(conf, "nope.deep", 42) == 42
    assert cfg.load_configuration("missing") == {}
    with pytest.raises(FileNotFoundError):
        cfg.load_configuration("missing", required=True)
