"""Concurrency stress harness (SURVEY §5 race-detection row; VERDICT r3
"no stress harness"): hammer the real in-process cluster from many threads
at once and assert integrity — the Python-side answer to the reference's
`go test -race` CI job. Each test is bounded (~seconds) but drives genuine
interleavings through the real gRPC/HTTP stack."""

import os
import random
import threading

import pytest

from seaweedfs_tpu.cluster.client import ClusterError, MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(2):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)], master.address, heartbeat_interval=0.4, max_volume_count=50
        )
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    yield master, servers, client
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()


def _run_threads(workers, timeout=60):
    threads = [threading.Thread(target=w, daemon=True) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "stress worker hung"


def test_concurrent_writers_readers_deleters(cluster):
    """8 writer/reader/deleter threads against the same cluster: every
    surviving fid must read back byte-identical; deleted fids must 404;
    no wrong-content reads ever."""
    master, servers, client = cluster
    errors: list[str] = []
    written: dict[str, bytes] = {}
    deleted: set[str] = set()
    lock = threading.Lock()
    rng = random.Random(7)

    def writer(seed):
        r = random.Random(seed)
        c = MasterClient(master.address)
        try:
            for _ in range(25):
                data = os.urandom(r.randint(100, 8000))
                try:
                    res = c.submit(data)
                except ClusterError as e:
                    errors.append(f"submit: {e}")
                    continue
                with lock:
                    written[res.fid] = data
        finally:
            c.close()

    def reader():
        c = MasterClient(master.address)
        try:
            for _ in range(60):
                with lock:
                    if not written:
                        continue
                    fid, want = rng.choice(list(written.items()))
                    if fid in deleted:
                        continue
                try:
                    got = c.read(fid)
                except ClusterError:
                    with lock:
                        if fid not in deleted:
                            errors.append(f"read of live fid {fid} failed")
                    continue
                if got != want:
                    errors.append(f"WRONG CONTENT for {fid}")
        finally:
            c.close()

    def deleter():
        c = MasterClient(master.address)
        try:
            for _ in range(15):
                with lock:
                    candidates = [f for f in written if f not in deleted]
                    if not candidates:
                        continue
                    fid = rng.choice(candidates)
                    deleted.add(fid)  # claim BEFORE deleting: readers tolerate
                c.delete(fid)
        finally:
            c.close()

    _run_threads([lambda s=i: writer(s) for i in range(4)] + [reader] * 3 + [deleter])
    assert not errors, errors[:5]
    # final sweep: all survivors intact, all deleted gone
    for fid, want in written.items():
        if fid in deleted:
            with pytest.raises(ClusterError):
                client.read(fid)
        else:
            assert client.read(fid) == want, f"{fid} corrupted after stress"


def test_concurrent_ec_encode_and_reads(cluster):
    """EC-encode a volume WHILE readers hammer its blobs: reads must never
    return wrong bytes — before, during, or after the cut-over."""
    import io

    from seaweedfs_tpu.shell import CommandEnv, run_command

    master, servers, client = cluster
    payloads = {}
    first = client.submit(os.urandom(4000))
    vid = int(first.fid.split(",")[0])
    payloads[first.fid] = client.read(first.fid)
    while len(payloads) < 15:
        a = client.assign()
        if int(a.fid.split(",")[0]) != vid:
            continue
        data = os.urandom(random.randint(500, 5000))
        client.upload(a.fid, data)
        payloads[a.fid] = data

    errors = []
    stop = threading.Event()

    def reader():
        c = MasterClient(master.address)
        try:
            while not stop.is_set():
                fid, want = random.choice(list(payloads.items()))
                try:
                    got = c.read(fid)
                except ClusterError:
                    continue  # transient during cut-over: retried next loop
                if got != want:
                    errors.append(f"WRONG CONTENT {fid} during ec.encode")
                    return
        finally:
            c.close()

    readers = [threading.Thread(target=reader, daemon=True) for _ in range(3)]
    for t in readers:
        t.start()
    env = CommandEnv(master.address)
    try:
        out = io.StringIO()
        run_command(env, "lock", out)
        run_command(env, f"ec.encode -volumeId {vid} -largeBlockSize 4096 -smallBlockSize 512", out)
    finally:
        stop.set()
        for t in readers:
            t.join(30)
        env.close()
    assert not errors, errors
    for fid, want in payloads.items():
        assert client.read(fid) == want, f"{fid} corrupted by concurrent encode"


def test_concurrent_admin_lock_contention(cluster):
    """N threads fight for the exclusive lock: at most one holds it at any
    instant (the invariant every mutating shell command relies on)."""
    from seaweedfs_tpu.shell import CommandEnv

    master, servers, client = cluster
    holders = {"current": 0, "max": 0}
    hlock = threading.Lock()
    acquired = {"n": 0}

    def fighter(i):
        env = CommandEnv(master.address, client_name=f"fighter-{i}")
        try:
            for _ in range(12):
                try:
                    env.lock()
                except Exception:
                    threading.Event().wait(0.03)  # holder active: back off, retry
                    continue
                with hlock:
                    holders["current"] += 1
                    holders["max"] = max(holders["max"], holders["current"])
                    acquired["n"] += 1
                threading.Event().wait(0.02)  # hold the lock long enough to overlap
                with hlock:
                    holders["current"] -= 1
                env.unlock()
        finally:
            try:
                env.close()
            except Exception:
                pass

    _run_threads([lambda i=i: fighter(i) for i in range(5)])
    assert holders["max"] == 1, "two clients held the exclusive lock at once"
    # the exact count depends on scheduling; what matters is that the lock
    # moved between clients at all while never being held twice
    assert acquired["n"] >= 3, "lock never circulated"
