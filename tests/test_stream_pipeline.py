"""Depth-N streaming pipeline tests (r6): tile-boundary and odd-size
byte-exactness against the numpy golden, depth-1 vs depth-N byte-identity,
fused per-shard CRC recording/verification, exception-safety (a mid-stream
failure must drain inflight device work and unlink partial shard files),
decode-matrix cache boundedness, and the kernel_sweep --smoke CI gate."""

import json
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import (
    Encoder,
    clear_decode_matrix_cache,
    decode_matrix_cache_info,
)
from seaweedfs_tpu.ops.rs_pallas import DEFAULT_TILE

ENC = Encoder(10, 4, backend="numpy")

# sizes straddling DEFAULT_TILE multiples, plus degenerate tails
TILE_EDGE_SIZES = [
    1,
    127,
    DEFAULT_TILE - 1,
    DEFAULT_TILE,
    DEFAULT_TILE + 1,
    2 * DEFAULT_TILE + 17,
]


# -- kernel-level: odd sizes must match the numpy golden byte-for-byte --------


@pytest.mark.parametrize("backend", ["jax", "pallas"])
@pytest.mark.parametrize("n", TILE_EDGE_SIZES)
def test_encode_batch_tile_edges_match_golden(backend, n):
    rng = np.random.default_rng(n)
    data = rng.integers(0, 256, size=(2, 10, n), dtype=np.uint8)
    enc = Encoder(10, 4, backend=backend)
    got = enc.encode_batch(data)
    pm = gf8.parity_matrix(10, 4)
    for b in range(2):
        want = gf8.gf_mat_mul(pm, data[b])
        np.testing.assert_array_equal(got[b, :10], data[b])
        np.testing.assert_array_equal(got[b, 10:], want, err_msg=f"n={n}")


@pytest.mark.parametrize("backend", ["jax", "pallas"])
@pytest.mark.parametrize("n", [1, DEFAULT_TILE - 1, DEFAULT_TILE + 1])
def test_reconstruct_batch_tile_edges_match_golden(backend, n):
    rng = np.random.default_rng(n + 1)
    data = rng.integers(0, 256, size=(10, n), dtype=np.uint8)
    full = ENC.encode(list(data))
    lost = [0, 5, 11, 13]
    survivors = [i for i in range(14) if i not in lost][:10]
    stack = np.stack([full[s] for s in survivors])[None]
    enc = Encoder(10, 4, backend=backend)
    out = enc.reconstruct_batch(stack, survivors, lost)
    for k, w in enumerate(lost):
        np.testing.assert_array_equal(out[0, k], full[w], err_msg=f"n={n} shard {w}")


@pytest.mark.parametrize("backend", ["numpy", "jax", "pallas"])
def test_encode_empty_width(backend):
    enc = Encoder(10, 4, backend=backend)
    out = enc.encode_batch(np.zeros((1, 10, 0), dtype=np.uint8))
    assert out.shape == (1, 14, 0)


# -- file-level: depth-1 vs depth-N byte-identity -----------------------------


def _write_dat(tmp_path, size, seed=1):
    base = os.path.join(str(tmp_path), "v")
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    return base


@pytest.mark.parametrize("size", [1, 123_457, 655_360])
def test_encode_depths_byte_identical(tmp_path, size):
    base = _write_dat(tmp_path, size)
    shards_by_depth = {}
    for depth in (1, 3):
        stripe.write_ec_files(
            base, large_block_size=16384, small_block_size=4096,
            buffer_size=4096, encoder=ENC, max_batch_bytes=10 * 3 * 4096,
            pipeline_depth=depth,
        )
        shards_by_depth[depth] = [
            open(stripe.shard_file_name(base, s), "rb").read()
            for s in range(TOTAL_SHARDS_COUNT)
        ]
    assert shards_by_depth[1] == shards_by_depth[3]


@pytest.mark.parametrize("depth", [1, 3])
def test_rebuild_depths_match_serial_oracle(tmp_path, depth):
    base = _write_dat(tmp_path, 200_000)
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    golden = {
        s: open(stripe.shard_file_name(base, s), "rb").read()
        for s in range(TOTAL_SHARDS_COUNT)
    }
    lost = [0, 5, 11, 13]
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    rebuilt = stripe.rebuild_ec_files(
        base, encoder=ENC, buffer_size=8192,
        max_batch_bytes=10 * 2 * 8192, pipeline_depth=depth,
    )
    assert rebuilt == lost
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == golden[s], f"depth={depth} shard {s}"


def test_empty_dat_roundtrip(tmp_path):
    base = _write_dat(tmp_path, 0)
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    for s in range(TOTAL_SHARDS_COUNT):
        assert os.path.getsize(stripe.shard_file_name(base, s)) == 0
    os.unlink(stripe.shard_file_name(base, 2))
    assert stripe.rebuild_ec_files(base, encoder=ENC) == [2]
    assert os.path.getsize(stripe.shard_file_name(base, 2)) == 0


# -- fused CRC recording + verification ---------------------------------------


def test_eci_records_streaming_crcs(tmp_path):
    base = _write_dat(tmp_path, 100_000)
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    info = stripe.read_ec_info(base)
    crcs = info["shard_crc32"]
    assert len(crcs) == TOTAL_SHARDS_COUNT
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert crcs[s] == zlib.crc32(f.read()), f"shard {s}"


def test_ec_volume_verify_local_shards(tmp_path):
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types

    base = _write_dat(tmp_path, 50_000)
    idx_mod.write_entries([(1, types.offset_to_bytes(0), 100)], base + ".idx")
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    # flip one byte in one shard without changing its length
    p = stripe.shard_file_name(base, 7)
    with open(p, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(10)
        f.write(bytes([b[0] ^ 0xFF]))
    with EcVolume(
        base, encoder=ENC, large_block_size=16384, small_block_size=4096,
        warm_on_mount=False,
    ) as ev:
        report = ev.verify_local_shards()
    assert report is not None
    assert report[7] is False
    assert all(ok for s, ok in report.items() if s != 7)


def test_rebuild_crc_gate_catches_corrupt_survivor(tmp_path):
    """A silently-corrupt survivor (same length, flipped bytes) produces a
    wrong rebuild; the streaming CRC check against the .eci record must
    fail the rebuild AND unlink the partial outputs."""
    base = _write_dat(tmp_path, 100_000)
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    os.unlink(stripe.shard_file_name(base, 13))
    p = stripe.shard_file_name(base, 3)  # survivor used by the decode
    with open(p, "r+b") as f:
        f.seek(100)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.raises(IOError, match="CRC mismatch"):
        stripe.rebuild_ec_files(base, encoder=ENC)
    assert not os.path.exists(stripe.shard_file_name(base, 13))


# -- exception safety ---------------------------------------------------------


class _Boom(RuntimeError):
    pass


class _FailingEncoder(Encoder):
    """Raises on the Nth device dispatch — models a mid-stream read/decode
    failure with batches still inflight."""

    def __init__(self, *a, fail_at=2, **kw):
        super().__init__(*a, **kw)
        self.calls = 0
        self.fail_at = fail_at

    def _maybe_boom(self):
        self.calls += 1
        if self.calls >= self.fail_at:
            raise _Boom("mid-stream failure")

    def encode_parity_lazy(self, data, donate=False):
        self._maybe_boom()
        return super().encode_parity_lazy(data, donate=donate)

    def reconstruct_lazy(self, stack, survivors, wanted, donate=False):
        self._maybe_boom()
        return super().reconstruct_lazy(stack, survivors, wanted, donate=donate)


def test_encode_failure_unlinks_partial_shards(tmp_path):
    base = _write_dat(tmp_path, 655_360)
    enc = _FailingEncoder(10, 4, backend="numpy", fail_at=2)
    with pytest.raises(_Boom):
        stripe.write_ec_files(
            base, large_block_size=16384, small_block_size=4096,
            buffer_size=4096, encoder=enc, max_batch_bytes=10 * 2 * 4096,
        )
    for s in range(TOTAL_SHARDS_COUNT):
        assert not os.path.exists(stripe.shard_file_name(base, s)), f"shard {s} leaked"
    assert not os.path.exists(base + ".eci")


def test_rebuild_failure_unlinks_partials_keeps_survivors(tmp_path):
    base = _write_dat(tmp_path, 655_360)
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    lost = [0, 13]
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    enc = _FailingEncoder(10, 4, backend="numpy", fail_at=2)
    with pytest.raises(_Boom):
        stripe.rebuild_ec_files(
            base, encoder=enc, buffer_size=8192, max_batch_bytes=10 * 2 * 8192
        )
    for s in lost:
        assert not os.path.exists(stripe.shard_file_name(base, s)), f"partial {s} leaked"
    for s in range(TOTAL_SHARDS_COUNT):
        if s not in lost:
            assert os.path.exists(stripe.shard_file_name(base, s)), f"survivor {s} gone"


# -- decode-matrix cache boundedness (satellite: LRU cap) ---------------------


def test_decode_matrix_cache_is_bounded():
    import itertools

    clear_decode_matrix_cache()
    try:
        # churn MORE distinct loss patterns than the cap (flapping peers /
        # rolling repairs on a long-lived volume server): the memo must
        # evict, never grow for the life of the process
        info = decode_matrix_cache_info()
        n_patterns = 0
        for survivors in itertools.combinations(range(1, 14), 10):
            for wanted in (w for w in range(14) if w not in survivors):
                ENC.reconstruction_matrix(survivors, (wanted,))
                n_patterns += 1
            if n_patterns > info.maxsize + 50:
                break
        assert n_patterns > info.maxsize, "fixture must overflow the cap"
        info = decode_matrix_cache_info()
        assert info.currsize <= info.maxsize
        assert info.maxsize >= 16
    finally:
        clear_decode_matrix_cache()


def test_warm_decode_matrices_stays_bounded():
    clear_decode_matrix_cache()
    try:
        built = ENC.warm_decode_matrices()
        assert built == 14
        info = decode_matrix_cache_info()
        assert info.currsize <= info.maxsize
    finally:
        clear_decode_matrix_cache()


# -- kernel_sweep --smoke CI gate ---------------------------------------------


def test_kernel_sweep_smoke_gate():
    """Kernel refactors must not silently break the sweep: the --smoke mode
    runs every encode+rebuild variant byte-exactness gate on tiny shapes
    under JAX_PLATFORMS=cpu (interpret mode) and exits nonzero on any
    failure. EVERY staged kernel variant (rs_pallas.VARIANTS: int8, bf16,
    u8, mplane, dma) must appear in the gated set — a variant missing from
    the sweep would reach its first device window uncompiled."""
    from seaweedfs_tpu.ops import rs_pallas

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "kernel_sweep.py"), "--smoke"],
        cwd=root,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout.decode(errors="replace")[-2000:]
    summary = None
    seen = set()
    for line in proc.stdout.decode(errors="replace").splitlines():
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
            if "smoke_ok" in rec:
                summary = rec
            elif rec.get("variant"):
                seen.add(rec["variant"])
    assert summary and summary["smoke_ok"], summary
    assert summary["variants"] >= 14
    for mxu in rs_pallas.VARIANTS:
        tag = "pallas-auto" if mxu == "int8" else f"pallas-{mxu}-auto"
        assert tag in seen, f"variant {mxu} missing from the smoke gate: {sorted(seen)}"
    assert any(v.startswith("rebuild-") for v in seen)
