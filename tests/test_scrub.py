"""Scrub & self-heal tests: detection classes (bit-flip / truncation /
deletion) against the `.eci` CRC record, the persisted resumable cursor
(mid-shard CRC accumulator), repair backoff policy, quarantine semantics
on EcVolume (reads route around a quarantined shard; EcShardCorrupt when
no clean copy exists), the VolumeEcShardsVerify RPC + ec.verify shell
command, and the tier-1 e2e smoke: injected bit-flip -> background detect
-> quarantine -> automatic trace-repair -> re-verified remount, in-process
and deterministic."""

import io
import json
import os
import threading
import time
import urllib.error
import urllib.request
import zlib

import numpy as np
import pytest

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import scrub, stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ec.ec_volume import (
    EcDegradedReadError,
    EcShardCorrupt,
    EcVolume,
)
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.pb import VOLUME_SERVICE
from seaweedfs_tpu.utils import config

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL = 16384, 4096
VID = 9


def _build_ec_volume(dirpath: str, size: int = 400_000, seed: int = 3):
    base = os.path.join(dirpath, str(VID))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


def _flip_byte(path: str, offset: int = None) -> None:
    size = os.path.getsize(path)
    offset = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0x40]))


def _wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


# -- detection classes ---------------------------------------------------------


def test_expected_shard_size_matches_files(tmp_path):
    base, golden = _build_ec_volume(str(tmp_path))
    info = stripe.read_ec_info(base)
    want = scrub.expected_shard_size(info)
    for s in range(TOTAL_SHARDS_COUNT):
        assert os.path.getsize(stripe.shard_file_name(base, s)) == want


@pytest.mark.parametrize("klass", ["ok", "corrupt", "truncated", "missing", "oversize"])
def test_scan_shard_file_classes(tmp_path, klass):
    base, golden = _build_ec_volume(str(tmp_path))
    info = stripe.read_ec_info(base)
    want_size = scrub.expected_shard_size(info)
    crcs = info["shard_crc32"]
    p = stripe.shard_file_name(base, 3)
    if klass == "corrupt":
        _flip_byte(p)
        expect = scrub.CORRUPT
    elif klass == "truncated":
        os.truncate(p, want_size - 17)
        expect = scrub.TRUNCATED
    elif klass == "missing":
        os.unlink(p)
        expect = scrub.MISSING
    elif klass == "oversize":
        with open(p, "ab") as f:
            f.write(b"x")  # longer than the geometry allows: unvouchable
        expect = scrub.CORRUPT
    else:
        expect = scrub.OK
    assert scrub.scan_shard_file(p, crcs[3], want_size, chunk_bytes=8192) == expect


def test_scan_shard_file_budget_hook_sees_every_chunk(tmp_path):
    base, _ = _build_ec_volume(str(tmp_path))
    info = stripe.read_ec_info(base)
    want_size = scrub.expected_shard_size(info)
    seen = []
    v = scrub.scan_shard_file(
        stripe.shard_file_name(base, 0),
        info["shard_crc32"][0],
        want_size,
        chunk_bytes=10_000,
        budget=seen.append,
    )
    assert v == scrub.OK
    assert sum(seen) == want_size
    assert max(seen) <= 10_000


# -- cursor --------------------------------------------------------------------


def test_cursor_roundtrip_and_garbage_tolerance(tmp_path):
    path = str(tmp_path / "cursor.json")
    c = scrub.ScrubCursor(path)
    c.point(7, 3, 123456, 0xDEAD)
    c.cycles = 4
    c.save()
    c2 = scrub.ScrubCursor(path)
    assert (c2.vid, c2.shard, c2.offset, c2.crc, c2.cycles) == (7, 3, 123456, 0xDEAD, 4)
    with open(path, "w") as f:
        f.write("{torn garbage")
    c3 = scrub.ScrubCursor(path)
    assert (c3.vid, c3.shard, c3.offset, c3.crc) == (0, 0, 0, 0)


def test_cursor_quarantine_entries_persist(tmp_path):
    path = str(tmp_path / "cursor.json")
    c = scrub.ScrubCursor(path)
    c.add_quarantine(7, 3, scrub.CORRUPT)
    c.add_quarantine(7, 3, scrub.CORRUPT)  # dedup
    c.add_quarantine(8, 1, scrub.TRUNCATED)
    c2 = scrub.ScrubCursor(path)
    assert len(c2.quarantine) == 2
    c2.remove_quarantine(7, 3)
    c3 = scrub.ScrubCursor(path)
    assert c3.quarantine == [{"vid": 8, "shard": 1, "reason": scrub.TRUNCATED}]


def test_mid_shard_resume_uses_saved_crc_accumulator(tmp_path):
    """The cursor's (offset, crc) pair makes resume EXACT: scanning the
    suffix with the saved accumulator must reproduce the full-file
    verdict — and a WRONG accumulator must flag a clean file, proving
    the resume actually folds from the cursor instead of rescanning."""
    base, golden = _build_ec_volume(str(tmp_path))
    info = stripe.read_ec_info(base)
    want_size = scrub.expected_shard_size(info)
    p = stripe.shard_file_name(base, 2)
    k = want_size // 3
    prefix_crc = zlib.crc32(golden[2][:k])
    assert scrub.scan_shard_file(
        p, info["shard_crc32"][2], want_size, offset=k, crc=prefix_crc
    ) == scrub.OK
    assert scrub.scan_shard_file(
        p, info["shard_crc32"][2], want_size, offset=k, crc=prefix_crc ^ 1
    ) == scrub.CORRUPT


# -- repair policy -------------------------------------------------------------


def test_repair_policy_backoff_doubles_and_caps():
    now = [0.0]
    pol = scrub.RepairPolicy(base=2.0, max_backoff=10.0, time_fn=lambda: now[0])
    key = (7, 3)
    assert pol.due(key)
    assert pol.failed(key) == 2.0
    assert not pol.due(key)
    assert pol.delay(key) == 2.0
    now[0] = 2.0
    assert pol.due(key)
    assert pol.failed(key) == 4.0
    assert pol.failed(key) == 8.0
    assert pol.failed(key) == 10.0  # capped
    assert pol.failed(key) == 10.0
    pol.succeeded(key)
    assert pol.due(key)


# -- scrubber cycles -----------------------------------------------------------


def _mounted(base) -> EcVolume:
    return EcVolume(base, encoder=ENC, warm_on_mount=False)


def test_run_cycle_detects_all_classes_and_reports(tmp_path):
    base, golden = _build_ec_volume(str(tmp_path))
    ev = _mounted(base)
    try:
        _flip_byte(stripe.shard_file_name(base, 1))
        os.truncate(stripe.shard_file_name(base, 5), 100)
        os.unlink(stripe.shard_file_name(base, 9))
        found = []
        c0 = {
            k: stats.ScrubCorruptionsFound.labels(k).value
            for k in scrub.FINDING_CLASSES
        }
        s = scrub.Scrubber(
            volumes=lambda: {VID: ev},
            on_finding=lambda vid, sh, v: found.append((vid, sh, v)),
            cursor_path=str(tmp_path / "cursor.json"),
            rate_mb=0.0,  # unthrottled for the test
            chunk_bytes=64 * 1024,
        )
        out = s.run_cycle()
        assert sorted(found) == [
            (VID, 1, scrub.CORRUPT),
            (VID, 5, scrub.TRUNCATED),
            (VID, 9, scrub.MISSING),
        ]
        assert sorted(out["findings"]) == sorted(found)
        assert out["shards_ok"] == TOTAL_SHARDS_COUNT - 3
        assert out["scanned_bytes"] > 0
        for k in scrub.FINDING_CLASSES:
            assert stats.ScrubCorruptionsFound.labels(k).value == c0[k] + 1
        # a clean second cycle (quarantine the bad ones like the server
        # policy would) reports nothing
        for sh, v in ((1, scrub.CORRUPT), (5, scrub.TRUNCATED), (9, scrub.MISSING)):
            ev.quarantine_shard(sh, v)
        out2 = s.run_cycle()
        assert out2["findings"] == []
        assert s.cursor.cycles == 2
    finally:
        ev.close()


def test_run_cycle_skips_volumes_without_crc_record(tmp_path):
    base, _ = _build_ec_volume(str(tmp_path))
    info = stripe.read_ec_info(base)
    # strip the CRCs, as a pre-PR-2 volume would look
    stripe.write_ec_info(
        base, info["large_block_size"], info["small_block_size"], info["dat_size"]
    )
    ev = _mounted(base)
    try:
        s = scrub.Scrubber(
            volumes=lambda: {VID: ev},
            on_finding=lambda *a: pytest.fail("nothing to find"),
            cursor_path=str(tmp_path / "cursor.json"),
            rate_mb=0.0,
        )
        out = s.run_cycle()
        assert out["unverifiable"] == 1 and out["findings"] == []
    finally:
        ev.close()


def test_scrub_admission_hook_yields_then_proceeds(tmp_path):
    """A refused admit() parks the scan (bounded sleep) until the lane
    frees — the scrubber must call it before every chunk read."""
    base, _ = _build_ec_volume(str(tmp_path), size=120_000)
    ev = _mounted(base)
    calls = []
    gate_open = threading.Event()

    def admit() -> bool:
        calls.append(1)
        return gate_open.is_set()

    try:
        s = scrub.Scrubber(
            volumes=lambda: {VID: ev},
            on_finding=lambda *a: None,
            cursor_path=str(tmp_path / "cursor.json"),
            rate_mb=0.0,
            chunk_bytes=64 * 1024,
            admit=admit,
        )
        t = threading.Thread(target=s.run_cycle, daemon=True)
        t.start()
        time.sleep(0.3)
        assert calls, "admit() must gate every chunk read"
        assert t.is_alive(), "scan must park while the lane is refused"
        gate_open.set()
        t.join(20)
        assert not t.is_alive()
    finally:
        ev.close()


def test_interrupted_cycle_preserves_mid_shard_cursor(tmp_path):
    """stop() during a scan must leave the persisted cursor pointing at
    the exact mid-shard resume point — a completed-cycle reset here would
    make every clean restart rescan from the top."""
    base, _ = _build_ec_volume(str(tmp_path), size=300_000)
    ev = _mounted(base)
    admits = [0]
    parked = threading.Event()

    def admit() -> bool:
        admits[0] += 1
        if admits[0] > 3:
            parked.set()
            return False  # park the scan mid-shard until stop()
        return True

    try:
        s = scrub.Scrubber(
            volumes=lambda: {VID: ev},
            on_finding=lambda *a: None,
            cursor_path=str(tmp_path / "cursor.json"),
            rate_mb=0.0,
            chunk_bytes=16 * 1024,
            interval=3600.0,
            admit=admit,
        )
        s.start()
        assert parked.wait(10), "scan never reached the parked chunk"
        s.stop()
        c = scrub.ScrubCursor(str(tmp_path / "cursor.json"))
        # the resume point may sit mid-shard (offset > 0, saved CRC
        # accumulator) or on a shard boundary (the released chunk finished
        # its file) — what it must NEVER be is the completed-cycle reset
        assert c.vid == VID and (c.shard > 0 or c.offset > 0), (
            "interrupted cycle must persist a resume point, got "
            f"(vid={c.vid}, shard={c.shard}, offset={c.offset})"
        )
        assert c.cycles == 0  # the cycle did NOT complete
    finally:
        ev.close()


def test_quarantine_recovered_on_restart_with_scrubber_off(tmp_path):
    """Pending quarantine entries must be re-queued at server START even
    when the continuous scrubber is off (ec.verify/-on-read quarantines
    exist in that mode too): a server that died mid-repair must finish
    the heal, not run one shard short forever."""
    (tmp_path / "srv").mkdir()
    base, golden = _build_ec_volume(str(tmp_path / "srv"))
    # previous generation's state: shard 4 quarantined (file aside as
    # .bad) with the repair still pending in the persisted ledger
    p = stripe.shard_file_name(base, 4)
    os.replace(p, p + ".bad")
    cur = scrub.ScrubCursor(os.path.join(str(tmp_path / "srv"), ".scrub_cursor.json"))
    cur.add_quarantine(VID, 4, scrub.CORRUPT)
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    ok0 = stats.ScrubRepairs.labels("ok").value
    vs = VolumeServer([str(tmp_path / "srv")], master.address, heartbeat_interval=0.3)
    vs.start()
    try:
        assert config.env("WEEDTPU_SCRUB") == "off"
        _wait_for(
            lambda: stats.ScrubRepairs.labels("ok").value > ok0,
            timeout=30,
            msg="restart-recovered repair",
        )
        ev = vs.store.get_ec_volume(VID)
        _wait_for(lambda: 4 in ev.shard_ids, msg="shard remounted")
        with open(p, "rb") as f:
            assert f.read() == golden[4]
        assert not os.path.exists(p + ".bad")
        # the ledger entry cleared with the verified repair
        cur2 = scrub.ScrubCursor(vs._scrub_cursor.path)
        assert cur2.quarantine == []
    finally:
        vs.stop()
        master.stop()


# -- quarantine on EcVolume ----------------------------------------------------


def test_quarantine_routes_reads_to_reconstruction(tmp_path):
    """A quarantined shard must stop serving locally and degraded reads
    must decode the interval from survivors instead — byte-identical."""
    base, golden = _build_ec_volume(str(tmp_path))
    ev = _mounted(base)
    try:
        want = golden[2][1000:1400]
        assert ev._read_shard_interval(2, 1000, 400).tobytes() == want
        # now corrupt + quarantine it: reads must NOT see the bad bytes
        _flip_byte(stripe.shard_file_name(base, 2), 1100)
        assert ev.quarantine_shard(2, scrub.CORRUPT)
        assert 2 not in ev.shard_ids and ev.quarantined == {2: "corrupt"}
        got = ev._read_shard_interval(2, 1000, 400).tobytes()
        assert got == want, "reconstruction must serve the CLEAN bytes"
    finally:
        ev.close()


def test_mount_local_shard_restores_serving_and_clears_quarantine(tmp_path):
    base, golden = _build_ec_volume(str(tmp_path))
    ev = _mounted(base)
    try:
        ev.quarantine_shard(4, scrub.TRUNCATED)
        assert 4 not in ev.shard_ids
        assert ev.mount_local_shard(4)
        assert 4 in ev.shard_ids and not ev.quarantined
        assert ev._read_local(4, 0, 64).tobytes() == golden[4][:64]
    finally:
        ev.close()


def test_ec_shard_corrupt_raised_when_no_clean_copy(tmp_path):
    base, _ = _build_ec_volume(str(tmp_path))
    ev = _mounted(base)
    try:
        for s in (0, 1, 2, 3, 4):
            ev.quarantine_shard(s, scrub.CORRUPT)
        errs0 = stats.DegradedReadErrors.labels("EcShardCorrupt").value
        with pytest.raises(EcShardCorrupt) as ei:
            ev._read_shard_interval(0, 0, 128)
        assert issubclass(EcShardCorrupt, EcDegradedReadError)  # -> HTTP 503
        assert ei.value.quarantined == {s: "corrupt" for s in range(5)}
        assert ei.value.retry_after == 5.0
        assert stats.DegradedReadErrors.labels("EcShardCorrupt").value == errs0 + 1
    finally:
        ev.close()


# -- knobs ---------------------------------------------------------------------


def test_scrub_env_knobs_registered():
    for name, want in (
        ("WEEDTPU_SCRUB", "off"),
        ("WEEDTPU_SCRUB_RATE_MB", 64.0),
        ("WEEDTPU_SCRUB_CHUNK", 4 * 1024 * 1024),
        ("WEEDTPU_SCRUB_INTERVAL", 30.0),
        ("WEEDTPU_SCRUB_CURSOR", ""),
        ("WEEDTPU_SCRUB_REPAIR_BACKOFF", 5.0),
        ("WEEDTPU_SCRUB_MAX_REPAIRS", 1),
    ):
        assert config.env(name) == want


# -- control plane: VolumeEcShardsVerify + ec.verify ---------------------------


@pytest.fixture
def mini_cluster(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "srv0"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    yield master, vs
    vs.stop()
    master.stop()


def test_verify_rpc_report_only_then_quarantine_repair(mini_cluster, tmp_path):
    master, vs = mini_cluster
    d = os.path.dirname(vs._base_path_for(VID))
    base, golden = _build_ec_volume(d)
    with rpc.RpcClient(vs.grpc_address) as c:
        c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
        _flip_byte(stripe.shard_file_name(base, 6))
        resp = c.call(
            VOLUME_SERVICE, "VolumeEcShardsVerify", {"volume_id": VID}, timeout=60
        )
        assert resp["has_crcs"] is True
        assert resp["verdicts"]["6"] == "corrupt"
        assert all(
            v == "ok" for s, v in resp["verdicts"].items() if s != "6"
        )
        assert resp["quarantined"] == []  # report-only by default
        ev = vs.store.get_ec_volume(VID)
        assert 6 in ev.shard_ids  # still serving (operator's call)
        # now with quarantine: the shard leaves serving and repair heals it
        ok0 = stats.ScrubRepairs.labels("ok").value
        resp = c.call(
            VOLUME_SERVICE,
            "VolumeEcShardsVerify",
            {"volume_id": VID, "quarantine": True},
            timeout=60,
        )
        assert resp["quarantined"] == [6]
        st = c.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": VID})
        if st.get("quarantined"):  # repair may already have healed it
            assert st["quarantined"] == {"6": "corrupt"}
        _wait_for(
            lambda: stats.ScrubRepairs.labels("ok").value > ok0,
            timeout=30,
            msg="automatic repair of the quarantined shard",
        )
        ev = vs.store.get_ec_volume(VID)
        _wait_for(lambda: 6 in ev.shard_ids, msg="shard remounted")
        assert not ev.quarantined
        with open(stripe.shard_file_name(base, 6), "rb") as f:
            assert f.read() == golden[6], "repair must restore exact bytes"
        assert not os.path.exists(stripe.shard_file_name(base, 6) + ".bad")
        resp = c.call(
            VOLUME_SERVICE, "VolumeEcShardsVerify", {"volume_id": VID}, timeout=60
        )
        assert all(v == "ok" for v in resp["verdicts"].values())


def test_ec_verify_shell_command(mini_cluster):
    from seaweedfs_tpu.shell import CommandEnv, run_command

    master, vs = mini_cluster
    d = os.path.dirname(vs._base_path_for(VID))
    base, _ = _build_ec_volume(d)
    with rpc.RpcClient(vs.grpc_address) as c:
        c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _flip_byte(stripe.shard_file_name(base, 2))
    env = CommandEnv(master.address)
    try:
        _wait_for(
            lambda: any(
                int(e.get("volume_id", -1)) == VID
                for n in env.topology_nodes()
                for e in n.get("ec_shards", [])
            ),
            msg="ec shards in topology",
        )
        out = io.StringIO()
        run_command(env, f"ec.verify -volumeId {VID}", out)
        text = out.getvalue()
        assert "2=corrupt" in text
        assert "failed verification" in text
        # repair the flip so the volume is clean again, then verify clean
        _flip_byte(stripe.shard_file_name(base, 2))
        out = io.StringIO()
        run_command(env, f"ec.verify -volumeId {VID}", out)
        assert "all shards verified clean" in out.getvalue()
    finally:
        env.close()


def test_verify_on_read_heals_corrupt_needle(mini_cluster):
    """The second detection layer: a client read that races AHEAD of the
    background scrubber hits the needle body crc32c, and the server must
    identify + quarantine the corrupt shard and serve the CLEAN
    reconstruction — corrupt bytes never reach the client, even with the
    continuous scrubber off."""
    master, vs = mini_cluster
    client = MasterClient(master.address)
    try:
        blobs = {}
        for _ in range(10):
            payload = os.urandom(16_000)
            r = client.submit(payload)
            blobs[r.fid] = payload
        vid = int(next(iter(blobs)).split(",", 1)[0])
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsGenerate",
                {
                    "volume_id": vid,
                    "large_block_size": LARGE,
                    "small_block_size": SMALL,
                },
                timeout=120,
            )
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
            c.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
        base = vs._base_path_for(vid)
        found0 = stats.ScrubCorruptionsFound.labels("corrupt").value
        _flip_byte(stripe.shard_file_name(base, 0), 5000)
        # every read must return byte-exact data — the one that hits the
        # flipped region heals inline instead of erroring or serving it
        for fid, want in blobs.items():
            with urllib.request.urlopen(f"http://{vs.url}/{fid}", timeout=30) as r:
                assert r.read() == want
        assert stats.ScrubCorruptionsFound.labels("corrupt").value > found0, (
            "the corrupt shard should have been detected by verify-on-read"
        )
        ev = vs.store.get_ec_volume(vid)
        ok0 = stats.ScrubRepairs.labels("ok").value
        _wait_for(
            lambda: stats.ScrubRepairs.labels("ok").value > ok0
            or (0 in ev.shard_ids and not ev.quarantined),
            timeout=30,
            msg="quarantined shard repaired",
        )
    finally:
        client.close()


# -- the e2e smoke: detect -> quarantine -> trace-repair -> re-verify ----------


def test_scrub_e2e_bitflip_detect_quarantine_repair(tmp_path, monkeypatch):
    """The tier-1 scrub smoke (<= 20 s): a server running with the
    background scrubber ON takes a bit-flip on a live shard; the scan
    must detect it, quarantine the shard out of serving, trace-repair it
    from the 13 clean survivors, re-verify against .eci, and remount —
    with client reads byte-correct THROUGHOUT (never served the flip)."""
    monkeypatch.setenv("WEEDTPU_SCRUB", "on")
    monkeypatch.setenv("WEEDTPU_SCRUB_INTERVAL", "0.2")
    monkeypatch.setenv("WEEDTPU_SCRUB_RATE_MB", "0")  # unthrottled smoke
    monkeypatch.setenv("WEEDTPU_SCRUB_REPAIR_BACKOFF", "0.3")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "scrubbed"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    client = MasterClient(master.address)
    try:
        # real needles through the real write path, so reads can verify
        blobs = {}
        for i in range(6):
            payload = os.urandom(20_000)
            r = client.submit(payload)
            blobs[r.fid] = payload
        vid = int(next(iter(blobs)).split(",", 1)[0])
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsGenerate",
                {
                    "volume_id": vid,
                    "large_block_size": LARGE,
                    "small_block_size": SMALL,
                },
                timeout=120,
            )
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
            c.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
        base = vs._base_path_for(vid)
        info = stripe.read_ec_info(base)
        golden_crcs = info["shard_crc32"]
        found0 = stats.ScrubCorruptionsFound.labels("corrupt").value
        ok0 = stats.ScrubRepairs.labels("ok").value
        # let at least one clean cycle pass, then inject the flip
        _wait_for(lambda: stats.ScrubCycles.value > 0, msg="first scrub cycle")
        target = 1  # a data shard most needles touch
        _flip_byte(stripe.shard_file_name(base, target))
        _wait_for(
            lambda: stats.ScrubCorruptionsFound.labels("corrupt").value > found0,
            msg="scrub detects the bit-flip",
        )
        _wait_for(
            lambda: stats.ScrubRepairs.labels("ok").value > ok0,
            msg="automatic repair completes",
        )
        ev = vs.store.get_ec_volume(vid)
        _wait_for(lambda: target in ev.shard_ids, msg="shard remounted")
        assert not ev.quarantined
        # re-verified: bytes on disk match the .eci record again
        with open(stripe.shard_file_name(base, target), "rb") as f:
            assert zlib.crc32(f.read()) == golden_crcs[target]
        assert not os.path.exists(stripe.shard_file_name(base, target) + ".bad")
        # zero corrupt bytes served: every needle reads back byte-exact
        for fid, want in blobs.items():
            with urllib.request.urlopen(f"http://{vs.url}/{fid}", timeout=30) as r:
                assert r.read() == want
        assert stats.ScrubBytesScanned.value > 0
        # the persisted cursor survived the cycle machinery
        assert os.path.exists(os.path.join(str(d), ".scrub_cursor.json"))
    finally:
        client.close()
        vs.stop()
        master.stop()
