"""Inline-EC ingest tests: encode-on-write stripe builders born byte-
identical to the warm `write_ec_files` conversion, GF-linear delta parity
updates byte-exact vs full re-encode (tile-edge/odd/multi-block shapes),
crash/resume journal semantics (torn tails, truncated partials, pending
overwrite intents), the off/on/threshold policy at the volume-server
level, PR-7 interop (a delta-updated stripe rebuilt via trace-repair
projections), fsync'd .ecj appends with torn-tail tolerance, and the
tier-1 `BENCH_MODE=ingest` smoke with its deterministic < 0.5x delta-
bytes gate."""

import json
import os
import shutil
import time

import numpy as np
import pytest

from seaweedfs_tpu.ec import ingest, stripe
from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    TOTAL_SHARDS_COUNT,
)
from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.storage import types

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL, BUF = 8192, 2048, 2048
LARGE_ROW = LARGE * DATA_SHARDS_COUNT
VID = 7


def _write_dat(base: str, n_bytes: int, seed: int = 11) -> bytes:
    os.makedirs(os.path.dirname(base), exist_ok=True)
    data = np.random.default_rng(seed).integers(
        0, 256, n_bytes, dtype=np.uint8
    ).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    return data


def _warm_reference(tmp_path, data: bytes, name: str = "warm") -> str:
    wbase = os.path.join(str(tmp_path), name, str(VID))
    os.makedirs(os.path.dirname(wbase), exist_ok=True)
    with open(wbase + ".dat", "wb") as f:
        f.write(data)
    stripe.write_ec_files(
        wbase, large_block_size=LARGE, small_block_size=SMALL,
        buffer_size=BUF, encoder=ENC,
    )
    return wbase


def _assert_identical(base: str, wbase: str) -> None:
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            got = f.read()
        with open(stripe.shard_file_name(wbase, s), "rb") as f:
            assert got == f.read(), f"shard {s} differs from warm reference"
    with open(base + ".eci", "rb") as f, open(wbase + ".eci", "rb") as g:
        assert f.read() == g.read(), ".eci differs from warm reference"


def _builder(base, **kw):
    kw.setdefault("buffer_size", BUF)
    return ingest.InlineStripeBuilder(base, ENC, LARGE, SMALL, **kw)


def _resume(base, **kw):
    kw.setdefault("buffer_size", BUF)
    return ingest.InlineStripeBuilder.resume(base, ENC, LARGE, SMALL, **kw)


# -- born-EC'd byte-identity --------------------------------------------------


@pytest.mark.parametrize(
    "n_bytes",
    [
        LARGE_ROW * 3 + 12345,      # large rows + odd small tail
        LARGE_ROW * 2,              # exact row multiple (last row is SMALL)
        LARGE_ROW + SMALL * 3 + 1,  # one large row + partial small rows
        SMALL * 2 + 7,              # no large rows at all
    ],
)
def test_streamed_ingest_byte_identical_to_warm(tmp_path, n_bytes):
    """Appending in bursts with a poll per burst, then sealing, yields
    .ec00-.ec13 + .eci byte-identical to warm write_ec_files on the same
    final .dat — across tile-edge/exact/odd/tiny layouts."""
    base = os.path.join(str(tmp_path), "v", str(VID))
    os.makedirs(os.path.dirname(base))
    data = np.random.default_rng(n_bytes).integers(
        0, 256, n_bytes, dtype=np.uint8
    ).tobytes()
    b = _builder(base)
    with open(base + ".dat", "wb") as f:
        for off in range(0, n_bytes, 30_000):
            f.write(data[off : off + 30_000])
            f.flush()
            b.poll()
    info = b.seal()
    assert info["rows_total"] == stripe.stripe_layout(n_bytes, LARGE, SMALL)[0]
    _assert_identical(base, _warm_reference(tmp_path, data, f"w{n_bytes}"))
    # journal and partials are gone after a clean seal
    assert not os.path.exists(ingest.journal_path(base))
    assert not any(
        os.path.exists(ingest.part_path(base, s)) for s in range(TOTAL_SHARDS_COUNT)
    )


def test_partials_invisible_to_shard_discovery(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    _write_dat(base, LARGE_ROW * 2 + 99)
    b = _builder(base)
    b.poll()
    assert stripe.find_local_shards(base) == []  # .inp never masquerades
    b.abort()
    assert not os.path.exists(ingest.journal_path(base))


# -- delta parity updates: Encoder.update_parity vs golden --------------------


@pytest.mark.parametrize("n", [1, 255, 256, 4096, 4097])  # tile-edge + odd
@pytest.mark.parametrize("shard", [0, 3, 9])
def test_update_parity_byte_exact_vs_reencode(n, shard):
    """parity' from update_parity == parity of a full re-encode of the
    mutated stripe, and parity_delta == the gf8 golden formulation."""
    rng = np.random.default_rng(n * 31 + shard)
    stack = rng.integers(0, 256, (DATA_SHARDS_COUNT, n), dtype=np.uint8)
    parity = np.asarray(ENC.encode_parity_lazy(stack))
    new_block = rng.integers(0, 256, n, dtype=np.uint8)
    got = ENC.update_parity(parity, shard, stack[shard], new_block)
    mutated = stack.copy()
    mutated[shard] = new_block
    want = np.asarray(ENC.encode_parity_lazy(mutated))
    np.testing.assert_array_equal(got, want)
    # the gf8 golden: generator column x delta
    delta = stack[shard] ^ new_block
    np.testing.assert_array_equal(
        ENC.parity_delta(shard, stack[shard], new_block),
        gf8.gf_delta_parity(ENC.parity_matrix[:, shard], delta),
    )


def test_update_parity_multi_block_composes():
    """Changes to SEVERAL data shards compose by chaining single-shard
    updates — the linearity the inline builder's segment loop relies on."""
    rng = np.random.default_rng(77)
    stack = rng.integers(0, 256, (DATA_SHARDS_COUNT, 1000), dtype=np.uint8)
    parity = np.asarray(ENC.encode_parity_lazy(stack))
    mutated = stack.copy()
    for shard in (2, 6, 9):
        new_block = rng.integers(0, 256, 1000, dtype=np.uint8)
        parity = ENC.update_parity(parity, shard, mutated[shard], new_block)
        mutated[shard] = new_block
    np.testing.assert_array_equal(
        parity, np.asarray(ENC.encode_parity_lazy(mutated))
    )


def test_update_parity_jax_backend_matches_numpy():
    """The delta column dispatch rides the same backend seam as bulk
    encode — the (P, 1) x (1, n) shape must survive the bit-plane lift."""
    jax_enc = Encoder(10, 4, backend="jax")
    rng = np.random.default_rng(13)
    stack = rng.integers(0, 256, (DATA_SHARDS_COUNT, 777), dtype=np.uint8)
    parity = np.asarray(ENC.encode_parity_lazy(stack))
    new_block = rng.integers(0, 256, 777, dtype=np.uint8)
    np.testing.assert_array_equal(
        jax_enc.update_parity(parity, 4, stack[4], new_block),
        ENC.update_parity(parity, 4, stack[4], new_block),
    )


def test_update_parity_validates_shapes():
    parity = np.zeros((4, 10), dtype=np.uint8)
    with pytest.raises(ValueError):
        ENC.update_parity(parity, 10, b"x" * 10, b"y" * 10)  # shard oob
    with pytest.raises(ValueError):
        ENC.update_parity(parity, 0, b"x" * 9, b"y" * 10)  # length mismatch
    with pytest.raises(ValueError):
        ENC.update_parity(parity, 0, b"x" * 11, b"y" * 11)  # parity span


def test_builder_overwrite_byte_identical_to_warm(tmp_path):
    """An overwrite spanning a data-shard block boundary inside encoded
    rows, folded in via the journaled delta path, seals byte-identical to
    a warm encode of the mutated .dat (CRCs recomputed)."""
    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 3 + 4321)
    b = _builder(base)
    b.poll()
    assert b.rows_done == 3
    off = LARGE * 5 - 100  # crosses the shard-4/shard-5 block boundary
    new = bytes(np.random.default_rng(1).integers(0, 256, 300, dtype=np.uint8))

    def mutate():
        with open(base + ".dat", "r+b") as f:
            f.seek(off)
            f.write(new)

    patched = b.overwrite(off, data[off : off + 300], new, mutate=mutate)
    assert patched == 300
    assert b.delta_stats["updates"] == 1
    assert b.delta_stats["changed_bytes"] == 300
    info = b.seal()
    assert info["delta_updates"] == 1
    final = bytearray(data)
    final[off : off + 300] = new
    _assert_identical(base, _warm_reference(tmp_path, bytes(final)))


def test_overwrite_identical_bytes_is_free(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2)
    b = _builder(base)
    b.poll()
    assert b.overwrite(100, data[100:200], data[100:200]) == 0
    assert b.delta_stats["updates"] == 0 and b.crc_valid
    b.abort()


def test_overwrite_with_delta_disabled_forces_warm(tmp_path):
    """WEEDTPU_INLINE_EC_DELTA off: a touched encoded range breaks the
    builder (stale parity must never seal) but the mutate still runs."""
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2 + 5)
    b = _builder(base, delta_enabled=False)
    b.poll()
    ran = []
    patched = b.overwrite(
        0, data[:50], b"\x01" * 50, mutate=lambda: ran.append(1)
    )
    assert patched == 0 and ran == [1] and b.broken
    with pytest.raises(IOError):
        b.seal()
    b.abort()


# -- crash/resume journal semantics -------------------------------------------


def test_resume_after_crash_continues_and_matches(tmp_path):
    base = os.path.join(str(tmp_path), "v", str(VID))
    os.makedirs(os.path.dirname(base))
    data = np.random.default_rng(9).integers(
        0, 256, LARGE_ROW * 4 + 777, dtype=np.uint8
    ).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data[: LARGE_ROW * 2 + 7])
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    assert b.rows_done == 2
    b._close_handles()  # crash: no seal, no abort
    with open(base + ".dat", "ab") as f:
        f.write(data[LARGE_ROW * 2 + 7 :])
    r = _resume(base)
    assert r is not None and r.resumed and r.rows_done == 2
    r.poll()
    assert r.rows_done == 4
    r.seal()
    _assert_identical(base, _warm_reference(tmp_path, data))


def test_resume_truncates_rows_past_durable_watermark(tmp_path):
    """Rows encoded but not yet watermarked (lazy durability) are dropped
    on resume and re-encoded — unfsync'd bytes are never trusted."""
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 3 + 10)
    b = _builder(base)
    b.poll()
    assert b.rows_done == 3 and b._durable_rows == 0
    b._close_handles()  # crash before ANY watermark record
    r = _resume(base)
    assert r is not None and r.rows_done == 0  # everything re-encodes
    r.poll()
    assert r.rows_done == 3
    r.seal()
    _assert_identical(base, _warm_reference(tmp_path, data))


def test_resume_ignores_torn_journal_tail(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2 + 50)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    b._close_handles()
    with open(ingest.journal_path(base), "ab") as f:
        f.write(b'{"kind":"rows","rows"')  # crash mid-append
    r = _resume(base)
    assert r is not None and r.rows_done == 2
    r.seal()
    _assert_identical(base, _warm_reference(tmp_path, data))


def test_resume_refuses_truncated_partial(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    _write_dat(base, LARGE_ROW * 2 + 50)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    b._close_handles()
    with open(ingest.part_path(base, 4), "r+b") as f:
        f.truncate(100)  # below the durable watermark: contract broken
    assert _resume(base) is None


def test_resume_refuses_geometry_or_codec_drift(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    _write_dat(base, LARGE_ROW * 2)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    b._close_handles()
    assert ingest.InlineStripeBuilder.resume(
        base, ENC, LARGE * 2, SMALL, buffer_size=BUF
    ) is None
    other = Encoder(10, 4, matrix_kind="cauchy", backend="numpy")
    assert ingest.InlineStripeBuilder.resume(
        base, other, LARGE, SMALL, buffer_size=BUF
    ) is None


def test_resume_refuses_compacted_dat(tmp_path):
    """The journal pins the .dat's compact revision (superblock bytes
    4:6): a stale journal surviving a restart must NOT resume over a
    compacted (offset-shifted) rewrite — its rows encode deleted bytes."""
    base = os.path.join(str(tmp_path), str(VID))
    _write_dat(base, LARGE_ROW * 2 + 9)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    b._close_handles()
    with open(base + ".dat", "r+b") as f:  # simulate a compaction: bump rev
        f.seek(4)
        f.write((99).to_bytes(2, "big"))
    assert _resume(base) is None
    # the replication byte (offset 1) is NOT part of the pin — the
    # configure-replication delta path rewrites it legitimately
    base2 = os.path.join(str(tmp_path), "v2", str(VID))
    _write_dat(base2, LARGE_ROW * 2 + 9)
    b2 = _builder(base2)
    b2.poll()
    b2._flush_watermark()
    b2._close_handles()
    with open(base2 + ".dat", "r+b") as f:
        f.seek(1)
        f.write(b"\x77")
    assert _resume(base2) is not None


def test_manager_discard_scrubs_disk_state(tmp_path):
    """discard(vid, base) drops the on-disk journal and partials too —
    compaction/volume-delete must not leave dead stripe state waiting."""
    base = os.path.join(str(tmp_path), str(VID))
    _write_dat(base, LARGE_ROW + 50)
    mgr = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
    )
    mgr.on_write(VID)
    with mgr._lock:
        b = mgr._builders.get(VID)
    b.poll()
    b._flush_watermark()
    assert os.path.exists(ingest.journal_path(base))
    mgr.discard(VID, base)
    assert not os.path.exists(ingest.journal_path(base))
    assert not any(
        os.path.exists(ingest.part_path(base, s))
        for s in range(TOTAL_SHARDS_COUNT)
    )
    # restart shape: journal on disk, empty builder dict, discard by base
    mgr2 = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
    )
    mgr2.on_write(VID)
    with mgr2._lock:
        b2 = mgr2._builders.pop(VID)
    b2.poll()
    b2._flush_watermark()
    b2._close_handles()  # "restart": no in-memory builder anywhere
    mgr3 = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
    )
    mgr3.discard(VID, base)
    assert not os.path.exists(ingest.journal_path(base))


def test_overwrite_mutate_failure_breaks_builder_and_propagates(tmp_path):
    """A mutate() that fails with encoded rows at stake may have partially
    rewritten the .dat: the builder must mark itself broken (warm fallback
    at seal) and the caller's error must propagate — the RPC has to fail
    exactly like the non-inline path's would."""
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2)
    b = _builder(base)
    b.poll()

    def bad_mutate():
        raise OSError("disk full")

    with pytest.raises(OSError):
        b.overwrite(0, data[:16], b"\x05" * 16, mutate=bad_mutate)
    assert b.broken
    with pytest.raises(IOError):
        b.seal()
    b.abort()


def test_overwrite_on_closed_builder_still_mutates(tmp_path):
    """A seal closing the builder between lookup and overwrite must not
    swallow the caller's .dat mutation."""
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW + 20)
    b = _builder(base)
    b.poll()
    b.seal()
    ran = []
    assert b.overwrite(0, data[:8], b"\x01" * 8, mutate=lambda: ran.append(1)) == 0
    assert ran == [1]


def test_resume_resolves_pending_overwrite_intent(tmp_path):
    """Crash between the intent record and the delta application: the
    resume compares the .dat against the recorded old/new bytes and
    finishes exactly the unapplied segments."""
    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 3 + 123)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    off = LARGE * 7 - 100  # spans two blocks -> two delta segments
    old = data[off : off + 300]
    new = bytes(np.random.default_rng(2).integers(0, 256, 300, dtype=np.uint8))
    ingest._append_record(
        b._journal,
        {"kind": "ow", "off": off, "old": ingest._b64(old), "new": ingest._b64(new)},
    )
    with open(base + ".dat", "r+b") as f:
        f.seek(off)
        f.write(new)
    # apply only the FIRST segment's delta before the "crash"
    row, q = divmod(off, LARGE_ROW)
    d, col = divmod(q, LARGE)
    seg = min(LARGE - col, 300)
    o_np = np.frombuffer(old, dtype=np.uint8)
    n_np = np.frombuffer(new, dtype=np.uint8)
    b._apply_delta(row * LARGE + col, d, o_np[:seg], n_np[:seg])
    b._close_handles()
    r = _resume(base)
    assert r is not None
    r.seal()
    final = bytearray(data)
    final[off : off + 300] = new
    _assert_identical(base, _warm_reference(tmp_path, bytes(final)))


def test_resume_pending_delta_seal_crcs_match_disk(tmp_path):
    """The stale-CRC seam: a crash-resume with a PENDING overwrite intent
    (crc_valid=False) followed by a further post-resume delta must force
    `_recompute_crcs()` before `seal()` writes `.eci` — asserted the
    strong way, CRC32 of the sealed shard BYTES ON DISK == the `.eci`
    record (a stale stream-fold here would make every later fsck/scrub
    pass flag a healthy volume as corrupt)."""
    import zlib

    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 3 + 123)
    b = _builder(base)
    b.poll()
    b._flush_watermark()  # durable watermark carries VALID streamed CRCs
    off = LARGE * 3 + 17
    old = data[off : off + 200]
    new = bytes(np.random.default_rng(5).integers(0, 256, 200, dtype=np.uint8))
    # crash mid-overwrite: intent journaled + .dat mutated, delta never ran
    ingest._append_record(
        b._journal,
        {"kind": "ow", "off": off, "old": ingest._b64(old), "new": ingest._b64(new)},
    )
    with open(base + ".dat", "r+b") as f:
        f.seek(off)
        f.write(new)
    b._close_handles()
    r = _resume(base)
    assert r is not None
    # the watermark's streamed CRCs can no longer be vouched for: the
    # pending intent's resolution patched shard bytes in place
    assert not r.crc_valid
    # a further post-resume delta through the resumed builder
    off2 = LARGE * 12 + 5
    cur = bytearray(data)
    cur[off : off + 200] = new
    new2 = bytes(np.random.default_rng(6).integers(0, 256, 100, dtype=np.uint8))

    def mutate():
        with open(base + ".dat", "r+b") as f:
            f.seek(off2)
            f.write(new2)

    patched = r.overwrite(off2, bytes(cur[off2 : off2 + 100]), new2, mutate=mutate)
    assert patched > 0
    r.seal()
    info = stripe.read_ec_info(base)
    recorded = info["shard_crc32"]
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert zlib.crc32(f.read()) == recorded[s], (
                f"shard {s}: sealed .eci CRC does not match the bytes on disk"
            )
    # and the whole set equals the warm conversion of the final .dat
    final = bytearray(data)
    final[off : off + 200] = new
    final[off2 : off2 + 100] = new2
    _assert_identical(base, _warm_reference(tmp_path, bytes(final), "wseam"))


def test_resume_refuses_unknown_dat_mutation(tmp_path):
    """A pending intent whose range matches NEITHER old nor new bytes
    means someone else mutated the .dat — not recoverable, warm fallback."""
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2)
    b = _builder(base)
    b.poll()
    b._flush_watermark()
    ingest._append_record(
        b._journal,
        {
            "kind": "ow",
            "off": 0,
            "old": ingest._b64(data[:50]),
            "new": ingest._b64(b"\x01" * 50),
        },
    )
    with open(base + ".dat", "r+b") as f:
        f.write(b"\x02" * 50)  # a third state
    b._close_handles()
    assert _resume(base) is None


# -- IngestManager + seal fallback --------------------------------------------


class _FakeVol:
    def __init__(self, base):
        self.base_path = base
        self.dat_path = base + ".dat"
        self.read_only = False
        self.tiered = False


class _FakeStore:
    def __init__(self, base, encoder=ENC):
        self.encoder = encoder
        self._vol = _FakeVol(base)

    def get_volume(self, vid):
        return self._vol


def test_manager_seal_inline_then_warm_fallback(tmp_path):
    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 2 + 999)
    mgr = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
        seal_bytes=0,
    )
    mgr.on_write(VID)
    info = mgr.seal_volume(VID, base)
    assert info["mode"] == "inline" and info["rows_inline"] == 2
    _assert_identical(base, _warm_reference(tmp_path, data))
    # second volume: corrupt journal -> resume fails -> warm fallback
    base2 = os.path.join(str(tmp_path), "v2", str(VID))
    data2 = _write_dat(base2, LARGE_ROW + 100, seed=3)
    mgr2 = ingest.IngestManager(
        _FakeStore(base2), large_block_size=LARGE, small_block_size=SMALL,
        seal_bytes=0,
    )
    mgr2.on_write(VID)
    with mgr2._lock:
        b = mgr2._builders.pop(VID)
    b.poll()  # deterministic: the worker may not have run yet
    b._flush_watermark()
    b._close_handles()
    with open(ingest.journal_path(base2), "r+b") as f:
        f.truncate(3)  # unreadable head: un-vouchable state
    info2 = mgr2.seal_volume(VID, base2)
    assert info2["mode"] == "warm"
    _assert_identical(base2, _warm_reference(tmp_path, data2, "w2"))
    # the fallback cleaned the leftovers
    assert not os.path.exists(ingest.journal_path(base2))


def test_manager_seal_resumed_after_crash(tmp_path):
    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 2 + 11)
    mgr = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
    )
    mgr.on_write(VID)
    with mgr._lock:
        b = mgr._builders.pop(VID)
    b.poll()  # deterministic: the worker may not have run yet
    b._flush_watermark()
    b._close_handles()  # crash; a NEW manager (fresh process) seals
    mgr2 = ingest.IngestManager(
        _FakeStore(base), large_block_size=LARGE, small_block_size=SMALL,
    )
    info = mgr2.seal_volume(VID, base)
    assert info["mode"] == "resumed"
    _assert_identical(base, _warm_reference(tmp_path, data))


# -- policy off/on/threshold at the volume-server level -----------------------


def _wait_for(cond, timeout=25.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {msg}")


def test_server_policy_off_by_default(tmp_path):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    vs = VolumeServer([str(tmp_path)], master.address, heartbeat_interval=0.5)
    vs.start()
    try:
        assert vs._ingest is None
        assert vs.store.on_write is None
    finally:
        vs.stop()
        master.stop()


def test_server_threshold_auto_seal_and_inline_generate(tmp_path, monkeypatch):
    """WEEDTPU_INLINE_EC=on + a seal threshold: PUTs stream through the
    builders, the volume crossing the threshold is sealed in place
    (read-only, shards byte-identical to warm, EC volume mounted), reads
    keep verifying, and the explicit inline-generate RPC serves a second
    volume from its live stripe state."""
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    monkeypatch.setenv("WEEDTPU_INLINE_EC", "on")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_LARGE_BLOCK", str(LARGE))
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SMALL_BLOCK", str(SMALL))
    seal_at = LARGE_ROW * 2 + 5000
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SEAL_BYTES", str(seal_at))
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    vdir = os.path.join(str(tmp_path), "v")
    os.makedirs(vdir)
    vs = VolumeServer([vdir], master.address, heartbeat_interval=0.4)
    vs.start()
    client = MasterClient(master.address)
    rng = np.random.default_rng(21)
    try:
        _wait_for(lambda: master.topology.nodes, msg="cluster form-up")
        blobs = {}
        for _ in range(40):
            payload = rng.integers(0, 256, 6000, dtype=np.uint8).tobytes()
            for _attempt in range(5):
                a = client.assign()
                try:
                    client.upload(a.fid, payload)
                    blobs[a.fid] = payload
                    break
                except Exception:  # noqa: BLE001 — sealing race: re-assign
                    time.sleep(0.3)
        vid = int(next(iter(blobs)).split(",")[0])
        base = vs._base_path_for(vid)
        _wait_for(
            lambda: stripe.find_local_shards(base) == list(range(TOTAL_SHARDS_COUNT)),
            msg="auto-seal",
        )
        with rpc.RpcClient(vs.grpc_address) as c:
            st = c.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": vid})
        assert st["kind"] == "normal" and st["read_only"]
        # byte-identity vs warm on the same sealed bytes
        wdir = os.path.join(str(tmp_path), "warm")
        os.makedirs(wdir)
        wbase = os.path.join(wdir, str(vid))
        shutil.copy(base + ".dat", wbase + ".dat")
        shutil.copy(base + ".idx", wbase + ".idx")
        stripe.write_ec_files(
            wbase, large_block_size=LARGE, small_block_size=SMALL,
            encoder=vs.store.encoder,
        )
        stripe.write_sorted_file_from_idx(wbase)
        for s in range(TOTAL_SHARDS_COUNT):
            with open(stripe.shard_file_name(base, s), "rb") as f:
                got = f.read()
            with open(stripe.shard_file_name(wbase, s), "rb") as f:
                assert got == f.read(), f"shard {s} differs"
        with open(base + ".ecx", "rb") as f, open(wbase + ".ecx", "rb") as g:
            assert f.read() == g.read()
        for fid, want in blobs.items():
            assert client.read(fid) == want
        # explicit inline generate on a later (unsealed) volume
        vid2 = max(
            int(fid.split(",")[0]) for fid in blobs
        )
        if vid2 != vid:
            with rpc.RpcClient(vs.grpc_address) as c:
                c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid2})
                resp = c.call(
                    VOLUME_SERVICE, "VolumeEcShardsGenerate",
                    {"volume_id": vid2, "inline": True}, timeout=120,
                )
            assert resp["mode"] in ("inline", "resumed"), resp
            assert resp["shard_ids"] == list(range(TOTAL_SHARDS_COUNT))
    finally:
        client.close()
        vs.stop()
        master.stop()


def test_server_inline_generate_mismatched_geometry_goes_warm(tmp_path, monkeypatch):
    """An inline request whose explicit block sizes disagree with the
    builders' geometry must warm-encode with the REQUESTED sizes."""
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.cluster.client import MasterClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    monkeypatch.setenv("WEEDTPU_INLINE_EC", "on")
    monkeypatch.setenv("WEEDTPU_INLINE_EC_LARGE_BLOCK", str(LARGE))
    monkeypatch.setenv("WEEDTPU_INLINE_EC_SMALL_BLOCK", str(SMALL))
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    vdir = os.path.join(str(tmp_path), "v")
    os.makedirs(vdir)
    vs = VolumeServer([vdir], master.address, heartbeat_interval=0.4)
    vs.start()
    client = MasterClient(master.address)
    rng = np.random.default_rng(4)
    try:
        _wait_for(lambda: master.topology.nodes, msg="cluster form-up")
        a = client.assign()
        client.upload(a.fid, rng.integers(0, 256, 9000, dtype=np.uint8).tobytes())
        vid = int(a.fid.split(",")[0])
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
            resp = c.call(
                VOLUME_SERVICE, "VolumeEcShardsGenerate",
                {
                    "volume_id": vid,
                    "inline": True,
                    "large_block_size": LARGE * 2,  # mismatched geometry
                    "small_block_size": SMALL,
                },
                timeout=120,
            )
        assert resp["mode"] == "warm", resp
        base = vs._base_path_for(vid)
        info = stripe.read_ec_info(base)
        assert info["large_block_size"] == LARGE * 2
    finally:
        client.close()
        vs.stop()
        master.stop()


# -- PR-7 interop: delta-updated stripe rebuilt via trace projections ---------


def test_delta_updated_shard_rebuilds_via_trace_repair(tmp_path):
    """A stripe sealed from inline state WITH a delta update rebuilds a
    lost shard via the trace-repair projection pipeline byte-identically
    — the two GF-linearity exploits (rank-1 parity update, projection
    XOR-combine) agree on the same bytes."""
    base = os.path.join(str(tmp_path), "v", str(VID))
    data = _write_dat(base, LARGE_ROW * 3 + 2222)
    b = _builder(base)
    b.poll()
    off = LARGE * 12 + 31  # row 1, shard 2
    new = bytes(np.random.default_rng(8).integers(0, 256, 400, dtype=np.uint8))

    def mutate():
        with open(base + ".dat", "r+b") as f:
            f.seek(off)
            f.write(new)

    assert b.overwrite(off, data[off : off + 400], new, mutate=mutate) == 400
    b.seal()
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    missing = [2]  # the delta-touched data shard itself
    os.unlink(stripe.shard_file_name(base, 2))
    shard_size = len(golden[0])
    survivors = sorted(stripe.find_local_shards(base))[:DATA_SHARDS_COUNT]
    plan = ENC.repair_projection_plan(survivors, missing)
    groups = [
        stripe.LocalProjectionSource(
            [stripe.shard_file_name(base, s) for s in survivors[:5]],
            np.stack([plan[s] for s in survivors[:5]], axis=1),
            ENC,
        ),
        stripe.LocalProjectionSource(
            [stripe.shard_file_name(base, s) for s in survivors[5:]],
            np.stack([plan[s] for s in survivors[5:]], axis=1),
            ENC,
        ),
    ]
    try:
        rebuilt = stripe.rebuild_ec_files_from_projections(
            base, groups, shard_size, missing, encoder=ENC,
            buffer_size=16384, max_batch_bytes=10 * 3 * 16384,
        )
    finally:
        for g in groups:
            g.close()
    assert rebuilt == missing
    with open(stripe.shard_file_name(base, 2), "rb") as f:
        assert f.read() == golden[2]


# -- .ecj fsync + torn-tail tolerance -----------------------------------------


def test_append_ecj_survives_torn_tail(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    stripe.append_ecj(base, 101)
    stripe.append_ecj(base, 202)
    with open(base + ".ecj", "ab") as f:
        f.write(b"\x00\x01\x02")  # torn tail: crash mid-append
    assert stripe.read_ecj(base) == [101, 202]
    # appending after the torn tail still replays the COMPLETE records
    # (the torn fragment corrupts alignment only past itself — compact
    # folds the journal long before that matters, but the reader must
    # not crash)
    assert len(stripe.read_ecj(base)) == 2


def test_journal_reader_ignores_torn_tail(tmp_path):
    base = os.path.join(str(tmp_path), str(VID))
    with open(ingest.journal_path(base), "wb") as f:
        f.write(b'{"kind":"begin","version":1}\n{"kind":"rows","rows":2}\n')
        f.write(b'{"kind":"rows","ro')  # torn
    recs = ingest.read_journal(base)
    assert [r["kind"] for r in recs] == ["begin", "rows"]


# -- stats + registry ---------------------------------------------------------


def test_inline_counters_move(tmp_path):
    from seaweedfs_tpu import stats

    rows0 = stats.InlineEcRows.value
    deltas0 = stats.InlineEcDeltaUpdates.value
    base = os.path.join(str(tmp_path), str(VID))
    data = _write_dat(base, LARGE_ROW * 2 + 10)
    b = _builder(base)
    b.poll()
    assert stats.InlineEcRows.value == rows0 + 2
    new = bytes(np.random.default_rng(5).integers(0, 256, 64, dtype=np.uint8))

    def mutate():
        with open(base + ".dat", "r+b") as f:
            f.seek(0)
            f.write(new)

    b.overwrite(0, data[:64], new, mutate=mutate)
    assert stats.InlineEcDeltaUpdates.value == deltas0 + 1
    b.abort()


def test_inline_env_knobs_registered():
    from seaweedfs_tpu.utils import config

    for name in (
        "WEEDTPU_INLINE_EC",
        "WEEDTPU_INLINE_EC_SEAL_BYTES",
        "WEEDTPU_INLINE_EC_DELTA",
        "WEEDTPU_INLINE_EC_LARGE_BLOCK",
        "WEEDTPU_INLINE_EC_SMALL_BLOCK",
    ):
        assert name in config.ENV_REGISTRY
    assert config.env("WEEDTPU_INLINE_EC") in ("on", "off")


# -- tier-1 bench smoke: the deterministic delta-bytes gate -------------------


def test_bench_ingest_smoke(tmp_path):
    """Fast CPU smoke of bench.py's ec_ingest harness: inline output must
    match warm byte-for-byte and the delta path's BYTE accounting (not a
    timing) must meet the < 0.5x gate for a ~1% overwrite mix."""
    import bench

    out = bench._measure_ingest(
        str(tmp_path),
        dat_bytes=1 << 20,
        large=16384,
        small=4096,
        buffer_size=4096,
        append_chunk=96 << 10,
        overwrite_count=4,
        encoder=ENC,
    )
    assert out["ok"], out
    assert out["match"] and out["delta"]["match"]
    assert out["inline"]["rows_inline"] == out["inline"]["rows_total"] > 0
    assert out["delta"]["bytes_ratio"] < 0.5, out["delta"]
    assert out["delta"]["overwrite_fraction"] <= 0.011
