"""Shell (weed/shell analog) end-to-end tests against a real in-process
cluster — the §3.1/§3.3 call stacks driven the way an operator drives
them: lock, ec.encode, degraded read, ec.rebuild, ec.balance,
volume.fix.replication (SURVEY.md §4 test strategy)."""

import io

import pytest

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.shell import CommandEnv, ShellError, run_command, run_script

LARGE, SMALL = 4096, 512


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(4):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)],
            master.address,
            heartbeat_interval=0.3,
            rack=f"rack{i % 2}",
            max_volume_count=50,
        )
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    env = CommandEnv(master.address)
    yield master, servers, client, env
    env.close()
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()


def run(env, line):
    out = io.StringIO()
    run_command(env, line, out)
    return out.getvalue()


def _upload_some(client, n=20, size=700):
    import os as _os

    fids = []
    for i in range(n):
        res = client.submit(_os.urandom(size))
        fids.append((res.fid, client.read(res.fid)))
    return fids


def _ec_shard_spread(env, vid):
    """url -> shard ids for vid, from the master's view."""
    out = {}
    for n in env.topology_nodes():
        for e in n.get("ec_shards", []):
            if int(e["volume_id"]) == vid:
                out[n["url"]] = ShardBits(e["shard_bits"]).shard_ids()
    return out


def test_lock_required_and_contention(cluster):
    master, servers, client, env = cluster
    with pytest.raises(ShellError, match="lock the cluster"):
        run(env, "volume.delete -volumeId 1")
    assert "locked" in run(env, "lock")
    env2 = CommandEnv(master.address, client_name="intruder")
    try:
        with pytest.raises(Exception, match="held by"):
            env2.lock()
    finally:
        env2.close()
    assert "unlocked" in run(env, "unlock")
    env2 = CommandEnv(master.address, client_name="second")
    try:
        env2.lock()  # free now
        env2.unlock()
    finally:
        env2.close()


def test_help_and_volume_list(cluster):
    master, servers, client, env = cluster
    _upload_some(client, n=3)
    out = run(env, "help")
    assert "ec.encode" in out and "volume.list" in out
    out = run(env, "volume.list")
    assert "DataCenter" in out and "volume 1" in out
    out = run(env, "collection.list")
    assert "collection: ''" in out
    out = run(env, "cluster.check")
    assert "4 nodes" in out and "unreachable" not in out.replace("0 unreachable", "")


def test_ec_encode_read_rebuild_balance(cluster):
    master, servers, client, env = cluster
    fids = _upload_some(client, n=25)
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")

    out = run(
        env,
        f"ec.encode -volumeId {vid} -largeBlockSize {LARGE} -smallBlockSize {SMALL}",
    )
    assert f"ec.encode volume {vid}" in out
    spread = _ec_shard_spread(env, vid)
    assert sorted(s for sids in spread.values() for s in sids) == list(range(14))
    assert len(spread) == 4  # spread across all nodes
    # original volume is gone from the topology
    assert not any(
        int(v["id"]) == vid
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
    ), "original volume must be deleted after cut-over"

    # every blob still readable through the EC path (incl. remote intervals)
    for fid, payload in fids:
        assert client.read(fid) == payload, f"fid {fid} corrupted after ec.encode"

    # lose one node's shards entirely -> rebuild restores 14/14
    victim_url, victim_sids = sorted(spread.items())[0]
    victim = next(s for s in servers if s.url == victim_url)
    host = victim_url.rsplit(":", 1)[0]
    env.vs_call(
        f"{host}:{victim.grpc_port}",
        "VolumeEcShardsDelete",
        {"volume_id": vid, "shard_ids": victim_sids},
    )
    assert sorted(
        s for sids in _ec_shard_spread(env, vid).values() for s in sids
    ) != list(range(14))
    out = run(env, "ec.rebuild")
    assert "rebuilt" in out
    spread2 = _ec_shard_spread(env, vid)
    assert sorted(s for sids in spread2.values() for s in sids) == list(range(14))
    for fid, payload in fids:
        assert client.read(fid) == payload, f"fid {fid} corrupted after ec.rebuild"

    # balance: counts within 1 of each other afterwards
    run(env, "ec.balance")
    counts = [len(s) for s in _ec_shard_spread(env, vid).values()]
    assert max(counts) - min(counts) <= 1 or len(counts) == 4

    # decode back to a normal volume; data still readable
    out = run(env, f"ec.decode -volumeId {vid}")
    assert "restored as normal volume" in out
    assert _ec_shard_spread(env, vid) == {}
    for fid, payload in fids:
        assert client.read(fid) == payload, f"fid {fid} corrupted after ec.decode"


def _make_second_volume(cluster):
    """Two live volumes in the default collection: fill vid 1, mark it
    readonly is not enough (ec.encode skips nothing by state) — instead
    grow by marking 1 readonly so the next upload allocates vid 2."""
    master, servers, client, env = cluster
    fids_a = _upload_some(client, n=6)
    vid_a = int(fids_a[0][0].split(",", 1)[0])
    owner = next(s for s in servers if s.store.get_volume(vid_a) is not None)
    owner.store.get_volume(vid_a).read_only = True
    # master must notice via heartbeat before assign picks a fresh volume
    import time as _time

    deadline = _time.monotonic() + 5
    vid_b = vid_a
    fids_b = []
    while _time.monotonic() < deadline and vid_b == vid_a:
        try:
            res = client.submit(b"second-volume-seed")
        except Exception:  # master hasn't seen the readonly mark yet (422)
            _time.sleep(0.1)
            continue
        fids_b.append((res.fid, b"second-volume-seed"))
        vid_b = int(res.fid.split(",", 1)[0])
        _time.sleep(0.1)
    assert vid_b != vid_a, "second volume never grew"
    owner.store.get_volume(vid_a).read_only = False
    return fids_a + fids_b, vid_a, vid_b


def test_ec_encode_batch_resume_after_interrupt(cluster, tmp_path, monkeypatch):
    """SURVEY §5: a batch ec.encode killed mid-run resumes — the rerun
    skips checkpointed volumes instead of re-encoding them."""
    import seaweedfs_tpu.shell.command_ec as cec

    master, servers, client, env = cluster
    fids, vid_a, vid_b = _make_second_volume(cluster)
    ckpt = str(tmp_path / "enc.ckpt")
    run(env, "lock")

    # simulated kill: the encode of the SECOND volume dies at its start —
    # after the first volume completed and was checkpointed
    real = cec._do_ec_encode
    calls = {"n": 0}

    def dying(env_, nodes, vid, coll, w, **kw):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise KeyboardInterrupt("simulated operator kill")
        return real(env_, nodes, vid, coll, w, **kw)

    monkeypatch.setattr(cec, "_do_ec_encode", dying)
    with pytest.raises(KeyboardInterrupt):
        run(env, f"ec.encode -collection '' -force -checkpoint {ckpt} "
                 f"-largeBlockSize {LARGE} -smallBlockSize {SMALL}")
    import json as _json

    with open(ckpt) as f:
        saved = _json.load(f)
    assert saved["done"] == [vid_a], "first volume must be checkpointed"

    # rerun (no kill): the checkpointed volume is skipped even though the
    # master's topology may still show it (stale heartbeat window)
    monkeypatch.setattr(cec, "_do_ec_encode", real)
    out = run(env, f"ec.encode -collection '' -force -checkpoint {ckpt} "
                   f"-largeBlockSize {LARGE} -smallBlockSize {SMALL}")
    if f"volume {vid_a}" in out:
        assert f"ec.encode volume {vid_a}: skip (checkpointed)" in out
    assert f"ec.encode volume {vid_b}" in out
    import os as _os

    assert not _os.path.exists(ckpt), "completed batch must clear checkpoint"
    # every blob from both volumes still readable
    for fid, payload in fids:
        assert client.read(fid) == payload, fid


def test_rebuild_shard_copies_run_concurrently(cluster, monkeypatch):
    """command_ec_rebuild.go's prepareDataToRecover analog: survivor shard
    pulls overlap in time — rebuild wall time is the slowest source, not
    the sum of copies."""
    import threading
    import time as _t

    master, servers, client, env = cluster
    fids = _upload_some(client, n=10)
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")
    run(env, f"ec.encode -volumeId {vid} -largeBlockSize {LARGE} -smallBlockSize {SMALL}")

    spread = _ec_shard_spread(env, vid)
    victim_url, victim_sids = sorted(spread.items())[0]
    victim = next(s for s in servers if s.url == victim_url)
    host = victim_url.rsplit(":", 1)[0]
    env.vs_call(
        f"{host}:{victim.grpc_port}",
        "VolumeEcShardsDelete",
        {"volume_id": vid, "shard_ids": victim_sids},
    )

    orig = env.vs_call
    lock = threading.Lock()
    state = {"cur": 0, "max": 0, "copies": 0}

    def tracked(addr, method, req, timeout=300):
        if method != "VolumeEcShardsCopy":
            return orig(addr, method, req, timeout=timeout)
        with lock:
            state["cur"] += 1
            state["copies"] += 1
            state["max"] = max(state["max"], state["cur"])
        _t.sleep(0.25)  # hold the slot so overlap is observable
        try:
            return orig(addr, method, req, timeout=timeout)
        finally:
            with lock:
                state["cur"] -= 1

    monkeypatch.setattr(env, "vs_call", tracked)
    out = run(env, "ec.rebuild")
    assert "rebuilt" in out
    assert state["copies"] >= 2, "expected pulls from >=2 survivor sources"
    assert state["max"] >= 2, "shard copies ran strictly serially"
    for fid, payload in fids:
        assert client.read(fid) == payload


def test_volume_vacuum_and_mark(cluster):
    master, servers, client, env = cluster
    fids = _upload_some(client, n=10)
    vid = int(fids[0][0].split(",", 1)[0])
    for fid, _ in fids[:6]:
        client.delete(fid)
    run(env, "lock")
    out = run(env, f"volume.vacuum -volumeId {vid}")
    assert "->" in out
    for fid, payload in fids[6:]:
        assert client.read(fid) == payload
    out = run(env, f"volume.mark -volumeId {vid} -readonly")
    assert "readonly" in out
    out = run(env, f"volume.mark -volumeId {vid} -writable")
    assert "writable" in out


def test_fix_replication(cluster):
    master, servers, client, env = cluster
    res = client.submit(b"replicated payload", replication="001")
    vid = int(res.fid.split(",", 1)[0])
    # wait for heartbeats to register both replicas
    holders = [
        n for n in env.topology_nodes()
        if any(int(v["id"]) == vid for v in n.get("volumes", []))
    ]
    assert len(holders) == 2
    # drop one replica behind the master's back
    victim = holders[0]
    host = victim["url"].rsplit(":", 1)[0]
    env.vs_call(f"{host}:{victim['grpc_port']}", "VolumeDelete", {"volume_id": vid})
    out = run(env, "volume.fix.replication -noFix")
    assert f"volume {vid}: 1/2 replicas" in out
    run(env, "lock")
    out = run(env, "volume.fix.replication")
    assert "fixed 1" in out
    holders = [
        n for n in env.topology_nodes()
        if any(int(v["id"]) == vid for v in n.get("volumes", []))
    ]
    assert len(holders) == 2
    assert client.read(res.fid) == b"replicated payload"


def test_lock_lost_after_lease_steal(cluster):
    """If the master re-leases the lock to someone else (our lease expired),
    the next renewal must drop the token so mutating commands abort."""
    import time as _time

    master, servers, client, env = cluster
    env.lock()
    assert env.is_locked
    with master._admin_lock_mu:
        master._admin_locks["admin"] = (999, _time.monotonic() + 30, "thief")
    assert env._renew_once() is False
    assert not env.is_locked
    with pytest.raises(ShellError, match="lock the cluster"):
        run(env, "volume.delete -volumeId 1")


def test_ec_lifecycle_with_collection(cluster):
    """Collection must ride the heartbeat into the EC registry so rebuild
    resolves the right shard paths without a flag."""
    master, servers, client, env = cluster
    import os as _os

    fids = []
    for i in range(8):
        res = client.submit(_os.urandom(600), collection="foo")
        fids.append((res.fid, client.read(res.fid)))
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")
    out = run(
        env,
        f"ec.encode -volumeId {vid} -largeBlockSize {LARGE} -smallBlockSize {SMALL}",
    )
    assert f"ec.encode volume {vid}" in out
    # master's registry knows the collection
    assert env.volume_list().get("ec_collections", {}).get(str(vid)) == "foo"
    # lose shards, rebuild WITHOUT passing -collection
    spread = _ec_shard_spread(env, vid)
    victim_url, victim_sids = sorted(spread.items())[0]
    victim = next(s for s in servers if s.url == victim_url)
    host = victim_url.rsplit(":", 1)[0]
    env.vs_call(
        f"{host}:{victim.grpc_port}",
        "VolumeEcShardsDelete",
        {"volume_id": vid, "collection": "foo", "shard_ids": victim_sids},
    )
    out = run(env, "ec.rebuild")
    assert "rebuilt" in out
    assert sorted(
        s for sids in _ec_shard_spread(env, vid).values() for s in sids
    ) == list(range(14))
    for fid, payload in fids:
        assert client.read(fid) == payload


def test_run_script_multiple_commands(cluster):
    master, servers, client, env = cluster
    out = io.StringIO()
    run_script(env, "lock; volume.list; unlock", out)
    s = out.getvalue()
    assert "locked" in s and "DataCenter" in s and "unlocked" in s


def test_volume_balance_moves_volumes(cluster):
    """command_volume_balance.go analog: an uneven cluster converges to
    counts within 1, moved volumes stay fully readable."""
    master, servers, client, env = cluster
    fids = _upload_some(client, n=30, size=900)
    # force growth of several volumes so there's something to move
    for _ in range(6):
        client.assign()  # each assign may grow a volume
    import time as _t

    _t.sleep(0.8)  # heartbeats settle
    counts_before = {
        n["url"]: len(n.get("volumes", [])) for n in env.topology_nodes()
    }
    run(env, "lock")
    out = run(env, "volume.balance")
    assert "volume.balance:" in out
    _t.sleep(0.8)  # heartbeats propagate the moves
    counts = {n["url"]: len(n.get("volumes", [])) for n in env.topology_nodes()}
    assert max(counts.values()) - min(counts.values()) <= 1, (counts_before, counts)
    for fid, payload in fids:
        assert client.read(fid) == payload, f"{fid} unreadable after balance"


def test_volume_move_to_named_node(cluster):
    master, servers, client, env = cluster
    fids = _upload_some(client, n=8, size=800)
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")
    src = next(s for s in servers if s.store.get_volume(vid) is not None)
    dst = next(
        s for s in servers
        if s.store.get_volume(vid) is None and s.url != src.url
    )
    out = run(env, f"volume.move -volumeId {vid} -target {dst.url}")
    assert f"-> {dst.url}" in out
    assert dst.store.get_volume(vid) is not None
    assert src.store.get_volume(vid) is None
    for fid, payload in fids:
        assert client.read(fid) == payload, f"{fid} unreadable after move"
    # moved volume accepts writes again (thawed on the destination)
    import os as _os

    res = client.submit(_os.urandom(500))
    assert client.read(res.fid)
    # moving again to the same node is a no-op
    out = run(env, f"volume.move -volumeId {vid} -target {dst.url}")
    assert "already on" in out
    # unknown target is refused
    with pytest.raises(ShellError, match="unknown node"):
        run(env, f"volume.move -volumeId {vid} -target 127.0.0.1:1")


def test_cluster_ps_and_raft_ps(cluster):
    master, servers, client, env = cluster
    out = run(env, "cluster.raft.ps")
    assert "raft disabled" in out and master.address in out
    out = run(env, "cluster.ps")
    assert out.count("volume server") == 4
    assert f"master * {master.address}" in out


def test_collection_delete(cluster):
    master, servers, client, env = cluster
    res = client.submit(b"c" * 300, collection="trash")
    keep = client.submit(b"k" * 300)
    run(env, "lock")
    out = run(env, "collection.delete -collection trash")
    assert "would delete" in out  # dry run without -force
    assert client.read(res.fid) == b"c" * 300  # still there
    out = run(env, "collection.delete -collection trash -force")
    assert "removed" in out
    import time as _t

    _t.sleep(0.5)
    for n in env.topology_nodes():
        assert not any(
            v.get("collection") == "trash" for v in n.get("volumes", [])
        )
    assert client.read(keep.fid) == b"k" * 300  # other collections untouched
    with pytest.raises(Exception):
        client.read(res.fid)


def test_volume_delete_empty(cluster):
    master, servers, client, env = cluster
    res = client.submit(b"e" * 200)
    vid = int(res.fid.split(",", 1)[0])
    import time as _t

    _t.sleep(0.6)  # heartbeat carries the new file_count
    run(env, "lock")
    out = run(env, "volume.deleteEmpty -force")
    # sibling volumes grown alongside ours may legitimately be empty; the
    # volume with a live needle must survive
    assert f"removed {vid} from" not in out
    assert any(
        int(v["id"]) == vid
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
    )
    client.delete(res.fid)
    _t.sleep(0.6)  # heartbeat carries the new delete_count
    out = run(env, "volume.deleteEmpty")
    assert f"volume {vid} is empty" in out  # dry run reports
    out = run(env, "volume.deleteEmpty -force")
    assert f"removed {vid} from" in out
    _t.sleep(0.5)
    assert all(
        int(v["id"]) != vid
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
    )


def test_volume_configure_replication(cluster):
    master, servers, client, env = cluster
    res = client.submit(b"r" * 100)
    vid = int(res.fid.split(",", 1)[0])
    run(env, "lock")
    out = run(env, f"volume.configure.replication -volumeId {vid} -replication 001")
    assert "replication -> 001" in out
    # persisted in the superblock: visible on the live volume object
    holder = next(s for s in servers if s.store.get_volume(vid) is not None)
    assert str(holder.store.get_volume(vid).super_block.replica_placement) == "001"
    import time as _t

    _t.sleep(0.6)
    v = next(
        v
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
        if int(v["id"]) == vid
    )
    assert v.get("replica_placement") == "001"
    with pytest.raises(ShellError, match="no matching volumes"):
        run(env, "volume.configure.replication -volumeId 9999 -replication 010")


def test_volume_check_disk_detects_and_fixes(cluster):
    import base64

    master, servers, client, env = cluster
    res = client.submit(b"sync me" * 50, replication="001")
    vid = int(res.fid.split(",", 1)[0])
    import time as _t

    _t.sleep(0.6)
    holders = [s for s in servers if s.store.get_volume(vid) is not None]
    assert len(holders) == 2  # 001 => two same-DC copies
    # diverge: write one needle directly to a single replica (bypasses the
    # HTTP fan-out), as if the other replica missed a write while down
    lone = f"{vid},deadbeef01020304"
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    with rpc.RpcClient(holders[0].grpc_address) as c:
        c.call(
            VOLUME_SERVICE,
            "WriteNeedle",
            {"fid": lone, "data": base64.b64encode(b"lone needle").decode()},
        )
    run(env, "lock")
    out = run(env, f"volume.check.disk -volumeId {vid}")
    assert "missing 1 needles" in out and "0 needles synced" in out
    out = run(env, f"volume.check.disk -volumeId {vid} -fix")
    assert "1 needles synced" in out
    # both replicas now serve the needle with identical bytes
    for h in holders:
        n = h.store.read_needle(vid, 0xDEADBEEF)
        assert n.data == b"lone needle"
        assert n.cookie == 0x01020304
    out = run(env, f"volume.check.disk -volumeId {vid}")
    assert "0 divergent" in out


def test_volume_server_evacuate_and_leave(cluster):
    master, servers, client, env = cluster
    fids = _upload_some(client, n=20, size=600)
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")
    run(env, f"ec.encode -volumeId {vid} -force")  # give the node EC shards too
    import time as _t

    _t.sleep(0.8)
    victim = next(
        n
        for n in env.topology_nodes()
        if n.get("volumes") or n.get("ec_shards")
    )
    out = run(env, f"volumeServer.evacuate -node {victim['url']} -noApply")
    assert "dry" in out
    out = run(env, f"volumeServer.evacuate -node {victim['url']}")
    assert "volumeServer.evacuate:" in out
    _t.sleep(0.8)
    after = next(n for n in env.topology_nodes() if n["url"] == victim["url"])
    assert not after.get("volumes") and not after.get("ec_shards"), after
    for fid, payload in fids:
        assert client.read(fid) == payload, f"{fid} unreadable after evacuate"
    # leave: the emptied node departs the topology and stops heartbeating
    out = run(env, f"volumeServer.leave -node {victim['url']}")
    assert "left the cluster" in out
    _t.sleep(0.8)
    assert all(n["url"] != victim["url"] for n in env.topology_nodes())


def test_volume_check_disk_propagates_deletes(cluster):
    """A replica that missed a DELETE must get the tombstone propagated —
    never the deleted needle resurrected from the lagging replica."""
    master, servers, client, env = cluster
    res = client.submit(b"doomed" * 30, replication="001")
    vid = int(res.fid.split(",", 1)[0])
    import time as _t

    _t.sleep(0.6)
    holders = [s for s in servers if s.store.get_volume(vid) is not None]
    assert len(holders) == 2
    # delete on ONE replica only (as if the other was down for the delete)
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    with rpc.RpcClient(holders[0].grpc_address) as c:
        c.call(VOLUME_SERVICE, "DeleteNeedle", {"fid": res.fid})
    nid = int(res.fid.split(",", 1)[1][:-8], 16)
    assert holders[1].store.get_volume(vid).nm.get(nid) is not None
    run(env, "lock")
    out = run(env, f"volume.check.disk -volumeId {vid}")
    assert "outlived its delete" in out
    out = run(env, f"volume.check.disk -volumeId {vid} -fix")
    assert "1 needles synced" in out
    # the delete propagated: gone from BOTH replicas, not resurrected
    for h in holders:
        assert h.store.get_volume(vid).nm.get(nid) is None
    out = run(env, f"volume.check.disk -volumeId {vid}")
    assert "0 divergent" in out


def test_volume_check_disk_rewrite_after_delete_wins(cluster):
    """A needle re-written AFTER its delete must not be destroyed by the
    tombstone rule: the rewrite postdates the delete, so check.disk copies
    the new write to the replica that missed it."""
    import base64

    master, servers, client, env = cluster
    res = client.submit(b"first life" * 20, replication="001")
    vid = int(res.fid.split(",", 1)[0])
    nid = int(res.fid.split(",", 1)[1][:-8], 16)
    import time as _t

    _t.sleep(0.6)
    holders = [s for s in servers if s.store.get_volume(vid) is not None]
    assert len(holders) == 2
    # delete everywhere (normal fan-out)...
    client.delete(res.fid)
    # ...then re-write the same needle on ONE replica only (replica B was
    # down for the re-write)
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    with rpc.RpcClient(holders[0].grpc_address) as c:
        c.call(
            VOLUME_SERVICE,
            "WriteNeedle",
            {"fid": res.fid, "data": base64.b64encode(b"second life").decode()},
        )
    run(env, "lock")
    out = run(env, f"volume.check.disk -volumeId {vid} -fix")
    assert "1 needles synced" in out and "outlived" not in out
    # the rewrite won: live with the new bytes on BOTH replicas
    for h in holders:
        n = h.store.read_needle(vid, nid)
        assert n.data == b"second life"


def test_volume_grow(cluster):
    master, servers, client, env = cluster
    run(env, "lock")
    before = sum(len(n.get("volumes", [])) for n in env.topology_nodes())
    out = run(env, "volume.grow -count 3 -collection grown")
    assert "3 volumes created" in out
    import time as _t

    _t.sleep(0.8)
    grown = [
        v
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
        if v.get("collection") == "grown"
    ]
    assert len(grown) == 3
    after = sum(len(n.get("volumes", [])) for n in env.topology_nodes())
    assert after >= before + 3
    # grown volumes are immediately writable
    res = client.submit(b"to a pre-grown volume", collection="grown")
    assert client.read(res.fid) == b"to a pre-grown volume"


def test_volume_unmount_and_mount(cluster):
    """volume.unmount fences a volume (files kept, dropped from topology);
    volume.mount brings it back with data intact."""
    master, servers, client, env = cluster
    res = client.submit(b"fence me" * 10)
    vid = int(res.fid.split(",", 1)[0])
    holder = next(s for s in servers if s.store.get_volume(vid) is not None)
    run(env, "lock")
    out = run(env, f"volume.unmount -volumeId {vid} -node {holder.url}")
    assert "volume.unmount" in out
    assert holder.store.get_volume(vid) is None  # not serving
    import os as _os
    import time as _t

    _t.sleep(0.5)
    assert all(  # gone from the topology
        int(v["id"]) != vid
        for n in env.topology_nodes()
        for v in n.get("volumes", [])
    )
    # files still on disk
    dat = [
        p
        for loc in holder.store.locations
        for p in _os.listdir(loc.directory)
        if p.endswith(".dat")
    ]
    assert dat
    out = run(env, f"volume.mount -volumeId {vid} -node {holder.url}")
    assert "volume.mount" in out
    assert client.read(res.fid) == b"fence me" * 10


def test_ec_encode_quiet_for_filter(cluster):
    """-quietFor skips volumes with recent writes (the reference's encode
    safety filter: a volume still taking writes must not be EC-frozen)."""
    master, servers, client, env = cluster
    _upload_some(client, n=4)
    import time as _t

    _t.sleep(0.6)  # heartbeat carries last_modified
    run(env, "lock")
    out = run(env, "ec.encode -quietFor 3600 -force")
    assert "no matching volumes" in out  # everything was just written
    out = run(env, "ec.encode -force")  # filter disabled: encodes
    assert "ec.encode volume" in out


def test_ec_balance_improves_rack_spread(cluster):
    """Integration: ec.balance's move path (copy/mount/delete RPCs) spreads
    a rack-concentrated volume back across racks; the candidate ORDERING
    itself is pinned by test_pick_balance_move_prefers_rack_spread."""
    master, servers, client, env = cluster
    fids = _upload_some(client, n=12)
    vid = int(fids[0][0].split(",", 1)[0])
    run(env, "lock")
    run(env, f"ec.encode -volumeId {vid} -force")
    # concentrate everything onto rack0's two nodes (racks are i%2)
    rack0 = [s for i, s in enumerate(servers) if i % 2 == 0]
    rack1 = [s for i, s in enumerate(servers) if i % 2 == 1]
    import time as _t

    _t.sleep(0.8)
    spread = _ec_shard_spread(env, vid)
    for s in rack1:
        sids = spread.get(s.url, [])
        if not sids:
            continue
        env.vs_call(
            rack0[0].grpc_address, "VolumeEcShardsCopy",
            {"volume_id": vid, "collection": "", "shard_ids": sids,
             "source_data_node": s.grpc_address, "copy_ecx_file": False},
        )
        env.vs_call(
            rack0[0].grpc_address, "VolumeEcShardsMount",
            {"volume_id": vid, "collection": "", "shard_ids": sids},
        )
        env.vs_call(
            s.grpc_address, "VolumeEcShardsDelete",
            {"volume_id": vid, "collection": "", "shard_ids": sids},
        )
    _t.sleep(0.8)
    spread = _ec_shard_spread(env, vid)
    rack1_before = sum(len(spread.get(s.url, [])) for s in rack1)
    assert rack1_before == 0  # fully concentrated in rack0
    run(env, "ec.balance")
    _t.sleep(0.8)
    spread = _ec_shard_spread(env, vid)
    rack1_after = sum(len(spread.get(s.url, [])) for s in rack1)
    assert rack1_after >= 5, spread  # balance pushed shards back across racks
    for fid, payload in fids:
        assert client.read(fid) == payload


def test_pick_balance_move_prefers_rack_spread():
    """Unit-pin the rack-preference ordering: with two candidate volumes,
    the one concentrated in the heavy node's rack moves first."""
    from seaweedfs_tpu.shell.command_ec import pick_balance_move

    by_url = {
        "a:1": {"rack": "r0"},
        "b:1": {"rack": "r0"},
        "c:1": {"rack": "r1"},
    }
    # vid 7: all shards in rack r0 (concentrated); vid 9: already spread
    placement = {
        "a:1": {7: {0, 1, 2}, 9: {0, 1}},
        "b:1": {7: {3, 4}},
        "c:1": {9: {2, 3}},
    }
    picked = pick_balance_move(placement, by_url, "a:1", "c:1", {}, "")
    assert picked is not None and picked[0] == 7  # spread gain wins
    # collection filter excludes vid 7 -> vid 9 is the only candidate
    picked = pick_balance_move(
        placement, by_url, "a:1", "c:1", {7: "x", 9: "y"}, "y"
    )
    assert picked is not None and picked[0] == 9
    # nothing movable -> None
    assert pick_balance_move({"a:1": {}, "c:1": {}}, by_url, "a:1", "c:1", {}, "") is None


def test_orphans_after_cutoff_chunks_and_classifies(monkeypatch):
    """fsck's orphan dating: the VolumeNeedleTs RPC is chunked (an
    unchunked JSON request can blow gRPC's 4 MB cap), a post-cutoff copy
    on ANY replica spares the needle, and ids NO reachable holder could
    date come back as 'undatable' (holder unreachable) — distinct from
    'dated after the cutoff'."""
    from seaweedfs_tpu.shell import command_volume as cv

    monkeypatch.setattr(cv, "_NEEDLE_TS_CHUNK", 3)
    cutoff = 1000
    nids = list(range(1, 11))  # 10 ids -> 4 chunks per holder

    calls = []

    class Env:
        def vs_call(self, addr, method, req, timeout=300):
            assert method == "VolumeNeedleTs"
            chunk = req["needle_ids"]
            assert len(chunk) <= 3
            calls.append((addr, tuple(chunk)))
            if addr.startswith("down"):
                raise ConnectionError("holder down")
            if 7 in chunk and addr.startswith("flaky"):
                raise ConnectionError("mid-volume failure")
            # holder 'a' dates needles 2 and 7 after the cutoff
            return {"ts": {str(n): 2000 if n in (2, 7) else 10 for n in chunk}}

    holders = [
        {"url": "a:80", "grpc_port": 1},
        {"url": "down:80", "grpc_port": 1},
    ]
    fresh, undatable = cv._orphans_after_cutoff(Env(), holders, 5, nids, cutoff)
    assert fresh == {2, 7}
    assert undatable == set()
    # chunking: 4 chunks on the live holder; the down holder fast-fails
    # after its first chunk (no RPC-timeout-per-chunk against a dead box)
    assert calls == [
        ("a:1", (1, 2, 3)),
        ("a:1", (4, 5, 6)),
        ("a:1", (7, 8, 9)),
        ("a:1", (10,)),
        ("down:1", (1, 2, 3)),
    ]

    # every holder down: nothing datable, nothing falsely 'in flight'
    fresh, undatable = cv._orphans_after_cutoff(
        Env(), [{"url": "down:80", "grpc_port": 1}], 5, nids, cutoff
    )
    assert fresh == set() and undatable == set(nids)

    # a mid-volume failure on the only holder: that chunk AND the holder's
    # remaining chunks are undatable (fast-fail), earlier chunks keep their
    # dates
    calls.clear()
    fresh, undatable = cv._orphans_after_cutoff(
        Env(), [{"url": "flaky:80", "grpc_port": 1}], 5, nids, cutoff
    )
    assert fresh == {2} and 7 not in fresh
    assert undatable == {7, 8, 9, 10}  # failed chunk + fast-failed remainder
