"""fs.* shell commands, filer cluster registration, and filer TTL
enforcement over a live stack (SURVEY.md §4 loopback pattern)."""

import io
import time

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.shell import CommandEnv, run_command


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fsstack")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp / "vol").mkdir()
    vs = VolumeServer([str(tmp / "vol")], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address)
    fs.start()
    # wait until the filer announced itself to the master
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        from seaweedfs_tpu import rpc

        with rpc.RpcClient(master.address) as c:
            if c.call("weedtpu.Master", "ListClusterNodes", {}).get("filers"):
                break
        time.sleep(0.2)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def _run(env, line):
    out = io.StringIO()
    run_command(env, line, out)
    return out.getvalue()


def test_fs_commands_roundtrip(stack, tmp_path):
    master, _, fs = stack
    import io as _io

    fs.write_file("/fsdemo/a/hello.txt", _io.BytesIO(b"hello fs"))
    fs.write_file("/fsdemo/b.bin", _io.BytesIO(b"12345"))
    with CommandEnv(master.address) as env:
        assert "a/" in _run(env, "fs.ls /fsdemo")
        listing = _run(env, "fs.ls -l /fsdemo")
        assert "b.bin" in listing and "5" in listing
        assert _run(env, "fs.cat /fsdemo/a/hello.txt") == "hello fs"
        _run(env, "fs.mkdir /fsdemo/newdir")
        assert "newdir/" in _run(env, "fs.ls /fsdemo")
        _run(env, "fs.mv /fsdemo/b.bin /fsdemo/newdir/b.bin")
        assert "b.bin" in _run(env, "fs.ls /fsdemo/newdir")
        du = _run(env, "fs.du /fsdemo")
        assert "2 files" in du and "13 bytes" in du
        # meta save / namespace-wipe / load restores entries that point
        # at the surviving chunk needles (a metadata restore, not a data
        # copy — the reference's fs.meta.load contract)
        dump = str(tmp_path / "meta.jsonl")
        out = _run(env, f"fs.meta.save -o {dump} /fsdemo")
        assert "saved" in out
        env.filer_client().delete("/fsdemo", recursive=True, delete_data=False)
        assert _run(env, "fs.ls /fsdemo") == ""
        out = _run(env, f"fs.meta.load -i {dump}")
        assert "loaded" in out
        assert _run(env, "fs.cat /fsdemo/a/hello.txt") == "hello fs"


def test_filer_ttl_expiry(stack):
    master, _, fs = stack
    import io as _io

    entry = fs.write_file("/ttl/ephemeral.txt", _io.BytesIO(b"short-lived"))
    # force a 1-second ttl and an already-old mtime
    entry.attributes.ttl_sec = 1
    entry.attributes.mtime = time.time() - 10
    fs.filer.update_entry(entry)
    from seaweedfs_tpu.filer.store import EntryNotFound

    with pytest.raises(EntryNotFound):
        fs.filer.find_entry("/ttl/ephemeral.txt")
    assert all(e.name != "ephemeral.txt" for e in fs.filer.list_entries("/ttl"))


def test_cpuprofile_flag(tmp_path, capsys):
    from seaweedfs_tpu.__main__ import main

    prof = str(tmp_path / "cpu.prof")
    assert main(["version", "-cpuprofile", prof]) == 0
    import pstats

    stats = pstats.Stats(prof)  # parses -> valid profile
    assert stats.total_calls > 0


def test_fs_tree(stack):
    master, _, fs = stack
    import io as _io

    fs.write_file("/treedemo/x/one.txt", _io.BytesIO(b"1"))
    fs.write_file("/treedemo/x/y/two.txt", _io.BytesIO(b"22"))
    fs.write_file("/treedemo/three.txt", _io.BytesIO(b"333"))
    with CommandEnv(master.address) as env:
        out = _run(env, "fs.tree /treedemo")
        assert "x/" in out and "one.txt" in out and "two.txt" in out
        assert "2 directories, 3 files" in out


def test_s3_bucket_commands(stack):
    master, _, fs = stack
    import io as _io

    with CommandEnv(master.address) as env:
        out = _run(env, "s3.bucket.create -name shellbkt")
        assert "created bucket shellbkt" in out
        out = _run(env, "s3.bucket.list")
        assert "shellbkt" in out and "total" in out
        # duplicate create refused
        import pytest as _pytest

        from seaweedfs_tpu.shell import ShellError

        with _pytest.raises(ShellError, match="already exists"):
            _run(env, "s3.bucket.create -name shellbkt")
        # non-empty bucket needs -force
        fs.write_file("/buckets/shellbkt/obj", _io.BytesIO(b"data"))
        with _pytest.raises(ShellError, match="not empty"):
            _run(env, "s3.bucket.delete -name shellbkt")
        out = _run(env, "s3.bucket.delete -name shellbkt -force")
        assert "deleted bucket" in out
        assert "shellbkt" not in _run(env, "s3.bucket.list")
        with _pytest.raises(ShellError, match="not found"):
            _run(env, "s3.bucket.delete -name shellbkt")


def test_fs_configure_rules(stack, tmp_path):
    """Per-path rules (filer_conf.go analog): a prefix rule pins the
    collection for uploads beneath it, read-only prefixes refuse writes
    and deletes, and the rule set survives a conf reload from KV."""
    import urllib.error
    import urllib.request

    master, vs, fs = stack
    with CommandEnv(master.address) as env:
        assert "no rules" in _run(env, "fs.configure")
        out = _run(env, "fs.configure -locationPrefix /confdemo/hot/ -collection hotcoll")
        assert "dry" in out  # no -apply
        _run(env, "fs.configure -locationPrefix /confdemo/hot/ -collection hotcoll -apply")
        _run(env, "fs.configure -locationPrefix /confdemo/frozen/ -readOnly -apply")
        listing = _run(env, "fs.configure")
        assert "/confdemo/hot/" in listing and "hotcoll" in listing
        assert "readOnly=True" in listing

        # upload under the hot prefix -> chunks land in collection hotcoll
        url = f"http://{fs.url}/confdemo/hot/a.bin"
        req = urllib.request.Request(url, data=b"x" * 100, method="PUT")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        entry = fs.filer.find_entry("/confdemo/hot/a.bin")
        assert entry.chunks
        vid = int(entry.chunks[0].fid.split(",", 1)[0])
        v = vs.store.get_volume(vid)
        assert v is not None and v.collection == "hotcoll"

        # read-only prefix refuses PUT and DELETE with 403
        for method in ("PUT", "DELETE"):
            req = urllib.request.Request(
                f"http://{fs.url}/confdemo/frozen/b.bin",
                data=b"nope" if method == "PUT" else None,
                method=method,
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    raise AssertionError(f"{method} succeeded: {r.status}")
            except urllib.error.HTTPError as e:
                assert e.code == 403, method

        # rule deletion frees the prefix again
        _run(env, "fs.configure -locationPrefix /confdemo/frozen/ -delete -apply")
        req = urllib.request.Request(
            f"http://{fs.url}/confdemo/frozen/b.bin", data=b"now ok", method="PUT"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201

        # persistence: the conf reloads from the store KV (what a filer
        # restart does at __init__)
        from seaweedfs_tpu.filer.filer_conf import CONF_KEY, FilerConf

        reloaded = FilerConf.from_json(fs.filer.store.kv_get(CONF_KEY))
        assert [r.location_prefix for r in reloaded.rules] == ["/confdemo/hot/"]
        assert reloaded.match("/confdemo/hot/x").collection == "hotcoll"


def test_volume_fsck(stack):
    """command_volume_fsck.go analog: an unreferenced needle is an orphan
    (purgeable), a filer chunk whose needle is gone is reported missing."""
    import io as _io

    from seaweedfs_tpu.cluster.client import MasterClient

    master, vs, fs = stack
    fs.write_file("/fsckdemo/keep.bin", _io.BytesIO(b"k" * 500))
    # orphan: a needle written straight to the volume tier, no filer entry
    mc = MasterClient(master.address)
    try:
        orphan = mc.submit(b"o" * 300)
        # missing: a filer entry whose backing needle we destroy
        lost = fs.write_file("/fsckdemo/lost.bin", _io.BytesIO(b"l" * 400))
        lost_fid = lost.chunks[0].fid
        mc.delete(lost_fid)
    finally:
        mc.close()
    with CommandEnv(master.address) as env:
        _run(env, "lock")
        # the default -cutoffTimeAgo spares a just-written orphan in BOTH
        # modes (report must agree with what a purge would do): it is
        # indistinguishable from an upload still in flight (the advisor's
        # race: chunks land before the scan, filer entry after the walk)
        out = _run(env, "volume.fsck")
        assert "spared" in out and "found 0 orphan" in out
        l_vid = lost_fid.split(",", 1)[0]
        l_nid = int(lost_fid.split(",", 1)[1][:-8], 16)
        assert f"volume {l_vid}: needle {l_nid:x} referenced but MISSING" in out
        out = _run(env, "volume.fsck -reallyDeleteFromVolume")
        assert "spared" in out and "deleted 0 orphan" in out
        # with the cutoff disabled the orphan is reported (counted, not
        # named) and the purge goes through; a rerun is clean
        out = _run(env, "volume.fsck -cutoffTimeAgo 0")
        assert "orphan needles" in out and "found 0" not in out
        o_vid, o_hex = orphan.fid.split(",", 1)
        assert f"needle {int('0x' + o_hex[:-8], 16):x}" not in out  # counted, not named
        out = _run(env, "volume.fsck -reallyDeleteFromVolume -cutoffTimeAgo 0")
        assert "deleted" in out
        out = _run(env, "volume.fsck")
        assert "found 0 orphan needles" in out
        _run(env, "unlock")
    # the referenced file is untouched by the purge
    assert fs.read_file(fs.filer.find_entry("/fsckdemo/keep.bin")) == b"k" * 500


def test_fs_configure_readonly_enforced_on_grpc_surface(stack):
    """Read-only rules must hold on EVERY mutation surface, not just the
    HTTP handlers — S3 DeleteObject and the mount go through gRPC
    CreateEntry/DeleteEntry/AtomicRenameEntry."""
    import io as _io

    import grpc as _grpc
    import pytest as _pytest

    from seaweedfs_tpu.filer.client import FilerClient
    from seaweedfs_tpu.filer.entry import Entry

    master, vs, fs = stack
    fs.write_file("/grpclock/keep.txt", _io.BytesIO(b"safe"))
    with CommandEnv(master.address) as env:
        _run(env, "fs.configure -locationPrefix /grpclock/ -readOnly -apply")
        with FilerClient(fs.grpc_address) as fc:
            with _pytest.raises(_grpc.RpcError) as ei:
                fc.delete("/grpclock/keep.txt")
            assert ei.value.code() == _grpc.StatusCode.PERMISSION_DENIED
            with _pytest.raises(_grpc.RpcError):
                fc.create(Entry(path="/grpclock/new.txt"))
            with _pytest.raises(_grpc.RpcError):
                fc.rename("/grpclock/keep.txt", "/elsewhere/keep.txt")
            # renaming INTO the subtree is a write there too
            with _pytest.raises(_grpc.RpcError):
                fc.rename("/probe.txt", "/grpclock/stolen.txt")
        assert fs.read_file(fs.filer.find_entry("/grpclock/keep.txt")) == b"safe"
        _run(env, "fs.configure -locationPrefix /grpclock/ -delete -apply")
        with FilerClient(fs.grpc_address) as fc:
            fc.delete("/grpclock/keep.txt")  # rule gone: delete works


def test_fs_configure_readonly_protects_ancestor_ops(stack):
    """Deleting/renaming the read-only directory itself — or an ancestor
    subtree containing it — must be refused, not just writes inside it."""
    import io as _io

    import pytest as _pytest

    master, vs, fs = stack
    fs.write_file("/anc/frozen/keep.txt", _io.BytesIO(b"x"))
    with CommandEnv(master.address) as env:
        _run(env, "fs.configure -locationPrefix /anc/frozen/ -readOnly -apply")
        try:
            with _pytest.raises(PermissionError):
                fs.filer.delete_entry("/anc/frozen", recursive=True)
            with _pytest.raises(PermissionError):
                fs.filer.delete_entry("/anc", recursive=True)  # ancestor subtree
            with _pytest.raises(PermissionError):
                fs.filer.rename("/anc/frozen", "/thawed")
            with _pytest.raises(PermissionError):
                fs.filer.rename("/anc", "/moved")
            assert fs.filer.find_entry("/anc/frozen/keep.txt")
        finally:
            _run(env, "fs.configure -locationPrefix /anc/frozen/ -delete -apply")


def test_fs_meta_cat_and_s3_clean_uploads(stack):
    import io as _io
    import time as _time

    master, vs, fs = stack
    fs.write_file("/catdemo/x.bin", _io.BytesIO(b"z" * 123))
    with CommandEnv(master.address) as env:
        out = _run(env, "fs.meta.cat /catdemo/x.bin")
        import json as _json

        meta = _json.loads(out)
        assert meta["chunks"] and meta["attributes"]["file_size"] == 123
        import pytest as _pytest

        from seaweedfs_tpu.shell import ShellError

        with _pytest.raises(ShellError, match="not found"):
            _run(env, "fs.meta.cat /catdemo/ghost")

        # stale multipart staging dirs get aborted; fresh ones survive
        fs.write_file(
            "/buckets/.uploads/bkt/stale123/0001.part", _io.BytesIO(b"p")
        )
        fs.write_file(
            "/buckets/.uploads/bkt/fresh456/0001.part", _io.BytesIO(b"p")
        )
        # age the dir AND its newest part: liveness is judged by the
        # latest activity under the staging dir, not dir creation time
        for p in ("/buckets/.uploads/bkt/stale123",
                  "/buckets/.uploads/bkt/stale123/0001.part"):
            e = fs.filer.find_entry(p)
            e.attributes.mtime = _time.time() - 7200
            fs.filer.update_entry(e)
        # a fresh part keeps an otherwise-old upload alive
        old_dir = fs.filer.find_entry("/buckets/.uploads/bkt/fresh456")
        old_dir.attributes.mtime = _time.time() - 7200
        fs.filer.update_entry(old_dir)
        _run(env, "lock")
        out = _run(env, "s3.clean.uploads -timeAgoSeconds 3600")
        assert "aborted stale upload bkt/stale123" in out
        assert "1 aborted, 1 kept" in out
        from seaweedfs_tpu.filer.store import EntryNotFound

        with _pytest.raises(EntryNotFound):
            fs.filer.find_entry("/buckets/.uploads/bkt/stale123")
        assert fs.filer.find_entry("/buckets/.uploads/bkt/fresh456")
        _run(env, "unlock")


def test_filer_meta_tail_cli(stack, capsys):
    import io as _io
    import json as _json

    from seaweedfs_tpu.__main__ import main

    master, vs, fs = stack
    fs.write_file("/taildemo/a.txt", _io.BytesIO(b"event me"))
    rc = main(
        [
            "filer.meta.tail",
            "-filerGrpc",
            fs.grpc_address,
            "-prefix",
            "/taildemo",
            "-maxIdleSeconds",
            "0.5",
        ]
    )
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert any(
        "/taildemo" == _json.loads(l)["directory"] for l in lines
    ), lines


def test_filer_copy_cli(stack, tmp_path, capsys):
    from seaweedfs_tpu.__main__ import main

    master, vs, fs = stack
    src = tmp_path / "copytree"
    (src / "sub").mkdir(parents=True)
    (src / "top.txt").write_bytes(b"root file")
    (src / "sub" / "leaf.bin").write_bytes(b"x" * 2048)
    rc = main(
        ["filer.copy", "-filer", fs.url, str(src), "/copied/"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "2 copied, 0 failed" in out
    assert fs.read_file(fs.filer.find_entry("/copied/copytree/top.txt")) == b"root file"
    assert (
        fs.read_file(fs.filer.find_entry("/copied/copytree/sub/leaf.bin"))
        == b"x" * 2048
    )


def test_fs_cd_pwd_relative_paths(stack):
    import io as _io

    import pytest as _pytest

    from seaweedfs_tpu.shell import ShellError

    master, _, fs = stack
    fs.write_file("/nav/inner/deep.txt", _io.BytesIO(b"navigate"))
    with CommandEnv(master.address) as env:
        assert _run(env, "fs.pwd") == "/\n"
        _run(env, "fs.cd /nav")
        assert _run(env, "fs.pwd") == "/nav\n"
        assert "inner/" in _run(env, "fs.ls")           # relative default "."
        assert _run(env, "fs.cat inner/deep.txt") == "navigate"
        _run(env, "fs.cd inner")                         # relative cd
        assert _run(env, "fs.pwd") == "/nav/inner\n"
        assert _run(env, "fs.cat deep.txt") == "navigate"
        _run(env, "fs.cd ..")
        assert _run(env, "fs.pwd") == "/nav\n"
        with _pytest.raises(ShellError, match="not a directory"):
            _run(env, "fs.cd inner/deep.txt")
        _run(env, "fs.cd")                               # bare cd -> /
        assert _run(env, "fs.pwd") == "/\n"
