"""fs.* shell commands, filer cluster registration, and filer TTL
enforcement over a live stack (SURVEY.md §4 loopback pattern)."""

import io
import time

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.shell import CommandEnv, run_command


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fsstack")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp / "vol").mkdir()
    vs = VolumeServer([str(tmp / "vol")], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address)
    fs.start()
    # wait until the filer announced itself to the master
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        from seaweedfs_tpu import rpc

        with rpc.RpcClient(master.address) as c:
            if c.call("weedtpu.Master", "ListClusterNodes", {}).get("filers"):
                break
        time.sleep(0.2)
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def _run(env, line):
    out = io.StringIO()
    run_command(env, line, out)
    return out.getvalue()


def test_fs_commands_roundtrip(stack, tmp_path):
    master, _, fs = stack
    import io as _io

    fs.write_file("/fsdemo/a/hello.txt", _io.BytesIO(b"hello fs"))
    fs.write_file("/fsdemo/b.bin", _io.BytesIO(b"12345"))
    with CommandEnv(master.address) as env:
        assert "a/" in _run(env, "fs.ls /fsdemo")
        listing = _run(env, "fs.ls -l /fsdemo")
        assert "b.bin" in listing and "5" in listing
        assert _run(env, "fs.cat /fsdemo/a/hello.txt") == "hello fs"
        _run(env, "fs.mkdir /fsdemo/newdir")
        assert "newdir/" in _run(env, "fs.ls /fsdemo")
        _run(env, "fs.mv /fsdemo/b.bin /fsdemo/newdir/b.bin")
        assert "b.bin" in _run(env, "fs.ls /fsdemo/newdir")
        du = _run(env, "fs.du /fsdemo")
        assert "2 files" in du and "13 bytes" in du
        # meta save / namespace-wipe / load restores entries that point
        # at the surviving chunk needles (a metadata restore, not a data
        # copy — the reference's fs.meta.load contract)
        dump = str(tmp_path / "meta.jsonl")
        out = _run(env, f"fs.meta.save -o {dump} /fsdemo")
        assert "saved" in out
        env.filer_client().delete("/fsdemo", recursive=True, delete_data=False)
        assert _run(env, "fs.ls /fsdemo") == ""
        out = _run(env, f"fs.meta.load -i {dump}")
        assert "loaded" in out
        assert _run(env, "fs.cat /fsdemo/a/hello.txt") == "hello fs"


def test_filer_ttl_expiry(stack):
    master, _, fs = stack
    import io as _io

    entry = fs.write_file("/ttl/ephemeral.txt", _io.BytesIO(b"short-lived"))
    # force a 1-second ttl and an already-old mtime
    entry.attributes.ttl_sec = 1
    entry.attributes.mtime = time.time() - 10
    fs.filer.update_entry(entry)
    from seaweedfs_tpu.filer.store import EntryNotFound

    with pytest.raises(EntryNotFound):
        fs.filer.find_entry("/ttl/ephemeral.txt")
    assert all(e.name != "ephemeral.txt" for e in fs.filer.list_entries("/ttl"))


def test_cpuprofile_flag(tmp_path, capsys):
    from seaweedfs_tpu.__main__ import main

    prof = str(tmp_path / "cpu.prof")
    assert main(["version", "-cpuprofile", prof]) == 0
    import pstats

    stats = pstats.Stats(prof)  # parses -> valid profile
    assert stats.total_calls > 0


def test_fs_tree(stack):
    master, _, fs = stack
    import io as _io

    fs.write_file("/treedemo/x/one.txt", _io.BytesIO(b"1"))
    fs.write_file("/treedemo/x/y/two.txt", _io.BytesIO(b"22"))
    fs.write_file("/treedemo/three.txt", _io.BytesIO(b"333"))
    with CommandEnv(master.address) as env:
        out = _run(env, "fs.tree /treedemo")
        assert "x/" in out and "one.txt" in out and "two.txt" in out
        assert "2 directories, 3 files" in out


def test_s3_bucket_commands(stack):
    master, _, fs = stack
    import io as _io

    with CommandEnv(master.address) as env:
        out = _run(env, "s3.bucket.create -name shellbkt")
        assert "created bucket shellbkt" in out
        out = _run(env, "s3.bucket.list")
        assert "shellbkt" in out and "total" in out
        # duplicate create refused
        import pytest as _pytest

        from seaweedfs_tpu.shell import ShellError

        with _pytest.raises(ShellError, match="already exists"):
            _run(env, "s3.bucket.create -name shellbkt")
        # non-empty bucket needs -force
        fs.write_file("/buckets/shellbkt/obj", _io.BytesIO(b"data"))
        with _pytest.raises(ShellError, match="not empty"):
            _run(env, "s3.bucket.delete -name shellbkt")
        out = _run(env, "s3.bucket.delete -name shellbkt -force")
        assert "deleted bucket" in out
        assert "shellbkt" not in _run(env, "s3.bucket.list")
        with _pytest.raises(ShellError, match="not found"):
            _run(env, "s3.bucket.delete -name shellbkt")
