"""Mount (WFS) tests — page-writer merge semantics as pure-unit tests,
then the full filesystem op set against a real master+volume+filer stack
(SURVEY.md §4 loopback pattern)."""

import os

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.mount import WFS, DirtyPages


# -- page writer (pure) -------------------------------------------------------


def test_dirty_pages_merge_and_overlay():
    dp = DirtyPages()
    dp.write(0, b"aaaa")
    dp.write(10, b"bbbb")
    assert dp.byte_count == 8 and dp.max_extent() == 14
    # bridge the gap: everything coalesces into one run
    dp.write(4, b"cccccc")
    assert len(dp._runs) == 1 and dp._runs[0] == (0, bytearray(b"aaaaccccccbbbb"))
    # overlap: latest write wins
    dp.write(2, b"XX")
    buf = bytearray(14)
    dp.read_overlay(0, buf)
    assert bytes(buf[:10]) == b"aaXXcccccc"
    runs = dp.drain()
    assert not dp.dirty
    assert runs[0][0] == 0 and runs[0][1][:10] == b"aaXXcccccc"


def test_dirty_pages_adjacent_coalesce():
    dp = DirtyPages()
    dp.write(0, b"1111")
    dp.write(4, b"2222")  # adjacent -> single run
    assert len(dp._runs) == 1 and dp._runs[0][1] == bytearray(b"11112222")
    dp.truncate(6)
    assert dp.max_extent() == 6


# -- WFS over a live stack ----------------------------------------------------


@pytest.fixture(scope="module")
def wfs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mnt")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp / "vol").mkdir()
    vs = VolumeServer([str(tmp / "vol")], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address, chunk_size=64 * 1024)
    fs.start()
    w = WFS(fs.grpc_address, auto_flush_bytes=256 * 1024)
    yield w
    w.close()
    fs.stop()
    vs.stop()
    master.stop()


def test_wfs_create_write_read(wfs):
    fh = wfs.create("/docs/hello.txt")
    fh.write(0, b"hello ")
    fh.write(6, b"world")
    assert fh.read(0, 100) == b"hello world"  # read-your-writes pre-flush
    fh.flush()
    fh.release()
    a = wfs.getattr("/docs/hello.txt")
    assert a is not None and a.size == 11 and not a.is_dir
    fh2 = wfs.open("/docs/hello.txt")
    assert fh2.read(0, 11) == b"hello world"
    assert fh2.read(6, 5) == b"world"
    fh2.release()


def test_wfs_random_writes_and_big_file(wfs):
    payload = bytearray(os.urandom(300 * 1024))  # crosses chunk + autoflush
    fh = wfs.create("/docs/big.bin")
    for off in range(0, len(payload), 50 * 1024):
        fh.write(off, bytes(payload[off : off + 50 * 1024]))
    # overwrite a window in the middle (random write)
    patch = os.urandom(10_000)
    payload[123_456 : 123_456 + len(patch)] = patch
    fh.write(123_456, patch)
    fh.flush()
    fh.release()
    fh = wfs.open("/docs/big.bin")
    assert fh.size == len(payload)
    got = fh.read(0, len(payload))
    assert got == bytes(payload)
    assert fh.read(123_000, 11_000) == bytes(payload[123_000:134_000])
    fh.release()


def test_wfs_truncate(wfs):
    fh = wfs.create("/docs/trunc.bin")
    fh.write(0, b"0123456789")
    fh.flush()
    fh.truncate(4)
    fh.flush()
    fh.release()
    fh = wfs.open("/docs/trunc.bin")
    assert fh.size == 4 and fh.read(0, 10) == b"0123"
    # extend-past-truncate via sparse write
    fh.write(8, b"ZZ")
    fh.flush()
    assert fh.read(0, 10) == b"0123\x00\x00\x00\x00ZZ"
    fh.release()


def test_wfs_dirs_and_rename(wfs):
    wfs.mkdir("/d1")
    fh = wfs.create("/d1/f.txt")
    fh.write(0, b"x")
    fh.release()
    names = [a.path for a in wfs.readdir("/d1")]
    assert names == ["/d1/f.txt"]
    with pytest.raises(OSError):
        wfs.rmdir("/d1")  # not empty
    wfs.rename("/d1/f.txt", "/d1/g.txt")
    assert wfs.getattr("/d1/f.txt") is None
    assert wfs.open("/d1/g.txt").read(0, 1) == b"x"
    wfs.unlink("/d1/g.txt")
    wfs.rmdir("/d1")
    assert wfs.getattr("/d1") is None


def test_wfs_open_semantics(wfs):
    with pytest.raises(FileNotFoundError):
        wfs.open("/nope")
    wfs.mkdir("/adir")
    with pytest.raises(IsADirectoryError):
        wfs.open("/adir")
    wfs.rmdir("/adir")
