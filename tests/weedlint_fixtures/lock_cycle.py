"""Planted violation: two paths acquire the same pair of locks in
opposite orders — the lock-order-cycle checker must flag both edges.
Never imported; parsed by tests/test_weedlint.py."""

import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def path_one():
    with lock_a:
        with lock_b:  # edge lock_a -> lock_b
            pass


def path_two():
    with lock_b:
        with lock_a:  # edge lock_b -> lock_a: CYCLE
            pass


def multi_item():
    # `with a, b:` orders left-to-right — consistent with path_one, adds
    # no new cycle beyond the planted one
    with lock_a, lock_b:
        pass
