"""Planted durability-protocol violations: every rule in weedlint's
`durability` family must fire exactly on its marked line here, and the
`good_*` twins must stay clean. Never imported — parsed by weedlint only.
"""

import json
import os


def bad_rename(path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("state")
    os.replace(tmp, path)  # MARK fsync-missing-before-rename


def good_rename(path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("state")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def bad_record(journal, shard):
    journal.append({"kind": "rows", "rows": 3})  # MARK record-before-fsync


def good_record(journal, shard):
    os.fsync(shard.fileno())
    journal.append({"kind": "rows", "rows": 3})


def bad_visible(base):
    with open(base + ".dat", "wb") as f:  # MARK tmp-visible-name
        f.write(b"x")


def bad_torn(f):
    out = []
    for line in f:
        out.append(json.loads(line))  # MARK torn-tail-unhandled
    return out


def good_torn(f):
    out = []
    for line in f:
        try:
            out.append(json.loads(line))
        except ValueError:
            break
    return out
