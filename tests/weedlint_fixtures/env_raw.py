"""Planted violations for the env-registry family. Never imported;
parsed only."""

import os

from seaweedfs_tpu.utils import config

DEPTH = int(os.environ.get("WEEDTPU_PIPELINE_DEPTH", "2"))  # BAD: raw .get
WHO = os.getenv("WEEDTPU_WHO", "")  # BAD: raw getenv
RAW = os.environ["WEEDTPU_RAW"]  # BAD: raw subscript read
TILE = os.environ.get("WEEDTPU_XORSCHED_TILE_KB", "4")  # BAD: raw .get of a registered knob
TYPO = config.env("WEEDTPU_NO_SUCH_KNOB")  # BAD: not in ENV_REGISTRY
XLRU = config.env("WEEDTPU_XORSCHED_LRU")  # BAD: unregistered (knob is _CACHE)

OK = config.env("WEEDTPU_PIPELINE_DEPTH")  # fine: registered read
OK2 = config.env("WEEDTPU_XORSCHED_CACHE")  # fine: registered read
os.environ["WEEDTPU_SET_FOR_SUBPROCESS"] = "1"  # fine: write is plumbing
CHILD_ENV = dict(os.environ)  # fine: whole-env passthrough
