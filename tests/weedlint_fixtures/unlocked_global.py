"""Planted violation: module-level mutable state written from an
executor callback without the lock. Never imported; parsed only."""

import threading
from concurrent.futures import ThreadPoolExecutor

_cache: dict = {}
_counters = {}
_cache_lock = threading.Lock()
_pool = ThreadPoolExecutor(2)


def _refresh(key):
    _cache[key] = 1  # BAD: no lock held
    _counters.pop(key, None)  # BAD: mutator call without lock
    with _cache_lock:
        _cache["ok"] = 2  # fine: under the lock


def _thread_body():
    with _cache_lock:
        _counters["ticks"] = 0  # fine


def kick():
    _pool.submit(_refresh, "a")
    threading.Thread(target=_thread_body).start()
    _cache["main"] = 3  # fine: kick() is not a registered callback


class Worker:
    """Bound-method callbacks (the package's dominant shape) count too."""

    def start(self):
        threading.Thread(target=self._loop).start()

    def _loop(self):
        _counters["loop"] = 1  # BAD: bound-method callback, no lock
