"""obs-drift fixture registry: one metric used by string, one used by
binding, one declared-and-never-touched (planted obs-metric-unused)."""


class _R:
    def counter(self, name, help_="", labels=()):
        return object()

    def gauge(self, name, help_="", labels=()):
        return object()

    def histogram(self, name, help_="", labels=()):
        return object()


REGISTRY = _R()

GoodCounter = REGISTRY.counter("weedtpu_good_total", "used via its string name")
BoundHistogram = REGISTRY.histogram(
    "weedtpu_bound_seconds", "used via its binding name"
)
OrphanCounter = REGISTRY.counter(
    "weedtpu_orphan_total", "declared but never referenced — planted violation"
)
