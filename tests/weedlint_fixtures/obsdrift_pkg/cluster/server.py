"""obs-drift fixture call sites: line numbers are asserted by
tests/test_weedlint.py — keep the planted violations where they are."""

from obsdrift_pkg import stats
from obsdrift_pkg.obs import trace as trace_mod

SCRAPED = (
    "weedtpu_good_total",        # declared: clean usage
    "weedtpu_missing_total",     # planted: obs-metric-undeclared (line 9)
    "weedtpu_gf_native_symbol",  # no metric suffix: NOT a metric, ignored
)


def serve():
    stats.BoundHistogram  # binding-name usage of weedtpu_bound_seconds
    with trace_mod.span("good.span", shard=1):
        pass
    with trace_mod.span("bad.span"):  # planted: obs-span-undeclared (line 18)
        pass
