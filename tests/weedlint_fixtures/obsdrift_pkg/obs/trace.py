"""obs-drift fixture span catalog: one used entry, one stale entry
(planted obs-span-unused)."""

SPAN_NAMES = {
    "good.span": "recorded by the fixture server",
    "stale.span": "registered but never recorded — planted violation",
}


def span(name, **attrs):  # the real contextmanager shape, body irrelevant
    return None
