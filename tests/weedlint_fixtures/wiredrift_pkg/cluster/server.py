"""Planted wire-drift violations: the handler reads a request field the
schema does not have and returns a response key it does not have.
Never imported; parsed only."""


class Server:
    def _build(self, svc):
        svc.add("DoThing", self._rpc_do_thing)
        svc.add("SlabThing", self._rpc_slab_thing)

    def _rpc_do_thing(self, req, ctx):
        vid = req["volume_id"]  # fine: in DoThingRequest
        who = req["requester"]  # BAD: not a DoThingRequest field
        return {"ok": True, "extra": who}  # "extra" BAD: not in DoThingResponse

    def _rpc_slab_thing(self, req, ctx):
        terms = req.get("projection")  # fine: repeated message field
        rows = req["projection_rows"]  # fine
        bad = req["projection_row"]  # BAD: singular typo of the field
        yield bytes(rows or 0) + bytes(len(terms or ())) + bytes(bool(bad))
