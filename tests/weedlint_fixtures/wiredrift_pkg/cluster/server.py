"""Planted wire-drift violations: the handler reads a request field the
schema does not have and returns a response key it does not have.
Never imported; parsed only."""


class Server:
    def _build(self, svc):
        svc.add("DoThing", self._rpc_do_thing)
        svc.add("SlabThing", self._rpc_slab_thing)

    def _rpc_do_thing(self, req, ctx):
        vid = req["volume_id"]  # fine: in DoThingRequest
        who = req["requester"]  # BAD: not a DoThingRequest field
        return {"ok": True, "extra": who}  # "extra" BAD: not in DoThingResponse

    def _rpc_slab_thing(self, req, ctx):
        terms = req.get("projection")  # fine: repeated message field
        rows = req["projection_rows"]  # fine
        bad = req["projection_row"]  # BAD: singular typo of the field
        yield bytes(rows or 0) + bytes(len(terms or ())) + bytes(bool(bad))


class GenServer:
    """Inline-encode-shaped drift: the handler reads a mode-switch field
    that exists, one that does not, and returns one good + one bad key."""

    def _build(self, svc):
        svc.add("GenerateThing", self._rpc_generate_thing)

    def _rpc_generate_thing(self, req, ctx):
        inline = req.get("inline")  # fine: in GenThingRequest
        bad = req["inlined"]  # BAD: typo of the mode-switch field
        return {"mode": "warm" if not inline else "inline", "rows_inline": bad}
        # "rows_inline" BAD: the response field is inline_rows


class ConvertServer:
    """Geometry-conversion-shaped drift: the handler reads the code-family
    string via a typo, and books the byte accounting under a response key
    the schema does not have."""

    def _build(self, svc):
        svc.add("ConvertShards", self._rpc_convert_shards)

    def _rpc_convert_shards(self, req, ctx):
        fam = req.get("target_family")  # fine: in ConvertThingRequest
        cut = req["cutover"]  # fine: the cut-over mode switch
        bad = req["target_familly"]  # BAD: typo of the code-family field
        return {
            "mode": "converted" if cut else "staged",
            "bytes_read": len(fam or ""),
            "bytes_wrote": bad,  # BAD: the response field is bytes_written
        }


class BatchServer:
    """Rebuild-batch-fusion-shaped drift: the handler reads the fuse
    mode-switch via a typo and returns the in-batch block order under a
    response key the schema does not have."""

    def _build(self, svc):
        svc.add("RebuildBatch", self._rpc_rebuild_batch)

    def _rpc_rebuild_batch(self, req, ctx):
        vids = req.get("volume_ids")  # fine: in BatchThingRequest
        fuse = req["fused"]  # BAD: typo of the fuse mode-switch
        return {
            "dispatch_groups": 1 if fuse else len(vids or ()),
            "signature_groups": len(vids or ()),
            "blocks_order": list(vids or ()),  # BAD: field is block_order
        }
