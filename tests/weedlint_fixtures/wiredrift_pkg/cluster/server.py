"""Planted wire-drift violations: the handler reads a request field the
schema does not have and returns a response key it does not have.
Never imported; parsed only."""


class Server:
    def _build(self, svc):
        svc.add("DoThing", self._rpc_do_thing)

    def _rpc_do_thing(self, req, ctx):
        vid = req["volume_id"]  # fine: in DoThingRequest
        who = req["requester"]  # BAD: not a DoThingRequest field
        return {"ok": True, "extra": who}  # "extra" BAD: not in DoThingResponse
