"""Planted violations for the resource-safety family. Never imported;
parsed only."""

import os
import tempfile
from contextlib import ExitStack


def leaky(path):
    f = open(path)  # BAD: no with
    return f.read()


def littered():
    t = tempfile.NamedTemporaryFile(delete=False)  # BAD: no unlink anywhere
    t.write(b"x")
    return t.name


def fine_with(path):
    with open(path) as f:  # fine
        return f.read()


def fine_stack(paths):
    with ExitStack() as stack:
        files = [stack.enter_context(open(p)) for p in paths]  # fine
        return [f.read() for f in files]


def fine_consumed():
    t = tempfile.NamedTemporaryFile(delete=False)  # fine: unlinked below
    try:
        t.write(b"x")
    finally:
        os.unlink(t.name)
