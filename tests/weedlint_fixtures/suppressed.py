"""Suppression-semantics fixture. Never imported; parsed only."""


def properly_suppressed(path):
    # weedlint: ignore[open-no-ctx] fixture: handle ownership is intentional here
    f = open(path)
    return f


def suppressed_without_reason(path):
    f = open(path)  # weedlint: ignore[open-no-ctx]
    return f


def unknown_rule(path):
    f = open(path)  # weedlint: ignore[not-a-rule] typo'd rule must not silence
    return f


# weedlint: ignore[tmpfile-no-unlink] nothing here ever fires this rule
UNUSED = 1
