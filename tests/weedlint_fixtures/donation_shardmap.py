"""Planted violations for the donation family's shard_map extension:
mapped bodies are traced (host sync inside them is flagged) and donated
names passed to shard_map-wrapped jits follow the same dead-until-
rebound rule. Never imported; parsed only (jax is not actually loaded)."""

import functools

import jax
import numpy as np
from jax.experimental.shard_map import shard_map

_MESH = object()


@functools.partial(shard_map, mesh=_MESH, in_specs=None, out_specs=None)
def _mapped_body(block):
    host = np.asarray(block)  # BAD: host materialization in a mapped body
    return host


def _combine(block):
    return block


_donating = jax.jit(
    shard_map(_combine, mesh=_MESH, in_specs=None, out_specs=None),
    donate_argnums=(0,),
)


@functools.partial(jax.jit, donate_argnums=(0,))
@functools.partial(shard_map, mesh=_MESH, in_specs=None, out_specs=None)
def _mapped_donating(block):
    return block


def run(staging):
    out = _donating(staging)
    checksum = staging.sum()  # BAD: staging was donated via the wrapper
    return out, checksum


def run_decorated(staging):
    out = _mapped_donating(staging)
    tail = staging[-1]  # BAD: donated through the decorator stack
    return out, tail


def run_rebound(staging):
    out = _mapped_donating(staging)
    staging = out + 1  # re-bind revives the name
    return staging  # fine: reads the new binding
