"""Planted violations for the donation-safety family. Never imported;
parsed only (jax is not actually loaded)."""

import jax
import numpy as np


def _impl(m, data):
    host = np.asarray(data)  # BAD: host materialization inside jit
    print("dispatching")  # BAD: host I/O inside jit
    data.block_until_ready()  # BAD: device sync inside jit
    return host


_donated = jax.jit(_impl, donate_argnums=(1,))


def run(m, staging):
    out = _donated(m, staging)
    checksum = staging.sum()  # BAD: staging was donated — buffer is XLA's
    return out, checksum


def run_rebound(m, staging):
    out = _donated(m, staging)
    staging = out + 1  # re-bind revives the name
    return staging  # fine: reads the new binding
