"""weedtrace tests: the context-local span recorder and tail-biased
trace ring (seaweedfs_tpu/obs/trace.py), per-stage attribution math,
the /debug/traces surface, the `ec.trace`/`ec.status` shell commands —
and the acceptance e2e: one trace id round-tripping a full distributed
degraded read (client -> master -> volume server -> remote holders and
back) including the hedge, coalesce, and rebuild slab/trace branches."""

import io
import json
import logging
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.obs import trace
from seaweedfs_tpu.shell import CommandEnv, run_command
from seaweedfs_tpu.utils import glog

LARGE, SMALL = 4096, 512


# -- helpers ------------------------------------------------------------------


def _mk(dur, kind="http.read", klass="healthy", error=None, tid=None):
    """A completed trace with a pinned duration (the ring orders and
    evicts on `dur`, never on wall time — so tests can fabricate it)."""
    st = trace._TraceState(tid or trace.new_trace_id(), kind, klass)
    root = trace.Span(kind, None, st)
    root.dur = dur
    return trace._Completed(root, st, error)


@pytest.fixture
def on(monkeypatch):
    monkeypatch.setenv("WEEDTPU_TRACE", "on")
    trace.RING.clear()
    yield
    trace.RING.clear()


# -- recording primitives -----------------------------------------------------


def test_disabled_tracing_is_total_noop(monkeypatch):
    monkeypatch.setenv("WEEDTPU_TRACE", "off")
    assert not trace.enabled()
    ctx = trace.start("http.read")
    assert ctx is trace._NULL  # shared singleton, no per-call allocation
    with ctx as root:
        assert root is None
        with trace.span("ec.recover", shard=1) as sp:
            assert sp is None  # no ambient trace -> span is a no-op
        assert trace.current_trace_id() is None
        trace.annotate(x=1)  # must not raise outside a trace
        trace.set_class("degraded")


def test_span_tree_records_nesting_attrs_and_errors(on):
    ring = trace.TraceRing(capacity=8, slowest_n=1, sample=1.0, seed=1)
    with trace.start("http.read", klass="degraded", ring=ring) as root:
        tid = root.trace.trace_id
        with trace.span("ec.recover", shard=3):
            with trace.span("ec.gather", shard=3) as g:
                g.annotate(have=9)
            with pytest.raises(ValueError):
                with trace.span("ec.decode", backend="numpy"):
                    raise ValueError("boom")
    [t] = ring.snapshot()
    assert t["trace_id"] == tid and t["class"] == "degraded"
    assert t["error"] is None  # the root exited clean: only the SPAN errored
    (recover,) = t["root"]["spans"]
    assert recover["name"] == "ec.recover" and recover["attrs"] == {"shard": 3}
    gather, decode = recover["spans"]
    assert gather["attrs"] == {"shard": 3, "have": 9}
    assert decode["error"] == "ValueError"
    assert t["duration_s"] >= recover["dur_ms"] / 1e3 >= 0


def test_root_error_always_retained(on):
    ring = trace.TraceRing(capacity=8, slowest_n=1, sample=0.0, seed=1)
    with pytest.raises(IOError):
        with trace.start("http.read", ring=ring):
            raise IOError("disk gone")
    snap = ring.snapshot()
    errs = [t for t in snap if t["error"]]
    assert len(errs) == 1 and "disk gone" in errs[0]["error"]


def test_continue_trace_only_roots_with_propagated_id(on):
    ring = trace.TraceRing(capacity=8, slowest_n=1, sample=1.0, seed=1)
    assert trace.continue_trace("rpc.server", None, ring=ring) is trace._NULL
    assert trace.continue_trace("rpc.server", "<script>", ring=ring) is trace._NULL
    with trace.continue_trace("rpc.server", "AbC123", ring=ring) as root:
        assert root.trace.trace_id == "abc123"  # sanitized lowercase
    assert ring.snapshot()[0]["trace_id"] == "abc123"


def test_valid_id_rejects_wire_junk():
    assert trace.valid_id("deadbeef01") == "deadbeef01"
    assert trace.valid_id("DEAD-BEEF") == "dead-beef"
    for bad in (None, 7, "", "-leading", "zz not hex start" * 8, "x" * 80,
                "inj\nected", "a b"):
        assert trace.valid_id(bad) is None, bad


def test_ensure_nests_under_ambient_else_roots(on):
    ring = trace.TraceRing(capacity=8, slowest_n=1, sample=1.0, seed=1)
    # no ambient trace: ensure() roots a fresh maintenance trace
    with trace.start("rebuild.run", klass="maint", ring=ring):
        pass
    assert ring.snapshot()[0]["kind"] == "rebuild.run"
    ring.clear()
    # ambient trace active: ensure() nests a span, no second root
    with trace.start("shell.command", klass="shell", ring=ring) as root:
        tid = root.trace.trace_id
        with trace.ensure("rebuild.run"):
            pass
    [t] = ring.snapshot()
    assert t["trace_id"] == tid
    assert [s["name"] for s in t["root"]["spans"]] == ["rebuild.run"]


def test_attach_bridges_worker_threads(on):
    ring = trace.TraceRing(capacity=8, slowest_n=1, sample=1.0, seed=1)
    with trace.start("http.read", ring=ring) as root:
        parent = trace.current()

        def worker():
            # a bare thread has no ambient span; attach adopts the parent
            assert trace.current() is None
            with trace.attach(parent), trace.span("ec.fetch", shard=2):
                assert trace.current_trace_id() == root.trace.trace_id

        t = threading.Thread(target=worker)
        t.start()
        t.join(10)
    [tr] = ring.snapshot()
    assert [s["name"] for s in tr["root"]["spans"]] == ["ec.fetch"]


# -- the ring: tail-biased retention ------------------------------------------


def test_ring_keeps_errors_and_slowest_drops_the_rest_at_sample_zero():
    ring = trace.TraceRing(capacity=16, slowest_n=2, sample=0.0, seed=7)
    # descending durations: the first two fill the slowest row, every
    # later (faster) trace must be dropped outright at sample=0
    for i in range(50):
        ring.offer(_mk(dur=0.001 * (50 - i), tid=f"aa{i:04x}"))
    ring.offer(_mk(dur=0.0005, error="IOError: x", tid="ee01"))
    snap = ring.snapshot()
    # 2 slowest + 1 error survived; the 48 fast healthy traces did not
    assert len(snap) == 3
    assert snap[0]["duration_s"] >= snap[1]["duration_s"]
    assert {t["trace_id"] for t in snap} == {"aa0000", "aa0001", "ee01"}
    st = ring.stats()
    assert st["offered"] == 51 and st["kept"] == 3
    assert st["sampled"] == 0 and st["errors"] == 1


def test_ring_slowest_is_per_kind_class_key():
    ring = trace.TraceRing(capacity=4, slowest_n=1, sample=0.0, seed=7)
    ring.offer(_mk(0.9, klass="healthy", tid="aa01"))
    ring.offer(_mk(0.1, klass="degraded", tid="aa02"))
    ring.offer(_mk(0.2, kind="http.write", klass="put", tid="aa03"))
    # each (kind, class) keeps its own slowest: the 0.1s degraded trace
    # survives even though a 0.9s healthy one exists
    assert {t["trace_id"] for t in ring.snapshot()} == {"aa01", "aa02", "aa03"}


def test_ring_sampled_fifo_is_bounded():
    ring = trace.TraceRing(capacity=10, slowest_n=1, sample=1.0, seed=7)
    for i in range(200):
        ring.offer(_mk(dur=0.001, tid=f"bb{i:04x}"))
    st = ring.stats()
    assert st["sampled"] == 10  # FIFO capped
    snap = ring.snapshot(limit=1000)
    assert len(snap) <= 10 + 1  # FIFO + at most one distinct slowest


def test_sampling_is_deterministic_under_seed():
    def kept_ids(seed):
        ring = trace.TraceRing(capacity=64, slowest_n=1, sample=0.5, seed=seed)
        for i in range(64):
            ring.offer(_mk(dur=0.001, tid=f"cc{i:04x}"))
        return [t["trace_id"] for t in ring.snapshot(limit=100)]

    assert kept_ids(42) == kept_ids(42)
    assert kept_ids(42) != kept_ids(43)  # 2^-64 flake odds, effectively zero


def test_snapshot_filters_and_debug_payload(monkeypatch):
    monkeypatch.setenv("WEEDTPU_TRACE", "on")
    ring = trace.TraceRing(capacity=32, slowest_n=1, sample=1.0, seed=1)
    ring.offer(_mk(0.500, klass="degraded", tid="dd01"))
    ring.offer(_mk(0.010, klass="healthy", tid="dd02"))
    ring.offer(_mk(0.020, kind="http.write", klass="put", tid="dd03"))
    assert {t["trace_id"] for t in ring.snapshot(klass="degraded")} == {"dd01"}
    assert {t["trace_id"] for t in ring.snapshot(kind="http.write")} == {"dd03"}
    assert {t["trace_id"] for t in ring.snapshot(min_duration=0.1)} == {"dd01"}
    assert len(ring.snapshot(limit=2)) == 2
    # slowest-first ordering
    assert [t["trace_id"] for t in ring.snapshot()][0] == "dd01"
    payload = trace.debug_payload(
        "/debug/traces?class=degraded&min_ms=100&limit=5", ring=ring
    )
    assert payload["enabled"] is True
    assert [t["trace_id"] for t in payload["traces"]] == ["dd01"]
    # junk query values fall back to defaults instead of raising
    junk = trace.debug_payload("/debug/traces?min_ms=zap&limit=zap", ring=ring)
    assert len(junk["traces"]) == 3


# -- render + attribution -----------------------------------------------------


def _fake_trace():
    return {
        "trace_id": "4f1d0000", "kind": "http.read", "class": "degraded",
        "start": 0.0, "duration_s": 1.0, "error": None,
        "root": {
            "name": "http.read", "t_ms": 0.0, "dur_ms": 1000.0,
            "spans": [
                {
                    "name": "ec.recover", "t_ms": 50.0, "dur_ms": 900.0,
                    "attrs": {"shard": 3},
                    "spans": [
                        # parallel fan-out: child durations sum to 1.2s
                        # inside a 0.9s parent -> must be scaled, never
                        # attributed more wall time than passed
                        {"name": "ec.fetch", "t_ms": 51.0, "dur_ms": 600.0},
                        {"name": "ec.fetch", "t_ms": 51.0, "dur_ms": 600.0},
                    ],
                },
            ],
        },
    }


def test_render_trace_shows_tree_attrs_and_times():
    out = trace.render_trace(_fake_trace())
    lines = out.splitlines()
    assert lines[0] == "trace=4f1d0000 http.read class=degraded 1000.0ms"
    assert "ec.recover" in lines[1] and "shard=3" in lines[1]
    assert lines[2].startswith("|  +-") and "ec.fetch" in lines[2]
    err = dict(_fake_trace(), error="IOError: x")
    assert "ERROR=IOError: x" in trace.render_trace(err).splitlines()[0]


def test_attribute_stages_sums_exactly_to_e2e():
    stages = trace.attribute_stages(_fake_trace())
    assert abs(sum(stages.values()) - 1.0) < 1e-9
    # parallel fetches scaled to the recover span's 0.9s wall budget
    assert abs(stages["ec.fetch"] - 0.9) < 1e-9
    assert abs(stages["ec.recover"] - 0.0) < 1e-9  # no self-time left
    assert abs(stages["other"] - 0.1) < 1e-9  # root self-time
    # a trivial single-span trace: all self-time on the stage
    t = {
        "duration_s": 0.5,
        "root": {"name": "r", "dur_ms": 500.0, "spans": [
            {"name": "ec.decode", "t_ms": 0.0, "dur_ms": 200.0},
        ]},
    }
    s = trace.attribute_stages(dict(_fake_trace(), **t))
    assert abs(s["ec.decode"] - 0.2) < 1e-9 and abs(s["other"] - 0.3) < 1e-9


def test_attribution_aggregation_consistency(on):
    """assemble_trace_attribution: per-class stage totals must equal the
    summed end-to-end latencies (stage_coverage == 1.0) — the artifact's
    committed consistency gate."""
    from seaweedfs_tpu.ec import slo

    traces = [_fake_trace() for _ in range(10)]
    for i, t in enumerate(traces):
        t["trace_id"] = f"ab{i:02x}"
        t["duration_s"] = 0.1 * (i + 1)
    attrib = slo.assemble_trace_attribution(traces)
    cls = attrib["classes"]["degraded"]
    assert cls["count"] == 10
    assert abs(cls["stage_coverage"] - 1.0) < 1e-6
    assert abs(cls["e2e_total_s"] - sum(0.1 * (i + 1) for i in range(10))) < 1e-6
    assert len(attrib["slowest"]) == 5
    assert attrib["slowest"][0]["duration_s"] == pytest.approx(1.0)
    shares = sum(s["share"] for s in cls["stages"].values())
    assert abs(shares - 1.0) < 1e-3


# -- glog context -------------------------------------------------------------


def test_glog_lines_carry_the_active_trace_id(on):
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("seaweedfs_tpu")
    h = _Capture(level=logging.INFO)
    logger.addHandler(h)
    try:
        ring = trace.TraceRing(capacity=8, slowest_n=1, sample=1.0, seed=1)
        glog.info("outside any trace")
        with trace.start("http.read", trace_id="feed0001", ring=ring):
            glog.info("inside span %s", glog.kv(vid=7))
    finally:
        logger.removeHandler(h)
    assert records[-2] == "outside any trace"
    assert records[-1] == "inside span vid=7 trace=feed0001"


def test_disabled_span_path_is_cheap(monkeypatch):
    """Overhead microbench (loose): with tracing off, 50k span call
    sites must cost well under a second total — the 'safe to leave the
    call sites in every hot loop' floor. The real 5% e2e gate lives in
    the weedload smoke (test_slo_harness)."""
    monkeypatch.setenv("WEEDTPU_TRACE", "off")
    t0 = time.monotonic()
    for _ in range(50_000):
        with trace.span("ec.decode"):
            pass
    assert time.monotonic() - t0 < 1.0


# -- live cluster e2e ---------------------------------------------------------


@pytest.fixture
def cluster(tmp_path, monkeypatch):
    monkeypatch.setenv("WEEDTPU_TRACE", "on")
    monkeypatch.setenv("WEEDTPU_TRACE_SAMPLE", "1.0")
    # deterministic hedging: the bench RPC delay makes every remote
    # shard fetch run ~20 ms (a modeled RTT), and a 5 ms hedge delay
    # guarantees the backup launches while the primary is still pending
    # wherever a second holder exists
    monkeypatch.setenv("WEEDTPU_BENCH_RPC_DELAY_MS", "20")
    monkeypatch.setenv("WEEDTPU_HEDGE_DELAY_MS", "5")
    trace.RING.clear()
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    env = CommandEnv(master.address)
    yield master, servers, client, env
    env.close()
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()
    trace.RING.clear()


def _shell(env, line):
    out = io.StringIO()
    run_command(env, line, out)
    return out.getvalue()


def _ec_spread_volume(client, env, n=16, size=3000):
    """Upload n blobs, EC-encode their volume spread across the cluster
    (the shell path operators use), return (vid, [(fid, payload)])."""
    fids = []
    for _ in range(n):
        import os as _os

        payload = _os.urandom(size)
        r = client.submit(payload)
        fids.append((r.fid, payload))
    vid = int(fids[0][0].split(",", 1)[0])
    _shell(env, "lock")
    _shell(
        env,
        f"ec.encode -volumeId {vid} -largeBlockSize {LARGE} "
        f"-smallBlockSize {SMALL}",
    )
    return vid, fids


def _holders_of(env, vid):
    """{shard_id: [node dict]} from the live topology."""
    out = {}
    for n in env.topology_nodes():
        for e in n.get("ec_shards", []):
            if int(e["volume_id"]) != vid:
                continue
            from seaweedfs_tpu.ec.shard_bits import ShardBits

            for s in ShardBits(e["shard_bits"]).shard_ids():
                out.setdefault(s, []).append(n)
    return out


def _grpc_of(node, servers):
    return next(s for s in servers if s.url == node["url"]).grpc_address


def _traced_get(url, fid, payload):
    tid = trace.new_trace_id()
    req = urllib.request.Request(
        f"http://{url}/{fid}", headers={trace.HTTP_HEADER: tid}
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        body = r.read()
        echo = r.headers.get(trace.HTTP_HEADER)
    assert body == payload, f"bytes differ for {fid}"
    assert echo == tid, "traced reply must echo the request's trace id"
    return tid


def test_trace_id_round_trips_distributed_degraded_read(cluster):
    """The acceptance e2e: ids minted at the client survive the full
    degraded read — serving VS (http.read root), its master lookup
    (rpc.server LookupEcVolume), remote holder fetches (rpc.server
    VolumeEcShardRead), the hedge branch, and the coalesce branch — and
    come back on the HTTP reply. In-process servers share one trace
    ring, so cross-process assertions reduce to: every leg's root landed
    in the ring under the SAME propagated id."""
    master, servers, client, env = cluster
    vid, fids = _ec_spread_volume(client, env)
    holders = _holders_of(env, vid)

    # drop two data shards cluster-wide -> needles there reconstruct
    lost = [2, 3]
    for s in lost:
        for node in holders[s]:
            env.vs_call(
                _grpc_of(node, servers), "VolumeEcShardsDelete",
                {"volume_id": vid, "shard_ids": [s]},
            )
    # give one surviving shard a SECOND holder so hedges have an
    # alternate to race (shell ec.encode places each shard once). The
    # duplicated shard must be REMOTE to the serving front, or its
    # fan-out never fetches it at all
    import shutil

    from seaweedfs_tpu.ec import stripe as stripe_mod

    front = servers[0]
    donor_shard, donor = next(
        (s, holders[s][0]) for s in sorted(holders)
        if s not in lost and holders[s][0]["url"] != front.url
    )
    donor = next(s for s in servers if s.url == donor["url"])
    recip = next(s for s in servers if s.url not in (front.url, donor.url))
    src = stripe_mod.shard_file_name(donor._base_path_for(vid), donor_shard)
    dst_base = recip._base_path_for(vid)
    shutil.copy(src, stripe_mod.shard_file_name(dst_base, donor_shard))
    for ext in (".ecx", ".eci"):
        shutil.copy(donor._base_path_for(vid) + ext, dst_base + ext)
    env.vs_call(recip.grpc_address, "VolumeEcShardsMount", {"volume_id": vid})

    # read everything through one serving VS with a fresh id per request
    tid_of = {fid: _traced_get(front.url, fid, payload) for fid, payload in fids}
    ids = set(tid_of.values())

    snap = trace.RING.snapshot(limit=100000)
    degraded = [
        t for t in snap
        if t["kind"] == "http.read" and t["class"] == "degraded"
        and t["trace_id"] in ids
    ]
    assert degraded, "no degraded read landed in the ring"
    names = {s["name"] for t in degraded for s in trace.iter_spans(t)}
    assert {"ec.recover", "ec.gather", "ec.fetch", "ec.decode"} <= names, names

    # the remote-holder leg: VolumeEcShardRead rpc.server roots under the
    # same ids the client minted
    fetch_legs = [
        t for t in snap
        if t["kind"] == "rpc.server" and t["trace_id"] in ids
        and t["root"].get("attrs", {}).get("method") == "VolumeEcShardRead"
    ]
    assert fetch_legs, "remote shard fetches did not continue the trace id"

    # the master leg: the serving VS's shard-location lookup carried the
    # id of whichever traced read was first to need it
    master_legs = [
        t for t in snap
        if t["kind"] == "rpc.server" and t["trace_id"] in ids
        and t["root"].get("attrs", {}).get("method") == "LookupEcVolume"
    ]
    assert master_legs, "master lookup did not continue the trace id"

    # the fids whose first read reconstructed (their id landed in the
    # ring classed degraded) — the needles the branch probes re-read
    degraded_ids = {t["trace_id"] for t in degraded}
    d_fids = [
        (fid, p) for fid, p in fids if tid_of[fid] in degraded_ids
    ]
    assert d_fids, "no fid classified degraded"

    # hedge branch: reads of a degraded needle re-issued until a backup
    # fetch span shows up under one of our ids (delay pinned to 1 ms, a
    # second holder exists -> fires almost every fan-out)
    hedge_seen = any("ec.hedge" in {s["name"] for s in trace.iter_spans(t)}
                     for t in degraded)
    tries = 0
    while not hedge_seen and tries < 40:
        tries += 1
        fid, p = d_fids[tries % len(d_fids)]
        tid = _traced_get(front.url, fid, p)
        for t in trace.RING.snapshot(limit=100000):
            if t["trace_id"] == tid and any(
                s["name"] == "ec.hedge" for s in trace.iter_spans(t)
            ):
                hedge_seen = True
                break
    assert hedge_seen, "hedge branch never recorded under a propagated id"

    # coalesce branch: concurrent readers of ONE degraded needle, each
    # with its own id — waiters must record ec.coalesce.wait under THEIR
    # id (ids never bleed across coalesced requests)
    deg_fid, deg_payload = d_fids[0]
    coalesce_tid = None
    for _ in range(10):
        tids, threads = [], []

        def rd():
            tids.append(_traced_get(front.url, deg_fid, deg_payload))

        for _ in range(12):
            threads.append(threading.Thread(target=rd))
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for tr in trace.RING.snapshot(limit=100000):
            if tr["trace_id"] in tids and any(
                s["name"] == "ec.coalesce.wait"
                for s in trace.iter_spans(tr)
            ):
                coalesce_tid = tr["trace_id"]
                break
        if coalesce_tid:
            break
    assert coalesce_tid, "coalesce waiter never recorded under its own id"

    # -- the /debug/traces surface, live --------------------------------------
    def dbg(query):
        with urllib.request.urlopen(
            f"http://{front.url}/debug/traces?{query}", timeout=10
        ) as r:
            return json.loads(r.read().decode())

    p = dbg("class=degraded&limit=3")
    assert p["enabled"] and len(p["traces"]) <= 3
    assert all(t["class"] == "degraded" for t in p["traces"])
    durs = [t["duration_s"] for t in p["traces"]]
    assert durs == sorted(durs, reverse=True), "slowest-first ordering"
    assert dbg("min_ms=10000000")["traces"] == []
    assert {t["kind"] for t in dbg("kind=rpc.server&limit=5")["traces"]} <= {
        "rpc.server"
    }

    # -- operator surfaces: ec.trace + ec.status ------------------------------
    out = _shell(env, "ec.trace -klass degraded -limit 2")
    assert "trace=" in out and "ec.recover" in out
    one = _shell(env, f"ec.trace -traceId {coalesce_tid}")
    assert f"trace={coalesce_tid}" in one
    status = _shell(env, "ec.status")
    for n in env.topology_nodes():
        assert n["url"] in status
    assert "ec_volumes=" in status and "scrub=" in status
    assert "backend=" in status and "rebuild=" in status
    assert "cache=" in status and "inval=" in status


def test_trace_id_round_trips_shell_rebuild_trace_and_slab(cluster):
    """The rebuild branches: `ec.rebuild -remote` under the shell's
    trace root must land the rebuild RPC (and the rebuild.run pipeline
    under it) in the ring with the SHELL's id — in projection (trace)
    mode AND forced-slab mode."""
    master, servers, client, env = cluster
    vid, fids = _ec_spread_volume(client, env)
    holders = _holders_of(env, vid)

    for mode, lost_shard in (("on", 12), ("off", 13)):
        for node in holders[lost_shard]:
            env.vs_call(
                _grpc_of(node, servers), "VolumeEcShardsDelete",
                {"volume_id": vid, "shard_ids": [lost_shard]},
            )
        trace.RING.clear()
        out = _shell(env, f"ec.rebuild -remote -trace {mode}")
        assert "rebuilt" in out
        snap = trace.RING.snapshot(limit=100000)
        shells = [
            t for t in snap
            if t["kind"] == "shell.command"
            and t["root"].get("attrs", {}).get("command") == "ec.rebuild"
        ]
        assert len(shells) == 1, "shell must root exactly one trace"
        tid = shells[0]["trace_id"]
        legs = [
            t for t in snap
            if t["kind"] == "rpc.server" and t["trace_id"] == tid
            and t["root"].get("attrs", {}).get("method")
            == "VolumeEcShardsRebuild"
        ]
        assert legs, f"-trace {mode}: rebuild RPC did not continue the id"
        names = {s["name"] for t in legs for s in trace.iter_spans(t)}
        assert "rebuild.run" in names, (mode, names)
        assert "rebuild.drain" in names, (mode, names)
        # holder-side slab/projection streams continued the same id too
        holder_methods = {
            t["root"].get("attrs", {}).get("method")
            for t in snap
            if t["kind"] == "rpc.server" and t["trace_id"] == tid
        }
        assert holder_methods & {
            "VolumeEcShardSlabRead", "VolumeEcShardSlabProject"
        }, holder_methods

    for fid, payload in fids:
        assert client.read(fid) == payload
