"""Black-box multi-process suite (SURVEY §4 row d — the compose-style
harness): REAL `python -m seaweedfs_tpu ...` server processes on loopback,
driven exclusively through their public surfaces (HTTP + shell CLI), no
in-process access. This is the committed form of the launch recipe in
.claude/skills/verify/SKILL.md."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(args, cwd):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # servers need no virtual mesh
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # logs go to a FILE, never an undrained pipe: a server that outgrew the
    # ~64 KiB pipe buffer would block on a log write and hang every test
    log = open(os.path.join(cwd, f"{args[0]}.log"), "ab")
    p = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    p._logfile = log  # closed implicitly at process exit
    return p


def _wait_http(url, timeout=40):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.read()
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.4)
    raise AssertionError(f"{url} never came up: {last}")


def _http(method, url, data=None, headers=None, timeout=15):
    req = urllib.request.Request(url, data=data, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except (urllib.error.URLError, TimeoutError, ConnectionError) as e:
        return 0, str(e).encode()  # not up (yet): readiness loops retry on 0


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("blackbox")
    (tmp / "v0").mkdir()
    (tmp / "meta").mkdir()
    procs = []
    try:
        procs.append(
            _spawn(["master", "-port", "29333", "-httpPort", "29433"], str(tmp))
        )
        time.sleep(1)
        procs.append(
            _spawn(
                ["volume", "-port", "28080", "-dir", "./v0",
                 "-mserver", "127.0.0.1:29333"],
                str(tmp),
            )
        )
        procs.append(
            _spawn(
                ["filer", "-port", "28888", "-master", "127.0.0.1:29333",
                 "-store", "log", "-dir", "./meta"],
                str(tmp),
            )
        )
        # readiness = a real write probe, not an HTTP 200: the filer answers
        # reads before the volume tier has heartbeated in
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            code, _ = _http("PUT", "http://127.0.0.1:28888/probe.txt", b"ready")
            if code == 201:
                break
            time.sleep(0.5)
        else:
            for p in procs:
                p.kill()
            logs = b""
            for name in ("master.log", "volume.log", "filer.log"):
                path = tmp / name
                if path.exists():
                    logs += b"\n== " + name.encode() + b" ==\n" + path.read_bytes()
            raise AssertionError(f"stack never ready:\n{logs.decode()[-2000:]}")
        yield tmp
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_filer_file_lifecycle_over_http(stack):
    payload = os.urandom(9000)
    code, body = _http("PUT", "http://127.0.0.1:28888/proj/a/report.bin", payload)
    assert code == 201, body
    assert json.loads(body)["size"] == len(payload)
    code, got = _http("GET", "http://127.0.0.1:28888/proj/a/report.bin")
    assert code == 200 and got == payload
    # range
    code, got = _http(
        "GET", "http://127.0.0.1:28888/proj/a/report.bin",
        headers={"Range": "bytes=100-299"},
    )
    assert code == 206 and got == payload[100:300]
    # rename via mv.from, then the old path 404s
    code, _ = _http(
        "POST", "http://127.0.0.1:28888/proj/a/final.bin?mv.from=/proj/a/report.bin"
    )
    assert code == 200
    code, got = _http("GET", "http://127.0.0.1:28888/proj/a/final.bin")
    assert code == 200 and got == payload
    code, _ = _http("GET", "http://127.0.0.1:28888/proj/a/report.bin")
    assert code == 404
    # listing
    code, body = _http("GET", "http://127.0.0.1:28888/proj/a")
    assert code == 200
    assert [e["path"] for e in json.loads(body)["Entries"]] == ["/proj/a/final.bin"]
    code, _ = _http("DELETE", "http://127.0.0.1:28888/proj/a/final.bin")
    assert code == 204


def test_shell_cli_ec_lifecycle(stack):
    """Drive the operator surface the way an operator does: the shell
    subcommand with -c scripts against the live processes."""
    tmp = stack
    # enough blobs to make volume 1 worth encoding
    for i in range(12):
        _http("PUT", f"http://127.0.0.1:28888/bulk/f{i:02d}.bin", os.urandom(1500))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "seaweedfs_tpu", "shell",
         "-master", "127.0.0.1:29333",
         "-c", "lock; volume.list; ec.encode -volumeId 1; ec.rebuild; unlock"],
        cwd=str(tmp),
        env=env,
        capture_output=True,
        timeout=120,
    )
    out = proc.stdout.decode()
    assert proc.returncode == 0, out + proc.stderr.decode()
    assert "ec.encode volume 1" in out, out
    # blobs on the now-EC volume still readable through the filer
    code, got = _http("GET", "http://127.0.0.1:28888/bulk/f00.bin")
    assert code == 200 and len(got) == 1500


def test_filer_restart_preserves_namespace(stack):
    """Kill -9 the filer and restart it on the same log store: the
    namespace replays (crash recovery, not graceful shutdown)."""
    tmp = stack
    payload = b"survives-a-filer-crash"
    code, _ = _http("PUT", "http://127.0.0.1:28888/crash/file.txt", payload)
    assert code == 201
    # find and kill the filer process hard
    import glob

    killed = False
    for pid_dir in glob.glob("/proc/[0-9]*"):
        try:
            with open(os.path.join(pid_dir, "cmdline"), "rb") as f:
                cmd = f.read().decode(errors="replace")
        except OSError:
            continue
        if "seaweedfs_tpu" in cmd and "filer" in cmd and "28888" in cmd:
            os.kill(int(os.path.basename(pid_dir)), signal.SIGKILL)
            killed = True
    assert killed, "filer process not found"
    time.sleep(1)
    p = _spawn(
        ["filer", "-port", "28888", "-master", "127.0.0.1:29333",
         "-store", "log", "-dir", "./meta"],
        str(tmp),
    )
    try:
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            code, got = _http("GET", "http://127.0.0.1:28888/crash/file.txt")
            if code == 200:
                break
            time.sleep(0.5)
        assert code == 200 and got == payload, "namespace lost across crash-restart"
    finally:
        p.send_signal(signal.SIGTERM)
        try:
            p.wait(10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_master_http_api_across_processes(stack):
    """The reference curl workflow against a REAL master process:
    /dir/assign -> POST the blob to the assigned volume server ->
    /dir/lookup resolves it -> /cluster/healthz answers."""
    import json as _json

    code, body = _http("GET", "http://127.0.0.1:29433/dir/assign")
    assert code == 200, body
    assign = _json.loads(body)
    assert assign["fid"] and assign["url"]
    code, _ = _http(
        "POST", f"http://{assign['url']}/{assign['fid']}", b"curl workflow"
    )
    assert code in (200, 201)
    vid = assign["fid"].split(",", 1)[0]
    code, body = _http("GET", f"http://127.0.0.1:29433/dir/lookup?volumeId={vid}")
    assert code == 200 and assign["url"] in body.decode()
    code, body = _http("GET", f"http://{assign['url']}/{assign['fid']}")
    assert code == 200 and body == b"curl workflow"
    code, _ = _http("GET", "http://127.0.0.1:29433/cluster/healthz")
    assert code == 200
