"""Storage backend + remote tiering tests: vendor clients, tier
move/fetch roundtrip through the volume engine, tiered reads via the
cluster, and the shell volume.tier.* commands (SURVEY.md §4 loopback
pattern)."""

import io
import os

import pytest

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.remote_storage import (
    LocalRemoteStorage,
    make_remote_client,
)
from seaweedfs_tpu.remote_storage.tier import tier_fetch, tier_move
from seaweedfs_tpu.storage.backend import MemoryMappedFile, RemoteDatFile
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume, VolumeReadOnly


def test_local_vendor_roundtrip(tmp_path):
    c = LocalRemoteStorage(str(tmp_path / "vendor"))
    c.write_file("a/b.dat", b"0123456789")
    assert c.size("a/b.dat") == 10
    assert c.read_range("a/b.dat", 2, 4) == b"2345"
    # location() -> factory roundtrip
    c2 = make_remote_client(c.location())
    assert c2.read_range("a/b.dat", 0, 10) == b"0123456789"
    c.delete("a/b.dat")
    with pytest.raises(FileNotFoundError):
        c.size("a/b.dat")
    with pytest.raises(ValueError):
        c.write_file("../escape", b"x")


def test_memory_mapped_backend(tmp_path):
    p = tmp_path / "m.bin"
    p.write_bytes(b"abcdefgh")
    mm = MemoryMappedFile(str(p))
    mm.seek(2)
    assert mm.read(3) == b"cde"
    assert mm.tell() == 5
    mm.seek(-2, os.SEEK_END)
    assert mm.read() == b"gh"
    with pytest.raises(IOError):
        mm.write(b"x")
    mm.close()


def test_remote_dat_file(tmp_path):
    c = LocalRemoteStorage(str(tmp_path / "v"))
    c.write_file("k", b"0123456789")
    r = RemoteDatFile(c, "k")
    r.seek(0, os.SEEK_END)
    assert r.tell() == 10
    r.seek(3)
    assert r.read(4) == b"3456"
    assert r.read(100) == b"789"  # clamped at EOF
    with pytest.raises(IOError):
        r.write(b"x")


def test_volume_tier_move_and_read_back(tmp_path):
    v = Volume(str(tmp_path), 7)
    needles = {}
    for i in range(1, 20):
        n = Needle(cookie=0x1234, id=i, data=os.urandom(100 + i))
        v.write_needle(n)
        needles[i] = n.data
    v.close()
    vendor = LocalRemoteStorage(str(tmp_path / "cold"))
    info = tier_move(os.path.join(str(tmp_path), "7"), vendor)
    assert not os.path.exists(tmp_path / "7.dat")
    assert os.path.exists(tmp_path / "7.tierinfo")
    assert vendor.size(info["key"]) == info["size"]
    # reopen: reads flow through the remote backend
    tv = Volume(str(tmp_path), 7)
    assert tv.tiered and tv.read_only
    for i, data in needles.items():
        assert tv.read_needle(i).data == data
    with pytest.raises(VolumeReadOnly):
        tv.write_needle(Needle(cookie=1, id=99, data=b"x"))
    with pytest.raises(IOError):
        tv.compact()
    tv.close()
    # fetch back: local again, writable again
    tier_fetch(os.path.join(str(tmp_path), "7"))
    assert os.path.exists(tmp_path / "7.dat")
    assert not os.path.exists(tmp_path / "7.tierinfo")
    lv = Volume(str(tmp_path), 7)
    assert not lv.tiered
    assert lv.read_needle(5).data == needles[5]
    lv.close()


@pytest.fixture
def cluster(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "vol"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
    vs.start()
    client = MasterClient(master.address)
    yield master, vs, client, tmp_path
    client.close()
    vs.stop()
    master.stop()


def test_tier_move_rpc_and_cluster_read(cluster):
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.pb import VOLUME_SERVICE

    master, vs, client, tmp_path = cluster
    res = client.submit(b"tiered needle payload")
    vid = int(res.fid.split(",")[0])
    with rpc.RpcClient(vs.grpc_address) as c:
        c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
        resp = c.call(
            VOLUME_SERVICE,
            "VolumeTierMove",
            {
                "volume_id": vid,
                "destination": {"vendor": "local", "root": str(tmp_path / "cold")},
            },
        )
        assert resp["size"] > 0
    # the read path is unchanged for clients
    assert client.read(res.fid) == b"tiered needle payload"
    # bring it back
    with rpc.RpcClient(vs.grpc_address) as c:
        c.call(VOLUME_SERVICE, "VolumeTierFetch", {"volume_id": vid})
    assert client.read(res.fid) == b"tiered needle payload"


def test_shell_tier_commands(cluster):
    import io as _io

    from seaweedfs_tpu.shell import CommandEnv, run_command

    master, vs, client, tmp_path = cluster
    res = client.submit(b"shell tier data")
    vid = int(res.fid.split(",")[0])
    with CommandEnv(master.address) as env:
        out = _io.StringIO()
        run_command(env, "lock", out)
        run_command(
            env, f"volume.tier.move -volumeId {vid} -dest local:{tmp_path}/cold2", out
        )
        assert "bytes ->" in out.getvalue()
        assert client.read(res.fid) == b"shell tier data"
        run_command(env, f"volume.tier.fetch -volumeId {vid}", out)
        assert "local again" in out.getvalue()
        assert client.read(res.fid) == b"shell tier data"


def test_benchmark_upload_download_commands(cluster, capsys, tmp_path):
    from seaweedfs_tpu.command import commands

    master, vs, client, base_tmp = cluster
    cmds = commands()
    import argparse

    # upload
    src = tmp_path / "up.bin"
    src.write_bytes(os.urandom(500))
    p = argparse.ArgumentParser()
    cmds["upload"].configure(p)
    args = p.parse_args(["-master", master.address, str(src)])
    assert cmds["upload"].run(args) == 0
    out = capsys.readouterr().out
    import json

    fid = json.loads(out)[0]["fid"]
    # download
    p = argparse.ArgumentParser()
    cmds["download"].configure(p)
    args = p.parse_args(["-master", master.address, "-dir", str(tmp_path / "dl"), fid])
    assert cmds["download"].run(args) == 0
    dl = tmp_path / "dl" / fid.replace(",", "_")
    assert dl.read_bytes() == src.read_bytes()
    # benchmark (small)
    p = argparse.ArgumentParser()
    cmds["benchmark"].configure(p)
    args = p.parse_args(["-master", master.address, "-n", "20", "-size", "256", "-c", "4"])
    assert cmds["benchmark"].run(args) == 0
    out = capsys.readouterr().out
    assert "write:" in out and "read:" in out and "p99" in out
