"""The fused Pallas kernel must LOWER for the TPU target (Mosaic), not just
run in interpret mode — interpret mode accepts patterns Mosaic rejects
(layouts, reshapes, sub-byte dtypes), so without this proof the kernel has
never been validated against the real compiler. Runs via jax.export in a
scrubbed subprocess (no device needed; the axon plugin must be off
PYTHONPATH or platform resolution wedges on the tunnel)."""

from __future__ import annotations

import pytest

from seaweedfs_tpu.ops import tpu_lowering


@pytest.fixture(scope="module")
def proof():
    results = tpu_lowering.run_lowering_proof(timeout=600)
    return {r["name"]: r for r in results}


def test_all_proof_shapes_lower(proof):
    assert set(proof) == {s["name"] for s in tpu_lowering.PROOF_SHAPES}, proof
    for name, meta in proof.items():
        assert meta.get("ok"), f"{name} failed to lower for TPU: {meta.get('error')}"


def test_lowering_embeds_mosaic_kernel(proof):
    # every lowered module must actually contain the serialized Mosaic
    # custom call — a module that traced around the pallas_call would
    # "pass" while proving nothing
    for name, meta in proof.items():
        assert meta.get("has_tpu_custom_call"), name
        assert meta.get("platforms") == ["tpu"], name
        assert meta.get("mlir_bytes", 0) > 1000, name
