"""Evidence-based `auto` backend + incremental sweep harvesting.

The r6 contract: `new_encoder("auto")` on TPU flips to the fused Pallas
kernel ONLY when a committed on-chip measurement artifact shows a fused
variant beating the XLA steady-state — fabricated evidence files (fused
faster / slower / absent / stale / off-chip) must each select the
expected backend. The sweep that produces the evidence persists one JSON
line per config as it lands and resumes past configs an interrupted run
already harvested; device_watch.sh's harvest output must round-trip
through device_window.py's assembler into exactly the file the factory
reads.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from seaweedfs_tpu.ops import rs_codec

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_evidence(dirpath, meas, name="DEVICE_MEASUREMENT_r91.json"):
    with open(os.path.join(dirpath, name), "w", encoding="utf-8") as f:
        json.dump(meas, f)


def _fresh_when():
    import datetime

    return datetime.datetime.utcnow().strftime("%Y-%m-%dT%H:%MZ")


# -- pick_device_backend: the decision table ---------------------------------


def test_fused_faster_flips_to_pallas_with_variant_config(tmp_path):
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0, "pallas_bf16_steady_gbps": 44.5,
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "pallas"
    assert dec["pallas_mxu"] == "bf16" and dec["pallas_tile"] is None
    assert "beats" in dec["reason"]
    assert dec["evidence_file"] == "DEVICE_MEASUREMENT_r91.json"


def test_fused_slower_keeps_xla(tmp_path):
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0, "pallas_auto_steady_gbps": 18.7,
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax"
    assert "no fused number beats" in dec["reason"]


def test_absent_evidence_keeps_xla(tmp_path):
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax"
    assert "no committed" in dec["reason"]


def test_stale_evidence_keeps_xla_even_when_fused_wins(tmp_path):
    _write_evidence(tmp_path, {
        "when": "2024-01-01T00:00Z", "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0, "pallas_bf16_steady_gbps": 44.5,
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax"
    assert "stale" in dec["reason"]


def test_off_chip_evidence_never_flips(tmp_path):
    # a cpu-platform artifact (e.g. someone committed a sanity run) is
    # not on-chip evidence, no matter what its numbers say
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "cpu",
        "xla_steady_gbps": 0.04, "pallas_auto_steady_gbps": 1.0,
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax"
    assert "not an on-chip" in dec["reason"]


def test_newest_round_wins_and_unreadable_newest_falls_back(tmp_path):
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu",
        "xla_steady_gbps": 31.0, "pallas_auto_steady_gbps": 18.0,
    }, name="DEVICE_MEASUREMENT_r04.json")
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu",
        "xla_steady_gbps": 31.0, "pallas_dma_steady_gbps": 50.0,
    }, name="DEVICE_MEASUREMENT_r06.json")
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "pallas" and dec["pallas_mxu"] == "dma"
    assert dec["evidence_file"] == "DEVICE_MEASUREMENT_r06.json"
    # corrupt the newest: the older readable round must serve
    with open(os.path.join(tmp_path, "DEVICE_MEASUREMENT_r06.json"), "w") as f:
        f.write("{torn")
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax"
    assert dec["evidence_file"] == "DEVICE_MEASUREMENT_r04.json"


def test_sweep_section_counts_as_evidence(tmp_path):
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0,
        "sweep": {"encode": {"pallas-mplane-32768": 47.2, "xla": 31.0},
                  "rebuild": {"rebuild-pallas-auto": 40.0}},
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "pallas"
    assert dec["pallas_mxu"] == "mplane" and dec["pallas_tile"] == 32768


def test_sweep_only_artifact_flips_without_stage1_keys(tmp_path):
    """The short-window case the harvest exists for: the watch-fired
    sweep landed (with its own xla anchor) but the window worker never
    wrote stage-1 scan-chain keys. The sweep table alone must decide."""
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "sweep": {"encode": {"xla": 31.2, "pallas-dma-65536": 45.0}},
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "pallas"
    assert dec["xla_steady_gbps"] == 31.2
    assert dec["pallas_mxu"] == "dma" and dec["pallas_tile"] == 65536
    # and a sweep whose fused numbers LOSE to its own xla anchor stays jax
    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "sweep": {"encode": {"xla": 31.2, "pallas-auto": 19.0}},
    })
    backend, dec = rs_codec.pick_device_backend(art_dir=str(tmp_path))
    assert backend == "jax" and "no fused number beats" in dec["reason"]


def test_sweep_resume_ignores_other_mode_records(tmp_path):
    """A cpu/--tiny sanity run landing in the harvest file must NOT mark
    configs done for the on-chip sweep (the assembler excludes those
    records from evidence, so skipping on them would leave the harvest
    permanently without usable numbers)."""
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        import kernel_sweep as ks
    finally:
        sys.path.pop(0)
    p = tmp_path / "SWEEP.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"variant": "pallas-auto", "platform": "cpu",
                            "tiny": True, "exact": True}) + "\n")
        f.write(json.dumps({"variant": "pallas-dma-auto", "platform": "tpu",
                            "tiny": False, "steady_gbps": 50.0}) + "\n")
    done = ks.load_done(str(p), platform="tpu", tiny=False)
    assert "pallas-dma-auto" in done and "pallas-auto" not in done
    # a cpu sanity re-run, conversely, resumes only its own records
    done = ks.load_done(str(p), platform="cpu", tiny=True)
    assert "pallas-auto" in done and "pallas-dma-auto" not in done


def test_variant_label_parsing():
    cases = {
        "pallas_steady_gbps": ("int8", None),
        "pallas_auto_steady_gbps": ("int8", None),
        "pallas_bf16_steady_gbps": ("bf16", None),
        "pallas_tile8192_steady_gbps": ("int8", 8192),
        "pallas-u8-16384": ("u8", 16384),
        "pallas-dma-auto": ("dma", None),
        "pallas-65536": ("int8", 65536),
    }
    for label, want in cases.items():
        assert rs_codec.parse_fused_variant(label) == want, label


# -- new_encoder integration --------------------------------------------------


class _FakeTpu:
    platform = "tpu"
    device_kind = "TPU v5 lite"


def test_new_encoder_flips_on_winning_evidence(tmp_path, monkeypatch):
    import jax

    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0, "pallas_dma_steady_gbps": 52.0,
    })
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_FakeTpu()])
    monkeypatch.setattr(rs_codec, "_artifacts_dir", lambda: str(tmp_path))
    enc = rs_codec.new_encoder()
    assert enc.backend == "pallas"
    assert enc.pallas_mxu == "dma" and enc.pallas_tile is None
    assert enc.selection["source"] == "on-chip-evidence"
    assert enc.selection["backend"] == "pallas"


def test_new_encoder_keeps_xla_on_losing_evidence(tmp_path, monkeypatch):
    import jax

    _write_evidence(tmp_path, {
        "when": _fresh_when(), "platform": "tpu (TPU v5 lite)",
        "xla_steady_gbps": 31.0, "pallas_auto_steady_gbps": 18.7,
    })
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_FakeTpu()])
    monkeypatch.setattr(rs_codec, "_artifacts_dir", lambda: str(tmp_path))
    enc = rs_codec.new_encoder()
    assert enc.backend == "jax"
    assert enc.selection["source"] == "on-chip-evidence"


def test_weedtpu_backend_env_overrides_auto(monkeypatch):
    monkeypatch.setenv("WEEDTPU_BACKEND", "numpy")
    enc = rs_codec.new_encoder()
    assert enc.backend == "numpy"
    assert enc.selection["source"] == "env:WEEDTPU_BACKEND"
    # explicit callers are never overridden
    enc = rs_codec.new_encoder(backend="jax")
    assert enc.backend == "jax"
    assert enc.selection["source"] == "explicit"
    monkeypatch.setenv("WEEDTPU_BACKEND", "bogus")
    with pytest.raises(ValueError, match="WEEDTPU_BACKEND"):
        rs_codec.new_encoder()


def test_selection_exported_through_stats(monkeypatch):
    from seaweedfs_tpu import stats

    monkeypatch.setenv("WEEDTPU_BACKEND", "numpy")
    rs_codec.new_encoder()
    lines = "\n".join(stats.EcBackendSelected.collect())
    assert (
        'weedtpu_ec_backend_selected{backend="numpy",source="env:WEEDTPU_BACKEND"} 1.0'
        in lines
    )
    # a later different selection zeroes the previous one
    monkeypatch.delenv("WEEDTPU_BACKEND")
    enc = rs_codec.new_encoder()
    lines = "\n".join(stats.EcBackendSelected.collect())
    assert (
        'weedtpu_ec_backend_selected{backend="numpy",source="env:WEEDTPU_BACKEND"} 0.0'
        in lines
    )
    src = enc.selection["source"]  # platform, or cpu-bench-evidence when
    assert f'backend="{enc.backend}",source="{src}"}} 1.0' in lines  # promoted


def test_pallas_encoder_honors_variant_config():
    """An evidence-selected variant config must actually reach the kernel
    dispatch and stay byte-exact vs the numpy golden."""
    import numpy as np

    rng = np.random.default_rng(5)
    gold = rs_codec.Encoder(10, 4, backend="numpy")
    data = [rng.integers(0, 256, 700, dtype=np.uint8) for _ in range(10)]
    want = gold.encode([d.copy() for d in data])
    for mxu, tile in (("dma", None), ("mplane", 8192), ("u8", None)):
        enc = rs_codec.Encoder(
            10, 4, backend="pallas", pallas_mxu=mxu, pallas_tile=tile
        )
        got = enc.encode([d.copy() for d in data])
        for a, b in zip(want, got):
            assert np.array_equal(a, b), (mxu, tile)


# -- interrupted-sweep resume + watch->assembler round-trip -------------------


def test_interrupted_sweep_resume_skips_persisted_configs(tmp_path):
    """Simulate the r5 failure mode: a sweep dies mid-run (here: its
    harvest file is truncated to a prefix + one torn line). The re-run
    must skip every persisted config, re-measure only the missing ones,
    and leave a complete harvest."""
    out = tmp_path / "SWEEP.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    run1 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "kernel_sweep.py"),
         "--smoke", "--out", str(out)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=560,
    )
    assert run1.returncode == 0, run1.stdout + run1.stderr
    lines = out.read_text().strip().splitlines()
    all_names = [json.loads(l)["variant"] for l in lines]
    assert len(all_names) >= 10
    # interrupt: keep a prefix, add a torn line (crash mid-write)
    keep = lines[:-3]
    out.write_text("\n".join(keep) + "\n" + '{"variant": "pallas-')
    run2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "kernel_sweep.py"),
         "--smoke", "--out", str(out)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=560,
    )
    assert run2.returncode == 0, run2.stdout + run2.stderr
    resumed = [
        json.loads(l)["variant"]
        for l in run2.stdout.splitlines()
        if '"resumed": true' in l
    ]
    assert sorted(resumed) == sorted(json.loads(l)["variant"] for l in keep)
    # every config exactly once in the final harvest (the torn fragment
    # is terminated, never glued onto an appended record)
    final = []
    for l in out.read_text().strip().splitlines():
        try:
            final.append(json.loads(l)["variant"])
        except ValueError:
            pass  # the terminated torn fragment
    assert sorted(final) == sorted(all_names)


def test_watch_harvest_round_trips_into_assembler(tmp_path):
    """Parse check for the device_watch.sh -> kernel_sweep --out ->
    device_window assembler chain: records shaped exactly as the sweep
    persists them (including a torn tail and cpu sanity records) must
    assemble into evidence pick_device_backend accepts."""
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        import device_window as dw
    finally:
        sys.path.pop(0)
    sweep = tmp_path / "SWEEP_r06.jsonl"
    recs = [
        {"variant": "xla", "platform": "tpu", "tiny": False,
         "when": "2026-08-02T01:00:00Z", "exact": True,
         "per_call_gbps": 4.4, "steady_gbps": 31.2},
        {"variant": "pallas-dma-65536", "platform": "tpu", "tiny": False,
         "when": "2026-08-02T01:05:00Z", "exact": True,
         "per_call_gbps": 4.2, "steady_gbps": 55.1},
        {"variant": "rebuild-pallas-auto", "platform": "tpu", "tiny": False,
         "when": "2026-08-02T01:06:00Z", "exact": True, "steady_gbps": 40.0},
        {"variant": "pallas-u8-8192", "platform": "tpu", "tiny": False,
         "when": "2026-08-02T01:07:00Z", "error": "Mosaic: unsupported"},
        {"variant": "pallas-bf16-8192", "platform": "cpu", "tiny": True,
         "exact": True, "steady_gbps": 0.04},  # sanity run: never evidence
    ]
    with open(sweep, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        f.write('{"variant": "pallas-16')  # torn tail: crash mid-write
    parsed = dw.parse_sweep_jsonl(str(sweep))
    assert parsed["encode"] == {"xla": 31.2, "pallas-dma-65536": 55.1}
    assert parsed["rebuild"] == {"rebuild-pallas-auto": 40.0}
    assert parsed["failed"] == ["pallas-u8-8192"]
    assert parsed["platform"] == "tpu"

    meas = dw.assemble_measurement(
        {"when": "2026-08-02T01:00Z", "round": 6,
         "platform": "tpu (TPU v5 lite)", "xla_steady_gbps": 31.2},
        str(sweep),
    )
    assert meas["sweep_best_encode"] == {
        "variant": "pallas-dma-65536", "steady_gbps": 55.1}
    assert meas["sweep_best_rebuild"] == {
        "variant": "rebuild-pallas-auto", "steady_gbps": 40.0}
    art = tmp_path / "artifacts"
    art.mkdir()
    with open(art / "DEVICE_MEASUREMENT_r06.json", "w") as f:
        json.dump(meas, f)
    backend, dec = rs_codec.pick_device_backend(art_dir=str(art))
    assert backend == "pallas"
    assert dec["pallas_mxu"] == "dma" and dec["pallas_tile"] == 65536
    assert dec["fused_variant"] == "pallas-dma-65536"
