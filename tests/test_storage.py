"""Storage-engine tests: needle codec round trips (all optional fields, both
versions, CRC enforcement), superblock/TTL/replica-placement codecs, file-id
parsing, volume append/read/delete/compact, and Store load incl. EC volumes —
mirroring the reference's weed/storage/*_test.go coverage (SURVEY.md §4)."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import CrcError, Needle, VERSION2, VERSION3
from seaweedfs_tpu.storage.store import Store, parse_base_name
from seaweedfs_tpu.storage.super_block import TTL, ReplicaPlacement, SuperBlock
from seaweedfs_tpu.storage.volume import Volume, VolumeReadOnly
from seaweedfs_tpu.utils.native import crc32c

ENC = Encoder(10, 4, backend="numpy")


# -- needle codec ------------------------------------------------------------


@pytest.mark.parametrize("version", [VERSION2, VERSION3])
def test_needle_roundtrip_full(version):
    n = Needle(
        cookie=0x1234ABCD,
        id=0xDEADBEEF01,
        data=b"hello world" * 10,
        name=b"file.txt",
        mime=b"text/plain",
        pairs=b'{"k":"v"}',
        last_modified=1_700_000_000,
        ttl=b"\x05\x02",
        is_compressed=True,
    )
    buf = n.to_bytes(version)
    assert len(buf) % types.NEEDLE_PADDING_SIZE == 0
    m = Needle.from_bytes(buf, version)
    assert (m.cookie, m.id, m.data, m.name, m.mime, m.pairs) == (
        n.cookie,
        n.id,
        n.data,
        n.name,
        n.mime,
        n.pairs,
    )
    assert m.last_modified == n.last_modified
    assert m.ttl == n.ttl
    assert m.is_compressed
    assert m.size == n.size
    if version == VERSION3:
        assert m.append_at_ns == n.append_at_ns


def test_needle_minimal_and_empty():
    n = Needle(cookie=1, id=2, data=b"x")
    m = Needle.from_bytes(n.to_bytes(), VERSION3)
    assert m.data == b"x" and not m.name
    # a LIVE empty needle still carries DataSize+flags (size 5), so a .dat
    # scan can tell it apart from a delete marker (size 0)
    empty = Needle(cookie=1, id=3)
    e = Needle.from_bytes(empty.to_bytes(), VERSION3)
    assert e.data == b"" and e.size == 5
    tomb = Needle(cookie=0, id=3)
    t = Needle.from_bytes(tomb.to_bytes(VERSION3, tombstone=True), VERSION3)
    assert t.size == 0


def test_needle_crc_rejects_corruption():
    buf = bytearray(Needle(cookie=1, id=2, data=b"payload").to_bytes())
    buf[types.NEEDLE_HEADER_SIZE + 4] ^= 0xFF  # flip a data byte
    with pytest.raises(CrcError):
        Needle.from_bytes(bytes(buf), VERSION3)


def test_crc32c_known_answer():
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


# -- superblock & friends ----------------------------------------------------


def test_super_block_roundtrip():
    sb = SuperBlock(
        version=3,
        replica_placement=ReplicaPlacement.parse("012"),
        ttl=TTL.parse("3d"),
        compact_revision=7,
    )
    out = SuperBlock.from_bytes(sb.to_bytes())
    assert str(out.replica_placement) == "012"
    assert str(out.ttl) == "3d"
    assert out.compact_revision == 7
    assert out.replica_placement.copy_count == 4


def test_ttl_parse():
    assert TTL.parse("") .minutes == 0
    assert TTL.parse("5m").minutes == 5
    assert TTL.parse("2h").minutes == 120
    assert str(TTL.parse("45")) == "45m"
    with pytest.raises(ValueError):
        TTL.parse("5q")


def test_file_id():
    f = FileId(3, 0x1637, 0x37D6F2A4)
    s = str(f)
    assert s == "3,163737d6f2a4"
    assert FileId.parse(s) == f
    with pytest.raises(ValueError):
        FileId.parse("nocomma")


# -- volume ------------------------------------------------------------------


def test_volume_write_read_delete_compact(tmp_path):
    with Volume(str(tmp_path), 7, "col") as v:
        offs = {}
        for i in range(1, 30):
            n = Needle(cookie=i, id=i, data=bytes([i]) * (i * 7 % 200 + 1))
            off, size = v.write_needle(n)
            offs[i] = off
            assert off % 8 == 0
        for i in range(1, 30):
            m = v.read_needle(i)
            assert m.data == bytes([i]) * (i * 7 % 200 + 1)
        # wrong cookie
        with pytest.raises(PermissionError):
            v.read_needle(5, cookie=999)
        # delete half
        for i in range(1, 30, 2):
            assert v.delete_needle(i)
        assert not v.delete_needle(1)  # already gone
        with pytest.raises(KeyError):
            v.read_needle(1)
        assert v.needle_count() == 14
        before, after = v.compact()
        assert after < before
        for i in range(2, 30, 2):
            assert v.read_needle(i).data == bytes([i]) * (i * 7 % 200 + 1)
        assert v.super_block.compact_revision == 1
        assert v.check_integrity() == 14

    # reload from disk
    with Volume(str(tmp_path), 7, "col") as v2:
        assert v2.needle_count() == 14
        assert v2.read_needle(4).cookie == 4


def test_volume_read_only(tmp_path):
    with Volume(str(tmp_path), 1) as v:
        v.read_only = True
        with pytest.raises(VolumeReadOnly):
            v.write_needle(Needle(cookie=1, id=1, data=b"z"))


def test_parse_base_name():
    assert parse_base_name("17") == ("", 17)
    assert parse_base_name("images_3") == ("images", 3)
    assert parse_base_name("a_b_9") == ("a_b", 9)
    assert parse_base_name("nope") is None


# -- store -------------------------------------------------------------------


def test_store_volumes_and_ec(tmp_path):
    d1, d2 = str(tmp_path / "d1"), str(tmp_path / "d2")
    store = Store([d1, d2], encoder=ENC)
    store.load()
    v = store.create_volume(5, collection="img", replication="001")
    store.write_needle(5, Needle(cookie=9, id=77, data=b"data77"))
    assert store.read_needle(5, 77).data == b"data77"

    # EC-encode volume 5's files in place (tiny blocks), then serve via Store
    base = v.base_path
    stripe.write_ec_files(base, large_block_size=1024, small_block_size=64, buffer_size=64, encoder=ENC)
    stripe.write_sorted_file_from_idx(base)
    store.mount_ec_volume(5, base)
    infos = store.ec_volume_infos()
    assert len(infos) == 1 and infos[0].volume_id == 5
    assert infos[0].shard_bits.shard_id_count() == 14

    # remove the normal volume -> reads go through the EC path
    v.close()
    for loc in store.locations:
        loc.volumes.pop(5, None)
    n = store.read_needle(5, 77)
    assert n.data == b"data77"

    # degraded EC read
    for s in (0, 13):
        os.remove(stripe.shard_file_name(base, s))
    store.unmount_ec_volume(5)
    store.mount_ec_volume(5, base)
    assert store.read_needle(5, 77).data == b"data77"

    vi = store.volume_infos()
    assert vi == [] or all(i["id"] != 5 for i in vi)
    store.close()


def test_store_reload_discovers(tmp_path):
    d = str(tmp_path / "x")
    s1 = Store([d], encoder=ENC)
    s1.create_volume(3)
    s1.write_needle(3, Needle(cookie=1, id=1, data=b"abc"))
    s1.close()
    s2 = Store([d], encoder=ENC)
    s2.load()
    assert s2.read_needle(3, 1).data == b"abc"
    s2.close()


# -- persistent needle map (SortedFileNeedleMap) ------------------------------


def _fill_volume(v, n, start=1):
    for i in range(start, start + n):
        v.write_needle(Needle(cookie=7, id=i, data=f"needle-{i}".encode()))


def test_sorted_file_map_volume_roundtrip(tmp_path):
    """sorted_file volumes serve the same reads/deletes/compaction as the
    in-memory map, and a reopen is O(tail): no full .idx replay."""
    v = Volume(str(tmp_path), 42, needle_map_kind="sorted_file")
    _fill_volume(v, 50)
    v.delete_needle(7)
    assert v.read_needle(3).data == b"needle-3"
    with pytest.raises(KeyError):
        v.read_needle(7)
    v.close()
    assert os.path.exists(tmp_path / "42.sdx")

    v2 = Volume(str(tmp_path), 42, needle_map_kind="sorted_file")
    # clean reopen: the map binary-searches the sidecar, no full rebuild
    assert not v2.nm.rebuilt_full
    assert v2.nm.replayed_tail == 0
    assert v2.read_needle(3).data == b"needle-3"
    with pytest.raises(KeyError):
        v2.read_needle(7)
    assert len(v2.nm) == 49
    # writes after reopen land in the overlay and survive the next cycle
    _fill_volume(v2, 5, start=100)
    v2.close()
    v3 = Volume(str(tmp_path), 42, needle_map_kind="sorted_file")
    assert v3.read_needle(104).data == b"needle-104"
    before, after = v3.compact()
    assert after <= before
    assert v3.read_needle(104).data == b"needle-104"
    with pytest.raises(KeyError):
        v3.read_needle(7)
    v3.close()


def test_sorted_file_map_crash_tail_replay(tmp_path):
    """Appends not yet merged into .sdx (simulated crash: no close()) are
    recovered from the .idx tail on the next mount."""
    v = Volume(str(tmp_path), 9, needle_map_kind="sorted_file")
    _fill_volume(v, 10)
    v.nm.flush()  # sidecar at watermark 10 entries
    _fill_volume(v, 5, start=50)
    v.delete_needle(2)
    v._idx.flush()
    v._dat.flush()
    # simulate crash: reopen without close() (no overlay merge)
    v2 = Volume(str(tmp_path), 9, needle_map_kind="sorted_file")
    assert not v2.nm.rebuilt_full
    assert v2.nm.replayed_tail == 6  # 5 appends + 1 tombstone
    assert v2.read_needle(52).data == b"needle-52"
    with pytest.raises(KeyError):
        v2.read_needle(2)
    v2.close()


def test_sorted_file_map_mid_replay_flush_watermark(tmp_path, monkeypatch):
    """A flush triggered while the mount is still replaying the .idx tail
    must not stamp the watermark past the replay cursor: a crash right
    after such a flush would otherwise skip the un-replayed remainder on
    the next mount (lost entries / resurrected deletes)."""
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage.needle_map import SortedFileNeedleMap

    base = str(tmp_path / "mid")
    m = SortedFileNeedleMap(base)
    m.set(1, 10, 100)
    m.close()  # sidecar built, watermark at 1 entry... but .idx is empty
    # append a 10-entry tail directly to the .idx (writes that the sidecar
    # has not merged), including a delete of a sidecar-resident key
    with open(base + ".idx", "ab") as f:
        idx_mod.write_entries(
            [(k, k * 10, 100) for k in range(2, 11)] + [(1, 10, -1)], f
        )
    # force an auto-flush after every replayed entry
    monkeypatch.setattr(SortedFileNeedleMap, "OVERLAY_FLUSH_ENTRIES", 1)
    m2 = SortedFileNeedleMap(base)
    assert m2.replayed_tail == 10
    # simulate a crash immediately after the first mid-replay flush by NOT
    # closing m2, then check the meta watermark never exceeded the cursor:
    # a fresh mount must still see the full tail applied
    m3 = SortedFileNeedleMap(base)
    assert m3.get(5) == (50, 100)
    assert m3.get(1) is None, "mid-replay flush resurrected a deleted key"
    m3.close()


def test_sorted_file_map_mount_reads_only_tail(tmp_path):
    """Mount cost scales with the .idx tail, not the needle population: a
    synthetic 1M-entry index mounts without a full replay and serves
    random lookups through the memmap."""
    import time as _time

    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage.needle_map import SortedFileNeedleMap

    base = str(tmp_path / "big")
    n = 1_000_000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    offsets = np.arange(1, n + 1, dtype=np.uint32)
    sizes = np.full(n, 100, dtype=np.int32)
    entries = np.zeros(n, dtype=idx_mod._BE_ENTRY_DTYPE)
    entries["key"], entries["offset"], entries["size"] = keys, offsets, sizes
    with open(base + ".idx", "wb") as f:
        f.write(entries.tobytes())

    m1 = SortedFileNeedleMap(base)  # first mount pays the one-time build
    assert m1.rebuilt_full and len(m1) == n
    m1.close()

    t0 = _time.perf_counter()
    m2 = SortedFileNeedleMap(base)
    mount_secs = _time.perf_counter() - t0
    assert not m2.rebuilt_full and m2.replayed_tail == 0
    assert mount_secs < 1.0, f"clean mount took {mount_secs:.2f}s — not O(tail)"
    assert m2.get(123_456) == (123_456, 100)
    assert m2.get(n + 1) is None
    assert len(m2) == n
    m2.close()


# -- TTL expiry ---------------------------------------------------------------


def test_ttl_needle_expires_on_read(tmp_path):
    import time as _t

    store = Store([str(tmp_path / "ttl")], encoder=ENC)
    store.load()
    store.create_volume(9, ttl="1m")
    # fresh needle reads fine
    store.write_needle(9, Needle(cookie=1, id=10, data=b"fresh"))
    assert store.read_needle(9, 10).data == b"fresh"
    # a needle whose append timestamp is older than the TTL reads as absent
    old = Needle(cookie=1, id=11, data=b"stale",
                 append_at_ns=_t.time_ns() - 120 * 10**9)
    store.write_needle(9, old)
    with pytest.raises(KeyError, match="expired"):
        store.read_needle(9, 11)
    # a non-TTL volume never expires needles
    store.create_volume(10)
    store.write_needle(10, Needle(cookie=1, id=12, data=b"x",
                                  append_at_ns=_t.time_ns() - 10**15))
    assert store.read_needle(10, 12).data == b"x"


def test_ttl_volume_reaped_when_newest_write_ages_out(tmp_path):
    import time as _t

    store = Store([str(tmp_path / "reap")], encoder=ENC)
    store.load()
    v = store.create_volume(20, ttl="1m")
    store.write_needle(20, Needle(cookie=1, id=1, data=b"doomed"))
    store.create_volume(21)  # no ttl: must survive
    store.write_needle(21, Needle(cookie=1, id=2, data=b"keeper"))
    assert store.reap_expired_volumes() == []  # newest write still fresh
    # age the TTL volume's last write past 1m
    past = _t.time() - 120
    os.utime(v.dat_path, (past, past))
    assert store.reap_expired_volumes() == [20]
    assert store.get_volume(20) is None
    assert not os.path.exists(v.dat_path)
    assert store.read_needle(21, 2).data == b"keeper"


def test_needle_append_ts_batch_matches_read_needle(tmp_path):
    """needle_append_ts must agree with the full record parse, skip
    unknown ids, and survive needles with names/mimes (the ts offset is
    computed from (offset, size), not by parsing the body)."""
    with Volume(str(tmp_path), 9) as v:
        n1 = Needle(cookie=1, id=1, data=b"plain" * 40)
        n2 = Needle(cookie=2, id=2, data=b"x", name=b"file.txt", mime=b"text/plain")
        v.write_needle(n1)
        v.write_needle(n2)
        ts = v.needle_append_ts([1, 2, 777])
        assert set(ts) == {1, 2}
        assert ts[1] == v.read_needle(1).append_at_ns > 0
        assert ts[2] == v.read_needle(2).append_at_ns > 0
