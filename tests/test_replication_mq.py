"""Replication, notification, and mq broker tests over real loopback
stacks (SURVEY.md §4)."""

import json
import os
import threading
import time

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.mq import Broker, BrokerClient
from seaweedfs_tpu.notification import LogFileQueue, MemoryQueue, make_queue
from seaweedfs_tpu.replication import LocalSink, FilerSink, Replicator
from seaweedfs_tpu.utils.log_buffer import LogBuffer


@pytest.fixture
def stack(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp_path / "vol").mkdir()
    vs = VolumeServer([str(tmp_path / "vol")], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address)
    fs.start()
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def _put(fs, path, data: bytes):
    import io

    return fs.write_file(path, io.BytesIO(data))


# -- log buffer (pure) --------------------------------------------------------


def test_log_buffer_flush_and_tail():
    flushed = []
    lb = LogBuffer(
        lambda f, l, recs: flushed.append(recs), max_bytes=200, flush_interval_s=3600
    )
    ts0 = lb.add(b"k1", b"v" * 50)
    assert lb.read_since(0)[0].key == b"k1"
    assert lb.read_since(ts0) == []
    lb.add(b"k2", b"v" * 200)  # crosses max_bytes -> flush
    assert len(flushed) == 1 and [r.key for r in flushed[0]] == [b"k1", b"k2"]
    assert lb.read_since(0) == []
    lb.add(b"k3", b"x")
    lb.close()  # close flushes the tail
    assert [r.key for r in flushed[1]] == [b"k3"]


def test_log_buffer_monotonic_ts():
    lb = LogBuffer(lambda *a: None, flush_interval_s=3600)
    ts = [lb.add(b"", b"x", ts_ns=123) for _ in range(3)]
    assert ts == sorted(ts) and len(set(ts)) == 3
    lb.close()


# -- notification -------------------------------------------------------------


def test_notification_queues(tmp_path):
    mq = MemoryQueue()
    got = []
    mq.subscribe(lambda k, m: got.append(k))
    mq.send_message("/a", {"x": 1})
    assert mq.messages[0][0] == "/a" and got == ["/a"]
    lq = LogFileQueue(str(tmp_path / "events.jsonl"))
    lq.send_message("/b", {"y": 2})
    lq.close()
    lines = open(tmp_path / "events.jsonl", encoding="utf-8").read().splitlines()
    assert json.loads(lines[0])["key"] == "/b"
    assert make_queue("none") is None


def test_filer_notification_wiring(stack):
    _, _, fs = stack
    q = MemoryQueue()
    fs.filer.notification_queue = q
    _put(fs, "/notify/f.txt", b"data")
    deadline = time.monotonic() + 5.0  # dispatch is off-thread
    while time.monotonic() < deadline:
        if "/notify/f.txt" in [k for k, _ in q.messages]:
            break
        time.sleep(0.05)
    assert "/notify/f.txt" in [k for k, _ in q.messages]


# -- replication --------------------------------------------------------------


def test_replicate_to_local_sink(stack, tmp_path):
    _, _, fs = stack
    _put(fs, "/site/a/x.txt", b"xx")
    _put(fs, "/site/y.txt", b"yy")
    sink_dir = tmp_path / "backup"
    rep = Replicator(fs.grpc_address, LocalSink(str(sink_dir)), prefix="/site")
    n = rep.run_once(max_idle_s=0.5)
    assert n >= 3  # dirs + files
    assert (sink_dir / "a" / "x.txt").read_bytes() == b"xx"
    assert (sink_dir / "y.txt").read_bytes() == b"yy"
    # incremental: only new events apply after checkpoint
    _put(fs, "/site/z.txt", b"zz")
    fs.filer.delete_entry("/site/y.txt")
    n2 = rep.run_once(max_idle_s=0.5)
    assert (sink_dir / "z.txt").read_bytes() == b"zz"
    assert not (sink_dir / "y.txt").exists()
    # events outside the prefix are ignored
    _put(fs, "/other/o.txt", b"oo")
    rep.run_once(max_idle_s=0.5)
    assert not (sink_dir / "o.txt").exists() and not (sink_dir / "other").exists()
    rep.close()


def test_replicate_filer_to_filer(stack, tmp_path):
    master, vs, fs = stack
    fs2 = FilerServer(master.address)
    fs2.start()
    try:
        _put(fs, "/data/doc.bin", os.urandom(2048))
        rep = Replicator(
            fs.grpc_address, FilerSink(fs2.url, target_root="/mirror"), prefix="/data"
        )
        rep.run_once(max_idle_s=0.5)
        got = fs2.read_file(fs2.filer.find_entry("/mirror/doc.bin"))
        assert got == fs.read_file(fs.filer.find_entry("/data/doc.bin"))
        # rename on source -> delete+create on sink
        fs.filer.rename("/data/doc.bin", "/data/doc2.bin")
        rep.run_once(max_idle_s=0.5)
        assert not fs2.filer.exists("/mirror/doc.bin")
        assert fs2.filer.exists("/mirror/doc2.bin")
        rep.close()
    finally:
        fs2.stop()


def test_replicate_history_with_renamed_source(stack, tmp_path):
    """A create event whose path was later renamed away must not poison
    the replay — the rename's own events reconcile the sink."""
    _, _, fs = stack
    _put(fs, "/hist/orig.bin", b"abc")
    fs.filer.rename("/hist/orig.bin", "/hist/final.bin")
    sink_dir = tmp_path / "hist-sink"
    rep = Replicator(fs.grpc_address, LocalSink(str(sink_dir)), prefix="/hist")
    rep.run_once(max_idle_s=0.5)
    assert (sink_dir / "final.bin").read_bytes() == b"abc"
    assert not (sink_dir / "orig.bin").exists()
    rep.close()


# -- mq broker ----------------------------------------------------------------


def test_mq_publish_subscribe(stack):
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("events", partition_count=2)
            assert c.list_topics()[0]["topic"] == "events"
            parts = set()
            for i in range(20):
                r = c.publish("events", f"k{i}".encode(), f"v{i}".encode())
                parts.add(r["partition"])
            assert parts == {0, 1}  # key hashing spreads partitions
            got = []
            for p in (0, 1):
                got.extend(
                    (r.key.decode(), r.value.decode())
                    for r in c.subscribe("events", partition=p, max_idle_s=0.5)
                )
            assert sorted(got) == sorted((f"k{i}", f"v{i}") for i in range(20))


def test_mq_durability_across_restart(stack):
    _, _, fs = stack
    broker = Broker(fs.url, fs.grpc_address)
    broker.start()
    with BrokerClient(broker.address) as c:
        c.configure_topic("persist", partition_count=1)
        for i in range(5):
            c.publish("persist", b"", f"m{i}".encode(), partition=0)
    broker.stop()  # flushes segments to the filer
    # the segments are filer files now
    segs = fs.filer.list_entries("/topics/default/persist/0000")
    assert segs and segs[0].name.endswith(".seg")
    broker2 = Broker(fs.url, fs.grpc_address)
    broker2.start()
    try:
        with BrokerClient(broker2.address) as c:
            vals = [
                r.value.decode()
                for r in c.subscribe("persist", partition=0, max_idle_s=0.5)
            ]
            assert vals == [f"m{i}" for i in range(5)]
    finally:
        broker2.stop()


def test_mq_live_subscription(stack):
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("live", partition_count=1)
            received = []
            done = threading.Event()

            def consume():
                with BrokerClient(broker.address) as sub:
                    for r in sub.subscribe("live", partition=0, max_idle_s=5.0):
                        received.append(r.value)
                        if len(received) >= 3:
                            break
                done.set()

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            time.sleep(0.3)
            for i in range(3):
                c.publish("live", b"", f"msg{i}".encode(), partition=0)
            assert done.wait(10.0)
            assert received == [b"msg0", b"msg1", b"msg2"]


def test_mq_consumer_group_assignment_and_rebalance(stack):
    """Two consumers split the partitions disjointly; when one leaves, the
    survivor is rebalanced onto all of them (sub_coordinator analog)."""
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("jobs", partition_count=4)
            a = c.join_group("jobs", "workers", "consumer-a")
            b = c.join_group("jobs", "workers", "consumer-b")
            # b's join bumped the generation: a refreshes its view
            a = c.join_group("jobs", "workers", "consumer-a")
            assert set(a["partitions"]) | set(b["partitions"]) == {0, 1, 2, 3}
            assert set(a["partitions"]) & set(b["partitions"]) == set()
            gen = c.group_heartbeat("jobs", "workers", "consumer-a")
            c.leave_group("jobs", "workers", "consumer-b")
            assert c.group_heartbeat("jobs", "workers", "consumer-a") != gen
            a = c.join_group("jobs", "workers", "consumer-a")
            assert set(a["partitions"]) == {0, 1, 2, 3}


def test_mq_group_offsets_resume_across_consumers(stack):
    """Committed offsets persist in the filer: a replacement consumer
    resumes after the last committed record, not from the beginning."""
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("ledger", partition_count=1)
            for i in range(6):
                c.publish("ledger", b"", f"m{i}".encode(), partition=0)
            # first consumer processes 3 then breaks; commit-on-next-poll
            # means the LAST record (m2) is uncommitted at the break —
            # at-least-once: it will be redelivered, never lost
            seen = []
            last = None
            for p, rec in c.consume("ledger", "g1", "c1", max_rounds=1):
                seen.append(rec.value.decode())
                last = (p, rec)
                if len(seen) == 3:
                    break
            assert seen == ["m0", "m1", "m2"]
            # a graceful shutdown commits its final record explicitly
            c.commit_offset("ledger", "g1", last[0], last[1].ts_ns)
            c.leave_group("ledger", "g1", "c1")
            # a different consumer in the same group picks up at m3
            rest = [
                rec.value.decode()
                for _, rec in c.consume("ledger", "g1", "c2", max_rounds=1)
            ]
            assert rest == ["m3", "m4", "m5"]
            # a different GROUP starts from scratch
            fresh = [
                rec.value.decode()
                for _, rec in c.consume("ledger", "g2", "c9", max_rounds=1)
            ]
            assert fresh == [f"m{i}" for i in range(6)]


def test_mq_stale_member_is_reaped(stack):
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address, group_session_timeout=0.3) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("t", partition_count=2)
            c.join_group("t", "g", "dead-consumer")
            live = c.join_group("t", "g", "live-consumer")
            assert len(live["partitions"]) == 1
            # live keeps heartbeating; dead goes silent past the 0.3 s TTL
            for _ in range(3):
                time.sleep(0.2)
                c.group_heartbeat("t", "g", "live-consumer")
            live = c.join_group("t", "g", "live-consumer")
            assert set(live["partitions"]) == {0, 1}
            # and a group whose EVERY member goes silent is swept entirely:
            # the next heartbeat tells the consumer to rejoin
            import grpc as _grpc

            time.sleep(0.5)
            with pytest.raises(_grpc.RpcError, match="unknown group"):
                c.group_heartbeat("t", "g", "live-consumer")
            assert set(c.join_group("t", "g", "live-consumer")["partitions"]) == {0, 1}


def test_mq_consume_crash_never_loses_a_record(stack):
    """At-least-once: a consumer that dies after RECEIVING but before
    COMMITTING a record (generator abandoned mid-stream) causes
    redelivery, never loss."""
    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("crashy", partition_count=1)
            for i in range(3):
                c.publish("crashy", b"", f"m{i}".encode(), partition=0)
            gen = c.consume("crashy", "g", "victim", max_rounds=1)
            _, first = next(gen)
            assert first.value == b"m0"
            gen.close()  # caller crashed mid-processing: m0 uncommitted
            got = [r.value.decode() for _, r in c.consume("crashy", "g", "heir", max_rounds=1)]
            assert got == ["m0", "m1", "m2"], got


def test_mq_group_heartbeat_unknown_group_errors(stack):
    import grpc as _grpc

    _, _, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        with BrokerClient(broker.address) as c:
            c.configure_topic("real", partition_count=1)
            with pytest.raises(_grpc.RpcError, match="unknown group"):
                c.group_heartbeat("real", "no-such-group", "x")
            c.leave_group("real", "no-such-group", "x")  # no-op, no state grown
            assert ("default", "real", "no-such-group") not in broker._groups


def test_mq_shell_commands_and_broker_discovery(stack):
    """Brokers announce to the master (node_type=broker) and the shell's
    mq.* commands drive topic admin through the discovered broker."""
    import io as _io
    import time as _time

    from seaweedfs_tpu.shell import CommandEnv, run_command

    master, vs, fs = stack
    with Broker(fs.url, fs.grpc_address) as broker:
        deadline = _time.monotonic() + 10
        found = []
        while _time.monotonic() < deadline and not found:
            from seaweedfs_tpu import rpc as _rpc

            with _rpc.RpcClient(master.address) as c:
                found = c.call("weedtpu.Master", "ListClusterNodes", {}).get(
                    "brokers", []
                )
            _time.sleep(0.2)
        assert found and found[0]["grpc_address"] == broker.address
        with CommandEnv(master.address) as env:
            def run(line):
                out = _io.StringIO()
                run_command(env, line, out)
                return out.getvalue()

            assert broker.address in run("mq.broker.list")
            out = run("mq.topic.configure -topic events -partitions 3")
            assert "3 partitions" in out
            out = run("mq.topic.list")
            assert "default/events: 3 partitions" in out and "total 1 topics" in out
