"""weedsafe crash-prefix replay — the dynamic half of the durability
family. Record every filesystem op of a real journaled workload (the
`analysis.fsrec` shims), then for every sampled crash prefix x variant
(clean/torn/lost tail) materialize the post-crash tree into a scratch
dir and drive the REAL resume entrypoint, asserting it either resumes
byte-identical to the warm path or refuses cleanly — never serves or
commits corrupt bytes.

Covers all four journal formats in the tree:
  .ecp  inline-ingest journal   -> InlineStripeBuilder.resume + seal
  .ecc  convert journal         -> convert_ec_files resume + cutover
  scrub cursor JSON             -> ScrubCursor load (fresh-or-saved)
  kernel_sweep harvest JSONL    -> load_done record recovery

Replayer primitives (trace determinism, torn/lost tail synthesis, prefix
byte accounting, schedule sampling) and a planted fsync-removal
regression (the harness must CATCH a deliberately broken watermark
protocol) ride along."""

import json
import os
import sys
import time
import zlib

import numpy as np
import pytest

from seaweedfs_tpu.analysis import fsrec
from seaweedfs_tpu.ec import convert, ingest, scrub, stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder, geometry_for
from seaweedfs_tpu.utils import config

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))
import kernel_sweep as ks  # noqa: E402

sys.path.pop(0)

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL, BUF = 8192, 2048, 2048
LARGE_ROW = LARGE * 10


# -- record / replay drivers --------------------------------------------------


def _record(root, workload) -> fsrec.FsTrace:
    rec = fsrec.install(str(root))
    try:
        workload()
    finally:
        trace = rec.trace()
        fsrec.uninstall()
    return trace


def _dedup_key(state: dict) -> tuple:
    return tuple(sorted((p, len(b), zlib.crc32(b)) for p, b in state.items()))


def _replay(trace, scratch_root, check, extra_prefixes=()):
    """Drive `check(scratch_dir, n_ops, variant)` over the sampled prefix
    schedule x crash variants (deduping identical post-crash states —
    many prefixes between durability points settle to the same bytes).
    `extra_prefixes` pins known-interesting crash points the even sample
    might skip. Returns the list of check results."""
    sched = set(
        fsrec.prefix_schedule(
            len(trace.ops), int(config.env("WEEDTPU_FSREPLAY_MAX_PREFIXES"))
        )
    )
    sched.update(extra_prefixes)
    seen, outcomes, n_dirs = set(), [], 0
    for n in sorted(sched):
        for variant in fsrec.VARIANTS:
            state = fsrec.simulate_prefix(trace, n, variant)
            key = _dedup_key(state)
            if key in seen:
                continue
            seen.add(key)
            dest = os.path.join(str(scratch_root), f"p{n_dirs}")
            n_dirs += 1
            os.makedirs(dest)
            for rel, data in state.items():
                p = os.path.join(dest, rel)
                d = os.path.dirname(p)
                if d:
                    os.makedirs(d, exist_ok=True)
                with open(p, "wb") as f:
                    f.write(data)
            outcomes.append(check(dest, n, variant))
    return outcomes


# -- ingest: the .ecp journal -------------------------------------------------


def _warm_oracle(cache_root, cache: dict, dat_bytes: bytes) -> str:
    """Warm write_ec_files reference for exactly these .dat bytes,
    memoized — many crash prefixes settle to the same .dat content."""
    key = (len(dat_bytes), zlib.crc32(dat_bytes))
    if key not in cache:
        wbase = os.path.join(str(cache_root), f"w{len(cache)}", "v")
        os.makedirs(os.path.dirname(wbase))
        with open(wbase + ".dat", "wb") as f:
            f.write(dat_bytes)
        stripe.write_ec_files(
            wbase, large_block_size=LARGE, small_block_size=SMALL,
            buffer_size=BUF, encoder=ENC,
        )
        cache[key] = wbase
    return cache[key]


def _assert_matches_warm(base: str, wbase: str, ctx: str) -> None:
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            got = f.read()
        with open(stripe.shard_file_name(wbase, s), "rb") as f:
            want = f.read()
        assert got == want, f"{ctx}: shard {s} differs from warm re-encode"
    with open(base + ".eci", "rb") as f, open(wbase + ".eci", "rb") as g:
        assert f.read() == g.read(), f"{ctx}: .eci differs from warm re-encode"


def test_ingest_journal_crash_prefix_replay(tmp_path, monkeypatch):
    """Every crash prefix of a full inline-ingest life (bursty appends +
    polls, a journaled delta overwrite, seal) resumes byte-identical to
    warm write_ec_files on whatever .dat survived, or refuses (resume ->
    None) and the warm fallback covers it. The mid-overwrite torn-write
    prefix — .dat matching neither the old nor the new intent bytes —
    must land on the refuse path."""
    t0 = time.monotonic()
    monkeypatch.setenv("WEEDTPU_INLINE_EC_DELTA", "1")
    work = tmp_path / "work"
    work.mkdir()
    base = os.path.join(str(work), "7")
    n_bytes = LARGE_ROW + SMALL * 10 + 617
    data = np.random.default_rng(7).integers(
        0, 256, n_bytes, dtype=np.uint8
    ).tobytes()
    ow_off, ow_len = 96, 64
    old_seg = data[ow_off : ow_off + ow_len]
    new_seg = bytes(b ^ 0xFF for b in old_seg)  # differs in EVERY byte:
    # a torn half-write can match neither old nor new

    def workload():
        # superblock prefix BEFORE the builder: the journal pins dat_rev
        # (bytes 4:6), so the pin must be durable when `begin` is journaled
        with open(base + ".dat", "wb") as f:
            f.write(data[:32])
            f.flush()
            os.fsync(f.fileno())
        b = ingest.InlineStripeBuilder(base, ENC, LARGE, SMALL, buffer_size=BUF)
        with open(base + ".dat", "ab") as f:
            for off in range(32, n_bytes, 30_000):
                f.write(data[off : off + 30_000])
                f.flush()
                os.fsync(f.fileno())
                b.poll()

        def mutate():
            with open(base + ".dat", "r+b") as g:
                g.seek(ow_off)
                g.write(new_seg)
                g.flush()
                os.fsync(g.fileno())

        b.overwrite(ow_off, old_seg, new_seg, mutate=mutate)
        b.seal()

    trace = _record(work, workload)

    # pin the crash point INSIDE the overwrite mutation: first .dat write
    # after the journaled "ow" intent record
    ow_idx = next(
        i for i, op in enumerate(trace.ops)
        if op.kind == "write" and op.path.endswith(".ecp") and b'"ow"' in op.data
    )
    mutate_idx = next(
        i for i, op in enumerate(trace.ops[ow_idx + 1 :], start=ow_idx + 1)
        if op.kind == "write" and op.path.endswith(".dat")
    )

    oracles = tmp_path / "oracles"
    oracles.mkdir()
    cache: dict = {}

    def check(dest, n, variant):
        sb = os.path.join(dest, "7")
        has_dat = os.path.exists(sb + ".dat")
        b = ingest.InlineStripeBuilder.resume(sb, ENC, LARGE, SMALL, buffer_size=BUF)
        ctx = f"{variant} prefix {n}"
        if b is not None:
            b.seal()
            with open(sb + ".dat", "rb") as f:
                dat = f.read()
            _assert_matches_warm(sb, _warm_oracle(oracles, cache, dat), ctx)
            return ("resumed", n, variant)
        if not has_dat:
            return ("no-dat", n, variant)
        with open(sb + ".dat", "rb") as f:
            dat = f.read()
        if len(dat) == 0:
            return ("empty-dat", n, variant)
        # refused: the warm fallback re-encodes from the durable .dat
        ingest._cleanup_partials(sb)
        stripe.write_ec_files(
            sb, large_block_size=LARGE, small_block_size=SMALL,
            buffer_size=BUF, encoder=ENC,
        )
        _assert_matches_warm(sb, _warm_oracle(oracles, cache, dat), ctx)
        return ("warm", n, variant)

    outcomes = _replay(trace, tmp_path / "replay", check,
                       extra_prefixes={mutate_idx + 1})
    kinds = [o[0] for o in outcomes]
    assert "resumed" in kinds, kinds
    assert "warm" in kinds, kinds
    # the torn mid-mutation .dat is unresolvable — must refuse, never patch
    assert ("warm", mutate_idx + 1, "torn") in outcomes, outcomes
    assert time.monotonic() - t0 < 30.0


# -- convert: the .ecc journal ------------------------------------------------


def test_convert_journal_crash_prefix_replay(tmp_path):
    """Every crash prefix of convert + cutover re-drives convert_ec_files
    (the documented recovery entrypoint) to a fully cut-over volume whose
    shards are byte-identical to the decode->re-encode oracle. Pinned
    prefixes guarantee the journal-watermark resume and the mid-swap
    finish_cutover windows are both exercised."""
    t0 = time.monotonic()
    CL, CS, FAM = 4096, 512, "cauchy_12_3"
    enc = Encoder(10, 4, matrix_kind="vandermonde", backend="numpy")
    work = tmp_path / "work"
    work.mkdir()
    base = os.path.join(str(work), "1")
    data = np.random.default_rng(3).integers(0, 256, 20000, dtype=np.uint8).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    stripe.write_ec_files(
        base, large_block_size=CL, small_block_size=CS, buffer_size=CS, encoder=enc
    )
    os.unlink(base + ".dat")  # conversions stream the virtual dat

    geom = geometry_for(FAM)
    ob = os.path.join(str(tmp_path), "oracle", "1")
    os.makedirs(os.path.dirname(ob))
    with open(ob + ".dat", "wb") as f:
        f.write(data)
    stripe.write_ec_files(
        ob, large_block_size=CL, small_block_size=CS, buffer_size=CS,
        encoder=Encoder(
            geom.data_shards, geom.parity_shards,
            matrix_kind=geom.matrix_kind, backend="numpy",
        ),
    )

    def convert_once(b):
        return convert.convert_ec_files(
            b, FAM, encoder=Encoder(10, 4, matrix_kind="vandermonde", backend="numpy"),
            buffer_size=CS, journal_bytes=2048, verify=True,
        )

    def workload():
        convert_once(base)
        convert.cutover(base)

    trace = _record(work, workload)

    def after_record(tag: bytes) -> int:
        i = next(
            k for k, op in enumerate(trace.ops)
            if op.kind == "write" and op.path.endswith(".ecc") and tag in op.data
        )
        assert trace.ops[i + 2].kind == "fsync", trace.ops[i : i + 3]
        return i + 3  # write, flush, fsync — record durable, nothing after

    extra = {after_record(b'"watermark"'), after_record(b'"cutover"')}

    def check(dest, n, variant):
        sb = os.path.join(dest, "1")
        res = convert_once(sb)
        if res["mode"] in ("converted", "resumed"):
            convert.cutover(sb)
        ctx = f"{variant} prefix {n}"
        info = stripe.read_ec_info(sb)
        assert info is not None, f"{ctx}: cut-over volume lost its .eci"
        assert stripe.geometry_from_info(info).family == FAM, ctx
        assert not convert.pending_cutover(sb), f"{ctx}: swap left unfinished"
        for s in range(geom.total_shards):
            with open(stripe.shard_file_name(sb, s), "rb") as f:
                got = f.read()
            with open(stripe.shard_file_name(ob, s), "rb") as f:
                want = f.read()
            assert got == want, f"{ctx}: shard {s} differs from oracle"
        return res["mode"]

    modes = _replay(trace, tmp_path / "replay", check, extra_prefixes=extra)
    assert "resumed" in modes, modes   # a journal-watermark resume ran
    assert "cutover" in modes, modes   # a mid-swap prefix was finished
    assert "noop" in modes, modes      # the complete trace needs nothing
    assert time.monotonic() - t0 < 30.0


# -- scrub cursor -------------------------------------------------------------


def test_scrub_cursor_crash_prefix_replay(tmp_path):
    """Every crash prefix of a point/save/quarantine sequence loads as
    either fresh zeros or EXACTLY one of the states save() persisted —
    the tmp+fsync+replace discipline never exposes a torn cursor."""
    work = tmp_path / "work"
    work.mkdir()
    cpath = os.path.join(str(work), "scrub_cursor.json")
    saved = []

    def workload():
        cur = scrub.ScrubCursor(cpath)
        for i in range(1, 6):
            cur.point(i, i % 14, i * 1000, i * 7)
            cur.save()
            saved.append((i, i % 14, i * 1000, i * 7, 0, ()))
        cur.add_quarantine(3, 5, "crc-mismatch")  # saves immediately
        saved.append((5, 5 % 14, 5000, 35, 0, ((3, 5),)))

    trace = _record(work, workload)
    fresh = (0, 0, 0, 0, 0, ())
    allowed = {fresh, *saved}
    states = set()

    def check(dest, n, variant):
        cur = scrub.ScrubCursor(os.path.join(dest, "scrub_cursor.json"))
        got = (
            cur.vid, cur.shard, cur.offset, cur.crc, cur.cycles,
            tuple((q["vid"], q["shard"]) for q in cur.quarantine),
        )
        assert got in allowed, (
            f"{variant} prefix {n}: cursor loaded state {got} that was "
            f"never saved"
        )
        states.add(got)
        return got

    _replay(trace, tmp_path / "replay", check)
    assert fresh in states
    assert len(states & set(saved)) >= 2  # real mid-sequence resumes seen


# -- kernel_sweep harvest JSONL ----------------------------------------------


def test_kernel_sweep_harvest_crash_prefix_replay(tmp_path):
    """Every crash prefix of a persist-per-record harvest (including a
    close + resume-reopen cycle) loads as an exact subset of the records
    actually persisted — a torn tail is skipped, never merged into a
    neighbouring record."""
    work = tmp_path / "work"
    work.mkdir()
    out = os.path.join(str(work), "harvest.jsonl")
    recs = [
        {"variant": f"v{i}", "platform": "cpu", "tiny": False, "steady_gbps": float(i)}
        for i in range(5)
    ]

    def workload():
        f = ks.open_resume_out(out, resume=False)
        for r in recs[:3]:
            ks.persist_record(f, r)
        f.close()
        f = ks.open_resume_out(out, resume=True)
        for r in recs[3:]:
            ks.persist_record(f, r)
        f.close()

    trace = _record(work, workload)
    by_name = {r["variant"]: r for r in recs}
    counts = set()

    def check(dest, n, variant):
        done = ks.load_done(
            os.path.join(dest, "harvest.jsonl"), platform="cpu", tiny=False
        )
        for name, rec in done.items():
            assert by_name.get(name) == rec, (
                f"{variant} prefix {n}: harvest recovered a record that was "
                f"never persisted: {rec}"
            )
        counts.add(len(done))
        return len(done)

    _replay(trace, tmp_path / "replay", check)
    # each fsync'd record becomes recoverable exactly once, in order
    assert counts >= {0, 1, 2, 3, 4, 5}


def test_open_resume_out_terminates_torn_tail(tmp_path):
    """Resuming over a harvest file whose last line is torn (crash
    mid-write, no newline) must not glue the next record onto the
    fragment — both the fragment's neighbours stay recoverable."""
    out = os.path.join(str(tmp_path), "h.jsonl")
    whole = {"variant": "v0", "platform": "cpu", "tiny": False}
    with open(out, "w", encoding="utf-8") as f:
        f.write(json.dumps(whole) + "\n")
        f.write('{"variant": "torn-v1", "plat')  # torn: no newline
    f2 = ks.open_resume_out(out, resume=True)
    fresh = {"variant": "v2", "platform": "cpu", "tiny": False}
    ks.persist_record(f2, fresh)
    f2.close()
    done = ks.load_done(out, platform="cpu", tiny=False)
    assert done == {"v0": whole, "v2": fresh}


# -- replayer primitives ------------------------------------------------------


def _simple_workload(root):
    a = os.path.join(str(root), "a.bin")
    with open(a, "wb") as f:
        f.write(b"0123456789")
        f.flush()
        os.fsync(f.fileno())
    with open(a, "r+b") as f:
        f.seek(2)
        f.write(b"XY")
        f.truncate(6)
    os.replace(a, os.path.join(str(root), "b.bin"))
    with open(os.path.join(str(root), "c.bin"), "wb") as f:
        f.write(b"unsynced-tail!")
    os.unlink(os.path.join(str(root), "b.bin"))


def test_trace_determinism(tmp_path):
    """Two identical workloads record identical op sequences (up to
    creation sites) — replay coverage is reproducible, not load-bearing
    on dict ordering or handle identity."""
    traces = []
    for name in ("one", "two"):
        d = tmp_path / name
        d.mkdir()
        traces.append(_record(d, lambda d=d: _simple_workload(d)))
    assert traces[0].ops, "recorder captured nothing"
    assert [op.sig() for op in traces[0].ops] == [op.sig() for op in traces[1].ops]


def test_torn_and_lost_tail_synthesis(tmp_path):
    d = tmp_path / "w"
    d.mkdir()

    def wl():
        with open(os.path.join(str(d), "t.bin"), "wb") as f:
            f.write(b"0123456789")  # never fsynced

    trace = _record(d, wl)
    n = len(trace.ops)
    assert fsrec.simulate_prefix(trace, n, "clean")["t.bin"] == b"0123456789"
    assert fsrec.simulate_prefix(trace, n, "torn")["t.bin"] == b"01234"
    assert fsrec.simulate_prefix(trace, n, "lost")["t.bin"] == b""
    with pytest.raises(ValueError, match="unknown variant"):
        fsrec.simulate_prefix(trace, n, "half-torn")


def test_prefix_byte_accounting(tmp_path):
    d = tmp_path / "w"
    d.mkdir()
    p = os.path.join(str(d), "a.bin")

    def wl():
        with open(p, "wb") as f:
            f.write(b"abcdef")
            f.flush()
            os.fsync(f.fileno())
        with open(p, "r+b") as f:
            f.seek(2)
            f.write(b"XY")
            f.truncate(4)

    trace = _record(d, wl)
    n = len(trace.ops)
    # the fsync'd base survives every variant; the unsynced patch+truncate
    # tail survives clean and torn (truncate is metadata: never "half")
    assert fsrec.simulate_prefix(trace, n, "lost")["a.bin"] == b"abcdef"
    assert fsrec.simulate_prefix(trace, n, "clean")["a.bin"] == b"abXY"
    assert fsrec.simulate_prefix(trace, n, "torn")["a.bin"] == b"abXY"
    # prefix ending right at the fsync: only the durable base exists
    k = next(i for i, op in enumerate(trace.ops) if op.kind == "fsync") + 1
    for v in fsrec.VARIANTS:
        assert fsrec.simulate_prefix(trace, k, v)["a.bin"] == b"abcdef"
    # prefix ending right after the 2-byte patch write: torn applies half
    w = next(
        i for i, op in enumerate(trace.ops)
        if op.kind == "write" and op.data == b"XY"
    ) + 1
    assert fsrec.simulate_prefix(trace, w, "torn")["a.bin"] == b"abXdef"
    assert fsrec.simulate_prefix(trace, w, "clean")["a.bin"] == b"abXYef"
    assert fsrec.simulate_prefix(trace, w, "lost")["a.bin"] == b"abcdef"


def test_prefix_schedule_sampling():
    assert fsrec.prefix_schedule(5, 0) == [0, 1, 2, 3, 4, 5]  # <=0: every prefix
    assert fsrec.prefix_schedule(5, 100) == [0, 1, 2, 3, 4, 5]
    s = fsrec.prefix_schedule(1000, 48)
    assert s[0] == 0 and s[-1] == 1000
    assert len(s) <= 48 and s == sorted(set(s))
    assert fsrec.prefix_schedule(7, 1) == [7]


# -- planted regression: the harness must catch a removed fsync ---------------


def _watermark_workload(root, broken: bool):
    """Miniature of the ingest watermark discipline: part bytes, (fsync),
    then a journaled watermark vouching for them. `broken=True` removes
    the part fsync — the classic record-before-fsync protocol hole."""
    part = os.path.join(str(root), "x.part")
    jrn = os.path.join(str(root), "x.journal")
    payload = bytes(range(64))
    jf = open(jrn, "ab")
    try:
        with open(part, "ab") as pf:
            for i in range(4):
                pf.write(payload)
                pf.flush()
                if not broken:
                    os.fsync(pf.fileno())
                jf.write(
                    json.dumps({"kind": "rows", "bytes": (i + 1) * 64}).encode()
                    + b"\n"
                )
                jf.flush()
                os.fsync(jf.fileno())
    finally:
        jf.close()


def _watermark_violations(trace) -> int:
    viol = 0
    for n in fsrec.prefix_schedule(len(trace.ops), 0):
        for variant in fsrec.VARIANTS:
            state = fsrec.simulate_prefix(trace, n, variant)
            vouched = 0
            for line in state.get("x.journal", b"").split(b"\n"):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail vouches for nothing
                vouched = max(vouched, int(rec.get("bytes", 0)))
            if len(state.get("x.part", b"")) < vouched:
                viol += 1
    return viol


def test_planted_fsync_removal_is_caught(tmp_path):
    good = tmp_path / "good"
    good.mkdir()
    bad = tmp_path / "bad"
    bad.mkdir()
    tg = _record(good, lambda: _watermark_workload(good, broken=False))
    tb = _record(bad, lambda: _watermark_workload(bad, broken=True))
    assert _watermark_violations(tg) == 0, (
        "fsync-then-record protocol flagged a false violation"
    )
    assert _watermark_violations(tb) > 0, (
        "replayer missed a journal watermark vouching for undurable bytes"
    )
