"""Trace-repair (repair-bandwidth-optimal rebuild) tests: the GF(2^8)
projection math byte-exact against the gf8 golden, XOR-combined holder
projections equal to the fused decode, the projection rebuild pipeline
byte-identical to `rebuild_ec_files_serial`, the end-to-end trace-mode
`ec.rebuild -remote` over real RPC servers (wire bytes strictly below the
full-slab baseline, counter accounting, capability-negotiation fallback,
mid-rebuild failure fallback, torn-stream CRC rejection), the
RemoteSlabSource multi-holder striping upgrade, and the tier-1
`ec_rebuild_trace` bench smoke."""

import base64
import os
import shutil
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.pb import VOLUME_SERVICE

ENC = Encoder(10, 4, backend="numpy")
LARGE, SMALL = 16384, 4096
VID = 17


# -- projection math ----------------------------------------------------------


@pytest.mark.parametrize(
    "rows,cols,width",
    [
        (1, 3, 1),        # minimal
        (2, 5, 127),      # odd width
        (4, 10, 8192),    # tile-edge-ish power of two
        (3, 13, 1000),    # non-power-of-two
        (1, 10, 4097),    # just past a tile edge
        (14, 14, 64),     # full-square
    ],
)
def test_gf_project_bits_byte_exact_vs_golden(rows, cols, width):
    """The GF(2)/GF(2^8) bit-plane lift of the projection must agree with
    the table-driven golden on every shape — tile-edge and odd sizes
    included — since it is the formulation device kernels run."""
    rng = np.random.default_rng(rows * 131 + cols)
    m = rng.integers(0, 256, (rows, cols), dtype=np.uint8)
    x = rng.integers(0, 256, (cols, width), dtype=np.uint8)
    want = gf8.gf_project(m, x)
    got = gf8.gf_project_bits(m, x)
    assert want.shape == (rows, width)
    assert np.array_equal(want, got)


def test_repair_projection_plan_matches_decode_matrix():
    survivors = [0, 1, 2, 4, 5, 6, 7, 8, 9, 10]
    wanted = [3, 12]
    plan = ENC.repair_projection_plan(survivors, wanted)
    m = ENC.reconstruction_matrix(survivors, wanted)
    assert sorted(plan) == sorted(survivors)
    for i, s in enumerate(survivors):
        assert np.array_equal(plan[s], m[:, i])


def test_project_validates_shapes():
    with pytest.raises(ValueError):
        ENC.project(np.zeros((2, 3), dtype=np.uint8), np.zeros((4, 8), dtype=np.uint8))
    with pytest.raises(ValueError):
        ENC.project(np.zeros(3, dtype=np.uint8), np.zeros((3, 8), dtype=np.uint8))
    with pytest.raises(ValueError):
        ENC.project_lazy(
            np.zeros((2, 3), dtype=np.uint8), np.zeros((4, 8), dtype=np.uint8)
        )


def test_xor_combined_group_projections_equal_fused_decode():
    """Splitting the survivor set across holder groups and XORing their
    projections must reproduce the fused decode exactly — the invariant
    that makes trace rebuilds byte-identical to slab rebuilds."""
    rng = np.random.default_rng(7)
    data = [rng.integers(0, 256, 2048, dtype=np.uint8) for _ in range(10)]
    shards = ENC.encode(data)
    missing = [0, 11, 13]
    survivors = [s for s in range(TOTAL_SHARDS_COUNT) if s not in missing][
        :DATA_SHARDS_COUNT
    ]
    plan = ENC.repair_projection_plan(survivors, missing)
    direct = ENC.reconstruct_batch(
        np.stack([shards[s] for s in survivors])[None], survivors, missing
    )[0]
    for split in ([4, 7], [1, 2, 3, 9], [10]):
        bounds = [0, *split, len(survivors)]
        acc = np.zeros((len(missing), 2048), dtype=np.uint8)
        for lo, hi in zip(bounds, bounds[1:]):
            group = survivors[lo:hi]
            if not group:
                continue
            coeffs = np.stack([plan[s] for s in group], axis=1)
            acc ^= ENC.project(coeffs, np.stack([shards[s] for s in group]))
        assert np.array_equal(acc, direct)
        for k, s in enumerate(missing):
            assert np.array_equal(acc[k], np.asarray(shards[s]))


# -- the projection rebuild pipeline (no servers) -----------------------------


def _build_shard_set(dirpath: str, size: int = 400_000, seed: int = 5):
    base = os.path.join(dirpath, str(VID))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=LARGE, small_block_size=SMALL, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


def _fake_remote_group(base, holder, sids, plan, rows, shard_size, **kw):
    """A TraceSlabSource whose transport projects straight from the local
    files — the server-side math without a server."""
    coeffs = np.stack([plan[s] for s in sids], axis=1)
    files = {s: open(stripe.shard_file_name(base, s), "rb") for s in sids}

    def fetch(offset: int, size: int) -> bytes:
        actual = max(0, min(size, shard_size - offset))
        if actual == 0:
            return b""
        stack = np.empty((len(sids), actual), dtype=np.uint8)
        for i, s in enumerate(sids):
            stripe.read_padded_into(files[s], offset, stack[i])
        return ENC.project(coeffs, stack).tobytes()

    src = stripe.TraceSlabSource(holder, sids, rows, fetch, **kw)
    orig_close = src.close

    def close():
        orig_close()
        for f in files.values():
            f.close()

    src.close = close
    return src


def test_projection_rebuild_byte_identical_vs_serial_oracle(tmp_path):
    """Trace-combine pipeline output == rebuild_ec_files_serial on the same
    survivor set, across odd window geometry and a multi-shard loss."""
    work = tmp_path / "work"
    work.mkdir()
    base, golden = _build_shard_set(str(work))
    missing = [3, 12]
    for s in missing:
        os.unlink(stripe.shard_file_name(base, s))
    shard_size = len(golden[0])
    survivors = sorted(stripe.find_local_shards(base))[:DATA_SHARDS_COUNT]
    plan = ENC.repair_projection_plan(survivors, missing)

    # serial oracle on a copy (same survivor set: its present == ours)
    oracle = tmp_path / "oracle"
    oracle.mkdir()
    obase = os.path.join(str(oracle), str(VID))
    for s in survivors:
        shutil.copy(stripe.shard_file_name(base, s), stripe.shard_file_name(obase, s))
    for ext in (".ecx", ".eci"):
        if os.path.exists(base + ext):
            shutil.copy(base + ext, obase + ext)
    stripe.rebuild_ec_files_serial(obase, encoder=ENC)

    groups = [
        _fake_remote_group(
            base, "a", survivors[:4], plan, len(missing), shard_size,
            chunk_bytes=70_000,  # odd chunk: forces multi-chunk windows
        ),
        _fake_remote_group(base, "b", survivors[4:9], plan, len(missing), shard_size),
        stripe.LocalProjectionSource(
            [stripe.shard_file_name(base, s) for s in survivors[9:]],
            np.stack([plan[s] for s in survivors[9:]], axis=1),
            ENC,
        ),
    ]
    try:
        rebuilt = stripe.rebuild_ec_files_from_projections(
            base, groups, shard_size, missing, encoder=ENC,
            buffer_size=16384, max_batch_bytes=10 * 3 * 16384,
        )
    finally:
        for g in groups:
            g.close()
    assert rebuilt == missing
    for s in missing:
        with open(stripe.shard_file_name(base, s), "rb") as f:
            got = f.read()
        with open(stripe.shard_file_name(obase, s), "rb") as f:
            assert got == f.read(), f"shard {s} differs from serial oracle"
        assert got == golden[s]
    # wire accounting: remote groups moved rows x shard bytes each
    assert groups[0].bytes_fetched == len(missing) * shard_size
    assert groups[1].bytes_fetched == len(missing) * shard_size
    assert groups[2].bytes_fetched == 0  # local group never hits the wire


def test_projection_rebuild_failure_unlinks_partials(tmp_path):
    base, golden = _build_shard_set(str(tmp_path))
    missing = [2]
    os.unlink(stripe.shard_file_name(base, 2))
    shard_size = len(golden[0])
    survivors = sorted(stripe.find_local_shards(base))[:DATA_SHARDS_COUNT]
    plan = ENC.repair_projection_plan(survivors, missing)
    calls = {"n": 0}

    def dying_fetch(offset: int, size: int) -> bytes:
        calls["n"] += 1
        if calls["n"] > 2:
            raise IOError("holder died mid-rebuild")
        actual = max(0, min(size, shard_size - offset))
        stack = np.empty((len(survivors), actual), dtype=np.uint8)
        for i, s in enumerate(survivors):
            with open(stripe.shard_file_name(base, s), "rb") as f:
                stripe.read_padded_into(f, offset, stack[i])
        coeffs = np.stack([plan[s] for s in survivors], axis=1)
        return ENC.project(coeffs, stack).tobytes()

    src = stripe.TraceSlabSource("dying", survivors, 1, dying_fetch, chunk_bytes=65536)
    with pytest.raises(IOError):
        stripe.rebuild_ec_files_from_projections(
            base, [src], shard_size, missing, encoder=ENC,
            buffer_size=16384, max_batch_bytes=10 * 16384,
        )
    src.close()
    assert not os.path.exists(stripe.shard_file_name(base, 2)), (
        "failed trace rebuild must not leave a partial shard"
    )


def test_trace_source_rejects_non_row_multiple_stream():
    src = stripe.TraceSlabSource("x", [0, 1], 3, lambda off, n: b"\x00" * 7)
    out = np.zeros(3 * 64, dtype=np.uint8)
    with pytest.raises(IOError, match="not a multiple"):
        src.read_into(0, out)
    src.close()


# -- RemoteSlabSource multi-holder striping -----------------------------------


def test_striped_windows_spread_across_holders_and_fail_over():
    """With two live replica holders the striped fetches must hit BOTH
    (bandwidth aggregation), and killing one mid-window must drain the
    remaining stripes through the survivor with the failover recorded."""
    counts = {"a": 0, "b": 0}
    dead = set()
    blob = bytes(range(256)) * 1024  # 256 KiB

    def fetch(addr, offset, size):
        if addr in dead:
            raise IOError(f"{addr} down")
        counts[addr] += 1
        return blob[offset : offset + size]

    src = stripe.RemoteSlabSource(
        0, ["a", "b"], fetch, stripe_bytes=64 * 1024, fanout=4
    )
    out = np.zeros(256 * 1024, dtype=np.uint8)
    src.read_into(0, out)
    assert bytes(out) == blob
    assert counts["a"] > 0 and counts["b"] > 0, (
        f"striping pinned one holder: {counts}"
    )
    assert src.bytes_fetched == len(blob)
    # now kill one holder: the next window must complete via the other
    dead.add("a")
    before_b = counts["b"]
    src.read_into(0, out)
    assert bytes(out) == blob
    assert counts["b"] >= before_b + 4
    assert src.failovers == ["a"]
    assert src.bytes_fetched == 2 * len(blob)
    src.close()


def test_least_inflight_pick_prefers_idle_holder():
    src = stripe.RemoteSlabSource(0, ["a", "b"], lambda *a: b"", fanout=2)
    with src._lock:
        src._inflight["a"] = 3
    assert src._pick_holder(["a", "b"], 0) == "b"
    # rotation still breaks ties once loads equalize
    with src._lock:
        src._inflight["b"] = 4
        src._inflight["a"] = 4
    first = src._pick_holder(["a", "b"], 0)
    second = src._pick_holder(["a", "b"], src._stripe)
    assert {first, second} == {"a", "b"}
    src.close()


# -- end to end over real RPC servers -----------------------------------------


def _wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def trace_cluster(tmp_path):
    """master + rebuild target + two peer holders, one data shard lost
    cluster-wide: peer A holds 0-6 minus the loss, peer B holds 7-13."""
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
        vs.start()
        servers.append(vs)
    target, peer_a, peer_b = servers
    stage = tmp_path / "stage"
    stage.mkdir()
    base_stage, golden = _build_shard_set(str(stage))
    os.unlink(stripe.shard_file_name(base_stage, 3))
    base_a = peer_a._base_path_for(VID)
    base_b = peer_b._base_path_for(VID)
    for s in (0, 1, 2, 4, 5, 6):
        os.replace(stripe.shard_file_name(base_stage, s), stripe.shard_file_name(base_a, s))
    for s in range(7, 14):
        os.replace(stripe.shard_file_name(base_stage, s), stripe.shard_file_name(base_b, s))
    for base_p in (base_a, base_b):
        for ext in (".ecx", ".eci"):
            shutil.copy(base_stage + ext, base_p + ext)
    for vs in (peer_a, peer_b):
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(VID)) == 13,
        msg="13 survivor shards registered",
    )
    yield master, servers, golden
    for vs in servers:
        vs.stop()
    master.stop()


def _rebuild(target, trace_mode, timeout=120):
    with rpc.RpcClient(target.grpc_address) as tc:
        return tc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsRebuild",
            {"volume_id": VID, "remote": True, "trace_mode": trace_mode},
            timeout=timeout,
        )


def _scrub(target, shard=3):
    p = stripe.shard_file_name(target._base_path_for(VID), shard)
    if os.path.exists(p):
        os.unlink(p)


def test_trace_rebuild_end_to_end_wire_bytes_below_slab(trace_cluster):
    """The headline: trace mode rebuilds byte-identically while moving
    strictly fewer survivor bytes than the slab baseline — asserted from
    BOTH the per-rebuild response accounting and the
    weedtpu_ec_repair_network_bytes_total counter."""
    master, (target, peer_a, peer_b), golden = trace_cluster
    shard_size = len(golden[0])
    trace_counter = stats.EcRepairNetworkBytes.labels("trace")
    slab_counter = stats.EcRepairNetworkBytes.labels("slab")
    t0 = trace_counter.value
    resp = _rebuild(target, "on")
    assert resp["mode"] == "trace", resp
    assert resp["rebuilt_shard_ids"] == [3]
    assert resp["trace_fallback"] == ""
    assert len(resp["trace_groups"]) == 2, resp["trace_groups"]
    with open(stripe.shard_file_name(target._base_path_for(VID), 3), "rb") as f:
        assert f.read() == golden[3]
    # 2 holder groups x 1 missing shard x shard bytes on the wire
    assert resp["wire_bytes"] == 2 * shard_size
    assert trace_counter.value - t0 == resp["wire_bytes"]

    _scrub(target)
    s0 = slab_counter.value
    resp_slab = _rebuild(target, "off")
    assert resp_slab["mode"] == "slab"
    assert resp_slab["wire_bytes"] == DATA_SHARDS_COUNT * shard_size
    assert slab_counter.value - s0 == resp_slab["wire_bytes"]
    with open(stripe.shard_file_name(target._base_path_for(VID), 3), "rb") as f:
        assert f.read() == golden[3]
    # the acceptance ratio, measured: strictly below, and below 0.6
    assert resp["wire_bytes"] < resp_slab["wire_bytes"]
    assert resp["wire_bytes"] / resp_slab["wire_bytes"] <= 0.6


def test_trace_auto_uses_projections_when_all_holders_capable(trace_cluster):
    master, (target, *_peers), golden = trace_cluster
    resp = _rebuild(target, "auto")
    assert resp["mode"] == "trace"
    with open(stripe.shard_file_name(target._base_path_for(VID), 3), "rb") as f:
        assert f.read() == golden[3]


def test_capability_negotiation_falls_back_to_slabs(trace_cluster):
    """A peer that does not speak projections (mixed-version cluster,
    modeled by WEEDTPU_TRACE_REPAIR=off latched on that server) must push
    auto mode onto the full-slab path — rebuild still succeeds, fallback
    reason recorded."""
    master, (target, peer_a, peer_b), golden = trace_cluster
    peer_b._trace_repair = "off"  # stops advertising slab_projection
    resp = _rebuild(target, "auto")
    assert resp["mode"] == "slab", resp
    assert "projection-capable" in resp["trace_fallback"], resp
    with open(stripe.shard_file_name(target._base_path_for(VID), 3), "rb") as f:
        assert f.read() == golden[3]


def test_incapable_peer_refuses_projection_read(trace_cluster):
    """Defense in depth: even if a planner raced the capability probe, an
    `off` peer refuses the projection read itself — and the rebuild's
    runtime fallback still lands on slabs with zero lost bytes."""
    master, (target, peer_a, peer_b), golden = trace_cluster
    with rpc.RpcClient(peer_b.grpc_address) as c:
        frames = c.stream(
            VOLUME_SERVICE,
            "VolumeEcShardSlabRead",
            {
                "volume_id": VID,
                "offset": 0,
                "size": 4096,
                "projection": [
                    {"shard_id": 7, "coeffs": base64.b64encode(b"\x01").decode()}
                ],
                "projection_rows": 1,
            },
            timeout=30,
        )
        peer_b._trace_repair = "off"
        with pytest.raises(Exception, match="disabled|UNIMPLEMENTED"):
            list(frames)


def test_midrebuild_trace_failure_falls_back_to_slab(trace_cluster, monkeypatch):
    """A trace pipeline that dies mid-rebuild (holder kill, torn stream)
    must fall back to the slab path within the SAME rebuild call: shards
    still rebuilt, zero lost bytes, reason recorded."""
    master, (target, *_peers), golden = trace_cluster

    def boom(*a, **kw):
        raise IOError("holder killed mid-rebuild")

    monkeypatch.setattr(stripe, "rebuild_ec_files_from_projections", boom)
    resp = _rebuild(target, "on")
    assert resp["mode"] == "slab", resp
    assert "holder killed mid-rebuild" in resp["trace_fallback"]
    with open(stripe.shard_file_name(target._base_path_for(VID), 3), "rb") as f:
        assert f.read() == golden[3]


def test_torn_projection_stream_is_rejected_by_crc(trace_cluster):
    """A flipped bit in a projected chunk must be caught at the transport
    seam (crc_unframe), not decoded into a silently-wrong shard."""
    master, (target, peer_a, peer_b), golden = trace_cluster

    class TornClient:
        def stream(self, service, method, req, timeout=None):
            good = rpc.crc_frame(b"\x00" * 128)
            torn = bytearray(rpc.crc_frame(b"\x11" * 128))
            torn[10] ^= 0x40  # flip one payload bit, keep the CRC
            return iter([good, bytes(torn)])

    class Pool:
        def get(self, addr):
            return TornClient()

    fetch = target._projection_fetcher("x:1", VID, [], 1)
    target_pool, target._peer_pool = target._peer_pool, Pool()
    try:
        with pytest.raises(IOError, match="CRC mismatch"):
            fetch(0, 4096)
    finally:
        target._peer_pool = target_pool


def test_volume_status_advertises_projection_capability(trace_cluster):
    master, (target, peer_a, peer_b), golden = trace_cluster
    with rpc.RpcClient(peer_a.grpc_address) as c:
        st = c.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": VID})
    assert "slab_projection" in st.get("capabilities", []), st
    peer_a._trace_repair = "off"
    with rpc.RpcClient(peer_a.grpc_address) as c:
        st = c.call(VOLUME_SERVICE, "VolumeStatus", {"volume_id": VID})
    assert st.get("capabilities") == []


# -- tier-1 CI smoke: the bench harness on tiny shards ------------------------


def test_bench_rebuild_trace_smoke(tmp_path):
    """Fast CPU smoke of bench.py's ec_rebuild_trace harness (tiny shards,
    three in-process servers): both modes must rebuild byte-identically
    and the wire ratio — a deterministic byte count, not a timing — must
    meet the <= 0.6 acceptance gate."""
    import bench

    out = bench._measure_rebuild_trace(
        str(tmp_path),
        dat_bytes=1 << 20,
        large=65536,
        small=16384,
        buffer_size=16384,
        max_batch_bytes=10 * 2 * 16384,
        delay_ms=0,
    )
    assert out["ok"], out
    assert out["trace"]["match"] and out["slab"]["match"]
    assert out["trace"]["mode_reported"] == "trace"
    assert out["wire_ratio"] is not None and out["wire_ratio"] <= 0.6, out
    # with survivors on two holders the trace wire cost is exactly
    # 2 x repaired bytes vs 10 full slabs
    assert out["trace"]["wire_bytes"] == 2 * out["slab"]["wire_bytes"] // 10
