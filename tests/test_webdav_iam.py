"""WebDAV + IAM gateway tests over a real loopback stack (SURVEY.md §4
in-process integration pattern)."""

import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import FilerServer
from seaweedfs_tpu.iamapi import IamApiServer, load_identities
from seaweedfs_tpu.s3api import Iam
from seaweedfs_tpu.webdav import WebDavServer


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("davstack")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    (tmp / "vol").mkdir()
    vs = VolumeServer([str(tmp / "vol")], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address)
    fs.start()
    dav = WebDavServer(fs.url, fs.grpc_address)
    dav.start()
    iam = IamApiServer(fs.grpc_address, iam=Iam([]), bootstrap_token="boot-secret")
    iam.start()
    yield fs, dav, iam
    iam.stop()
    dav.stop()
    fs.stop()
    vs.stop()
    master.stop()


def _req(base, method, path, body=None, headers=None):
    req = urllib.request.Request(
        base + path, data=body, method=method, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def test_webdav_lifecycle(stack):
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    code, headers, _ = _req(base, "OPTIONS", "/")
    assert code == 200 and "PROPFIND" in headers["Allow"]
    # MKCOL + PUT + GET
    assert _req(base, "MKCOL", "/davdir")[0] == 201
    assert _req(base, "MKCOL", "/davdir")[0] == 405  # exists
    code, _, _ = _req(base, "PUT", "/davdir/note.txt", b"dav content",
                      {"Content-Type": "text/plain"})
    assert code == 201
    code, _, got = _req(base, "GET", "/davdir/note.txt")
    assert code == 200 and got == b"dav content"
    code, headers, _ = _req(base, "HEAD", "/davdir/note.txt")
    assert code == 200 and headers["Content-Length"] == "11"
    # PROPFIND depth 1 on the collection
    code, _, body = _req(base, "PROPFIND", "/davdir", headers={"Depth": "1"})
    assert code == 207
    ms = ET.fromstring(body)
    hrefs = [h.text for h in ms.findall(".//{DAV:}href")]
    assert "/davdir/" in hrefs and "/davdir/note.txt" in hrefs
    lengths = [e.text for e in ms.findall(".//{DAV:}getcontentlength")]
    assert "11" in lengths
    # COPY then MOVE
    code, _, _ = _req(base, "COPY", "/davdir/note.txt",
                      headers={"Destination": f"http://{dav.url}/davdir/copy.txt"})
    assert code == 201
    code, _, _ = _req(base, "MOVE", "/davdir/copy.txt",
                      headers={"Destination": f"http://{dav.url}/davdir/moved.txt"})
    assert code == 201
    assert _req(base, "GET", "/davdir/moved.txt")[2] == b"dav content"
    assert _req(base, "GET", "/davdir/copy.txt")[0] == 404
    # Overwrite: F refuses to clobber
    code, _, _ = _req(base, "MOVE", "/davdir/moved.txt",
                      headers={"Destination": f"http://{dav.url}/davdir/note.txt",
                               "Overwrite": "F"})
    assert code == 412
    # DELETE collection
    assert _req(base, "DELETE", "/davdir")[0] == 204
    assert _req(base, "PROPFIND", "/davdir")[0] == 404


def _iam_call(url, creds=None, token=None, **form):
    from seaweedfs_tpu.s3api.auth import sign_request

    data = urllib.parse.urlencode(form).encode()
    headers = {}
    if creds:
        headers = sign_request(creds[0], creds[1], "POST", url, data, service="iam")
    if token:
        headers["x-seaweedfs-bootstrap-token"] = token
    req = urllib.request.Request(url, data=data, method="POST", headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_iam_user_and_key_lifecycle(stack):
    fs, _, iam = stack
    url = f"http://{iam.url}/"
    ns = "{https://iam.amazonaws.com/doc/2010-05-08/}"
    # fresh cluster: anonymous calls are rejected outright — bootstrap
    # needs the pre-shared token (first-to-the-port must not mint Admin)
    code, _ = _iam_call(url, Action="CreateUser", UserName="eve")
    assert code == 403
    code, _ = _iam_call(url, token="wrong-token", Action="CreateUser", UserName="eve")
    assert code == 403
    boot = "boot-secret"
    # AWS-natural order: CreateUser → CreateAccessKey → PutUserPolicy.
    # The key exists with empty actions mid-sequence; the token gate must
    # stay open until a credentialed ADMIN exists, or the API self-locks.
    code, _ = _iam_call(url, token=boot, Action="CreateUser", UserName="root")
    assert code == 200
    code, body = _iam_call(url, token=boot, Action="CreateAccessKey", UserName="root")
    assert code == 200
    # malformed policy documents get 400, not a crashed handler thread
    for bad in ('[]', '"x"', '{"Statement": ["x"]}', '{"Statement": 3}'):
        code, _ = _iam_call(url, token=boot, Action="PutUserPolicy",
                            UserName="root", PolicyDocument=bad)
        assert code == 400, bad
    code, _ = _iam_call(url, token=boot, Action="PutUserPolicy", UserName="root",
                        PolicyDocument='{"Statement": [{"Effect": "Allow", '
                                       '"Action": "s3:*", "Resource": "*"}]}')
    assert code == 200
    root_el = ET.fromstring(body)
    admin = (root_el.find(f".//{ns}AccessKeyId").text,
             root_el.find(f".//{ns}SecretAccessKey").text)
    # the first minted key locks the API: unsigned mutations now 403
    code, _ = _iam_call(url, Action="CreateUser", UserName="eve")
    assert code == 403
    code, body = _iam_call(url, admin, Action="CreateUser", UserName="alice")
    assert code == 200 and b"alice" in body
    code, body = _iam_call(url, admin, Action="CreateAccessKey", UserName="alice")
    assert code == 200
    doc = ET.fromstring(body)
    ak = doc.find(f".//{ns}AccessKeyId").text
    sk = doc.find(f".//{ns}SecretAccessKey").text
    assert ak and sk
    # policy -> action mapping
    policy = (
        '{"Statement": [{"Effect": "Allow", "Action": ["s3:GetObject", '
        '"s3:ListBucket"], "Resource": "arn:aws:s3:::mybucket/*"}]}'
    )
    code, _ = _iam_call(url, admin, Action="PutUserPolicy", UserName="alice",
                        PolicyDocument=policy)
    assert code == 200
    ident = iam.iam.lookup(ak)
    assert ident is not None
    assert ident.actions == ["List:mybucket", "Read:mybucket"]
    assert ident.can_do("Read", "mybucket") and not ident.can_do("Read", "other")
    # a valid signature without Admin privileges is still rejected
    code, _ = _iam_call(url, (ak, sk), Action="CreateUser", UserName="mallory")
    assert code == 403
    # identities persisted to filer kv: reload sees alice
    from seaweedfs_tpu.filer.client import FilerClient

    with FilerClient(fs.grpc_address) as fc:
        loaded = load_identities(fc)
    assert loaded is not None and loaded.lookup(ak) is not None
    # list/get/delete
    code, body = _iam_call(url, admin, Action="ListUsers")
    assert b"alice" in body
    code, _ = _iam_call(url, admin, Action="DeleteAccessKey", AccessKeyId=ak)
    assert code == 200
    code, _ = _iam_call(url, admin, Action="DeleteUser", UserName="alice")
    assert code == 200
    code, _ = _iam_call(url, admin, Action="GetUser", UserName="alice")
    assert code == 404
    code, _ = _iam_call(url, admin, Action="BogusAction")
    assert code == 400
    # the last credentialed admin cannot be revoked/deleted/demoted — any
    # of those would lock the IAM API (key exists, bootstrap gate closed)
    code, _ = _iam_call(url, admin, Action="DeleteAccessKey", AccessKeyId=admin[0])
    assert code == 409
    code, _ = _iam_call(url, admin, Action="DeleteUser", UserName="root")
    assert code == 409
    code, _ = _iam_call(url, admin, Action="PutUserPolicy", UserName="root",
                        PolicyDocument='{"Statement": [{"Effect": "Allow", '
                                       '"Action": "s3:GetObject", "Resource": "*"}]}')
    assert code == 409
    # a signature scoped for service=s3 must not verify on the IAM endpoint
    from seaweedfs_tpu.s3api.auth import sign_request as _sr

    data = urllib.parse.urlencode({"Action": "ListUsers"}).encode()
    h = _sr(admin[0], admin[1], "POST", url, data, service="s3")
    req = urllib.request.Request(url, data=data, method="POST", headers=h)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            code = r.status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 403

def test_webdav_class2_locks(stack):
    """RFC 4918 class-2 exclusive write locks: LOCK grants a token, writes
    without it 423, writes with it pass, refresh extends, UNLOCK frees."""
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    _req(base, "PUT", "/locked.txt", b"v1")

    lockinfo = (
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype>"
        b"<D:owner>alice</D:owner></D:lockinfo>"
    )
    code, headers, body = _req(
        base, "LOCK", "/locked.txt", lockinfo, {"Timeout": "Second-60"}
    )
    assert code == 200, body
    token = headers["Lock-Token"].strip("<>")
    assert token.startswith("opaquelocktoken:")
    assert b"lockdiscovery" in body and b"alice" in body

    # second client cannot lock or write
    code, _, _ = _req(base, "LOCK", "/locked.txt", lockinfo)
    assert code == 423
    code, _, _ = _req(base, "PUT", "/locked.txt", b"intruder")
    assert code == 423
    code, _, _ = _req(base, "DELETE", "/locked.txt")
    assert code == 423
    code, _, _ = _req(
        base, "MOVE", "/locked.txt", None,
        {"Destination": f"http://{dav.url}/stolen.txt"},
    )
    assert code == 423

    # the holder writes fine with If: (<token>)
    code, _, _ = _req(
        base, "PUT", "/locked.txt", b"v2", {"If": f"(<{token}>)"}
    )
    assert code == 201
    code, _, body = _req(base, "GET", "/locked.txt")
    assert code == 200 and body == b"v2"

    # refresh: LOCK with empty body + the token
    code, headers, _ = _req(
        base, "LOCK", "/locked.txt", None,
        {"If": f"(<{token}>)", "Timeout": "Second-120"},
    )
    assert code == 200
    assert headers["Lock-Token"].strip("<>") == token  # same lock, extended

    # unlock with the wrong token fails; right token frees the resource
    code, _, _ = _req(
        base, "UNLOCK", "/locked.txt", None,
        {"Lock-Token": "<opaquelocktoken:bogus>"},
    )
    assert code == 409
    code, _, _ = _req(
        base, "UNLOCK", "/locked.txt", None, {"Lock-Token": f"<{token}>"}
    )
    assert code == 204
    code, _, _ = _req(base, "PUT", "/locked.txt", b"free again")
    assert code == 201


def test_webdav_lock_expires(stack):
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    _req(base, "PUT", "/expire.txt", b"x")
    code, headers, _ = _req(
        base, "LOCK", "/expire.txt",
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype></D:lockinfo>",
        {"Timeout": "Second-1"},
    )
    assert code == 200
    import time as _t

    _t.sleep(1.2)
    code, _, _ = _req(base, "PUT", "/expire.txt", b"after-expiry")
    assert code == 201, "expired lock must not block writers"


def test_webdav_locks_cleared_by_delete_and_move(stack):
    """RFC 4918: DELETE destroys the lock; MOVE leaves no stale lock at
    either path; COPY and MKCOL respect a locked destination."""
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    lockinfo = (
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype></D:lockinfo>"
    )
    # DELETE destroys the lock
    _req(base, "PUT", "/gone.txt", b"x")
    code, headers, _ = _req(base, "LOCK", "/gone.txt", lockinfo)
    token = headers["Lock-Token"].strip("<>")
    code, _, _ = _req(base, "DELETE", "/gone.txt", None, {"If": f"(<{token}>)"})
    assert code == 204
    code, _, _ = _req(base, "PUT", "/gone.txt", b"fresh")  # no stale 423
    assert code == 201

    # MOVE leaves no stale lock at src
    _req(base, "PUT", "/mv-src.txt", b"x")
    code, headers, _ = _req(base, "LOCK", "/mv-src.txt", lockinfo)
    token = headers["Lock-Token"].strip("<>")
    code, _, _ = _req(
        base, "MOVE", "/mv-src.txt", None,
        {"Destination": f"http://{dav.url}/mv-dst.txt", "If": f"(<{token}>)"},
    )
    assert code in (201, 204)
    code, _, _ = _req(base, "PUT", "/mv-src.txt", b"new tenant")
    assert code == 201
    code, _, _ = _req(base, "PUT", "/mv-dst.txt", b"unlocked")
    assert code == 201

    # COPY over a locked destination 423s; MKCOL at a locked path 423s
    _req(base, "PUT", "/copy-src.txt", b"src")
    _req(base, "PUT", "/copy-dst.txt", b"dst")
    code, headers, _ = _req(base, "LOCK", "/copy-dst.txt", lockinfo)
    token = headers["Lock-Token"].strip("<>")
    code, _, _ = _req(
        base, "COPY", "/copy-src.txt", None,
        {"Destination": f"http://{dav.url}/copy-dst.txt"},
    )
    assert code == 423
    code, headers2, _ = _req(base, "LOCK", "/newdir", lockinfo)
    tok2 = headers2["Lock-Token"].strip("<>")
    code, _, _ = _req(base, "MKCOL", "/newdir")
    assert code == 423
    _req(base, "UNLOCK", "/copy-dst.txt", None, {"Lock-Token": f"<{token}>"})
    _req(base, "UNLOCK", "/newdir", None, {"Lock-Token": f"<{tok2}>"})


def test_webdav_collection_ops_honor_child_locks(stack):
    """DELETE/MOVE of a directory must 423 while a child is locked by
    someone else, and a completed delete clears the subtree's locks."""
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    lockinfo = (
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype></D:lockinfo>"
    )
    _req(base, "MKCOL", "/tree")
    _req(base, "PUT", "/tree/child.txt", b"x")
    code, headers, _ = _req(base, "LOCK", "/tree/child.txt", lockinfo)
    token = headers["Lock-Token"].strip("<>")
    # tokenless collection delete/move is refused while the child is locked
    code, _, _ = _req(base, "DELETE", "/tree")
    assert code == 423
    code, _, _ = _req(
        base, "MOVE", "/tree", None, {"Destination": f"http://{dav.url}/tree2"}
    )
    assert code == 423
    # the lock holder may delete the whole tree; child locks die with it
    code, _, _ = _req(base, "DELETE", "/tree", None, {"If": f"(<{token}>)"})
    assert code == 204
    _req(base, "MKCOL", "/tree")
    code, _, _ = _req(base, "PUT", "/tree/child.txt", b"fresh")  # no stale 423
    assert code == 201


def test_webdav_collection_lock_protects_members(stack):
    """RFC 4918 §7: an exclusive write lock on a collection protects
    internal member creation/modification/removal from tokenless writes,
    while the holder's token covers the whole subtree."""
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    lockinfo = (
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype></D:lockinfo>"
    )
    _req(base, "MKCOL", "/treelock")
    _req(base, "PUT", "/treelock/child.txt", b"v1")
    code, headers, _ = _req(base, "LOCK", "/treelock", lockinfo)
    assert code == 200
    token = headers["Lock-Token"].strip("<>")

    # tokenless member writes are blocked by the collection lock
    code, _, _ = _req(base, "PUT", "/treelock/child.txt", b"intruder")
    assert code == 423
    code, _, _ = _req(base, "PUT", "/treelock/new.txt", b"intruder")
    assert code == 423
    code, _, _ = _req(base, "DELETE", "/treelock/child.txt")
    assert code == 423
    code, _, body = _req(base, "GET", "/treelock/child.txt")
    assert code == 200 and body == b"v1"

    # the holder's token covers members
    code, _, _ = _req(
        base, "PUT", "/treelock/child.txt", b"v2", {"If": f"(<{token}>)"}
    )
    assert code == 201
    code, _, _ = _req(
        base, "UNLOCK", "/treelock", None, {"Lock-Token": f"<{token}>"}
    )
    assert code == 204
    code, _, _ = _req(base, "PUT", "/treelock/child.txt", b"v3")
    assert code == 201


def test_webdav_child_lock_cannot_tunnel_collection_lock(stack):
    """A client must not bypass an exclusive collection lock by taking its
    own lock on a child — conflicting LOCK grants are refused in both
    directions (ancestor and descendant)."""
    fs, dav, _ = stack
    base = f"http://{dav.url}"
    lockinfo = (
        b'<?xml version="1.0"?><D:lockinfo xmlns:D="DAV:">'
        b"<D:lockscope><D:exclusive/></D:lockscope>"
        b"<D:locktype><D:write/></D:locktype></D:lockinfo>"
    )
    _req(base, "MKCOL", "/lockcol")
    _req(base, "PUT", "/lockcol/f.txt", b"v1")
    code, headers, _ = _req(base, "LOCK", "/lockcol", lockinfo)
    assert code == 200
    token = headers["Lock-Token"].strip("<>")
    # child lock under a locked collection: refused
    code, _, _ = _req(base, "LOCK", "/lockcol/f.txt", lockinfo)
    assert code == 423
    code, _, _ = _req(
        base, "UNLOCK", "/lockcol", None, {"Lock-Token": f"<{token}>"}
    )
    assert code == 204
    # now the child lock grants; an ancestor lock is then refused
    code, headers, _ = _req(base, "LOCK", "/lockcol/f.txt", lockinfo)
    assert code == 200
    child_token = headers["Lock-Token"].strip("<>")
    code, _, _ = _req(base, "LOCK", "/lockcol", lockinfo)
    assert code == 423
    _req(base, "UNLOCK", "/lockcol/f.txt", None, {"Lock-Token": f"<{child_token}>"})
