"""Codec tests: numpy-vs-jax backend equality (byte-for-byte), encode/
reconstruct round trips under every loss pattern up to 4 shards, verify(),
split/join — the golden-roundtrip pattern of the reference's ec_test.go
(SURVEY.md §4)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder


def _shards(rng, n=10, size=1024):
    return [rng.integers(0, 256, size=size).astype(np.uint8) for _ in range(n)]


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(42)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
@pytest.mark.parametrize("kind", ["vandermonde", "cauchy"])
def test_encode_verify_roundtrip(rng, backend, kind):
    enc = Encoder(10, 4, matrix_kind=kind, backend=backend)
    shards = enc.encode(_shards(rng))
    assert len(shards) == 14
    assert enc.verify(shards)
    # corrupt one byte -> verify fails
    bad = [s.copy() for s in shards]
    bad[12][7] ^= 0xFF
    assert not enc.verify(bad)


def test_numpy_jax_byte_identical(rng):
    data = _shards(rng, size=4096)
    a = Encoder(10, 4, backend="numpy").encode([d.copy() for d in data])
    b = Encoder(10, 4, backend="jax").encode([d.copy() for d in data])
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_reconstruct_all_loss_patterns_up_to_4(rng, backend):
    enc = Encoder(10, 4, backend=backend)
    orig = enc.encode(_shards(rng, size=257))
    patterns = list(itertools.combinations(range(14), 4))
    # all 1001 4-loss patterns on numpy is slow-ish; sample deterministically
    sel = patterns[::7] if backend == "numpy" else patterns[::3]
    for lost in sel:
        shards = [None if i in lost else orig[i].copy() for i in range(14)]
        got = enc.reconstruct(shards)
        for i in range(14):
            assert np.array_equal(got[i], orig[i]), f"shard {i}, lost={lost}"


def test_reconstruct_data_only(rng):
    enc = Encoder(10, 4, backend="numpy")
    orig = enc.encode(_shards(rng, size=100))
    shards = [None if i in (0, 5, 13) else orig[i].copy() for i in range(14)]
    got = enc.reconstruct_data(shards)
    for i in range(10):
        assert np.array_equal(got[i], orig[i])
    assert got[13] is None  # parity not repaired on data-only path


def test_too_few_shards_raises(rng):
    enc = Encoder(10, 4, backend="numpy")
    orig = enc.encode(_shards(rng, size=64))
    shards = [None if i < 5 else orig[i].copy() for i in range(14)]
    with pytest.raises(ValueError, match="too few"):
        enc.reconstruct(shards)


def test_split_join(rng):
    enc = Encoder(10, 4, backend="numpy")
    blob = bytes(rng.integers(0, 256, size=1000, dtype=np.uint8))
    parts = enc.split(blob)
    assert len(parts) == 10 and all(len(p) == 100 for p in parts)
    assert enc.join(parts, len(blob)) == blob


def test_factory_auto_backend():
    enc = new_encoder()
    assert enc.backend in ("numpy", "native", "xorsched", "jax")


def test_other_geometries(rng):
    for d, p in [(4, 2), (6, 3), (17, 3)]:
        enc = Encoder(d, p, backend="numpy")
        orig = enc.encode(_shards(rng, n=d, size=50))
        lost = list(range(p))
        shards = [None if i in lost else orig[i].copy() for i in range(d + p)]
        got = enc.reconstruct(shards)
        for i in range(d + p):
            assert np.array_equal(got[i], orig[i])


@pytest.mark.parametrize("backend", ["jax", "pallas"])
@pytest.mark.parametrize("size", [999, 4096, 5000, 70_000])
def test_bucketed_reconstruct_matches_numpy(rng, backend, size):
    """Pad-and-mask bucketing on the accelerator backends must be exact:
    odd interval sizes reconstruct byte-identically to the numpy oracle."""
    data = _shards(rng, size=size)
    gold = Encoder(10, 4, backend="numpy")
    full = gold.encode([d.copy() for d in data])
    enc = Encoder(10, 4, backend=backend)
    assert enc._bucket_for(size) is not None  # the path under test
    lost = [0, 5, 11]
    holed = [None if i in lost else s.copy() for i, s in enumerate(full)]
    rec = enc.reconstruct(holed)
    for i in lost:
        np.testing.assert_array_equal(rec[i], full[i], err_msg=f"shard {i}")


def test_warm_reconstruct_precompiles_buckets(rng):
    enc = Encoder(10, 4, backend="jax")
    assert enc.warm_reconstruct() == len(Encoder.RECONSTRUCT_BUCKETS)
    assert Encoder(10, 4, backend="numpy").warm_reconstruct() == 0


def test_warm_decode_matrices_covers_single_loss_patterns():
    from seaweedfs_tpu.ops import rs_codec

    enc = Encoder(10, 4, backend="numpy")
    # local shards never need reconstructing -> excluded from prewarm
    assert enc.warm_decode_matrices(local_shards=[0, 1, 2]) == 11
    info = rs_codec._reconstruction_matrix.cache_info()
    # every prebuilt pattern is a cache hit when the serving path asks
    before = info.hits
    survivors = tuple(s for s in range(14) if s != 5)[:10]
    rs_codec._reconstruction_matrix("vandermonde", 10, 4, survivors, (5,))
    assert rs_codec._reconstruction_matrix.cache_info().hits == before + 1


def test_native_backend_matches_numpy_golden():
    """The C++ AVX2 backend must be byte-identical to the numpy golden
    path across encode, batched encode, reconstruct, and verify."""
    import numpy as np
    import pytest

    from seaweedfs_tpu.ops.rs_codec import Encoder
    from seaweedfs_tpu.utils import native as native_mod

    if native_mod.load() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(21)
    gold = Encoder(10, 4, backend="numpy")
    fast = Encoder(10, 4, backend="native")
    data = [rng.integers(0, 256, 4096, dtype=np.uint8) for _ in range(10)]
    g = gold.encode(data)
    f = fast.encode(data)
    assert all(np.array_equal(a, b) for a, b in zip(g, f))
    batch = rng.integers(0, 256, (3, 10, 2048), dtype=np.uint8)
    assert np.array_equal(gold.encode_batch(batch), fast.encode_batch(batch))
    # kill 4 shards, reconstruct
    shards = list(f)
    for i in (0, 5, 11, 13):
        shards[i] = None
    rec = fast.reconstruct(shards)
    assert all(np.array_equal(rec[i], g[i]) for i in range(14))
    assert fast.verify(rec)


def test_auto_backend_on_cpu_follows_evidence_rule():
    """auto on a CPU host is the pick_cpu_backend decision: the AVX2
    library by default, promoted to the compiled XOR-schedule backend
    only under fresh committed same-host BENCH evidence in which
    xorsched beat native in the same run (the r17 CPU-floor rule —
    fabricated-evidence decision table lives in test_xorsched.py)."""
    import pytest

    from seaweedfs_tpu.ops import rs_codec
    from seaweedfs_tpu.utils import native as native_mod

    if native_mod.load() is None:
        pytest.skip("native library unavailable")
    expected, dec = rs_codec.pick_cpu_backend()
    assert expected in ("native", "xorsched")
    enc = rs_codec.new_encoder()  # conftest pins cpu
    assert enc.backend == expected
    if expected == "xorsched":
        assert enc.selection["source"] == "cpu-bench-evidence"
        assert enc.selection["evidence_file"].startswith("BENCH_r")


def test_auto_backend_on_tpu_prefers_measured_fastest(monkeypatch):
    """On TPU, auto must resolve to the XLA bit-plane path, not pallas:
    on-chip measurement (artifacts/DEVICE_MEASUREMENT_r04.json) has XLA at
    31-32 GB/s steady vs pallas 18.7. Guard against a regression that
    re-selects the slower kernel in production."""
    import jax

    from seaweedfs_tpu.ops.rs_codec import new_encoder

    class _FakeTpu:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [_FakeTpu()])
    assert new_encoder().backend == "jax"
