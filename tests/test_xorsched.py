"""Compiled XOR-schedule backend (ops/xorsched) + the CPU promotion rule.

The r17 contract: every GF(2^8) matrix the Encoder dispatches — encode
parity, fused decode, projection column-slice, delta-parity column — lowers
through gf8's bit-plane decomposition into an XOR program that is
byte-exact against the gf8 numpy golden at tile-edge/odd/tiny widths, on
BOTH executors (numpy interpreter and the native SIMD path when the .so
carries the entry point). Compilation is deterministic, the shared-
subexpression grouping pass measurably shrinks the program, the schedule
LRU is bounded, and `new_encoder("auto")` on CPU promotes xorsched over
the AVX2 library ONLY under fresh committed same-host BENCH evidence in
which xorsched beat native in the same run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf8, rs_codec, xorsched

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# full tiles (512-symbol SIMD groups), partial tiles, sub-8-symbol scalar
# tails, and widths straddling the default 4096-symbol tile boundary
WIDTHS = [1, 7, 8, 31, 255, 512, 513, 4095, 4096, 4097]


def _forms() -> list[tuple[str, np.ndarray]]:
    """The four matrix shapes the Encoder dispatches (bench's list is the
    same by construction — both derive from one 10+4 encoder)."""
    enc = rs_codec.Encoder(10, 4, backend="numpy")
    survivors = [i for i in range(14) if i not in (2, 11)][:10]
    decode = enc.reconstruction_matrix(survivors, [2, 11])
    plan = enc.repair_projection_plan(survivors, [2, 11])
    projection = np.stack([plan[s] for s in survivors[:5]], axis=1)
    delta = enc.parity_matrix[:, [3]]
    return [
        ("encode", enc.parity_matrix),
        ("decode", decode),
        ("projection", projection),
        ("delta", delta),
    ]


# -- byte-exactness ----------------------------------------------------------


@pytest.mark.parametrize("name", ["encode", "decode", "projection", "delta"])
def test_interpreter_byte_exact_vs_golden(name):
    m = dict(_forms())[name]
    rng = np.random.default_rng(17)
    prog = xorsched.compile_schedule(m)
    for n in WIDTHS:
        stack = rng.integers(0, 256, size=(m.shape[1], n), dtype=np.uint8)
        golden = gf8.gf_mat_vec(m, stack)
        got = np.stack(xorsched.apply(prog, list(stack)))
        assert (got == golden).all(), f"{name} interpreter mismatch at n={n}"


@pytest.mark.parametrize("name", ["encode", "decode", "projection", "delta"])
def test_native_executor_byte_exact_vs_golden(name):
    if not xorsched.native_available():
        pytest.skip("libweedtpu.so lacks the xorsched entry point")
    m = dict(_forms())[name]
    rng = np.random.default_rng(18)
    prog = xorsched.compile_schedule(m)
    for n in WIDTHS:
        stack = rng.integers(0, 256, size=(m.shape[1], n), dtype=np.uint8)
        golden = gf8.gf_mat_vec(m, stack)
        outs = xorsched.apply_native(prog, list(stack))
        assert outs is not None
        assert (np.stack(outs) == golden).all(), f"{name} native mismatch at n={n}"


def test_non_multiple_of_tile_and_large_width():
    m = dict(_forms())["encode"]
    rng = np.random.default_rng(19)
    prog = xorsched.compile_schedule(m, tile_sym=1024)
    n = 65536 + 488  # many tiles + a ragged final tile + scalar tail
    stack = rng.integers(0, 256, size=(10, n), dtype=np.uint8)
    golden = gf8.gf_mat_vec(m, stack)
    assert (np.stack(xorsched.apply(prog, list(stack))) == golden).all()
    if xorsched.native_available():
        outs = xorsched.apply_native(prog, list(stack))
        assert (np.stack(outs) == golden).all()


# -- compiler properties -----------------------------------------------------


def test_schedule_determinism():
    m = dict(_forms())["encode"]
    a = xorsched.compile_schedule(m)
    b = xorsched.compile_schedule(m)
    assert np.array_equal(a.ops, b.ops)
    assert (a.n_slots, a.out_base, a.xor_count, a.n_temps) == (
        b.n_slots, b.out_base, b.xor_count, b.n_temps
    )


def test_grouping_reduces_xor_count_on_cauchy_10p4():
    m = gf8.parity_matrix(10, 4, kind="cauchy")
    prog = xorsched.compile_schedule(m)
    # the numeric claim, not just "smaller": the greedy pair-CSE pass must
    # remove at least a third of the raw bit-plane XORs on this matrix
    # (measured ~52% on vandermonde; cauchy is in the same density class)
    assert prog.raw_xors > 0
    assert prog.n_temps > 0
    assert prog.xor_count <= (2 * prog.raw_xors) // 3, (
        f"grouping too weak: {prog.xor_count} of {prog.raw_xors} raw XORs"
    )


def test_tile_clamped_to_simd_multiple():
    m = dict(_forms())["delta"]
    prog = xorsched.compile_schedule(m, tile_sym=100)  # below the 512 floor
    assert prog.tile_sym == 512
    prog = xorsched.compile_schedule(m, tile_sym=1000)
    assert prog.tile_sym % 512 == 0


# -- schedule LRU ------------------------------------------------------------


def test_lru_bound_and_eviction(monkeypatch):
    monkeypatch.setenv("WEEDTPU_XORSCHED_CACHE", "2")
    xorsched.clear_schedule_cache()
    try:
        mats = [gf8.parity_matrix(4, 2), gf8.parity_matrix(5, 2),
                gf8.parity_matrix(6, 2)]
        for m in mats:
            xorsched.get_schedule(m)
        info = xorsched.schedule_cache_info()
        assert info["cap"] == 2
        assert info["size"] == 2
        assert info["evictions"] == 1
        assert info["misses"] == 3
        # the oldest entry was evicted: touching it again is a miss
        xorsched.get_schedule(mats[0])
        assert xorsched.schedule_cache_info()["misses"] == 4
        # the newest is still resident: a hit
        xorsched.get_schedule(mats[0])
        assert xorsched.schedule_cache_info()["hits"] == 1
    finally:
        monkeypatch.delenv("WEEDTPU_XORSCHED_CACHE")
        xorsched.clear_schedule_cache()


def test_cache_keyed_by_tile_geometry():
    xorsched.clear_schedule_cache()
    m = gf8.parity_matrix(4, 2)
    a = xorsched.get_schedule(m, tile_sym=1024)
    b = xorsched.get_schedule(m, tile_sym=2048)
    assert a.tile_sym != b.tile_sym
    assert xorsched.schedule_cache_info()["size"] == 2
    xorsched.clear_schedule_cache()


# -- Encoder integration -----------------------------------------------------


def test_encoder_xorsched_equals_numpy_on_public_ops():
    e_x = rs_codec.Encoder(10, 4, backend="xorsched")
    e_n = rs_codec.Encoder(10, 4, backend="numpy")
    rng = np.random.default_rng(20)
    data = [rng.integers(0, 256, 4097, dtype=np.uint8) for _ in range(10)]
    sx, sn = e_x.encode(data), e_n.encode(data)
    assert all((a == b).all() for a, b in zip(sx, sn))
    shards = [None if i in (2, 11) else sx[i] for i in range(14)]
    rx = e_x.reconstruct(shards)
    assert all((rx[i] == sx[i]).all() for i in range(14))
    # delta-parity update rides the same dispatch
    parity = np.stack(sx[10:])
    old = data[3][100:200]
    new = (~old).astype(np.uint8)
    px = e_x.update_parity(parity[:, 100:200], 3, old, new)
    pn = e_n.update_parity(parity[:, 100:200], 3, old, new)
    assert (px == pn).all()
    # batched 3D stack (the streaming-pipeline shape)
    stack = np.stack([np.stack(data), np.stack(data)[:, ::-1]])
    assert (
        e_x._apply(e_x.parity_matrix, stack)
        == e_n._apply(e_n.parity_matrix, stack)
    ).all()


def test_dispatch_counter_ticks_xorsched_label():
    from seaweedfs_tpu import stats

    before = 0.0
    for line in stats.EcDispatchTotal.collect():
        if 'backend="xorsched"' in line:
            before = float(line.rsplit(" ", 1)[1])
    enc = rs_codec.Encoder(4, 2, backend="xorsched")
    enc.encode([np.zeros(64, dtype=np.uint8)] * 4)
    after = None
    for line in stats.EcDispatchTotal.collect():
        if 'backend="xorsched"' in line:
            after = float(line.rsplit(" ", 1)[1])
    assert after is not None and after >= before + 1


def test_stale_so_falls_back_to_interpreter(monkeypatch):
    """A libweedtpu.so predating the xorsched entry point must degrade to
    the numpy interpreter, never crash or mis-encode."""
    monkeypatch.setattr("seaweedfs_tpu.utils.native.load", lambda *a, **k: None)
    assert xorsched.native_available() is False
    assert xorsched.native_level() == "unavailable"
    m = gf8.parity_matrix(4, 2)
    prog = xorsched.compile_schedule(m)
    stack = np.arange(4 * 100, dtype=np.uint8).reshape(4, 100) % 251
    assert xorsched.apply_native(prog, list(stack)) is None
    got = np.stack(xorsched.apply_matrix(m, list(stack)))
    assert (got == gf8.gf_mat_vec(m, stack)).all()


def test_stripe_pipeline_rides_xorsched_byte_identical(tmp_path):
    """The streaming file pipelines (stripe._encode_rows via
    write_ec_files, rebuild_ec_files) must ride the xorsched backend
    unchanged and produce byte-identical shard files to the numpy path."""
    from seaweedfs_tpu.ec import stripe

    rng = np.random.default_rng(21)
    dat = rng.integers(0, 256, 123_457, dtype=np.uint8).tobytes()
    goldens = {}
    for backend in ("numpy", "xorsched"):
        base = str(tmp_path / f"v_{backend}")
        with open(base + ".dat", "wb") as f:
            f.write(dat)
        enc = rs_codec.Encoder(10, 4, backend=backend)
        stripe.write_ec_files(
            base, large_block_size=16384, small_block_size=4096,
            buffer_size=4096, encoder=enc, max_batch_bytes=10 * 3 * 4096,
        )
        goldens[backend] = [
            open(stripe.shard_file_name(base, s), "rb").read()
            for s in range(14)
        ]
        if backend == "xorsched":
            lost = [0, 5, 11]
            for s in lost:
                os.unlink(stripe.shard_file_name(base, s))
            assert stripe.rebuild_ec_files(base, encoder=enc) == lost
            for s in range(14):
                with open(stripe.shard_file_name(base, s), "rb") as f:
                    assert f.read() == goldens[backend][s], f"shard {s}"
    assert goldens["numpy"] == goldens["xorsched"]


# -- pick_cpu_backend: the decision table ------------------------------------


def _xor_evidence(when=None, host=None, xorsched_gbps=4.0, native_gbps=1.6,
                  match=True):
    import datetime

    return {
        "when": when or datetime.datetime.utcnow().strftime("%Y-%m-%dT%H:%MZ"),
        "host": host if host is not None else rs_codec._host_fingerprint(),
        "match": match,
        "encode": {"xorsched_gbps": xorsched_gbps, "native_gbps": native_gbps},
    }


def _write_bench(dirpath, xor, name="BENCH_r91.json"):
    with open(os.path.join(dirpath, name), "w", encoding="utf-8") as f:
        json.dump({"n": 91, "rc": 0, "parsed": {"xor": xor}}, f)


def test_winning_fresh_same_host_evidence_promotes(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(tmp_path, _xor_evidence())
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == "xorsched"
    assert "beats" in dec["reason"]
    assert dec["evidence_file"] == "BENCH_r91.json"
    assert dec["evidence_round"] == 91
    assert dec["xorsched_gbps"] == 4.0 and dec["native_gbps"] == 1.6


def test_absent_evidence_keeps_library_path(tmp_path):
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "no committed" in dec["reason"]


def test_stale_evidence_keeps_library_path(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(tmp_path, _xor_evidence(when="2020-01-01T00:00Z"))
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "stale" in dec["reason"]


def test_losing_evidence_keeps_library_path(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(tmp_path, _xor_evidence(xorsched_gbps=1.5, native_gbps=1.6))
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "does not beat" in dec["reason"]


def test_other_host_evidence_never_promotes(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(
        tmp_path, _xor_evidence(host={"cpu": "AMD EPYC 9999", "cores": 128})
    )
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "different host" in dec["reason"]


def test_unverified_evidence_never_promotes(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(tmp_path, _xor_evidence(match=False))
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "byte-verification" in dec["reason"]


def test_stale_so_blocks_promotion_even_on_winning_evidence(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: False)
    _write_bench(tmp_path, _xor_evidence())
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == rs_codec._cpu_backend()
    assert "weedtpu_xor_schedule_apply" in dec["reason"]


def test_rounds_without_xor_section_are_skipped_not_depromoting(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    _write_bench(tmp_path, _xor_evidence(), name="BENCH_r91.json")
    # a NEWER round measuring other subsystems must not hide the xor one
    with open(os.path.join(tmp_path, "BENCH_r92.json"), "w", encoding="utf-8") as f:
        json.dump({"n": 92, "rc": 0, "parsed": {"metric": "other"}}, f)
    backend, dec = rs_codec.pick_cpu_backend(art_dir=str(tmp_path))
    assert backend == "xorsched"
    assert dec["evidence_file"] == "BENCH_r91.json"


def test_new_encoder_auto_promotes_on_cpu_evidence(tmp_path, monkeypatch):
    monkeypatch.setattr(xorsched, "native_available", lambda: True)
    monkeypatch.setattr(rs_codec, "_multichip_dir", lambda: str(tmp_path))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    _write_bench(tmp_path, _xor_evidence())
    enc = rs_codec.new_encoder()
    assert enc.backend == "xorsched"
    assert enc.selection["source"] == "cpu-bench-evidence"
    assert enc.selection["evidence_round"] == 91


def test_new_encoder_auto_keeps_library_without_evidence(tmp_path, monkeypatch):
    monkeypatch.setattr(rs_codec, "_multichip_dir", lambda: str(tmp_path))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    enc = rs_codec.new_encoder()
    assert enc.backend == rs_codec._cpu_backend()
    assert enc.selection["source"] == "platform"


# -- knobs + bench smoke -----------------------------------------------------


def test_xorsched_knobs_registered():
    from seaweedfs_tpu.utils import config

    assert config.env("WEEDTPU_XORSCHED_TILE_KB") == 4
    assert config.env("WEEDTPU_XORSCHED_CACHE") == 64
    assert {"WEEDTPU_XORSCHED_TILE_KB", "WEEDTPU_XORSCHED_CACHE"} <= set(
        config.ENV_REGISTRY
    )


def test_bench_xor_smoke_deterministic():
    """The tier-1 gate the issue names: `BENCH_MODE=xor bench.py --smoke`
    byte-verifies all four matrix forms on both executors and emits a
    deterministic JSON (no timing fields, no timestamp)."""
    env = dict(os.environ, BENCH_MODE="xor", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        timeout=120,
    )
    out = None
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        if line.strip().startswith("{"):
            out = json.loads(line)
            break
    assert out is not None, "no JSON from the smoke child"
    assert out["ok"] is True and out["match"] is True
    assert all(out["verify"].values())
    assert "when" not in out, "smoke output must be timestamp-free"
    assert out["cache"]["size"] == 4  # one schedule per matrix form
