"""weedlint (seaweedfs_tpu.analysis) tests: every checker family fires on
its planted-violation fixture, the real tree stays clean in --strict
(the tier-1 CI gate, run exactly as CI runs it), suppression-comment
semantics, the env registry, and the dynamic lock-order recorder
(synthetic deadlock + real concurrent code staying acyclic)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from seaweedfs_tpu.analysis import PKG_ROOT, RULES, lockrec, run
from seaweedfs_tpu.analysis import graph as graph_mod
from seaweedfs_tpu.utils import config

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "weedlint_fixtures")


def fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_at(findings, path_suffix=None):
    return {
        (f.rule, f.line)
        for f in findings
        if path_suffix is None or f.path.endswith(path_suffix)
    }


# -- planted violations: every family must FIRE -------------------------------


def test_lock_order_cycle_fixture_fires():
    findings = run(paths=[fixture("lock_cycle.py")])
    cycles = [f for f in findings if f.rule == "lock-order-cycle"]
    assert len(cycles) >= 2, findings  # both edges of the a<->b cycle
    assert any("lock_a" in f.message and "lock_b" in f.message for f in cycles)


def test_lock_order_clean_when_consistent(tmp_path):
    src = (
        "import threading\n"
        "a = threading.Lock()\nb = threading.Lock()\n"
        "def one():\n    with a:\n        with b:\n            pass\n"
        "def two():\n    with a:\n        with b:\n            pass\n"
    )
    p = tmp_path / "consistent.py"
    p.write_text(src)
    findings = run(paths=[str(p)])
    assert not [f for f in findings if f.rule == "lock-order-cycle"]


def test_unlocked_global_write_fixture_fires():
    findings = run(paths=[fixture("unlocked_global.py")])
    hits = [f for f in findings if f.rule == "unlocked-global-write"]
    # the two unlocked writes in _refresh + the bound-method one in Worker
    assert len(hits) == 3, findings
    assert {f.line for f in hits} == {14, 15, 38}, hits


def test_donation_fixture_fires():
    findings = run(paths=[fixture("donation_bad.py")])
    sync = [f for f in findings if f.rule == "jit-host-sync"]
    donated = [f for f in findings if f.rule == "donated-buffer-read"]
    assert len(sync) == 3, findings  # np.asarray, print, block_until_ready
    assert len(donated) == 1, findings
    assert donated[0].line == 20  # staging.sum() after donation
    # run_rebound's re-binding must NOT be flagged (its reads are >= 24)
    assert all(f.line < 24 for f in donated)


def test_donation_shardmap_fixture_fires():
    """The mesh-backend shapes: a shard_map-decorated body is traced (host
    sync inside it fires), and names passed at donated positions of
    shard_map-wrapped jits — the `jax.jit(shard_map(f), donate_argnums=...)`
    binding AND the `@partial(jax.jit, donate_argnums=...)` decorator
    stack — are dead until re-bound."""
    findings = run(paths=[fixture("donation_shardmap.py")])
    sync = [f for f in findings if f.rule == "jit-host-sync"]
    donated = [f for f in findings if f.rule == "donated-buffer-read"]
    assert len(sync) == 1 and sync[0].line == 17, findings
    assert {f.line for f in donated} == {39, 45}, findings
    # run_rebound's re-binding must NOT be flagged (its reads are >= 49)
    assert all(f.line < 49 for f in donated)


def test_env_fixture_fires():
    findings = run(paths=[fixture("env_raw.py")])
    raw = [f for f in findings if f.rule == "env-raw-read"]
    unreg = [f for f in findings if f.rule == "env-unregistered"]
    assert len(raw) == 4, findings  # .get x2, getenv, subscript read
    unreg_names = " ".join(f.message for f in unreg)
    assert len(unreg) == 2, findings
    assert "WEEDTPU_NO_SUCH_KNOB" in unreg_names
    assert "WEEDTPU_XORSCHED_LRU" in unreg_names
    # writes and whole-env passthrough stay clean
    assert all(f.line <= 14 for f in raw), raw


def test_resource_fixture_fires():
    findings = run(paths=[fixture("resource_bad.py")])
    opens = [f for f in findings if f.rule == "open-no-ctx"]
    tmps = [f for f in findings if f.rule == "tmpfile-no-unlink"]
    assert len(opens) == 1 and opens[0].line == 10, findings
    assert len(tmps) == 1 and tmps[0].line == 15, findings


def test_wire_drift_fixture_fires():
    pkg = fixture("wiredrift_pkg")
    findings = run(
        paths=[os.path.join(pkg, "cluster", "server.py")], root=pkg
    )
    drift = [f for f in findings if f.rule == "wire-drift"]
    msgs = " | ".join(f.message for f in drift)
    assert "requester" in msgs, findings
    assert "extra" in msgs, findings
    # the singular typo of the repeated-projection shape fires too
    assert "projection_row" in msgs, findings
    # the inline-encode shapes: the mode-switch typo and the response-key
    # drift both fire
    assert "inlined" in msgs, findings
    assert "rows_inline" in msgs, findings
    # the geometry-conversion shapes: the code-family typo and the
    # byte-accounting response-key drift both fire
    assert "target_familly" in msgs, findings
    assert "bytes_wrote" in msgs, findings
    # the rebuild-batch fusion shapes: the fuse mode-switch typo and the
    # block-order response-key drift both fire
    assert "'fused'" in msgs, findings
    assert "blocks_order" in msgs, findings
    # the legitimate reads stay clean: req["volume_id"] (line 12), the
    # extended slab-read shape's projection/projection_rows (lines 17-18),
    # the inline mode-switch read req.get("inline") (line 31), the
    # convert shape's target_family/cutover reads (lines 46-47), and the
    # batch shape's volume_ids read (line 65) — and the good "mode"
    # (lines 33/49) and fusion-accounting response keys (lines 68-69) are
    # flagged only for their BAD sibling keys, never for themselves
    assert not any(f.line in (12, 17, 18, 31, 46, 47, 65) for f in drift), drift
    assert "returns key 'mode'" not in msgs, drift
    assert "returns key 'bytes_read'" not in msgs, drift
    assert "returns key 'dispatch_groups'" not in msgs, drift
    assert "returns key 'signature_groups'" not in msgs, drift


def test_parse_proto_oneof_fields_belong_to_message():
    from seaweedfs_tpu.analysis.wire_drift import parse_proto

    messages, _, methods = parse_proto(
        fixture(os.path.join("wiredrift_pkg", "pb", "contracts.proto"))
    )
    # oneof members are fields OF THE MESSAGE (a oneof in contracts.proto
    # must not produce phantom desc-drift findings)
    assert messages["DoThingResponse"] == {"ok", "detail", "code"}
    assert messages["DoThingRequest"] == {"volume_id", "collection"}
    assert methods["StreamThing"][0][2] is True  # stream response parsed
    # the extended slab-read fixture: repeated nested-message field parsed
    assert messages["SlabThingRequest"] == {
        "volume_id", "projection", "projection_rows"
    }
    assert messages["ProjTerm"] == {"shard_id", "coeffs"}
    # the inline-encode fixture shapes parse too
    assert messages["GenThingRequest"] == {
        "volume_id", "large_block_size", "inline"
    }
    assert messages["GenThingResponse"] == {
        "shard_ids", "mode", "inline_rows", "delta_updates"
    }
    # the rebuild-batch fusion fixture shapes parse too
    assert messages["BatchThingRequest"] == {"volume_ids", "fuse"}
    assert messages["BatchThingResponse"] == {
        "dispatch_groups", "signature_groups", "volumes_fused", "block_order"
    }


def test_obs_drift_fixture_fires():
    pkg = fixture("obsdrift_pkg")
    findings = run(
        paths=[os.path.join(pkg, "cluster", "server.py")], root=pkg
    )
    got = {(f.rule, os.path.basename(f.path), f.line) for f in findings}
    # planted: a metric-suffixed literal the fixture registry never
    # declared, and a span call site missing from the catalog
    assert ("obs-metric-undeclared", "server.py", 9) in got, findings
    assert ("obs-span-undeclared", "server.py", 18) in got, findings
    # planted: dead telemetry + a stale catalog entry, reported AT their
    # declaration sites in the registry/catalog files
    assert any(
        f.rule == "obs-metric-unused" and "weedtpu_orphan_total" in f.message
        and f.path.endswith(os.path.join("stats", "__init__.py"))
        for f in findings
    ), findings
    assert any(
        f.rule == "obs-span-unused" and "stale.span" in f.message
        for f in findings
    ), findings
    # the clean usages stay clean: the declared metric scraped by string
    # (line 8), the binding-name histogram use, the registered span, and
    # the suffix-less native symbol name (line 10, NOT a metric)
    msgs = " | ".join(f.message for f in findings)
    assert "weedtpu_good_total" not in msgs
    assert "weedtpu_bound_seconds" not in msgs
    assert "good.span" not in msgs
    assert "weedtpu_gf_native_symbol" not in msgs
    obs_rules = {f.rule for f in findings if f.rule.startswith("obs-")}
    assert obs_rules == {
        "obs-metric-undeclared", "obs-metric-unused",
        "obs-span-undeclared", "obs-span-unused",
    }


def test_obs_drift_real_tree_is_clean():
    """The real package's metric + span catalogs are drift-free — the
    same assertion CI makes, scoped to the obs-drift family."""
    findings = run()
    assert not [f for f in findings if f.rule.startswith("obs-")], [
        (f.path, f.line, f.message)
        for f in findings if f.rule.startswith("obs-")
    ]


# -- suppression semantics ----------------------------------------------------


def test_suppression_with_reason_suppresses():
    findings = run(paths=[fixture("suppressed.py")])
    opens = rules_at(findings, "suppressed.py")
    # properly_suppressed (line 6) must NOT appear
    assert ("open-no-ctx", 6) not in opens
    # missing reason: the open is suppressed but the pragma is flagged
    assert ("open-no-ctx", 11) not in opens
    assert ("bad-suppression", 11) in opens
    # unknown rule: pragma flagged AND the finding survives
    assert ("bad-suppression", 16) in opens
    assert ("open-no-ctx", 16) in opens


def test_unused_suppression_flagged_in_strict_only():
    loose = run(paths=[fixture("suppressed.py")], strict=False)
    assert not [f for f in loose if f.rule == "unused-suppression"]
    strict = run(paths=[fixture("suppressed.py")], strict=True)
    unused = [f for f in strict if f.rule == "unused-suppression"]
    assert len(unused) == 1 and unused[0].line == 20, strict


# -- the real tree is the clean-tree assertion (and the CI gate) --------------


def test_weedlint_strict_clean_tree_subprocess():
    """THE tier-1 gate: `python -m seaweedfs_tpu.analysis --strict` exits 0
    on the tree, within the <30 s runtime budget."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "seaweedfs_tpu.analysis", "--strict"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, f"weedlint strict failed:\n{proc.stdout}\n{proc.stderr}"
    assert wall < 30.0, f"weedlint took {wall:.1f}s — over the 30 s tier-1 budget"


def test_weedlint_changed_only_mode():
    proc = subprocess.run(
        [sys.executable, "-m", "seaweedfs_tpu.analysis", "--strict", "--changed-only"],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=ROOT,
    )
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"


def test_weedlint_exits_nonzero_on_findings():
    proc = subprocess.run(
        [sys.executable, "-m", "seaweedfs_tpu.analysis", fixture("resource_bad.py")],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=ROOT,
    )
    assert proc.returncode == 1
    assert "open-no-ctx" in proc.stdout


def test_every_rule_documented():
    # every rule a checker can emit is in the catalog the CLI prints and
    # BASELINE.md documents
    emitted = set()
    for name in os.listdir(FIXTURES):
        if name.endswith(".py"):
            emitted |= {f.rule for f in run(paths=[fixture(name)], strict=True)}
    assert emitted <= set(RULES)


# -- env registry -------------------------------------------------------------


def test_env_registry_types_and_clamps(monkeypatch):
    monkeypatch.delenv("WEEDTPU_PIPELINE_DEPTH", raising=False)
    assert config.env("WEEDTPU_PIPELINE_DEPTH") == 2
    monkeypatch.setenv("WEEDTPU_PIPELINE_DEPTH", "0")
    assert config.env("WEEDTPU_PIPELINE_DEPTH") == 1  # clamped
    monkeypatch.setenv("WEEDTPU_WIRE", "PROTO")
    assert config.env("WEEDTPU_WIRE") == "proto"
    monkeypatch.setenv("WEEDTPU_WIRE", "nonsense")
    assert config.env("WEEDTPU_WIRE") == "json"
    monkeypatch.setenv("WEEDTPU_LOCK_OBSERVE", "yes")
    assert config.env("WEEDTPU_LOCK_OBSERVE") is True
    with pytest.raises(KeyError):
        config.env("WEEDTPU_NOT_A_KNOB")


def test_every_weedtpu_literal_in_package_is_registered():
    """No WEEDTPU_* name may exist in package source without a registry
    entry — the completeness side of the env-registry family."""
    import re

    names = set()
    for dirpath, dirnames, filenames in os.walk(PKG_ROOT):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                    names |= set(re.findall(r"WEEDTPU_[A-Z][A-Z0-9_]*", f.read()))
    missing = names - set(config.ENV_REGISTRY)
    assert not missing, f"unregistered WEEDTPU_* names in package: {sorted(missing)}"


def test_readme_env_table_is_generated_and_current():
    readme = os.path.join(ROOT, "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    assert "<!-- weedlint:env-table:begin -->" in text
    table = config.env_table_markdown()
    assert table in text, (
        "README env table is stale — run "
        "`python -m seaweedfs_tpu.analysis --write-env-table`"
    )
    for name in config.ENV_REGISTRY:
        assert f"`{name}`" in table


# -- dynamic lock-order recorder ----------------------------------------------


def test_recorder_detects_synthetic_deadlock():
    import _thread

    rec = lockrec.LockOrderRecorder()
    # raw _thread locks, NOT threading.Lock(): under WEEDTPU_LOCK_OBSERVE
    # the session's global recorder wraps threading.Lock too, and this
    # test's deliberately-conflicting orders must not plant a cycle in
    # the session-wide graph the conftest gate asserts on
    a = lockrec._ObservedLock(_thread.allocate_lock(), "siteA", rec)
    b = lockrec._ObservedLock(_thread.allocate_lock(), "siteB", rec)

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    # run sequentially on two threads: no actual deadlock, but the orders
    # conflict — exactly what the recorder must catch BEFORE the unlucky
    # interleaving ships
    for fn in (order_ab, order_ba):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    cycles = rec.cycles()
    assert cycles == [["siteA", "siteB"]], rec.edges()
    assert "CYCLE" in rec.report()


def test_recorder_acyclic_on_consistent_order():
    import _thread

    rec = lockrec.LockOrderRecorder()
    a = lockrec._ObservedLock(_thread.allocate_lock(), "siteA", rec)
    b = lockrec._ObservedLock(_thread.allocate_lock(), "siteB", rec)
    for _ in range(3):
        with a:
            with b:
                pass
    assert rec.cycles() == []
    assert rec.edges() == {("siteA", "siteB"): 3}


def test_recorder_reentrant_rlock_no_self_edge():
    rec = lockrec.LockOrderRecorder()
    r = lockrec._ObservedLock(threading.RLock(), "siteR", rec)
    with r:
        with r:  # reentrant: orders nothing new
            pass
    assert rec.edges() == {}
    assert rec.cycles() == []


def test_recorder_condition_compat():
    """Observed locks must stay usable under threading.Condition (both
    Lock and RLock flavors — the package wraps conditions around both)."""
    rec = lockrec.LockOrderRecorder()
    for factory in (threading.Lock, threading.RLock):
        lock = lockrec._ObservedLock(factory(), f"site-{factory.__name__}", rec)
        cond = threading.Condition(lock)
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            hits.append(1)
            cond.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()


def test_recorder_observes_real_degraded_read(tmp_path):
    """Instrumented-lock mode on REAL code: install the recorder, exercise
    EcVolume's concurrent degraded-read ladder (suspect lock + fetch-pool
    lock + stats under load), and assert the observed package graph is
    acyclic — the in-process version of the tier-1 session gate."""
    import numpy as np

    from seaweedfs_tpu.ec import stripe
    from seaweedfs_tpu.ec.ec_volume import EcVolume
    from seaweedfs_tpu.ops.rs_codec import Encoder

    rng = np.random.default_rng(5)
    base = str(tmp_path / "v1")
    data = rng.integers(0, 256, size=64 * 10 * 3, dtype=np.uint8).tobytes()
    with open(base + ".dat", "wb") as f:
        f.write(data)
    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage import types

    idx_mod.write_entries([(1, types.offset_to_bytes(8), 100)], base + ".idx")
    enc = Encoder(10, 4, backend="numpy")
    stripe.write_ec_files(base, large_block_size=256, small_block_size=64,
                          buffer_size=64, encoder=enc)
    stripe.write_sorted_file_from_idx(base)

    # under WEEDTPU_LOCK_OBSERVE the session already installed the global
    # recorder: reuse it and DON'T uninstall (that would silently strip
    # instrumentation from the rest of the session)
    pre_installed = lockrec.active_recorder() is not None
    rec = lockrec.install()
    baseline = set(rec.edges())
    try:
        with EcVolume(base, encoder=enc, large_block_size=256,
                      small_block_size=64, warm_on_mount=False,
                      remote_reader=lambda s, o, n: None) as ev:
            for s in (0, 3, 7):
                ev.drop_local_shard(s)
            threads = [
                threading.Thread(target=ev.read_needle_blob, args=(1,))
                for _ in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    finally:
        if not pre_installed:
            lockrec.uninstall()
    assert rec.cycles(only_containing="seaweedfs_tpu") == []
    # the run must have actually observed SOMETHING (the gate is not
    # vacuous): new edges appeared during the degraded reads
    assert set(rec.edges()) - baseline or rec.edges()


def test_recorder_dump_roundtrip(tmp_path):
    rec = lockrec.LockOrderRecorder()
    rec.on_acquire("A")
    rec.on_acquire("B")
    rec.on_release("B")
    rec.on_release("A")
    out = tmp_path / "graph.json"
    rec.dump(str(out))
    payload = json.loads(out.read_text())
    assert payload["edges"] == [{"from": "A", "to": "B", "count": 1}]
    assert payload["cycles"] == []


def test_graph_cycle_detection():
    edges = graph_mod.edges_from_pairs([("a", "b"), ("b", "c"), ("c", "a"), ("c", "d")])
    assert graph_mod.cyclic_components(edges) == [["a", "b", "c"]]
    assert graph_mod.cyclic_components({"x": {"x"}}) == [["x"]]
    assert graph_mod.cyclic_components({"x": {"y"}}) == []


# -- durability family --------------------------------------------------------

DURABILITY_RULES = (
    "fsync-missing-before-rename",
    "record-before-fsync",
    "tmp-visible-name",
    "torn-tail-unhandled",
)


def test_durability_fixture_fires_each_rule_on_marked_line():
    """Every durability rule fires exactly on its `MARK <rule>` line in
    the planted fixture, and the good_* twins stay clean (exact-count
    check: no extra findings anywhere else in the file)."""
    findings = [
        f
        for f in run(paths=[fixture("durability_bad.py")])
        if f.rule in DURABILITY_RULES
    ]
    with open(fixture("durability_bad.py"), encoding="utf-8") as f:
        lines = f.read().splitlines()
    expected = {
        (rule, i + 1)
        for i, line in enumerate(lines)
        for rule in DURABILITY_RULES
        if f"MARK {rule}" in line
    }
    assert len(expected) == len(DURABILITY_RULES)
    assert {(f.rule, f.line) for f in findings} == expected, findings


def test_durability_rules_in_catalog():
    for rule in DURABILITY_RULES:
        assert rule in RULES


# -- per-file parse cache -----------------------------------------------------


def test_parse_cache_reuses_context_and_invalidates(tmp_path):
    """load_files returns the SAME FileContext for an unchanged file (one
    ast.parse per file per CI run, not per checker invocation) and
    re-parses when content changes."""
    from seaweedfs_tpu.analysis import load_files

    p = tmp_path / "m.py"
    p.write_text("import json\nx = 1\n")
    (a,), _ = load_files([str(p)])
    (b,), _ = load_files([str(p)])
    assert a is b
    p.write_text("import json\nxx = 22  # longer\n")
    (c,), _ = load_files([str(p)])
    assert c is not b


def test_parse_cache_resets_suppression_state():
    """A cached FileContext is shared across runs; suppression used-flags
    must reset on reuse or the second strict run would mis-report
    unused-suppression findings."""
    first = {(f.rule, f.line) for f in run(paths=[fixture("suppressed.py")], strict=True)}
    second = {(f.rule, f.line) for f in run(paths=[fixture("suppressed.py")], strict=True)}
    assert first == second
