"""Filer tests — namespace store semantics (memory + sqlite parity), Filer
CRUD/rename/recursive-delete + metadata events, and in-process integration
with a real master + volume server (HTTP upload/read/Range, chunking,
FilerClient RPC) — the reference's filer store tests + loopback pattern
(SURVEY.md §4)."""

import io
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer import (
    Attributes,
    Entry,
    FileChunk,
    Filer,
    FilerClient,
    FilerServer,
    MemoryStore,
    SqliteStore,
)
from seaweedfs_tpu.filer.store import EntryNotFound


# -- store parity -------------------------------------------------------------


def _stores(tmp_path):
    from seaweedfs_tpu.filer.bucketstore import BucketedLogStore
    from seaweedfs_tpu.filer.logstore import LogFilerStore

    return [
        MemoryStore(),
        SqliteStore(str(tmp_path / "f.db")),
        LogFilerStore(str(tmp_path / "lg")),
        BucketedLogStore(str(tmp_path / "lg3")),
    ]


def test_store_crud_and_listing(tmp_path):
    for store in _stores(tmp_path):
        e = Entry(path="/a/b/hello.txt", attributes=Attributes(mtime=1.0))
        store.insert(Entry(path="/a", is_directory=True))
        store.insert(Entry(path="/a/b", is_directory=True))
        store.insert(e)
        got = store.find("/a/b/hello.txt")
        assert got.path == "/a/b/hello.txt" and not got.is_directory
        with pytest.raises(EntryNotFound):
            store.find("/a/b/missing")
        # listing is lexicographic, supports start_from/prefix/limit
        for n in ("z.txt", "m.txt", "aa.txt"):
            store.insert(Entry(path=f"/a/b/{n}"))
        names = [x.name for x in store.list("/a/b")]
        assert names == sorted(names)
        assert [x.name for x in store.list("/a/b", prefix="a")] == ["aa.txt"]
        page1 = store.list("/a/b", limit=2)
        page2 = store.list("/a/b", start_from=page1[-1].name, limit=10)
        assert [x.name for x in page1 + page2] == names
        store.delete("/a/b/z.txt")
        assert "z.txt" not in [x.name for x in store.list("/a/b")]
        store.delete_folder_children("/a")
        assert store.list("/a") == []
        store.kv_put("k1", b"v1")
        assert store.kv_get("k1") == b"v1"
        store.kv_delete("k1")
        assert store.kv_get("k1") is None
        store.close()


def test_sqlite_store_persists(tmp_path):
    db = str(tmp_path / "p.db")
    s = SqliteStore(db)
    s.insert(Entry(path="/x", is_directory=True))
    s.insert(Entry(path="/x/f", attributes=Attributes(mtime=2.0)))
    s.close()
    s2 = SqliteStore(db)
    assert s2.find("/x/f").attributes.mtime == 2.0
    s2.close()


# -- filer core (no cluster) --------------------------------------------------


def test_filer_mkdirs_create_delete_rename():
    f = Filer(MemoryStore())
    events = []
    f.create_entry(Entry(path="/d1/d2/file", attributes=Attributes(mtime=1.0)))
    # implicit parents exist and are directories
    assert f.find_entry("/d1").is_directory
    assert f.find_entry("/d1/d2").is_directory
    # o_excl
    with pytest.raises(FileExistsError):
        f.create_entry(Entry(path="/d1/d2/file"), o_excl=True)
    # non-empty dir needs recursive
    with pytest.raises(OSError):
        f.delete_entry("/d1")
    f.rename("/d1/d2/file", "/d1/renamed")
    assert f.exists("/d1/renamed") and not f.exists("/d1/d2/file")
    f.delete_entry("/d1", recursive=True)
    assert not f.exists("/d1")
    # events were recorded for every mutation
    evs = list(f.subscribe(since_ns=0, stop=None))
    assert len(evs) >= 5


def test_filer_refuses_file_over_directory():
    f = Filer(MemoryStore())
    f.create_entry(Entry(path="/d/child"))
    with pytest.raises(IsADirectoryError):
        f.create_entry(Entry(path="/d"))
    f.create_entry(Entry(path="/plain"))
    with pytest.raises(IsADirectoryError):
        f.rename("/plain", "/d")
    assert f.exists("/d/child")


def test_filer_rename_subtree():
    f = Filer(MemoryStore())
    for p in ("/src/a/f1", "/src/a/f2", "/src/f3"):
        f.create_entry(Entry(path=p))
    f.rename("/src", "/dst")
    assert {e.path for e in f.walk("/dst")} == {
        "/dst/a", "/dst/a/f1", "/dst/a/f2", "/dst/f3",
    }
    assert not f.exists("/src")


def test_filer_meta_log_resume(tmp_path):
    f = Filer(MemoryStore(), log_dir=str(tmp_path))
    f.create_entry(Entry(path="/one"))
    f.create_entry(Entry(path="/two"))
    f.close()
    # a fresh Filer over the same log dir replays events from disk
    f2 = Filer(MemoryStore(), log_dir=str(tmp_path))
    evs = f2._read_log_since(0)
    paths = [e.new_entry["path"] for e in evs if e.new_entry]
    assert "/one" in paths and "/two" in paths
    f2.close()


# -- integration with the volume tier ----------------------------------------


@pytest.fixture
def stack(tmp_path):
    """master + volume server + filer server on loopback."""
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "vol"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.4)
    vs.start()
    fs = FilerServer(master.address, chunk_size=1024, log_dir=str(tmp_path / "meta"))
    fs.start()
    yield master, vs, fs
    fs.stop()
    vs.stop()
    master.stop()


def _http(method, url, data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_filer_http_roundtrip(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    payload = os.urandom(5000)  # > chunk_size=1024 -> multiple chunks
    code, _, body = _http("PUT", base + "/docs/report.bin", payload,
                          {"Content-Type": "application/x-bin"})
    assert code == 201, body
    meta = json.loads(body)
    assert meta["size"] == len(payload)
    entry = fs.filer.find_entry("/docs/report.bin")
    assert len(entry.chunks) == 5  # 5000 / 1024 -> 5 chunks
    code, headers, got = _http("GET", base + "/docs/report.bin")
    assert code == 200 and got == payload
    assert headers["Content-Type"] == "application/x-bin"
    # range read
    code, headers, got = _http("GET", base + "/docs/report.bin",
                               headers={"Range": "bytes=1000-2999"})
    assert code == 206 and got == payload[1000:3000]
    assert headers["Content-Range"] == f"bytes 1000-2999/{len(payload)}"
    # suffix range
    code, _, got = _http("GET", base + "/docs/report.bin",
                         headers={"Range": "bytes=-100"})
    assert code == 206 and got == payload[-100:]
    # directory listing
    code, _, body = _http("GET", base + "/docs")
    listing = json.loads(body)
    assert [e["path"] for e in listing["Entries"]] == ["/docs/report.bin"]
    # overwrite reclaims old chunks
    code, _, _ = _http("PUT", base + "/docs/report.bin", b"tiny")
    assert code == 201
    _, _, got = _http("GET", base + "/docs/report.bin")
    assert got == b"tiny"
    # delete
    code, _, _ = _http("DELETE", base + "/docs/report.bin")
    assert code == 204
    code, _, _ = _http("GET", base + "/docs/report.bin")
    assert code == 404


def test_filer_http_rename_and_mkdir(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    _http("PUT", base + "/a/x.txt", b"hello")
    code, _, _ = _http("POST", base + "/b/y.txt?mv.from=/a/x.txt", b"")
    assert code == 200
    code, _, got = _http("GET", base + "/b/y.txt")
    assert code == 200 and got == b"hello"
    code, _, _ = _http("PUT", base + "/newdir/?op=mkdir", b"")
    assert code == 201
    assert fs.filer.find_entry("/newdir").is_directory


def test_filer_client_rpc(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    _http("PUT", base + "/rpc/data.bin", b"x" * 3000)
    with FilerClient(fs.grpc_address) as fc:
        e = fc.lookup("/rpc/data.bin")
        assert e is not None and e.size == 3000
        assert fc.lookup("/rpc/missing") is None
        assert fc.read_file("/rpc/data.bin") == b"x" * 3000
        assert [x.name for x in fc.list("/rpc")] == ["data.bin"]
        fc.rename("/rpc/data.bin", "/rpc/renamed.bin")
        assert fc.lookup("/rpc/renamed.bin") is not None
        fc.kv_put("mark", b"v")
        assert fc.kv_get("mark") == b"v"
        fc.delete("/rpc", recursive=True)
        assert fc.lookup("/rpc") is None


def test_filer_subscribe_stream(stack):
    _, _, fs = stack
    base = f"http://{fs.url}"
    seen = []
    done = threading.Event()

    def tail():
        with FilerClient(fs.grpc_address) as fc:
            for ev in fc.subscribe(since_ns=0, max_idle_s=3.0):
                if ev.new_entry:
                    seen.append(ev.new_entry["path"])
                if "/sub/a.txt" in seen and "/sub/b.txt" in seen:
                    break
        done.set()

    t = threading.Thread(target=tail, daemon=True)
    t.start()
    time.sleep(0.2)
    _http("PUT", base + "/sub/a.txt", b"1")
    _http("PUT", base + "/sub/b.txt", b"2")
    assert done.wait(10.0)
    assert "/sub/a.txt" in seen and "/sub/b.txt" in seen


def test_chunk_manifest_roundtrip(stack):
    """Long chunk lists fold into manifest chunks and resolve on read."""
    _, _, fs = stack
    import seaweedfs_tpu.filer.chunks as chunks_mod

    old = chunks_mod.MANIFEST_BATCH
    chunks_mod.MANIFEST_BATCH = 3
    try:
        payload = os.urandom(1024 * 8)  # 8 chunks > batch of 3
        entry = fs.write_file("/mani/big.bin", io.BytesIO(payload))
        assert any(c.is_chunk_manifest for c in entry.chunks)
        assert len(entry.chunks) < 8
        assert fs.read_file(entry) == payload
        # deleting the entry reclaims manifest + data needles
        fs.filer.delete_entry("/mani/big.bin")
    finally:
        chunks_mod.MANIFEST_BATCH = old


# -- log-structured store engine (leveldb2-analog) ---------------------------


def test_logkv_persistence_and_torn_tail(tmp_path):
    from seaweedfs_tpu.filer.logstore import LogKv

    p = str(tmp_path / "kv" / "filer.log")
    kv = LogKv(p)
    for i in range(50):
        kv.put(f"k{i:03d}".encode(), f"value-{i}".encode() * 3)
    kv.delete(b"k010")
    kv.put(b"k011", b"updated")
    kv.close()

    # reopen: replay rebuilds exactly the surviving state
    kv2 = LogKv(p)
    assert kv2.get(b"k010") is None
    assert kv2.get(b"k011") == b"updated"
    assert kv2.get(b"k049") == b"value-49" * 3
    assert len(kv2) == 49
    kv2.close()

    # torn tail: append garbage + half a record -> replay truncates, data intact
    with open(p, "ab") as f:
        f.write(b"\x01\x02\x03half-a-record")
    kv3 = LogKv(p)
    assert len(kv3) == 49 and kv3.get(b"k011") == b"updated"
    kv3.put(b"after", b"torn-tail-write")  # log still appendable
    kv3.close()
    assert LogKv(p).get(b"after") == b"torn-tail-write"


def test_logkv_compaction_reclaims_dead_bytes(tmp_path):
    import os

    from seaweedfs_tpu.filer.logstore import LogKv

    p = str(tmp_path / "kv" / "filer.log")
    kv = LogKv(p, compact_ratio=100.0)  # disable auto-compaction for the test
    blob = b"x" * 4096
    for round_ in range(20):  # rewrite the same keys -> mostly dead log
        for i in range(16):
            kv.put(f"k{i}".encode(), blob + str(round_).encode())
    size_before = os.path.getsize(p)
    kv.compact()
    size_after = os.path.getsize(p)
    assert size_after < size_before / 4, (size_before, size_after)
    for i in range(16):
        assert kv.get(f"k{i}".encode()) == blob + b"19"
    kv.close()
    kv2 = LogKv(p)  # compacted log replays clean
    assert len(kv2) == 16
    kv2.close()


def test_log_filer_store_persists_namespace(tmp_path):
    from seaweedfs_tpu.filer.logstore import LogFilerStore

    d = str(tmp_path / "lgp")
    st = LogFilerStore(d)
    st.insert(Entry(path="/docs", is_directory=True))
    st.insert(Entry(path="/docs/a.txt"))
    st.insert(Entry(path="/docs/b.txt"))
    st.kv_put("bookkeeping", b"\x01\x02")
    st.close()
    st2 = LogFilerStore(d)
    assert [e.name for e in st2.list("/docs")] == ["a.txt", "b.txt"]
    assert st2.kv_get("bookkeeping") == b"\x01\x02"
    st2.close()


# -- transactions -------------------------------------------------------------


def test_sqlite_transaction_rollback_and_batch(tmp_path):
    st = SqliteStore(str(tmp_path / "t.db"))
    st.insert(Entry(path="/keep.txt"))
    with pytest.raises(RuntimeError):
        with st.transaction():
            st.insert(Entry(path="/doomed1.txt"))
            st.insert(Entry(path="/doomed2.txt"))
            raise RuntimeError("abort")
    with pytest.raises(EntryNotFound):
        st.find("/doomed1.txt")
    with pytest.raises(EntryNotFound):
        st.find("/doomed2.txt")
    assert st.find("/keep.txt").name == "keep.txt"

    st.insert_batch([Entry(path=f"/b{i}.txt") for i in range(10)])
    assert len(st.list("/", prefix="b")) == 10
    st.close()


def test_filer_rename_subtree_is_transactional(tmp_path):
    """A store failure mid-subtree-rename must leave the namespace at the
    ORIGINAL paths on a transactional store (no half-moved tree)."""
    from seaweedfs_tpu.filer.filer import Filer

    st = SqliteStore(str(tmp_path / "r.db"))
    f = Filer(st, None)
    f.mkdirs("/src/sub")
    for n in ("a", "b", "c"):
        f.create_entry(Entry(path=f"/src/sub/{n}.txt"))

    calls = {"n": 0}
    orig_insert = st.insert

    def failing_insert(entry):
        calls["n"] += 1
        if calls["n"] == 3:  # blow up mid-move
            raise IOError("disk full")
        orig_insert(entry)

    st.insert = failing_insert
    events_before = len(f._events)
    with pytest.raises(IOError):
        f.rename("/src", "/dst")
    st.insert = orig_insert
    # rollback: everything still at the source, nothing at the destination
    assert {e.name for e in st.list("/src/sub")} == {"a.txt", "b.txt", "c.txt"}
    with pytest.raises(EntryNotFound):
        st.find("/dst")
    # and NO phantom rename events escaped to subscribers/replicators
    assert len(f._events) == events_before, "rolled-back rename leaked events"
    # a successful rename emits its (deferred) events after commit
    f.rename("/src", "/dst2")
    assert len(f._events) > events_before
    assert {e.name for e in st.list("/dst2/sub")} == {"a.txt", "b.txt", "c.txt"}
    st.close()


def test_sqlite_transaction_blocks_other_writers(tmp_path):
    """A KvPut landing mid-transaction from another thread must not be
    swallowed into (and rolled back with) the transaction."""
    import threading as _th
    import time as _t

    st = SqliteStore(str(tmp_path / "iso.db"))
    done = _th.Event()

    def other_writer():
        st.kv_put("other", b"acknowledged")  # blocks until the txn ends
        done.set()

    with pytest.raises(RuntimeError):
        with st.transaction():
            st.insert(Entry(path="/doomed.txt"))
            t = _th.Thread(target=other_writer, daemon=True)
            t.start()
            _t.sleep(0.2)
            assert not done.is_set(), "writer slipped into the open txn"
            raise RuntimeError("abort")
    assert done.wait(5), "writer never unblocked after rollback"
    # the other thread's acknowledged write survived the rollback
    assert st.kv_get("other") == b"acknowledged"
    with pytest.raises(EntryNotFound):
        st.find("/doomed.txt")
    st.close()


# -- chunk cache (weed/util/chunk_cache analog) -------------------------------


def test_chunk_cache_lru_and_tiers(tmp_path):
    from seaweedfs_tpu.utils.chunk_cache import ChunkCache

    cc = ChunkCache(memory_bytes=10_000, max_item_bytes=6_000,
                    disk_dir=str(tmp_path / "cc"), disk_bytes=50_000)
    cc.put("1,aa", b"x" * 4000)
    cc.put("2,bb", b"y" * 4000)
    assert cc.get("1,aa") == b"x" * 4000  # refreshes LRU position
    cc.put("3,cc", b"z" * 4000)  # budget 10k: evicts 2,bb from memory
    assert cc.memory_bytes_used <= 10_000
    assert cc.get("2,bb") == b"y" * 4000  # disk tier still has it (promoted)
    # oversized items bypass the cache entirely
    cc.put("4,dd", b"w" * 7000)
    assert cc.get("4,dd") is None
    # delete evicts every tier
    cc.delete("1,aa")
    cc.clear()
    assert cc.get("1,aa") is None
    assert cc.hits >= 2 and cc.misses >= 2


def test_chunk_cache_serves_filer_rereads(stack):
    """Re-reading the same file must hit the cache, not the volume tier."""
    _, _, fs = stack
    payload = os.urandom(4000)
    _http("PUT", f"http://{fs.url}/cached/file.bin", payload)
    _http("GET", f"http://{fs.url}/cached/file.bin")  # populate
    cache = fs.chunk_io.cache
    h0 = cache.hits
    reads = {"n": 0}
    orig = fs.chunk_io.master.read

    def counting_read(fid):
        reads["n"] += 1
        return orig(fid)

    fs.chunk_io.master.read = counting_read
    _, _, got = _http("GET", f"http://{fs.url}/cached/file.bin")
    fs.chunk_io.master.read = orig
    assert got == payload
    assert reads["n"] == 0, "re-read went to the volume tier despite cache"
    assert cache.hits > h0


def test_filer_conf_matches_on_segment_boundaries():
    """A rule stored without a trailing slash ('/buckets/logs') must govern
    its subtree only — raw startswith would also hit the sibling
    '/buckets/logs2/x' and apply collection/TTL/read-only policy to the
    wrong tree (r4 advisor finding)."""
    from seaweedfs_tpu.filer.filer_conf import FilerConf, PathConf

    conf = FilerConf()
    conf.upsert(PathConf(location_prefix="/buckets/logs", collection="logs"))
    conf.upsert(PathConf(location_prefix="/buckets/logs/hot/", ttl="1d"))
    assert conf.match("/buckets/logs").collection == "logs"
    assert conf.match("/buckets/logs/a.txt").collection == "logs"
    assert conf.match("/buckets/logs/hot/x").ttl == "1d"  # longest wins
    assert conf.match("/buckets/logs2/x") is None
    assert conf.match("/buckets/logsx") is None
    # a root rule still matches everything
    conf.upsert(PathConf(location_prefix="/", replication="001"))
    assert conf.match("/anything").replication == "001"
    assert conf.match("/buckets/logs/a.txt").collection == "logs"


def test_filer_readonly_rule_respects_segment_boundaries():
    """'/frozen' read-only must not freeze writes under '/frozen2'."""
    import pytest as _pytest

    from seaweedfs_tpu.filer.filer import Entry, Filer
    from seaweedfs_tpu.filer.filer_conf import PathConf
    from seaweedfs_tpu.filer.store import MemoryStore

    f = Filer(MemoryStore())
    f.path_conf.upsert(PathConf(location_prefix="/frozen", read_only=True))
    with _pytest.raises(PermissionError):
        f.create_entry(Entry(path="/frozen/a"))
    with _pytest.raises(PermissionError):
        f.create_entry(Entry(path="/frozen"))
    f.create_entry(Entry(path="/frozen2/a"))  # sibling stays writable
    assert f.find_entry("/frozen2/a")


def test_logkv_crash_before_compaction_swap_loses_nothing(tmp_path, monkeypatch):
    """Kill-during-compaction, before the atomic swap: the original log is
    still the database; a stray .compact must be ignored AND not corrupt a
    later reopen or compaction."""
    import os as _os

    from seaweedfs_tpu.filer.logstore import LogKv

    p = str(tmp_path / "kv.log")
    kv = LogKv(p)
    data = {f"k{i}".encode(): _os.urandom(50) for i in range(40)}
    for k, v in data.items():
        kv.put(k, v)
    for i in range(0, 40, 2):  # dead weight so compact() has work
        kv.put(f"k{i}".encode(), data[f"k{i}".encode()] + b"v2")
        data[f"k{i}".encode()] += b"v2"

    real_replace = _os.replace

    def boom(src, dst):
        raise OSError("killed mid-swap")

    monkeypatch.setattr(_os, "replace", boom)
    with pytest.raises(OSError):
        kv.compact()
    monkeypatch.setattr(_os, "replace", real_replace)
    # the partial .compact exists; the original log was never replaced
    assert _os.path.exists(p + ".compact")
    re1 = LogKv(p)
    assert {k: re1.get(k) for k in data} == data
    # and a successful compaction afterwards still converges
    re1.compact()
    re1.close()
    re2 = LogKv(p)
    assert {k: re2.get(k) for k in data} == data
    # the successful compaction renamed the staging file into place
    assert not _os.path.exists(p + ".compact")
    re2.close()


def test_logkv_compaction_fsyncs_before_swap(tmp_path, monkeypatch):
    """Swap ordering: the .compact file must be fsynced BEFORE os.replace
    makes it the database — replace-then-sync can surface an empty or
    partial log after power loss."""
    import os as _os

    from seaweedfs_tpu.filer.logstore import LogKv

    p = str(tmp_path / "kv.log")
    kv = LogKv(p)
    for i in range(30):
        kv.put(f"k{i}".encode(), b"x" * 64)
        kv.put(f"k{i}".encode(), b"y" * 64)  # garbage to compact

    calls = []
    real_fsync, real_replace = _os.fsync, _os.replace
    monkeypatch.setattr(_os, "fsync", lambda fd: (calls.append("fsync"), real_fsync(fd))[1])
    monkeypatch.setattr(
        _os, "replace", lambda a, b: (calls.append("replace"), real_replace(a, b))[1]
    )
    kv.compact()
    assert "replace" in calls and "fsync" in calls
    assert calls.index("fsync") < calls.index("replace"), calls
    kv.close()


def test_logkv_random_killpoint_fuzz_is_prefix_consistent(tmp_path):
    """Crash anywhere = the on-disk log is some byte prefix of the op
    stream. Reopening must (a) never raise, (b) truncate to a record
    boundary, and (c) land EXACTLY on the state after some prefix of the
    acknowledged ops — no resurrected deletes, no half-applied values."""
    import os as _os
    import random

    from seaweedfs_tpu.filer.logstore import LogKv

    rng = random.Random(1234)
    for trial in range(12):
        p = str(tmp_path / f"fuzz{trial}.log")
        kv = LogKv(p)
        snapshots = [dict()]  # state after k ops
        model: dict[bytes, bytes] = {}
        for _ in range(rng.randrange(5, 40)):
            k = f"key{rng.randrange(8)}".encode()
            if rng.random() < 0.25 and model:
                kv.delete(k)
                model.pop(k, None)
            else:
                v = _os.urandom(rng.randrange(1, 80))
                kv.put(k, v)
                model[k] = v
            snapshots.append(dict(model))
        kv.close()
        size = _os.path.getsize(p)
        cut = rng.randrange(0, size + 1)  # the crash point
        with open(p, "r+b") as f:
            f.truncate(cut)
        re = LogKv(p)  # must not raise
        state = {k: re.get(k) for k in re.keys()}
        assert state in snapshots, (
            f"trial {trial}: post-crash state matches no op prefix "
            f"(cut {cut}/{size})"
        )
        # the torn tail was truncated: a fresh append must be readable
        re.put(b"after", b"crash")
        re.close()
        re2 = LogKv(p)
        assert re2.get(b"after") == b"crash"
        re2.close()


def test_log_filer_store_reopen_invariants_after_kill(tmp_path):
    """FilerStore-level crash check: after a mid-stream kill (simulated by
    truncating the backing log), every name the reopened store LISTS must
    also FIND, directories stay listable, and the kv facet stays
    readable — the namespace is consistent even if recent ops vanished."""
    import os as _os
    import random

    from seaweedfs_tpu.filer.entry import Entry
    from seaweedfs_tpu.filer.logstore import LogFilerStore

    rng = random.Random(99)
    for trial in range(6):
        d = tmp_path / f"st{trial}"
        d.mkdir()
        st = LogFilerStore(str(d))
        for i in range(30):
            dir_i = f"/d{rng.randrange(4)}"
            st.insert(Entry(path=dir_i, is_directory=True))
            st.insert(Entry(path=f"{dir_i}/f{i}.txt"))
            if rng.random() < 0.2:
                st.kv_put(f"conf{i}", b"v" * i)
            if rng.random() < 0.15:
                victims = st.list(dir_i, limit=5)
                if victims:
                    st.delete(victims[0].path)
        st.close()
        log = _os.path.join(str(d), "filer.log")
        size = _os.path.getsize(log)
        with open(log, "r+b") as f:
            f.truncate(rng.randrange(0, size + 1))
        re = LogFilerStore(str(d))
        # exercise the raw name index, not list() (which silently drops
        # names find() can't back): every name the rebuilt _dirs knows
        # must have a live record, or the namespace diverged from the log
        import posixpath as _pp

        for sub, names in re._dirs.items():
            for name in names:
                assert re.find(_pp.join(sub, name)).name == name
        re.close()


def test_bucketed_store_routes_and_isolates(tmp_path):
    """leveldb3-analog semantics: each /buckets/<name> subtree lives in
    its own shard directory, non-bucket paths and the KV facet in the
    default store, and listings stitch both views together."""
    import os as _os

    from seaweedfs_tpu.filer.bucketstore import BucketedLogStore
    from seaweedfs_tpu.filer.entry import Entry
    from seaweedfs_tpu.filer.filer import Filer

    st = BucketedLogStore(str(tmp_path))
    f = Filer(st)
    f.create_entry(Entry(path="/buckets", is_directory=True))
    for b in ("alpha", "beta"):
        f.create_entry(Entry(path=f"/buckets/{b}", is_directory=True))
        f.create_entry(Entry(path=f"/buckets/{b}/obj.txt"))
        f.create_entry(Entry(path=f"/buckets/{b}/dir", is_directory=True))
        f.create_entry(Entry(path=f"/buckets/{b}/dir/deep.txt"))
    f.create_entry(Entry(path="/plain", is_directory=True))
    f.create_entry(Entry(path="/plain/file.txt"))
    st.kv_put("identities", b"kvdata")

    # physical separation on disk
    assert _os.path.exists(tmp_path / "buckets" / "alpha" / "filer.log")
    assert _os.path.exists(tmp_path / "buckets" / "beta" / "filer.log")
    assert _os.path.exists(tmp_path / "default" / "filer.log")
    # routing round-trips
    assert f.find_entry("/buckets/alpha/dir/deep.txt").name == "deep.txt"
    assert f.find_entry("/plain/file.txt").name == "file.txt"
    assert sorted(e.name for e in st.list("/buckets")) == ["alpha", "beta"]
    assert st.kv_get("identities") == b"kvdata"
    st.close()

    # reopen: shards rediscovered from the directory layout
    re = BucketedLogStore(str(tmp_path))
    f2 = Filer(re)
    assert f2.find_entry("/buckets/beta/obj.txt").name == "obj.txt"
    assert sorted(e.name for e in re.list("/buckets")) == ["alpha", "beta"]

    # deleting a bucket subtree unlinks its shard wholesale
    f2.delete_entry("/buckets/alpha", recursive=True, delete_chunks=False)
    assert not _os.path.exists(tmp_path / "buckets" / "alpha")
    assert [e.name for e in re.list("/buckets")] == ["beta"]
    import pytest as _pytest

    from seaweedfs_tpu.filer.store import EntryNotFound

    with _pytest.raises(EntryNotFound):
        re.find("/buckets/alpha/obj.txt")
    # the other bucket and the flat namespace are untouched
    assert f2.find_entry("/buckets/beta/dir/deep.txt").name == "deep.txt"
    assert f2.find_entry("/plain/file.txt").name == "file.txt"
    re.close()


def test_bucketed_store_rename_across_buckets(tmp_path):
    """A bucket-root rename migrates every entry into the target shard
    and drops the emptied source shard."""
    import os as _os

    from seaweedfs_tpu.filer.bucketstore import BucketedLogStore
    from seaweedfs_tpu.filer.entry import Entry
    from seaweedfs_tpu.filer.filer import Filer

    st = BucketedLogStore(str(tmp_path))
    f = Filer(st)
    f.create_entry(Entry(path="/buckets/src", is_directory=True))
    f.create_entry(Entry(path="/buckets/src/a.txt"))
    f.create_entry(Entry(path="/buckets/src/sub", is_directory=True))
    f.create_entry(Entry(path="/buckets/src/sub/b.txt"))
    f.rename("/buckets/src", "/buckets/dst")
    assert f.find_entry("/buckets/dst/sub/b.txt").name == "b.txt"
    assert _os.path.exists(tmp_path / "buckets" / "dst" / "filer.log")
    assert not _os.path.exists(tmp_path / "buckets" / "src")
    assert [e.name for e in st.list("/buckets")] == ["dst"]
    st.close()


def test_filer_html_directory_browsing(stack):
    """Browsers (Accept: text/html) get a navigable HTML listing with
    escaped names; API clients keep their JSON."""
    _, _, fs = stack
    base = f"http://{fs.url}"
    _http("PUT", base + "/web/sub/", None)
    _http("PUT", base + "/web/a.txt", b"x")
    evil = "/web/%3Cb%3Ename.txt"  # stored name contains <b>
    _http("PUT", base + evil, b"y")
    code, headers, body = _http(
        "GET", base + "/web", headers={"Accept": "text/html,application/xhtml+xml"}
    )
    assert code == 200 and headers["Content-Type"].startswith("text/html")
    assert b"<table>" in body and b'href="/web/a.txt"' in body
    assert b"sub/" in body
    assert b"<b>name" not in body and b"&lt;b&gt;name.txt" in body  # escaped
    # JSON unchanged for API clients
    code, headers, body = _http("GET", base + "/web")
    assert headers["Content-Type"].startswith("application/json")
    assert b'"Entries"' in body


def test_bucketed_store_root_discovery_and_html_pagination(tmp_path, stack):
    """(1) /buckets must be a REAL entry discoverable from a root walk on
    the log3 store; (2) the HTML listing paginates instead of presenting
    a truncated view as complete."""
    from seaweedfs_tpu.filer.bucketstore import BucketedLogStore
    from seaweedfs_tpu.filer.filer import Filer as _Filer

    st = BucketedLogStore(str(tmp_path / "disc"))
    f = _Filer(st)
    f.create_entry(Entry(path="/buckets/bb", is_directory=True))
    assert "/buckets" in {e.path for e in st.list("/")}, "root walk must see /buckets"
    st.close()

    _, _, fs = stack
    base = f"http://{fs.url}"
    for i in range(5):
        _http("PUT", base + f"/pagedir/f{i:02d}.txt", b"x")
    code, _, body = _http(
        "GET", base + "/pagedir", headers={"Accept": "text/html"},
    )
    assert b"5 entries" in body and b"next page" not in body
    code, _, body = _http(
        "GET", base + "/pagedir?limit=2", headers={"Accept": "text/html"},
    )
    assert b"first 2 entries" in body and b"next page" in body
    assert b"lastFileName=f01.txt" in body
    code, _, body = _http(
        "GET", base + "/pagedir?limit=2&lastFileName=f01.txt",
        headers={"Accept": "text/html"},
    )
    assert b"f02.txt" in body and b"f00.txt" not in body
