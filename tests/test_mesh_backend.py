"""Mesh backend: pod-scale encode/rebuild reachable from ec.encode/
ec.rebuild — byte-identity vs the single-device oracle on tile-edge/odd/
multi-loss shapes (the r9 contract), the per-mesh-shape MULTICHIP
evidence rule for `auto` promotion, the WEEDTPU_MESH* knobs, stats, the
BENCH_MODE=mesh smoke, and the ingest persistent-staging-ring follow-up.
All on the 8 virtual CPU devices conftest forces — no TPU needed."""

import io
import json
import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ops import rs_codec
from seaweedfs_tpu.ops.rs_codec import Encoder

pytestmark = []


def _golden():
    return Encoder(10, 4, backend="numpy")


def _encode_all(enc, data):
    return np.stack(enc.encode(list(data)))


# -- dispatch-level byte-identity --------------------------------------------


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_mesh_encode_matches_golden_odd_width(shape):
    """Odd widths force the internal zero-pad path; output must still be
    byte-identical to the numpy oracle."""
    enc = Encoder(10, 4, backend="mesh", mesh_shape=shape)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(10, 1003), dtype=np.uint8)
    out = np.asarray(enc.encode_parity_lazy(data))
    want = np.asarray(_golden().encode_parity_lazy(data))
    assert np.array_equal(out, want)


@pytest.mark.parametrize("rebuild", ["ring", "alltoall"])
@pytest.mark.parametrize("lost", [(3,), (1, 5, 10, 13), (0, 1, 2, 3)])
def test_mesh_reconstruct_lazy_matches_golden(rebuild, lost):
    """The rebuild pipeline's flat (survivors, width) form through BOTH
    distributed formulations, single- and multi-loss, odd width."""
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(4, 2), mesh_rebuild=rebuild)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(10, 777), dtype=np.uint8)
    shards = _encode_all(_golden(), data)
    surv = [i for i in range(14) if i not in lost][:10]
    got = np.asarray(enc.reconstruct_lazy(shards[surv], surv, list(lost), donate=True))
    assert np.array_equal(got, shards[list(lost)])


def test_mesh_batched_forms_match_golden():
    """3-D (B, C, N) encode/reconstruct forms (serving/batched paths)."""
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(2, 4))
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(3, 10, 257), dtype=np.uint8)
    assert np.array_equal(enc.encode_batch(data), _golden().encode_batch(data))
    shards = np.stack([_encode_all(_golden(), v) for v in data])
    lost = [2, 7, 11]
    surv = [i for i in range(14) if i not in lost][:10]
    got = enc.reconstruct_batch(shards[:, surv, :], surv, lost)
    assert np.array_equal(got, shards[:, lost, :])


def test_mesh_serving_reconstruct_and_verify():
    """The reedsolomon-parity API surface (reconstruct/verify/encode)
    through the mesh backend, including the bucketed serving path."""
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(4, 2))
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, size=(10, 5000), dtype=np.uint8)
    shards = list(_encode_all(_golden(), data))
    assert enc.verify(shards)
    holed = list(shards)
    holed[0] = holed[12] = None
    rec = enc.reconstruct(holed)
    for s in range(14):
        assert np.array_equal(rec[s], shards[s]), s


# -- file-pipeline byte-identity (the production path) ------------------------


def _write_dat(base, data):
    os.makedirs(os.path.dirname(base), exist_ok=True)
    with open(base + ".dat", "wb") as f:
        f.write(data)


def test_mesh_write_ec_files_byte_identical_tile_edge(tmp_path):
    """write_ec_files through the mesh streaming pipeline (aligned spans,
    zero-filled tail gap, donation, inline CRC) vs the warm oracle on a
    tile-edge/odd layout."""
    rng = np.random.default_rng(5)
    large, small, buf = 64 * 1024, 16 * 1024, 16 * 1024
    data = rng.integers(
        0, 256, 2 * large * 10 + 3 * small * 10 + 4321, dtype=np.uint8
    ).tobytes()
    base_o, base_m = str(tmp_path / "o" / "7"), str(tmp_path / "m" / "7")
    for b in (base_o, base_m):
        _write_dat(b, data)
    stripe.write_ec_files(base_o, large, small, buf, encoder=_golden(),
                          max_batch_bytes=1 << 20)
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(4, 2))
    stripe.write_ec_files(base_m, large, small, buf, encoder=enc,
                          max_batch_bytes=1 << 20)
    for s in range(14):
        assert (
            open(stripe.shard_file_name(base_o, s), "rb").read()
            == open(stripe.shard_file_name(base_m, s), "rb").read()
        ), f"shard {s}"
    # identical geometry AND identical streamed CRCs in the sidecar
    assert open(base_o + ".eci", "rb").read() == open(base_m + ".eci", "rb").read()


@pytest.mark.parametrize("rebuild", ["ring", "alltoall"])
def test_mesh_rebuild_ec_files_byte_identical_to_serial(tmp_path, rebuild):
    """rebuild_ec_files with the mesh encoder (both variants) vs the
    serial oracle on the same survivor set, multi-loss, with the .eci CRC
    gate active (a byte drift would fail the rebuild, not just the
    comparison)."""
    rng = np.random.default_rng(6)
    large, small, buf = 64 * 1024, 16 * 1024, 16 * 1024
    data = rng.integers(0, 256, 3 * large * 10 + 987, dtype=np.uint8).tobytes()
    base = str(tmp_path / "v" / "7")
    _write_dat(base, data)
    stripe.write_ec_files(base, large, small, buf, encoder=_golden(),
                          max_batch_bytes=1 << 20)
    lost = (0, 5, 11, 13)
    expected = {
        s: open(stripe.shard_file_name(base, s), "rb").read() for s in lost
    }
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(2, 4), mesh_rebuild=rebuild)
    rebuilt = stripe.rebuild_ec_files(
        base, encoder=enc, buffer_size=48 * 1024, max_batch_bytes=1 << 20
    )
    assert sorted(rebuilt) == sorted(lost)
    for s in lost:
        assert open(stripe.shard_file_name(base, s), "rb").read() == expected[s]
    # serial oracle on the SAME survivor set agrees (transitivity check)
    for s in lost:
        os.unlink(stripe.shard_file_name(base, s))
    stripe.rebuild_ec_files_serial(base, encoder=_golden())
    for s in lost:
        assert open(stripe.shard_file_name(base, s), "rb").read() == expected[s]


# -- factory, knobs, audit -----------------------------------------------------


def test_new_encoder_mesh_explicit_and_audit():
    enc = rs_codec.new_encoder(backend="mesh")
    assert enc.backend == "mesh"
    sel = enc.selection
    assert sel.get("mesh_shape") and "x" in sel["mesh_shape"]
    assert sel.get("mesh_rebuild") in ("ring", "alltoall")
    assert sel.get("mesh_devices") >= 1
    assert "mesh" in sel.get("audit", "")


def test_mesh_shape_env_knob(monkeypatch):
    monkeypatch.setenv("WEEDTPU_MESH_SHAPE", "2x2")
    enc = Encoder(10, 4, backend="mesh")
    md = enc._mesh_dispatch()
    assert (md.dp, md.sp) == (2, 2)
    assert md.width_align == 4


def test_mesh_shape_env_knob_malformed(monkeypatch):
    monkeypatch.setenv("WEEDTPU_MESH_SHAPE", "banana")
    enc = Encoder(10, 4, backend="mesh")
    with pytest.raises(ValueError, match="DPxSP"):
        enc._mesh_dispatch()


def test_mesh_rebuild_variant_validation():
    enc = Encoder(10, 4, backend="mesh", mesh_shape=(2, 2), mesh_rebuild="bogus")
    with pytest.raises(ValueError, match="variant"):
        enc._mesh_dispatch()


def test_default_mesh_shape_rule():
    from seaweedfs_tpu.parallel import backend as mb

    assert mb.default_mesh_shape(8) == (4, 2)
    assert mb.default_mesh_shape(2) == (2, 1)
    assert mb.parse_mesh_shape("") is None
    assert mb.parse_mesh_shape("auto") is None
    assert mb.parse_mesh_shape("4x2") == (4, 2)
    with pytest.raises(ValueError):
        mb.parse_mesh_shape("0x4")


def test_mesh_stats_gauge_and_dispatch_counter():
    from seaweedfs_tpu import stats

    enc = Encoder(10, 4, backend="mesh", mesh_shape=(4, 2))
    before = stats.EcDispatchTotal.labels("mesh").value
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, size=(10, 64), dtype=np.uint8)
    np.asarray(enc.encode_parity_lazy(data))
    assert stats.EcMeshDevices.value == 8
    assert stats.EcDispatchTotal.labels("mesh").value == before + 1


# -- per-mesh-shape evidence rule ---------------------------------------------


def _fresh_when():
    import datetime

    return datetime.datetime.utcnow().strftime("%Y-%m-%dT%H:%MZ")


def _write_multichip(dirpath, meas, name="MULTICHIP_r91.json"):
    with open(os.path.join(dirpath, name), "w", encoding="utf-8") as f:
        json.dump(meas, f)


def _evidence(**kw):
    ev = {
        "when": _fresh_when(),
        "platform": "tpu (TPU v5 lite)",
        "round": 91,
        "single_device": {"encode_gbps": 31.0},
        "shapes": {
            "4x2": {
                "encode_gbps": 180.0,
                "rebuild_ring_gbps": 120.0,
                "rebuild_alltoall_gbps": 95.0,
                "match": True,
            },
            "16x2": {"encode_gbps": 500.0, "match": True},
        },
    }
    ev.update(kw)
    return ev


def test_mesh_evidence_promotes_on_fresh_onchip(tmp_path):
    _write_multichip(tmp_path, _evidence())
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert ok
    # 16x2 is faster but needs 32 devices — only achievable shapes count
    assert dec["mesh_shape"] == "4x2"
    assert dec["mesh_rebuild"] == "ring"  # ring beats alltoall in the evidence
    assert dec["evidence_round"] == 91
    assert "beats single-device" in dec["reason"]


def test_mesh_evidence_alltoall_wins_when_faster(tmp_path):
    ev = _evidence()
    ev["shapes"]["4x2"]["rebuild_alltoall_gbps"] = 200.0
    _write_multichip(tmp_path, ev)
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert ok and dec["mesh_rebuild"] == "alltoall"


def test_mesh_evidence_absent_keeps_backend(tmp_path):
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "no committed mesh evidence" in dec["reason"]


def test_mesh_evidence_off_chip_never_promotes(tmp_path):
    _write_multichip(tmp_path, _evidence(platform="cpu (cpu)"))
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "on-chip" in dec["reason"]


def test_mesh_evidence_stale_never_promotes(tmp_path):
    _write_multichip(tmp_path, _evidence(when="2020-01-01T00:00Z"))
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "stale" in dec["reason"]


def test_mesh_evidence_unparseable_age_is_stale(tmp_path):
    _write_multichip(tmp_path, _evidence(when="yesterday-ish"))
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "stale" in dec["reason"]


def test_mesh_evidence_losing_shape_keeps_backend(tmp_path):
    ev = _evidence()
    ev["shapes"]["4x2"]["encode_gbps"] = 12.0  # below single_device 31.0
    del ev["shapes"]["16x2"]
    _write_multichip(tmp_path, ev)
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "beats the single-device" in dec["reason"]


def test_mesh_evidence_failed_byte_verify_disqualifies(tmp_path):
    ev = _evidence()
    ev["shapes"]["4x2"]["match"] = False
    del ev["shapes"]["16x2"]
    _write_multichip(tmp_path, ev)
    ok, _dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok


def test_mesh_evidence_no_shape_table_keeps_backend(tmp_path):
    _write_multichip(tmp_path, {"when": _fresh_when(), "platform": "tpu", "tail": "ok"})
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    assert not ok and "per-mesh-shape" in dec["reason"]


def test_mesh_evidence_newest_round_wins(tmp_path):
    _write_multichip(tmp_path, _evidence(), name="MULTICHIP_r90.json")
    ev2 = _evidence(platform="cpu (cpu)")
    _write_multichip(tmp_path, ev2, name="MULTICHIP_r91.json")
    ok, dec = rs_codec.pick_mesh_backend(8, art_dir=str(tmp_path))
    # the newest round is off-chip: it must NOT fall back to older rounds
    assert not ok and dec["evidence_file"] == "MULTICHIP_r91.json"


def test_committed_multichip_r06_never_promotes_on_this_box():
    """The artifact this PR commits is a CPU host-device run: the
    evidence rule must refuse it (platform gate), so `auto` on a future
    8-device host cannot silently flip to mesh without on-chip numbers."""
    ev = rs_codec.load_mesh_evidence()
    assert ev is not None and ev["_file"] >= "MULTICHIP_r06.json"
    if ev["_file"] == "MULTICHIP_r06.json":
        ok, dec = rs_codec.pick_mesh_backend(8)
        assert not ok


def test_new_encoder_auto_promotes_to_mesh_on_evidence(tmp_path, monkeypatch):
    """End-to-end `auto` flow: a simulated TPU pod (device identity
    faked, the 8 virtual CPU devices kept for the actual mesh build)
    with committed fresh mesh evidence promotes to the mesh backend with
    the evidence's shape + rebuild variant in the audit."""
    from seaweedfs_tpu.utils import devices as devices_mod

    _write_multichip(tmp_path, _evidence())
    monkeypatch.setattr(devices_mod, "is_tpu_device", lambda d: True)
    monkeypatch.setattr(rs_codec, "_artifacts_dir", lambda: str(tmp_path / "none"))
    monkeypatch.setattr(rs_codec, "_multichip_dir", lambda: str(tmp_path))
    enc = rs_codec.new_encoder()
    assert enc.backend == "mesh"
    assert enc.mesh_shape == (4, 2) and enc.mesh_rebuild == "ring"
    sel = enc.selection
    assert sel["source"] == "mesh-evidence"
    assert sel["mesh_shape"] == "4x2" and sel["mesh_devices"] == 8
    assert "evidence=r91" in sel["audit"]
    # and the promoted encoder still encodes byte-identically
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(10, 123), dtype=np.uint8)
    assert np.array_equal(
        np.asarray(enc.encode_parity_lazy(data)),
        np.asarray(_golden().encode_parity_lazy(data)),
    )


def test_new_encoder_auto_keeps_backend_without_mesh_evidence(tmp_path, monkeypatch):
    from seaweedfs_tpu.utils import devices as devices_mod

    monkeypatch.setattr(devices_mod, "is_tpu_device", lambda d: True)
    monkeypatch.setattr(rs_codec, "_artifacts_dir", lambda: str(tmp_path / "none"))
    monkeypatch.setattr(rs_codec, "_multichip_dir", lambda: str(tmp_path))
    enc = rs_codec.new_encoder()
    assert enc.backend == "jax"  # tpu default without kernel evidence
    assert "no committed mesh evidence" in enc.selection["mesh"]["reason"]


# -- shell audit command ------------------------------------------------------


def test_ec_backend_shell_command_reports_selection():
    from seaweedfs_tpu.shell import commands

    buf = io.StringIO()
    commands()["ec.backend"].do([], None, buf)
    out = buf.getvalue()
    assert out.startswith("ec.backend: ")
    assert "backend=" in out and "source=" in out


# -- BENCH_MODE=mesh smoke (tier-1) -------------------------------------------


def test_bench_mesh_smoke_schema_and_byte_verify(tmp_path):
    """Scaled-down run of bench.py's mesh harness on the forced 8-device
    CPU mesh: per-shape encode + both rebuild variants measured, every
    shape byte-verified, artifact body round-trips through
    device_window's MULTICHIP assembler."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    import bench

    out = bench._measure_mesh(
        str(tmp_path),
        dat_bytes=2 * 64 * 1024 * 10 + 12345,
        large=64 * 1024,
        small=16 * 1024,
        buffer_size=16 * 1024,
        max_batch_bytes=1 << 20,
        shapes=[(4, 2)],
    )
    assert out["kind"] == "multichip" and out["n_devices"] == 8
    assert out["ok"] is True
    rec = out["shapes"]["4x2"]
    assert rec["match"] is True
    for key in ("encode_gbps", "rebuild_ring_gbps", "rebuild_alltoall_gbps"):
        assert rec[key] > 0
    assert out["single_device"]["encode_gbps"] > 0
    # assembler round-trip: this is exactly what a device window commits
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts"))
    import device_window

    meas = device_window.assemble_multichip(out)
    assert meas["shapes"] == out["shapes"] and meas["round"] == 6


# -- ingest persistent staging ring (ROADMAP follow-up 1) ---------------------


def test_inline_builder_reuses_staging_ring_across_polls(tmp_path):
    """Steady-state polls must hit the SAME cached ring (no per-poll
    buffer churn) and reuse the builder-lifetime .dat handle."""
    from seaweedfs_tpu.ec import ingest

    large, small, buf = 64 * 1024, 16 * 1024, 16 * 1024
    base = str(tmp_path / "5")
    b = ingest.InlineStripeBuilder(base, _golden(), large, small, buffer_size=buf)
    rng = np.random.default_rng(9)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, large * 10 + 1, dtype=np.uint8).tobytes())
        f.flush()
        assert b.poll() == 1
        ring_ids = {id(r) for r in b._ring_cache.values()}
        dat_handle = b._dat
        assert len(ring_ids) == 1 and dat_handle is not None
        f.write(rng.integers(0, 256, large * 10, dtype=np.uint8).tobytes())
        f.flush()
        assert b.poll() == 1
        assert {id(r) for r in b._ring_cache.values()} == ring_ids
        assert b._dat is dat_handle
    b.abort()
    assert b._dat is None and not b._ring_cache


def test_inline_builder_async_watermark_lands_before_seal(tmp_path):
    """The flusher-thread watermark keeps the fsync-before-record
    ordering: after polls cross the durable batch, the journal's last
    rows record must describe bytes already on disk, and seal still
    produces the warm-identical shard set."""
    from seaweedfs_tpu.ec import ingest

    large, small, buf = 64 * 1024, 16 * 1024, 16 * 1024
    base_i, base_w = str(tmp_path / "i"), str(tmp_path / "w")
    rng = np.random.default_rng(10)
    data = rng.integers(0, 256, 4 * large * 10 + 321, dtype=np.uint8).tobytes()
    b = ingest.InlineStripeBuilder(base_i, _golden(), large, small, buffer_size=buf)
    b._durable_batch = large * 10  # force a watermark per row
    with open(base_i + ".dat", "wb") as f:
        f.write(data)
        f.flush()
    assert b.poll() == 4
    if b._flusher is not None:
        b._flusher.shutdown(wait=True)  # drain the async watermark
        b._flusher = None
    records = ingest.read_journal(base_i)
    rows_records = [r for r in records if r.get("kind") == "rows"]
    assert rows_records and rows_records[-1]["rows"] >= 1
    for s in range(14):
        size = os.path.getsize(ingest.part_path(base_i, s))
        assert size >= rows_records[-1]["rows"] * large
    b.seal()
    with open(base_w + ".dat", "wb") as f:
        f.write(data)
    stripe.write_ec_files(base_w, large, small, buf, encoder=_golden())
    for s in range(14):
        assert (
            open(stripe.shard_file_name(base_i, s), "rb").read()
            == open(stripe.shard_file_name(base_w, s), "rb").read()
        ), s
