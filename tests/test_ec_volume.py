"""EcVolume read-path tests: needle lookup through .ecx, interval reads,
degraded reads with shards deleted (reconstruct-on-read), remote-reader
fallback, and deletion journal semantics — the SURVEY.md §3.2 latency path."""

import os
import shutil

import numpy as np
import pytest

from seaweedfs_tpu import stats
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT
from seaweedfs_tpu.ec.ec_volume import EcVolume, NeedleDeleted, NeedleNotFound
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types

LARGE = 1024
SMALL = 64
ENC = Encoder(10, 4, backend="numpy")


@pytest.fixture()
def volume(tmp_path):
    """Synthetic volume: blob records at 8-aligned offsets + matching index."""
    rng = np.random.default_rng(11)
    base = str(tmp_path / "v7")
    records = {}  # needle_id -> (offset, body_size, record_bytes)
    # first 8 bytes of a .dat hold the superblock, so needles start at 8
    offset = types.NEEDLE_PADDING_SIZE
    blobs = [b"\x03" + bytes(7)]
    for nid in [3, 10, 42, 999, 2**40 + 5]:
        body = int(rng.integers(1, 300))
        total = types.actual_size(body, version=3)
        rec = rng.integers(0, 256, size=total, dtype=np.uint8).tobytes()
        records[nid] = (offset, body, rec)
        blobs.append(rec)
        offset += total
    with open(base + ".dat", "wb") as f:
        f.write(b"".join(blobs))
    idx_mod.write_entries(
        [(nid, types.offset_to_bytes(off) , size) for nid, (off, size, _) in records.items()],
        base + ".idx",
    )
    stripe.write_ec_files(base, large_block_size=LARGE, small_block_size=SMALL, buffer_size=64, encoder=ENC)
    stripe.write_sorted_file_from_idx(base)
    return base, records


def open_vol(base, **kw):
    kw.setdefault("encoder", ENC)
    return EcVolume(base, large_block_size=LARGE, small_block_size=SMALL, **kw)


def test_read_all_needles(volume):
    base, records = volume
    with open_vol(base) as ev:
        assert ev.shard_ids == list(range(14))
        for nid, (off, size, rec) in records.items():
            got = ev.read_needle_blob(nid)
            assert got[: len(rec)] == rec, f"needle {nid}"


def test_not_found_and_deleted(volume):
    base, records = volume
    with open_vol(base) as ev:
        with pytest.raises(NeedleNotFound):
            ev.read_needle_blob(12345)
        ev.delete_needle(42)
        with pytest.raises(NeedleDeleted):
            ev.read_needle_blob(42)
    # journal persisted: reopen still deleted
    with open_vol(base) as ev:
        with pytest.raises(NeedleDeleted):
            ev.read_needle_blob(42)


def test_degraded_read_with_lost_shards(volume):
    base, records = volume
    for s in (0, 4, 11, 13):
        os.remove(stripe.shard_file_name(base, s))
    with open_vol(base) as ev:
        for nid, (off, size, rec) in records.items():
            got = ev.read_needle_blob(nid)
            assert got[: len(rec)] == rec, f"needle {nid} after 4-shard loss"


def test_remote_reader_fallback(volume, tmp_path):
    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    # move shards 0-4 "to another node"
    for s in range(5):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")

    calls = []

    def remote(shard_id, offset, size):
        calls.append(shard_id)
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    with open_vol(base, remote_reader=remote) as ev:
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
    assert calls, "remote reader should have been consulted"


def test_unreadable_when_too_many_lost(volume):
    base, _ = volume
    for s in range(5):
        os.remove(stripe.shard_file_name(base, s))
    with open_vol(base) as ev:
        nid = 3
        with pytest.raises(IOError, match="surviving"):
            ev.read_needle_blob(nid)


def test_ecj_compaction_preserves_read_behavior(volume):
    """delete -> remount -> read must be identical before and after
    compaction: journaled deletes become .ecx tombstones, .ecj is dropped,
    and a second compaction is a no-op (idempotent after a crash that
    leaves a stale journal)."""
    base, records = volume
    dead = [10, 999]
    with open_vol(base) as ev:
        for nid in dead:
            assert ev.delete_needle(nid)

    def behavior():
        out = {}
        with open_vol(base, ecj_compact_threshold=0) as ev:
            for nid in list(records) + [12345]:
                try:
                    out[nid] = ev.read_needle_blob(nid)
                except NeedleDeleted:
                    out[nid] = "deleted"
                except NeedleNotFound:
                    out[nid] = "not-found"
        return out

    before = behavior()
    assert before[10] == "deleted" and before[999] == "deleted"

    folded = stripe.compact_ecj(base)
    assert folded == len(dead)
    assert not os.path.exists(base + ".ecj"), ".ecj must be dropped"
    assert behavior() == before, "read behavior changed across compaction"
    assert stripe.compact_ecj(base) == 0  # idempotent: nothing left to fold

    # deletes after compaction start a fresh journal; a re-delete of an
    # already-tombstoned needle reports False like any dead needle
    with open_vol(base) as ev:
        assert not ev.delete_needle(10)
        assert ev.delete_needle(3)
    assert os.path.exists(base + ".ecj")
    after = behavior()
    assert after[3] == "deleted"

    # ec.decode's idx conversion sees the same deletions either way
    stripe.write_idx_file_from_ec_index(base)
    tombs = {
        key
        for key, _, size in idx_mod.walk_index_buffer(open(base + ".idx", "rb").read())
        if types.is_deleted(size)
    }
    assert tombs == {3, 10, 999}


def test_ecj_compaction_triggers_at_mount_threshold(volume):
    base, records = volume
    with open_vol(base) as ev:
        assert ev.delete_needle(42)
    assert os.path.exists(base + ".ecj")
    # below threshold: journal stays
    with open_vol(base, ecj_compact_threshold=1 << 20):
        pass
    assert os.path.exists(base + ".ecj")
    # at/above threshold: mount folds it, reads unchanged
    with open_vol(base, ecj_compact_threshold=8) as ev:
        with pytest.raises(NeedleDeleted):
            ev.read_needle_blob(42)
        assert ev.read_needle_blob(3)
    assert not os.path.exists(base + ".ecj")


def test_truncated_shard_falls_back_to_reconstruct(volume):
    """A truncated local shard must not serve zero-padded (corrupt) data."""
    base, records = volume
    p = stripe.shard_file_name(base, 0)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with open_vol(base) as ev:
        for nid, (off, size, rec) in records.items():
            got = ev.read_needle_blob(nid)
            assert got[: len(rec)] == rec, f"needle {nid} corrupt after truncation"


def test_recover_fetches_survivors_in_parallel(volume, tmp_path):
    """The degraded-read survivor fan-out must overlap remote RTTs: with 9
    remote survivors each costing 60 ms, a serial ladder pays ~540 ms while
    the parallel one pays ~1-2 RTTs. Also checks byte-correctness and that
    the recover fan-out itself never re-targets the missing shard (the one
    direct remote attempt per interval happens before recovery starts)."""
    import threading
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    # keep 4 shards local (one of them the target), push 10 remote,
    # delete the target's remote copy so the read must reconstruct
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    os.remove(remote_dir / "v7.ec00")

    in_flight = 0
    peak = 0
    gauge = threading.Lock()
    asked = []

    def remote(shard_id, offset, size):
        nonlocal in_flight, peak
        with gauge:
            in_flight += 1
            peak = max(peak, in_flight)
        try:
            asked.append(shard_id)
            time.sleep(0.06)
            p = remote_dir / f"v7.ec{shard_id:02d}"
            if not p.exists():
                return None
            with open(p, "rb") as f:
                f.seek(offset)
                return f.read(size)
        finally:
            with gauge:
                in_flight -= 1

    with open_vol(base, remote_reader=remote, recover_fetch_parallelism=16) as ev:
        t0 = time.monotonic()
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
        dt = time.monotonic() - t0
    # the direct ladder tries the missing shard once per interval; the
    # fan-out must not pile further attempts onto it
    per_needle = {nid: ev.locate_needle(nid)[2] for nid in records}
    n_intervals = sum(len(ivs) for ivs in per_needle.values())
    n_on_missing = sum(
        1
        for ivs in per_needle.values()
        for iv in ivs
        if iv.to_shard_id_and_offset(LARGE, SMALL)[0] == 0
    )
    assert n_on_missing > 0, "fixture should exercise the recover path"
    assert asked.count(0) <= n_intervals
    assert peak >= 4, f"fetches did not overlap (peak in-flight {peak})"
    # Every interval pays one direct 60 ms attempt (reads are serial per
    # interval — that ladder is not under test); each interval on the
    # missing shard additionally pays the recover fan-out, which parallel
    # costs <=2 waves (~120 ms) but serial costs 6 survivors x 60 ms.
    # Since r6 the direct attempt rides the fetch pool (per-holder cap),
    # adding a thread-scheduling hop per interval — budget it as fixed
    # slack (NOT extra RTTs: the total must stay under the serial floor
    # so a serialized fan-out still fails this test).
    rtt = 0.06
    sched_slack = 0.02 * n_intervals
    parallel_budget = rtt * (n_intervals + 3 * n_on_missing) + sched_slack
    serial_floor = rtt * (n_intervals + 6 * n_on_missing)
    assert parallel_budget < serial_floor - rtt  # budget still discriminates
    assert dt < min(parallel_budget, serial_floor - rtt), (
        f"degraded reads took {dt:.2f}s over {n_intervals} intervals "
        f"({n_on_missing} reconstructing) — fan-out looks serial"
    )


def test_recover_tolerates_hung_and_failing_peers(volume, tmp_path):
    """First-10-of-13 completion: one peer that raises and one that hangs
    past the deadline must not fail the read while 10 survivors answer."""
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    os.remove(remote_dir / "v7.ec00")

    def remote(shard_id, offset, size):
        if shard_id == 1:
            raise ConnectionError("peer down")
        if shard_id == 2:
            time.sleep(5.0)  # hung peer; deadline would cut this
            return None
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    with open_vol(
        base,
        remote_reader=remote,
        recover_fetch_parallelism=16,
        recover_fetch_deadline=3.0,
    ) as ev:
        t0 = time.monotonic()
        nid = 3
        _, _, rec = records[nid]
        assert ev.read_needle_blob(nid)[: len(rec)] == rec
        assert time.monotonic() - t0 < 3.0, "read waited on the hung peer"


def test_wedged_holder_per_holder_cap_and_deadline(volume, tmp_path):
    """SIGSTOP-style chaos: a WEDGED holder (neither answers nor errors —
    the semantics of a SIGSTOPped volume server) that the reconstruct
    NEEDS must cost exactly one per-holder-capped wait, not the overall
    deadline and never a hang; afterwards the holder sits in the
    suspicion window."""
    import threading
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    # 4 local shards (10-13), 10 remote; the target's remote copy is gone,
    # three more remote copies are gone, and one remote holder is wedged —
    # leaving exactly 9 fast survivors (4 local + 5 remote) + the wedged
    # one, so reconstruction NEEDS the wedged holder to reach 10
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    for s in (0, 1, 2, 4):
        os.remove(remote_dir / f"v7.ec{s:02d}")
    wedge = threading.Event()

    def remote(shard_id, offset, size):
        if shard_id == 3:
            wedge.wait(30.0)  # SIGSTOPped: no answer, no error
            return None
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    try:
        with open_vol(
            base,
            remote_reader=remote,
            recover_fetch_parallelism=16,
            recover_fetch_deadline=30.0,
            recover_holder_timeout=0.6,
            recover_holder_backoff=60.0,
        ) as ev:
            # only needles with an interval ON the lost shard reconstruct
            on_missing = [
                nid
                for nid in records
                if any(
                    iv.to_shard_id_and_offset(LARGE, SMALL)[0] == 0
                    for iv in ev.locate_needle(nid)[2]
                )
            ]
            assert on_missing, "fixture should place intervals on shard 0"
            t0 = time.monotonic()
            with pytest.raises(IOError, match="surviving"):
                ev.read_needle_blob(on_missing[0])
            dt = time.monotonic() - t0
            # the per-holder cap cut the wedged holder — NOT the 30 s
            # overall deadline, and no unbounded wait
            assert 0.5 <= dt < 5.0, f"expected ~0.6s per-holder cap, took {dt:.2f}s"
            assert ev._holder_suspected(3), "wedged holder must enter the suspicion window"
            # while suspected, the fan-out skips the wedged holder outright:
            # the next read fails FAST instead of re-paying the cap
            t0 = time.monotonic()
            with pytest.raises(IOError, match="surviving"):
                ev.read_needle_blob(on_missing[-1])
            assert time.monotonic() - t0 < 0.4, "suspected holder was re-waited on"
    finally:
        wedge.set()


def test_internally_timed_out_reader_marks_suspect(volume, tmp_path):
    """Production remote readers carry their own transport timeout and
    report a wedged peer as a SLOW None — the ladder must read that
    slow-nothing signature as suspicion (without the hard cap firing),
    while a fast None (shard simply absent) never suspects."""
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")

    def remote(shard_id, offset, size):
        if shard_id == 0:
            time.sleep(0.6)  # internal transport timeout swallowed a wedge
            return None
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None  # fast miss
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    with open_vol(
        base,
        remote_reader=remote,
        recover_fetch_parallelism=16,
        recover_fetch_deadline=10.0,
        recover_holder_timeout=30.0,  # hard cap never fires here
        recover_suspect_after=0.3,
        recover_holder_backoff=60.0,
    ) as ev:
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
        assert ev._holder_suspected(0), "slow-None holder not suspected"
        assert not any(ev._holder_suspected(s) for s in range(1, 14)), (
            "a fast miss or healthy holder was suspected"
        )


def test_slow_but_healthy_holders_use_full_deadline(volume, tmp_path):
    """The per-holder cap must not collapse the OVERALL deadline: holders
    that answer slower than the cap-wait granularity but well within the
    configured `recover_fetch_deadline` still serve the read, and none of
    them is marked suspect (slow is not wedged)."""
    import threading
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    for s in (0, 1, 2, 4):
        os.remove(remote_dir / f"v7.ec{s:02d}")

    def remote(shard_id, offset, size):
        time.sleep(0.35)  # slower than the 0.2 s cap granularity below
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    with open_vol(
        base,
        remote_reader=remote,
        recover_fetch_parallelism=16,
        recover_fetch_deadline=10.0,
        recover_holder_timeout=2.0,
        recover_holder_backoff=60.0,
    ) as ev:
        for nid, (off, size, rec) in records.items():
            assert ev.read_needle_blob(nid)[: len(rec)] == rec
        assert not any(
            ev._holder_suspected(s) for s in range(14)
        ), "a slow-but-answering holder was marked suspect"


def test_wedged_holder_latency_ladder_holds(volume, tmp_path):
    """The p50/p99 ladder under a wedged (SIGSTOPped) holder of the READ
    TARGET's shard: the first degraded read pays one capped direct
    attempt, marks the holder suspect, and every later read skips it —
    so p50 stays at reconstruct-path levels and p99 is bounded by the
    per-holder cap, while every byte still reads back correct."""
    import threading
    import time

    base, records = volume
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    # shard 0 lives ONLY on the wedged holder; shards 1-9 healthy remote
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    wedge = threading.Event()

    def remote(shard_id, offset, size):
        if shard_id == 0:
            wedge.wait(30.0)  # SIGSTOPped holder of the target shard
            return None
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    cap = 0.5
    try:
        with open_vol(
            base,
            remote_reader=remote,
            recover_fetch_parallelism=16,
            recover_fetch_deadline=10.0,
            recover_holder_timeout=cap,
            recover_holder_backoff=60.0,
        ) as ev:
            lat = []
            for _ in range(3):  # several passes: p50 must reflect steady state
                for nid, (off, size, rec) in records.items():
                    t0 = time.monotonic()
                    got = ev.read_needle_blob(nid)
                    lat.append(time.monotonic() - t0)
                    assert got[: len(rec)] == rec, f"needle {nid} under wedge"
            assert ev._holder_suspected(0)
            lat.sort()
            p50 = lat[len(lat) // 2]
            p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
            # p50: suspicion makes the steady state one reconstruct, no
            # capped waits; p99: at most the first read's single capped
            # attempt (plus reconstruct slack)
            assert p50 < cap / 2, f"p50 {p50:.3f}s — wedged holder still on the p50 path"
            assert p99 < cap + 2.0, f"p99 {p99:.3f}s — more than one capped wait leaked in"
    finally:
        wedge.set()


def test_wedged_peer_suspected_process_wide(volume, tmp_path):
    """The PR 4 follow-up: one wedged PEER serving shards of MANY volumes
    must cost one capped attempt process-wide, not one per volume. Readers
    that can name the peer behind a shard (`peer_for`) share suspicion
    through the process-wide registry: volume A's capped timeout marks the
    peer, and volume B skips it without ever calling its reader."""
    import threading

    from seaweedfs_tpu.ec import suspicion

    base_a, _ = volume
    base_b = str(tmp_path / "v8")
    for ext in [".ecx", ".ecj", ".eci"] + [stripe.to_ext(s) for s in range(14)]:
        if os.path.exists(base_a + ext):
            shutil.copy(base_a + ext, base_b + ext)

    calls = {"a": 0, "b": 0}
    wedge = threading.Event()
    PEER = "10.0.0.9:18080"

    def reader_a(shard_id, offset, size):
        calls["a"] += 1
        wedge.wait(30.0)  # SIGSTOPped peer: no answer, no error
        return None

    reader_a.peer_for = lambda shard_id: PEER

    def reader_b(shard_id, offset, size):
        calls["b"] += 1
        return None

    reader_b.peer_for = lambda shard_id: PEER

    reg = suspicion.HolderSuspicion()
    try:
        with open_vol(
            base_a,
            remote_reader=reader_a,
            warm_on_mount=False,
            recover_holder_timeout=0.3,
            recover_holder_backoff=60.0,
            suspicion=reg,
        ) as ev_a, open_vol(
            base_b,
            remote_reader=reader_b,
            warm_on_mount=False,
            recover_holder_backoff=60.0,
            suspicion=reg,
        ) as ev_b:
            # volume A pays the one capped attempt against the wedged peer
            assert ev_a._remote_fetch_capped(0, 0, 16) is None
            assert calls["a"] == 1
            assert ev_a._holder_suspected(0)
            # volume B sees the SAME peer suspected — for every shard it
            # serves, with zero reader calls
            assert ev_b._holder_suspected(0) and ev_b._holder_suspected(5)
            assert ev_b._remote_fetch_capped(0, 0, 16) is None
            assert calls["b"] == 0, "wedged peer was rediscovered by volume B"
    finally:
        wedge.set()


def test_suspicion_without_peer_identity_stays_per_volume(volume, tmp_path):
    """Fallback scope check: a reader that CANNOT name peers keys
    suspicion by (volume, shard) — another volume with its own reader is
    unaffected (the narrower pre-peer-identity behavior, preserved)."""
    from seaweedfs_tpu.ec import suspicion

    base_a, _ = volume
    base_b = str(tmp_path / "v9")
    for ext in [".ecx", ".ecj", ".eci"] + [stripe.to_ext(s) for s in range(14)]:
        if os.path.exists(base_a + ext):
            shutil.copy(base_a + ext, base_b + ext)

    reg = suspicion.HolderSuspicion()
    with open_vol(
        base_a, remote_reader=lambda s, o, n: None, warm_on_mount=False, suspicion=reg
    ) as ev_a, open_vol(
        base_b, remote_reader=lambda s, o, n: None, warm_on_mount=False, suspicion=reg
    ) as ev_b:
        ev_a._mark_holder_suspect(3)
        assert ev_a._holder_suspected(3)
        assert not ev_a._holder_suspected(4)
        assert not ev_b._holder_suspected(3), "per-volume suspicion leaked across volumes"


def test_suspicion_registry_prunes_expired_keys():
    """The process-wide registry outlives every volume: expired windows
    must be dropped (on check and on the next mark), not accumulate for
    the life of the server."""
    from seaweedfs_tpu.ec import suspicion

    reg = suspicion.HolderSuspicion()
    reg.mark(("peer", "a:1"), backoff=-1.0)  # already expired
    reg.mark(("peer", "b:2"), backoff=-1.0)
    assert not reg.suspected(("peer", "a:1"))  # prunes a:1 on sight
    assert ("peer", "a:1") not in reg._until
    reg.mark(("peer", "c:3"), backoff=60.0)  # mark sweeps b:2
    assert ("peer", "b:2") not in reg._until
    assert reg.suspected(("peer", "c:3"))
    assert list(reg._until) == [("peer", "c:3")]


# -- hedged fetches, coalescing, typed errors (PR 6) --------------------------


def _exact_survivor_set(base, tmp_path, missing=(0,), absent_remote=(7, 8, 9)):
    """Move shards 0-9 remote, delete the remote copies of `missing` (the
    read targets, lost everywhere) and `absent_remote` — leaving EXACTLY
    DATA_SHARDS survivors, so reconstruction needs every one of them and
    a single slow holder sits on the critical path (a richer survivor set
    would just route around it and hide the hedge)."""
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    for s in list(missing) + list(absent_remote):
        os.remove(remote_dir / f"v7.ec{s:02d}")

    def fetch_bytes(shard_id, offset, size):
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    return remote_dir, fetch_bytes


def _needles_on_shard(ev, records, shard, avoiding=()):
    """Needle ids with >=1 interval on `shard` and none on `avoiding`
    (keeps a deliberately-slow survivor off the DIRECT read ladder so the
    test measures the recover fan-out, not the direct rung)."""
    out = []
    for nid in records:
        sids = {
            iv.to_shard_id_and_offset(LARGE, SMALL)[0]
            for iv in ev.locate_needle(nid)[2]
        }
        if shard in sids and not (sids & set(avoiding)):
            out.append(nid)
    return out


def test_hedge_delay_derived_from_latency_ewma():
    """The pure half of 'hedge fires at the EWMA-derived delay': with
    injected observations the delay is an exact deterministic function
    (Jacobson/Karels mean+4*dev), and below the sample floor there is no
    delay at all (no hedging on no evidence)."""
    from seaweedfs_tpu.ec import suspicion

    reg = suspicion.HolderSuspicion()
    key = ("peer", "10.0.0.1:1")
    assert reg.hedge_delay(key) is None
    obs = [0.10, 0.12, 0.08, 0.11]
    for s in obs:
        reg.observe_latency(key, s)
    ewma, dev = obs[0], obs[0] / 2.0
    for s in obs[1:]:
        err = s - ewma
        ewma += suspicion.HolderSuspicion._LAT_ALPHA * err
        dev += suspicion.HolderSuspicion._LAT_BETA * (abs(err) - dev)
    expect = min(30.0, max(0.002, ewma + suspicion.HolderSuspicion._LAT_K * dev))
    assert reg.hedge_delay(key) == pytest.approx(expect, rel=1e-9)
    # below the sample floor: no evidence, no hedge
    reg2 = suspicion.HolderSuspicion()
    reg2.observe_latency(key, 0.1)
    reg2.observe_latency(key, 0.1)
    assert reg2.hedge_delay(key) is None
    # failures must not be fed as samples
    reg2.observe_latency(key, -1.0)
    assert reg2.latency_estimate(key)[2] == 2


def test_hedge_delay_env_override_and_clamp(volume, monkeypatch):
    base, _ = volume
    with open_vol(base, warm_on_mount=False, recover_holder_timeout=2.0) as ev:
        monkeypatch.setenv("WEEDTPU_HEDGE_DELAY_MS", "123")
        assert ev._hedge_delay(0) == pytest.approx(0.123)
        monkeypatch.delenv("WEEDTPU_HEDGE_DELAY_MS")
        # cold start: half the slow-miss threshold, never past cap/2
        expect = min(max(0.05, ev.recover_suspect_after / 2.0), 1.0)
        assert ev._hedge_delay(0) == pytest.approx(expect)


def test_hedged_fetch_first_success_wins_loser_drained(volume, tmp_path, monkeypatch):
    """A wedged survivor on the critical path: the backup fetch launches
    at the configured delay against the OTHER holder, wins, and the read
    completes far under the wedge — the loser is drained in the
    background and its (byte-identical) late answer raises no mismatch."""
    import threading
    import time

    base, records = volume
    _, fetch_bytes = _exact_survivor_set(base, tmp_path)
    slow_gate = threading.Event()
    via_calls = []

    def remote(shard_id, offset, size):
        if shard_id == 3:
            slow_gate.wait(10.0)  # wedged primary holder of shard 3
        return fetch_bytes(shard_id, offset, size)

    def via(addr, shard_id, offset, size):
        via_calls.append((addr, shard_id, time.monotonic()))
        return fetch_bytes(shard_id, offset, size)

    remote.via = via
    remote.holders_for = lambda sid: ["peerA:1", "peerB:2"]
    remote.peer_for = lambda sid: "peerA:1"

    monkeypatch.setenv("WEEDTPU_HEDGE_DELAY_MS", "100")
    fired0, won0 = stats.HedgeFired.value, stats.HedgeWon.value
    mism0 = stats.DegradedReadErrors.labels("HedgeMismatch").value
    try:
        with open_vol(
            base,
            remote_reader=remote,
            warm_on_mount=False,
            recover_fetch_parallelism=16,
            recover_fetch_deadline=10.0,
            recover_holder_timeout=8.0,
        ) as ev:
            nids = _needles_on_shard(ev, records, 0, avoiding=(3,))
            assert nids, "fixture should place an interval on shard 0 off shard 3"
            t0 = time.monotonic()
            got = ev.read_needle_blob(nids[0])
            dt = time.monotonic() - t0
            rec = records[nids[0]][2]
            assert got[: len(rec)] == rec
            assert dt < 2.0, f"read waited on the wedged primary ({dt:.2f}s)"
            assert stats.HedgeFired.value - fired0 >= 1
            assert stats.HedgeWon.value - won0 >= 1
            hedge3 = [c for c in via_calls if c[1] == 3]
            assert hedge3 and hedge3[0][0] == "peerB:2", (
                "backup must land on the OTHER holder"
            )
            # the hedge fired AT the configured delay (the wait loop wakes
            # exactly then; slack covers scheduler jitter only)
            assert 0.09 <= hedge3[0][2] - t0 <= 0.6
    finally:
        slow_gate.set()
    time.sleep(0.3)  # loser drains byte-identical: no mismatch counted
    assert stats.DegradedReadErrors.labels("HedgeMismatch").value == mism0


def test_wedged_holder_ladder_improves_with_hedging(volume, tmp_path, monkeypatch):
    """The p50/p99 ladder with a slow survivor on the critical path: with
    hedging OFF every reconstruct eats the slow holder's full latency;
    ON, the backup caps it near the hedge delay — byte-identical either
    way."""
    import time

    from seaweedfs_tpu.ec import suspicion

    base, records = volume
    _, fetch_bytes = _exact_survivor_set(base, tmp_path)
    SLOW = 0.7

    def mk_reader():
        def remote(shard_id, offset, size):
            if shard_id == 3:
                time.sleep(SLOW)  # slow holder (internal failover shape)
            return fetch_bytes(shard_id, offset, size)

        remote.via = lambda addr, sid, off, n: fetch_bytes(sid, off, n)
        remote.holders_for = lambda sid: ["peerA:1", "peerB:2"]
        remote.peer_for = lambda sid: "peerA:1"
        return remote

    def run(hedge_on: bool) -> list[float]:
        monkeypatch.setenv("WEEDTPU_HEDGE_READS", "1" if hedge_on else "0")
        monkeypatch.setenv("WEEDTPU_HEDGE_DELAY_MS", "60")
        lats = []
        with open_vol(
            base,
            remote_reader=mk_reader(),
            warm_on_mount=False,
            recover_fetch_parallelism=16,
            recover_fetch_deadline=10.0,
            recover_holder_timeout=30.0,
            suspicion=suspicion.HolderSuspicion(),  # fresh: no cross-arm state
        ) as ev:
            nids = _needles_on_shard(ev, records, 0, avoiding=(3,))
            assert nids
            for _ in range(2):
                for nid in nids:
                    t0 = time.monotonic()
                    got = ev.read_needle_blob(nid)
                    lats.append(time.monotonic() - t0)
                    rec = records[nid][2]
                    assert got[: len(rec)] == rec
        lats.sort()
        return lats

    off = run(False)
    on = run(True)
    p99 = lambda l: l[min(len(l) - 1, int(len(l) * 0.99))]  # noqa: E731
    assert p99(off) >= SLOW * 0.9, "slow survivor was not on the path"
    assert p99(on) < SLOW * 0.6, (
        f"hedging did not cut the tail: p99 on={p99(on):.3f} off={p99(off):.3f}"
    )
    assert on[len(on) // 2] <= off[len(off) // 2] + 0.05


def test_coalesced_degraded_decodes_single_flight(volume, tmp_path, monkeypatch):
    """N concurrent degraded reads of the SAME interval: one survivor
    fan-out + decode total (the leader's), every waiter byte-identical,
    and the coalesced counter accounts for the absorbed decodes. With the
    knob off, every reader decodes for itself again."""
    import threading

    base, records = volume
    with open(stripe.shard_file_name(base, 0), "rb") as f:
        golden0 = f.read()
    remote_dir = tmp_path / "remote"
    remote_dir.mkdir()
    for s in range(10):
        shutil.move(stripe.shard_file_name(base, s), remote_dir / f"v7.ec{s:02d}")
    os.remove(remote_dir / "v7.ec00")

    def remote(shard_id, offset, size):
        import time

        time.sleep(0.08)  # widen the coalesce window deterministically
        p = remote_dir / f"v7.ec{shard_id:02d}"
        if not p.exists():
            return None
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(size)

    with open_vol(
        base, remote_reader=remote, warm_on_mount=False,
        recover_fetch_parallelism=32,
    ) as ev:
        decodes = []
        real_reconstruct = ev.encoder.reconstruct

        def counting(shards, wanted=None, **kw):
            decodes.append(1)
            return real_reconstruct(shards, wanted=wanted, **kw)

        monkeypatch.setattr(ev.encoder, "reconstruct", counting)

        def storm(n: int) -> list[bytes]:
            results: list[bytes] = []
            lock = threading.Lock()
            barrier = threading.Barrier(n)

            def one():
                barrier.wait()
                out = ev._recover_interval(0, 0, 64).tobytes()
                with lock:
                    results.append(out)

            threads = [threading.Thread(target=one) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(20)
            return results

        coal0 = stats.CoalescedReads.value
        results = storm(6)
        assert len(results) == 6
        assert all(r == golden0[:64] for r in results), "waiter bytes differ"
        assert len(decodes) <= 2, f"{len(decodes)} decodes for one hot interval"
        assert stats.CoalescedReads.value - coal0 >= 4

        # knob off: everyone decodes for themselves
        monkeypatch.setenv("WEEDTPU_COALESCE_READS", "0")
        decodes.clear()
        results = storm(4)
        assert all(r == golden0[:64] for r in results)
        assert len(decodes) == 4, "coalescing off must decode per reader"


def test_no_viable_holders_typed_error_carries_context(volume):
    from seaweedfs_tpu.ec.ec_volume import EcDegradedReadError, EcNoViableHolders

    base, records = volume
    for s in range(5):
        os.remove(stripe.shard_file_name(base, s))
    calls = []

    def reader(shard_id, offset, size):
        calls.append(shard_id)
        return None  # fast miss everywhere

    errs0 = stats.DegradedReadErrors.labels("EcNoViableHolders").value
    with open_vol(base, remote_reader=reader, warm_on_mount=False) as ev:
        nids = _needles_on_shard(ev, records, 0)
        with pytest.raises(EcNoViableHolders) as ei:
            ev.read_needle_blob(nids[0] if nids else 3)
    e = ei.value
    assert isinstance(e, (IOError, EcDegradedReadError))
    assert "surviving" in str(e)
    assert e.shard_id in range(5)
    assert e.attempted, "attempted holder keys must ride the error"
    assert isinstance(e.suspected, list)
    assert e.retry_after >= 1.0
    assert calls, "remote candidates should have been attempted"
    assert stats.DegradedReadErrors.labels("EcNoViableHolders").value > errs0


def test_degraded_timeout_typed_error(volume):
    import threading

    from seaweedfs_tpu.ec.ec_volume import EcDegradedReadTimeout

    base, records = volume
    for s in range(5):
        os.remove(stripe.shard_file_name(base, s))
    release = threading.Event()

    def hang(shard_id, offset, size):
        release.wait(5.0)
        return None

    errs0 = stats.DegradedReadErrors.labels("EcDegradedReadTimeout").value
    try:
        with open_vol(
            base, remote_reader=hang, warm_on_mount=False,
            recover_fetch_deadline=0.4,
        ) as ev:
            nids = _needles_on_shard(ev, records, 0)
            with pytest.raises(EcDegradedReadTimeout) as ei:
                ev.read_needle_blob(nids[0] if nids else 3)
    finally:
        release.set()
    assert "deadline expired" in str(ei.value)
    assert "surviving" in str(ei.value)
    assert ei.value.attempted
    assert stats.DegradedReadErrors.labels("EcDegradedReadTimeout").value > errs0


def test_unmount_forgets_volume_scoped_suspicion(volume):
    """close() drops this volume's (volume, shard) fallback keys — a
    remount after repairing a flaky holder must not inherit the stale
    window — while PEER-scoped windows persist (they describe the peer
    process, and are bounded by the backoff either way)."""
    from seaweedfs_tpu.ec import suspicion

    base, _ = volume
    reg = suspicion.HolderSuspicion()
    with open_vol(
        base, remote_reader=lambda s, o, n: None, warm_on_mount=False,
        recover_holder_backoff=60.0, suspicion=reg,
    ) as ev:
        ev._mark_holder_suspect(2)
        assert ev._holder_suspected(2)
    reg.mark(("peer", "10.0.0.9:18080"), backoff=60.0)  # unrelated peer window
    # remount: volume-scoped window is gone, peer window untouched
    with open_vol(
        base, remote_reader=lambda s, o, n: None, warm_on_mount=False,
        suspicion=reg,
    ) as ev2:
        assert not ev2._holder_suspected(2), "remount inherited stale suspicion"
    assert reg.suspected(("peer", "10.0.0.9:18080"))
