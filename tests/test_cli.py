"""CLI tests — the single-chip tpuec slice (SURVEY.md §7.1.3): encode,
rebuild, verify, decode, fix, compact, export on local volume files, driven
through the real argparse entry point."""

import json
import os

import pytest

from seaweedfs_tpu.__main__ import main
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

LARGE, SMALL = 4096, 512  # scaled-down stripe geometry for tests


@pytest.fixture
def vol(tmp_path):
    """A small volume with a few needles; returns its base path."""
    v = Volume(str(tmp_path), 7, "")
    needles = {}
    for i in range(1, 9):
        n = Needle(cookie=0x1000 + i, id=i, data=bytes([i]) * (100 * i))
        v.write_needle(n)
        needles[i] = n.data
    v.delete_needle(3)
    v.close()
    return str(tmp_path / "7"), needles


def run_cli(*argv):
    return main(list(argv))


def test_encode_rebuild_verify_roundtrip(vol, capsys):
    base, _ = vol
    assert run_cli("encode", base, "--large-block", str(LARGE), "--small-block", str(SMALL)) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["shards"] == TOTAL_SHARDS_COUNT

    assert run_cli("verify", base) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["verified"]

    # kill 4 shards, rebuild, verify again
    for s in (0, 5, 11, 13):
        os.remove(stripe.shard_file_name(base, s))
    assert run_cli("rebuild", base) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["rebuilt_shards"] == [
        0,
        5,
        11,
        13,
    ]
    assert run_cli("verify", base) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])["verified"]


def test_decode_restores_dat(vol, capsys):
    base, needles = vol
    with open(base + ".dat", "rb") as f:
        original = f.read()
    run_cli("encode", base, "--large-block", str(LARGE), "--small-block", str(SMALL))
    os.remove(base + ".dat")
    os.remove(stripe.shard_file_name(base, 2))  # decode must tolerate a lost data shard
    assert run_cli("decode", base) == 0
    with open(base + ".dat", "rb") as f:
        assert f.read() == original
    # .idx regenerated from .ecx (+.ecj): volume must open and serve needles
    v = Volume(os.path.dirname(base), 7, "")
    assert v.read_needle(5).data == needles[5]
    with pytest.raises(KeyError):
        v.read_needle(3)  # deleted pre-encode
    v.close()


def test_fix_rebuilds_idx(vol, capsys):
    base, needles = vol
    os.remove(base + ".idx")
    assert run_cli("fix", base) == 0
    v = Volume(os.path.dirname(base), 7, "")
    assert v.read_needle(8).data == needles[8]
    with pytest.raises(KeyError):
        v.read_needle(3)  # tombstone must survive the rebuild
    v.close()


def test_compact_drops_deleted(vol, capsys):
    base, needles = vol
    assert run_cli("compact", base) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["bytes_after"] < out["bytes_before"]
    v = Volume(os.path.dirname(base), 7, "")
    assert v.read_needle(4).data == needles[4]
    v.close()


def test_export_lists_live_needles(vol, capsys):
    base, needles = vol
    assert run_cli("export", base) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    ids = {int(r["id"], 16) for r in lines}
    assert ids == {1, 2, 4, 5, 6, 7, 8}  # 3 deleted


def test_version(capsys):
    assert run_cli("version") == 0
    assert "seaweedfs_tpu" in capsys.readouterr().out


def test_fix_preserves_live_empty_needle(tmp_path, capsys):
    """A live needle with empty data must survive an index rebuild — its
    on-disk record (size 5: DataSize+flags) is distinct from a delete
    marker (size 0)."""
    v = Volume(str(tmp_path), 9, "")
    v.write_needle(Needle(cookie=0xAA, id=1, data=b""))
    v.write_needle(Needle(cookie=0xBB, id=2, data=b"live"))
    v.delete_needle(2)
    v.close()
    base = str(tmp_path / "9")
    os.remove(base + ".idx")
    assert run_cli("fix", base) == 0
    v = Volume(str(tmp_path), 9, "")
    assert v.read_needle(1).data == b""
    with pytest.raises(KeyError):
        v.read_needle(2)
    v.close()


def test_compact_refuses_empty_index_with_data(vol, capsys):
    """compact on a volume whose .idx was lost must not wipe the data."""
    base, _ = vol
    os.remove(base + ".idx")
    # constructing Volume now self-heals by scan; simulate the dangerous
    # state directly: empty map + populated .dat
    v = Volume.__new__(Volume)
    import threading

    from seaweedfs_tpu.storage.needle_map import CompactMap

    v.dir, v.id, v.collection = os.path.dirname(base), 7, ""
    v.read_only = False
    v.tiered = False
    v._lock = threading.RLock()
    v.nm = CompactMap()
    v.base_path, v.dat_path, v.idx_path = base, base + ".dat", base + ".idx"
    v._dat = open(v.dat_path, "r+b")
    from seaweedfs_tpu.storage.super_block import SuperBlock

    v._dat.seek(0)
    v.super_block = SuperBlock.from_bytes(v._dat.read(8))
    v._idx = open(v.idx_path, "ab")
    with pytest.raises(IOError):
        v.compact()
    v.close()
    with open(base + ".dat", "rb") as f:
        assert len(f.read()) > 8  # data untouched


def test_volume_self_heals_missing_idx(vol):
    base, needles = vol
    os.remove(base + ".idx")
    v = Volume(os.path.dirname(base), 7, "")
    assert v.read_needle(5).data == needles[5]
    v.close()


def test_scan_detects_midfile_corruption(vol):
    """A corrupted size field mid-file must raise CorruptVolume (valid
    records follow), never silently truncate the index — silent truncation
    plus compact would destroy everything after the bad record."""
    from seaweedfs_tpu.storage import scan as scan_mod
    from seaweedfs_tpu.storage import types as t

    base, _ = vol
    # find the offset of needle id=2's record via a clean scan
    records = list(scan_mod.scan_volume_file(base + ".dat"))
    off2 = next(off for off, n in records if n.id == 2)
    with open(base + ".dat", "r+b") as f:
        f.seek(off2 + 12)  # size field of the header
        f.write((0x7FFF0000).to_bytes(4, "big"))
    with pytest.raises(scan_mod.CorruptVolume):
        list(scan_mod.scan_volume_file(base + ".dat"))
    with pytest.raises(scan_mod.CorruptVolume):
        scan_mod.rebuild_idx(base)
    assert not os.path.exists(base + ".idx.tmp")  # no litter on failure


def test_scan_tolerates_truncated_tail(vol):
    base, _ = vol
    full = list(scan_mod_records(base))
    with open(base + ".dat", "r+b") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 37)  # chop mid-record
    partial = list(scan_mod_records(base))
    assert len(partial) == len(full) - 1


def scan_mod_records(base):
    from seaweedfs_tpu.storage import scan as scan_mod

    return scan_mod.scan_volume_file(base + ".dat")


def test_compact_fully_deleted_volume_reclaims(tmp_path):
    """All-needles-deleted is a legitimate empty state (tombstones in .idx)
    — compact must reclaim it, not confuse it with a lost index."""
    v = Volume(str(tmp_path), 11, "")
    for i in (1, 2, 3):
        v.write_needle(Needle(cookie=i, id=i, data=b"z" * 500))
    for i in (1, 2, 3):
        v.delete_needle(i)
    before, after = v.compact()
    assert after < before and after == 8  # superblock only
    v.close()


def test_ttl_rejects_out_of_range():
    from seaweedfs_tpu.storage.super_block import TTL

    for bad in ("300m", "-3m", "256h"):
        with pytest.raises(ValueError):
            TTL.parse(bad)
    assert TTL.parse("255m").count == 255
