"""Multi-chip sharding tests on the 8-device CPU mesh: dp x sp sharded
encode, reconstruction, the full ec-cycle step with its psum integrity check,
and the driver's graft entry points."""

import sys

import numpy as np
import pytest

sys.path.insert(0, "/root/repo")  # for __graft_entry__ at repo root

import jax

from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import Encoder, _reconstruction_matrix
from seaweedfs_tpu.parallel import mesh as mesh_mod
from seaweedfs_tpu.parallel import sharded


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"


@pytest.mark.parametrize("shape,axes", [((8, 1), ("dp", "sp")), ((4, 2), ("dp", "sp")), ((2, 4), ("dp", "sp"))])
def test_sharded_encode_matches_golden(shape, axes):
    mesh = mesh_mod.device_mesh(axes, shape=shape)
    enc_fn = sharded.make_encode_fn(mesh, gf8.parity_matrix(10, 4))
    rng = np.random.default_rng(0)
    b, n = shape[0], 128 * shape[1]
    data = rng.integers(0, 256, size=(b, 10, n), dtype=np.uint8)
    out = np.asarray(enc_fn(sharded.shard_batch(mesh, data)))
    golden = Encoder(10, 4, backend="numpy")
    for i in range(b):
        want = np.stack(golden.encode(list(data[i])))
        assert np.array_equal(out[i], want)


def test_sharded_reconstruct():
    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(4, 2))
    lost = (2, 7, 10, 12)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    enc_fn = sharded.make_encode_fn(mesh, gf8.parity_matrix(10, 4))
    apply_fn = sharded.make_apply_fn(mesh, recon)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(4, 10, 256), dtype=np.uint8)
    shards = np.asarray(enc_fn(sharded.shard_batch(mesh, data)))
    rebuilt = np.asarray(apply_fn(sharded.shard_batch(mesh, shards[:, surv, :])))
    assert np.array_equal(rebuilt, shards[:, lost, :])


def test_ec_cycle_step_psum():
    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(2, 4))
    lost = (0, 3, 11, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    step = sharded.make_ec_cycle_fn(mesh, gf8.parity_matrix(10, 4), recon, lost, surv)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(4, 10, 512), dtype=np.uint8)
    shards, bad = step(sharded.shard_batch(mesh, data))
    assert shards.shape == (4, 14, 512)
    assert int(bad) == 0


def test_graft_entry_single():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.block_until_ready(jax.jit(fn)(*args))
    assert out.shape == (args[0].shape[0], 14, args[0].shape[2])
    golden = Encoder(10, 4, backend="numpy")
    want = np.stack(golden.encode(list(args[0][0])))
    assert np.array_equal(np.asarray(out)[0], want)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_graft_dryrun_multichip(n):
    import __graft_entry__ as ge

    ge.dryrun_multichip(n)


def test_mesh_too_many_devices():
    with pytest.raises(ValueError, match="needs"):
        mesh_mod.device_mesh(("dp",), shape=(64,))


@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (1, 8)])
def test_distributed_rebuild_all_to_all(shape):
    """Shard-major survivors -> all_to_all regroup -> byte-sharded rebuild
    of 4 lost shards matches the golden reconstruction (the SURVEY §7.1
    step-4 multi-chip rebuild model)."""
    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=shape)
    lost = (1, 5, 10, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    rng = np.random.default_rng(3)
    b, n = shape[0] * 2, 128 * 8  # divisible by any sp in the matrix
    data = rng.integers(0, 256, size=(b, 10, n), dtype=np.uint8)
    golden = Encoder(10, 4, backend="numpy")
    shards = np.stack([np.stack(golden.encode(list(v))) for v in data])
    rebuild = sharded.make_distributed_rebuild_fn(mesh, recon)
    rebuilt = np.asarray(rebuild(shards[:, surv, :]))
    assert rebuilt.shape == (b, 4, n)
    assert np.array_equal(rebuilt, shards[:, lost, :])


def test_distributed_rebuild_rejects_bad_survivor_count():
    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(4, 2))
    recon = np.zeros((4, 10), dtype=np.uint8)
    rebuild = sharded.make_distributed_rebuild_fn(mesh, recon)
    with pytest.raises(ValueError):
        rebuild(np.zeros((4, 9, 256), dtype=np.uint8))


@pytest.mark.parametrize("shape", [(4, 2), (2, 4), (1, 8)])
def test_ring_rebuild_matches_all_to_all_and_golden(shape):
    """The ring-pipelined rebuild (ppermute rotation, one resident block
    per chip) must produce byte-identical output to both the all_to_all
    formulation and the golden numpy reconstruction."""
    from seaweedfs_tpu.parallel import ring

    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=shape)
    lost = (1, 5, 10, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    rng = np.random.default_rng(11)
    b, n = shape[0] * 2, 128 * 8
    data = rng.integers(0, 256, size=(b, 10, n), dtype=np.uint8)
    golden = Encoder(10, 4, backend="numpy")
    shards = np.stack([np.stack(golden.encode(list(v))) for v in data])

    ring_fn = ring.make_ring_rebuild_fn(mesh, recon)
    ring_out = np.asarray(ring_fn(shards[:, surv, :]))
    assert ring_out.shape == (b, 4, n)
    assert np.array_equal(ring_out, shards[:, lost, :])

    a2a_fn = sharded.make_distributed_rebuild_fn(mesh, recon)
    a2a_out = np.asarray(a2a_fn(shards[:, surv, :]))
    assert np.array_equal(ring_out, a2a_out)


def test_ring_rebuild_rejects_bad_shapes():
    from seaweedfs_tpu.parallel import ring

    mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(2, 4))
    recon = np.zeros((4, 10), dtype=np.uint8)
    fn = ring.make_ring_rebuild_fn(mesh, recon)
    with pytest.raises(ValueError, match="survivor"):
        fn(np.zeros((2, 9, 256), dtype=np.uint8))
    with pytest.raises(ValueError, match="divide"):
        fn(np.zeros((3, 10, 256), dtype=np.uint8))
    with pytest.raises(ValueError, match="divide"):
        fn(np.zeros((2, 10, 257), dtype=np.uint8))


def test_multislice_ec_cycle_dcn_mesh():
    """('dcn','dp','sp') mesh: slices own disjoint volume sub-batches,
    heavy collectives stay intra-slice, one scalar crosses 'dcn' —
    byte-identical to the golden encode, zero mismatches."""
    mesh = mesh_mod.device_mesh(("dcn", "dp", "sp"), shape=(2, 2, 2))
    lost = (0, 3, 11, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    run = sharded.make_multislice_ec_cycle_fn(
        mesh, gf8.parity_matrix(10, 4), recon, lost, surv
    )
    rng = np.random.default_rng(17)
    b, n = 8, 512
    data = rng.integers(0, 256, size=(b, 10, n), dtype=np.uint8)
    shards, bad = run(data)
    assert int(bad) == 0
    golden = Encoder(10, 4, backend="numpy")
    want = np.stack(golden.encode(list(data[0])))
    assert np.array_equal(np.asarray(shards)[0], want)


def test_multislice_run_rejects_bad_shapes():
    from seaweedfs_tpu.parallel import sharded as sh

    mesh = mesh_mod.device_mesh(("dcn", "dp", "sp"), shape=(2, 2, 2))
    lost = (0, 3, 11, 13)
    surv = tuple(i for i in range(14) if i not in lost)
    recon = _reconstruction_matrix("vandermonde", 10, 4, surv, lost)
    run = sh.make_multislice_ec_cycle_fn(
        mesh, gf8.parity_matrix(10, 4), recon, lost, surv
    )
    with pytest.raises(ValueError, match="divide"):
        run(np.zeros((6, 10, 512), dtype=np.uint8))
    with pytest.raises(ValueError, match="divide"):
        run(np.zeros((8, 10, 511), dtype=np.uint8))
    with pytest.raises(ValueError, match="dcn"):
        sh.make_multislice_ec_cycle_fn(
            mesh_mod.device_mesh(("dp", "sp"), shape=(4, 2)),
            gf8.parity_matrix(10, 4), recon, lost, surv,
        )
