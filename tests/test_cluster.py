"""In-process cluster integration tests — real master + volume servers on
loopback with real gRPC + HTTP (SURVEY.md §4: "in-process integration ...
no mocks of gRPC — real loopback"). Exercises the §3 call stacks:
write path, ec encode/spread/mount, degraded read, blob delete, rebuild."""

import os
import time

import pytest

from seaweedfs_tpu import rpc
from seaweedfs_tpu.cluster.client import ClusterError, MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.pb import VOLUME_SERVICE

LARGE, SMALL = 4096, 512


@pytest.fixture
def cluster(tmp_path):
    """master + 3 volume servers, each with one disk dir."""
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    servers = []
    for i in range(3):
        d = tmp_path / f"srv{i}"
        d.mkdir()
        vs = VolumeServer(
            [str(d)],
            master.address,
            heartbeat_interval=0.4,
            rack=f"rack{i % 2}",
        )
        vs.start()
        servers.append(vs)
    client = MasterClient(master.address)
    yield master, servers, client
    client.close()
    for vs in servers:
        vs.stop()
    master.stop()


def test_assign_upload_read_delete(cluster):
    master, servers, client = cluster
    a = client.assign()
    assert a.fid and a.url
    payload = os.urandom(10_000)
    client.upload(a.fid, payload, mime="application/x-test")
    assert client.read(a.fid) == payload
    assert client.delete(a.fid)
    with pytest.raises(ClusterError):
        client.read(a.fid)


def test_submit_and_statistics(cluster):
    master, servers, client = cluster
    res = client.submit(b"hello weed tpu")
    assert client.read(res.fid) == b"hello weed tpu"
    stats = client.statistics()
    assert stats["node_count"] == 3
    assert stats["volume_count"] >= 1


def test_volume_list_shows_topology(cluster):
    master, servers, client = cluster
    client.submit(b"x")
    tree = client.volume_list()
    racks = set()
    for dc, rr in tree["data_centers"].items():
        racks.update(rr.keys())
    assert racks == {"rack0", "rack1"}


def _wait_for(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


def test_ec_lifecycle_spread_degraded_read_rebuild(cluster):
    """The §3.1-§3.3 stacks end to end: encode on A, spread shards to B/C,
    drop the source volume, read through EC (local + remote + reconstruct),
    delete a blob, rebuild lost shards."""
    master, servers, client = cluster
    A, B, C = servers

    # write a few needles -> they land on some server's volume
    fids, payloads = [], {}
    first = client.submit(os.urandom(20_000))
    fids.append(first.fid)
    payloads[first.fid] = client.read(first.fid)
    vid = int(first.fid.split(",")[0])
    for _ in range(5):
        a = client.assign()
        if int(a.fid.split(",")[0]) != vid:
            continue
        data = os.urandom(9_000)
        client.upload(a.fid, data)
        fids.append(a.fid)
        payloads[a.fid] = data
    owner = next(s for s in servers if s.store.get_volume(vid) is not None)

    with rpc.RpcClient(owner.grpc_address) as oc:
        oc.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
        oc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsGenerate",
            {"volume_id": vid, "large_block_size": LARGE, "small_block_size": SMALL},
        )

    # spread: shards 0-4 stay on owner; 5-9 -> B'; 10-13 -> C' (B'/C' = the
    # other two servers)
    others = [s for s in servers if s is not owner]
    plan = {owner: [0, 1, 2, 3, 4], others[0]: [5, 6, 7, 8, 9], others[1]: [10, 11, 12, 13]}
    for target, shard_ids in plan.items():
        if target is not owner:
            with rpc.RpcClient(target.grpc_address) as tc:
                tc.call(
                    VOLUME_SERVICE,
                    "VolumeEcShardsCopy",
                    {
                        "volume_id": vid,
                        "shard_ids": shard_ids,
                        "source_data_node": owner.grpc_address,
                    },
                )
    # owner deletes the shards it handed off, keeps 0-4
    with rpc.RpcClient(owner.grpc_address) as oc:
        base = owner._base_path_for(vid)
        for s in range(5, 14):
            os.remove(stripe.shard_file_name(base, s))
        oc.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
    for target, shard_ids in plan.items():
        with rpc.RpcClient(target.grpc_address) as tc:
            tc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})

    _wait_for(
        lambda: len(master.topology.lookup_ec_shards(vid)) == 14,
        msg="all 14 shards registered",
    )
    assert master.topology.lookup(vid) == []  # normal volume gone

    # reads now go through the EC path; needles on shards 5-13 need remote
    # interval reads from B'/C'
    for fid, want in payloads.items():
        assert client.read(fid) == want, f"EC read mismatch for {fid}"

    # blob delete via the EC journal, fanned to every shard holder
    del_fid = fids[1]
    for target in plan:
        with rpc.RpcClient(target.grpc_address) as tc:
            tc.call(VOLUME_SERVICE, "VolumeEcBlobDelete", {"volume_id": vid, "fid": del_fid})
    with pytest.raises(ClusterError):
        client.read(del_fid)

    # rebuild: copy all surviving shards to others[0], lose 10-13, rebuild
    rebuilder = others[0]
    with rpc.RpcClient(rebuilder.grpc_address) as rc:
        rc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsCopy",
            {
                "volume_id": vid,
                "shard_ids": [0, 1, 2, 3, 4],
                "source_data_node": owner.grpc_address,
                "copy_ecx_file": False,
            },
        )
        resp = rc.call(VOLUME_SERVICE, "VolumeEcShardsRebuild", {"volume_id": vid})
        assert resp["rebuilt_shard_ids"] == [10, 11, 12, 13]
        rc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
    # rebuilt shards must byte-match the originals on C'
    base_r = rebuilder._base_path_for(vid)
    base_c = others[1]._base_path_for(vid)
    for s in (10, 11, 12, 13):
        with open(stripe.shard_file_name(base_r, s), "rb") as f1, open(
            stripe.shard_file_name(base_c, s), "rb"
        ) as f2:
            assert f1.read() == f2.read(), f"rebuilt shard {s} differs"


def test_shard_location_cache_and_invalidation(cluster, monkeypatch):
    """Degraded reads must not pay a master LookupEcVolume per interval:
    lookups are cached per vid with expiry and invalidated when a holder
    read fails (VERDICT r3 #3 / SURVEY §3.2 ShardLocations)."""
    master, servers, client = cluster
    A = servers[0]
    calls = {"n": 0}
    real_query = A._master_query

    def counting_query(method, req, timeout=5.0):
        if method == "LookupEcVolume":
            calls["n"] += 1
        return real_query(method, req, timeout)

    monkeypatch.setattr(A, "_master_query", counting_query)
    # seed the master's EC registry with a fake layout on server B (which
    # holds no such shards — reads against it must fail and invalidate)
    B = servers[1]
    master.topology.ec_locations[77] = {sid: {B.url} for sid in range(14)}

    A.ec_lookup_ttl = 30.0
    for _ in range(10):
        locs = A._lookup_shard_locations(77)
    assert calls["n"] == 1, "repeated lookups within TTL must hit the cache"
    assert set(locs) == set(range(14))

    # expiry: force the deadline into the past
    with A._shard_locs_lock:
        exp, data = A._shard_locs[77]
        A._shard_locs[77] = (time.monotonic() - 1, data)
    A._lookup_shard_locations(77)
    assert calls["n"] == 2, "expired entry must refresh"

    # a failed holder read invalidates the cache entry (shard 0 holder B
    # has no such volume -> stream fails -> next read re-asks the master)
    reader = A._remote_reader_for(77)
    assert reader(0, 0, 16) is None
    assert 77 not in A._shard_locs
    reader(0, 0, 16)
    assert calls["n"] >= 3, "post-failure read must re-lookup"


def test_ec_shard_read_rpc_stream(cluster):
    """VolumeEcShardRead streams exactly the requested byte range."""
    master, servers, client = cluster
    res = client.submit(os.urandom(30_000))
    vid = int(res.fid.split(",")[0])
    owner = next(s for s in servers if s.store.get_volume(vid) is not None)
    with rpc.RpcClient(owner.grpc_address) as oc:
        oc.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
        oc.call(
            VOLUME_SERVICE,
            "VolumeEcShardsGenerate",
            {"volume_id": vid, "large_block_size": LARGE, "small_block_size": SMALL},
        )
        oc.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
        base = owner._base_path_for(vid)
        with open(stripe.shard_file_name(base, 3), "rb") as f:
            f.seek(100)
            want = f.read(1000)
        got = b"".join(
            oc.stream(
                VOLUME_SERVICE,
                "VolumeEcShardRead",
                {"volume_id": vid, "shard_id": 3, "offset": 100, "size": 1000},
            )
        )
        assert got == want


def test_replicated_write_lands_on_all_replicas(cluster):
    """store_replicate analog: a 001 write fans out so every replica can
    serve the needle directly."""
    import urllib.request

    master, servers, client = cluster
    res = client.submit(b"replicated-payload", replication="001")
    vid = int(res.fid.split(",")[0])
    holders = [s for s in servers if s.store.get_volume(vid) is not None]
    assert len(holders) == 2, "001 must create 2 copies"
    for s in holders:
        with urllib.request.urlopen(f"http://{s.url}/{res.fid}", timeout=10) as r:
            assert r.read() == b"replicated-payload"
    # replicated delete
    assert client.delete(res.fid)
    for s in holders:
        assert s.store.get_volume(vid).nm.get(
            __import__("seaweedfs_tpu.storage.file_id", fromlist=["FileId"]).FileId.parse(res.fid).key
        ) is None


def test_replication_fanout_is_parallel_and_timeout_bounded(cluster, monkeypatch):
    """store_replicate.go analog: the fan-out runs replicas concurrently
    (two slow replicas cost max(delay), not sum), and a stalled replica
    costs `replicate_timeout`, never the old serial 30 s."""
    import threading
    import urllib.error
    import urllib.request

    from seaweedfs_tpu.cluster import volume_server as vs_mod

    master, servers, client = cluster
    barrier = {"delay": 0.0}
    orig = vs_mod._Handler.do_POST

    def slow_replica_post(self):
        if "X-Weed-Replicate" in self.headers and barrier["delay"]:
            time.sleep(barrier["delay"])
        orig(self)

    monkeypatch.setattr(vs_mod._Handler, "do_POST", slow_replica_post)

    # 011 -> 3 copies (1 same-rack + 1 diff-rack): primary fans out to 2 replicas.
    barrier["delay"] = 0.4
    t0 = time.monotonic()
    res = client.submit(b"parallel-fanout", replication="011")
    elapsed = time.monotonic() - t0
    assert elapsed < 0.75, f"fan-out took {elapsed:.2f}s — replicas ran serially"
    vid = int(res.fid.split(",")[0])
    holders = [s for s in servers if s.store.get_volume(vid) is not None]
    assert len(holders) == 3
    for s in holders:
        with urllib.request.urlopen(f"http://{s.url}/{res.fid}", timeout=10) as r:
            assert r.read() == b"parallel-fanout"

    # A wedged replica: the write fails after ~replicate_timeout, not 30 s.
    for s in servers:
        s.replicate_timeout = 0.5
    barrier["delay"] = 3.0
    a = client.assign(replication="011")
    t0 = time.monotonic()
    with pytest.raises(ClusterError):
        client.upload(a.fid, b"stalled-replica")
    # the client retries every location (3), each bounded by the 0.5 s
    # replicate_timeout — the old serial path cost 30 s per dead replica
    elapsed = time.monotonic() - t0
    assert elapsed < 2.5, f"dead replica stalled the write {elapsed:.2f}s"
    barrier["delay"] = 0.0


def test_head_request_returns_no_body(cluster):
    import http.client

    master, servers, client = cluster
    res = client.submit(b"head-test-payload")
    vid_server = next(s for s in servers if s.store.get_volume(int(res.fid.split(",")[0])))
    conn = http.client.HTTPConnection(vid_server.host, vid_server.port, timeout=10)
    try:
        conn.request("HEAD", f"/{res.fid}")
        r1 = conn.getresponse()
        assert r1.status == 200
        assert r1.read() == b""  # no body
        assert int(r1.headers["Content-Length"]) == len(b"head-test-payload")
        # connection must stay usable (keep-alive not desynced)
        conn.request("GET", f"/{res.fid}")
        r2 = conn.getresponse()
        assert r2.read() == b"head-test-payload"
    finally:
        conn.close()


def test_snowflake_monotonic_against_clock():
    from seaweedfs_tpu.cluster.sequence import SnowflakeSequencer

    sq = SnowflakeSequencer(5)
    ids = [sq.next_ids() for _ in range(100)]
    assert len(set(ids)) == 100
    assert ids == sorted(ids)
    # simulate a backwards clock step: future last_ms must not be reused
    sq._last_ms += 10_000
    a, b = sq.next_ids(), sq.next_ids()
    assert b > a >= ((sq._last_ms - sq.EPOCH_MS) << 22)


def test_master_auto_vacuum(tmp_path):
    """topology_vacuum.go analog: the master spots garbage-heavy volumes
    from heartbeat-reported garbage ratios and compacts them on every
    holder — no operator involved."""
    master = MasterServer(port=0, reap_interval=3600, garbage_threshold=0.3,
                          vacuum_interval=3600)  # sweep driven manually
    master.start()
    d = tmp_path / "srv"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    client = MasterClient(master.address)
    try:
        fids = []
        for i in range(20):
            r = client.submit(os.urandom(3000))
            fids.append(r.fid)
        vid = int(fids[0].split(",")[0])
        vol = vs.store.get_volume(vid)
        assert vol.garbage_ratio() < 0.05
        # delete 75% -> garbage crosses the threshold
        for fid in fids[:15]:
            client.delete(fid)
        assert vol.garbage_ratio() > 0.3
        size_before = vol.content_size()
        _wait_for(
            lambda: any(
                vi.garbage_ratio > 0.3
                for n in master.topology.nodes.values()
                for vi in n.volumes.values()
            ),
            msg="garbage ratio reaches the master via heartbeat",
        )
        done = master.vacuum_once()
        assert vid in done
        vol2 = vs.store.get_volume(vid)
        assert vol2.content_size() < size_before / 2, "compaction did not shrink .dat"
        assert vol2.garbage_ratio() < 0.05
        # survivors intact, deleted stay gone
        for fid in fids[15:]:
            assert client.read(fid)
        for fid in fids[:3]:
            with pytest.raises(ClusterError):
                client.read(fid)
    finally:
        client.close()
        vs.stop()
        master.stop()


def test_master_http_api(tmp_path):
    """The reference's signature HTTP surface on the master: /dir/assign,
    /dir/lookup, /dir/status, /cluster/status, /cluster/healthz, /metrics,
    /vol/grow, /col/delete."""
    import json as _json
    import time as _time
    import urllib.request

    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "httpvol"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    try:
        base = f"http://{master.host}:{master.http_port}"

        def get(path, want=200):
            try:
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.status, r.read()
            except urllib.error.HTTPError as e:
                assert e.code == want, (path, e.code)
                return e.code, e.read()

        code, body = get("/cluster/healthz")
        assert code == 200
        code, body = get("/dir/assign?count=2")
        assign = _json.loads(body)
        assert assign["fid"] and assign["url"] == vs.url and assign["count"] == 2
        # upload through the assigned fid, then lookup by fid AND vid
        data = b"assigned over http"
        req = urllib.request.Request(
            f"http://{assign['url']}/{assign['fid']}", data=data, method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status in (200, 201)
        vid = assign["fid"].split(",", 1)[0]
        for q in (vid, assign["fid"]):
            code, body = get(f"/dir/lookup?volumeId={q}")
            lk = _json.loads(body)
            assert lk["locations"][0]["url"] == vs.url, lk
        code, body = get("/dir/lookup?volumeId=9999", want=404)
        assert b"not found" in body
        code, body = get("/dir/status")
        topo = _json.loads(body)["Topology"]
        assert topo["data_centers"]
        code, body = get("/cluster/status")
        st = _json.loads(body)
        assert st["IsLeader"] is True and master.address in st["Leader"]
        code, body = get("/metrics")
        assert b"weedtpu" in body
        code, body = get("/vol/grow?count=1&collection=httpgrow")
        assert _json.loads(body)["grown"] == 1
        _time.sleep(0.5)
        code, body = get("/col/delete?collection=httpgrow")
        assert _json.loads(body)["deleted"] >= 1
        code, body = get("/nope", want=404)
    finally:
        vs.stop()
        master.stop()


def test_status_ui_pages(tmp_path):
    """Operator HTML status pages on master (/ui) and volume server (/ui)."""
    import urllib.request

    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer

    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "uivol"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    try:
        from seaweedfs_tpu.cluster.client import MasterClient

        mc = MasterClient(master.address)
        mc.submit(b"ui demo data")
        mc.close()
        import time as _time

        _time.sleep(0.5)
        with urllib.request.urlopen(
            f"http://{master.host}:{master.http_port}/ui", timeout=10
        ) as r:
            body = r.read().decode()
        assert "Master" in body and vs.url in body and "Topology" in body
        with urllib.request.urlopen(f"http://{vs.url}/ui", timeout=10) as r:
            body = r.read().decode()
        assert "Volume Server" in body and "<table>" in body and "volume" in body.lower()
    finally:
        vs.stop()
        master.stop()


def test_multipart_form_upload_stores_file_bytes(cluster):
    """The reference's canonical workflow is `curl -F file=@x URL` against
    the assigned volume server — the needle must store exactly the
    attached bytes, not the multipart framing."""
    import urllib.request

    master, servers, client = cluster
    a = client.assign()
    payload = bytes(range(256)) * 40
    boundary = "------------------------deadbeefcafe"
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="file"; filename="blob.bin"\r\n'
        "Content-Type: application/x-payload\r\n\r\n"
    ).encode() + payload + f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        f"http://{a.url}/{a.fid}",
        data=body,
        method="POST",
        headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    assert client.read(a.fid) == payload
    # raw-body uploads keep working unchanged
    b = client.assign()
    client.upload(b.fid, b"raw body bytes")
    assert client.read(b.fid) == b"raw body bytes"


def test_multipart_filename_rides_replica_hop(cluster):
    """The primary forwards a form upload's filename to replicas via
    X-Weed-Filename so sibling needles stay byte-identical (check.disk
    compares per-id sizes and the name is part of the needle body)."""
    import base64 as _b64
    import urllib.request

    from seaweedfs_tpu import rpc as _rpc
    from seaweedfs_tpu.pb import VOLUME_SERVICE
    from seaweedfs_tpu.storage.file_id import FileId

    master, servers, client = cluster
    a = client.assign()
    req = urllib.request.Request(
        f"http://{a.url}/{a.fid}",
        data=b"replica bytes",
        method="POST",
        headers={
            "X-Weed-Replicate": "1",  # simulate the replica-side hop
            "X-Weed-Filename": _b64.b64encode(b"fancy name.bin").decode(),
        },
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    fid = FileId.parse(a.fid)
    holder = next(s for s in servers if s.store.get_volume(fid.volume_id))
    with _rpc.RpcClient(holder.grpc_address) as c:
        resp = c.call(
            VOLUME_SERVICE, "ReadNeedle",
            {"volume_id": fid.volume_id, "needle_id": fid.key},
        )
    assert _b64.b64decode(resp["name_b64"]) == b"fancy name.bin"
    # oversized names answer 400 instead of dropping the connection
    boundary = "----bb"
    body = (
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="file"; filename="{"x" * 300}"\r\n'
        "\r\n"
    ).encode() + b"d" + f"\r\n--{boundary}--\r\n".encode()
    b2 = client.assign()
    req = urllib.request.Request(
        f"http://{b2.url}/{b2.fid}", data=body, method="POST",
        headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
    )
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
