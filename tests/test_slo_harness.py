"""SLO harness tests: the HDR-style latency recorder and artifact schema
(seaweedfs_tpu/ec/slo.py), the weedload open-loop smoke (tiny in-process
cluster, schema + zero-loss gate, <=20 s), rebuild admission control,
the typed-degraded-error -> HTTP 503 mapping, and the bounded-retry
master lookup."""

import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.ec import slo, stripe
from seaweedfs_tpu.ec.constants import TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.pb import VOLUME_SERVICE

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")
ENC = Encoder(10, 4, backend="numpy")
VID = 9


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, name + ".py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- recorder -----------------------------------------------------------------


def test_recorder_quantiles_track_numpy():
    """Bucketed quantiles must stay within the geometric bucket width
    (~5%) of exact numpy percentiles on a skewed distribution — the
    recorder's one job is not lying about the tail."""
    rng = np.random.default_rng(5)
    samples = np.exp(rng.normal(-4.0, 1.0, size=20_000))  # lognormal, ~18ms median
    rec = slo.LatencyRecorder()
    for s in samples:
        rec.observe("steady", "healthy", float(s))
    cell = rec.merged("healthy")
    for q in (0.50, 0.95, 0.99):
        exact = float(np.percentile(samples, q * 100))
        got = cell.quantile(q)
        assert exact * 0.9 <= got <= exact * 1.12, (
            f"p{int(q*100)}: recorder {got} vs exact {exact}"
        )
    assert cell.total == len(samples)


def test_recorder_phases_classes_and_errors():
    rec = slo.LatencyRecorder()
    rec.observe("steady", "healthy", 0.01)
    rec.observe("steady", "degraded", 0.05)
    rec.observe("chaos", "degraded", 0.2)
    rec.error("chaos", "degraded")
    phases = rec.phases()
    assert set(phases) == {"steady", "chaos"}
    assert phases["chaos"]["degraded"]["errors"] == 1
    assert phases["steady"]["healthy"]["count"] == 1
    merged = rec.merged("degraded")
    assert merged.total == 2 and merged.errors == 1


def test_recorder_round_trips_and_merges_across_processes():
    """to_dict/merge_dict is the multi-process generator contract: a
    worker ships its cells as JSON and the driver folds them in. The
    merge must be bucket-exact (same quantiles as observing locally)
    and refuse cells from a different bucket layout."""
    a, b = slo.LatencyRecorder(), slo.LatencyRecorder()
    local = slo.LatencyRecorder()
    rng = np.random.default_rng(11)
    for s in np.exp(rng.normal(-4.0, 1.0, size=2000)):
        a.observe("steady", "cached", float(s))
        local.observe("steady", "cached", float(s))
    for s in np.exp(rng.normal(-3.0, 1.0, size=500)):
        b.observe("chaos", "degraded", float(s))
        local.observe("chaos", "degraded", float(s))
    b.error("chaos", "degraded")
    local.error("chaos", "degraded")

    merged = slo.LatencyRecorder()
    # JSON round-trip exactly as the worker files do
    merged.merge_dict(json.loads(json.dumps(a.to_dict())))
    merged.merge_dict(json.loads(json.dumps(b.to_dict())))
    for klass in ("cached", "degraded"):
        want, got = local.merged(klass), merged.merged(klass)
        assert got.total == want.total and got.errors == want.errors
        for q in (0.5, 0.99):
            assert got.quantile(q) == want.quantile(q)
    # a cell serialized by a different code version (bucket layout
    # mismatch) must be rejected loudly, not merged wrong
    bad = a.to_dict()
    key = next(iter(bad))
    bad[key]["counts"] = bad[key]["counts"][:-1]
    with pytest.raises(ValueError, match="bucket count mismatch"):
        slo.LatencyRecorder().merge_dict(bad)


def test_slo_verdict_and_report_schema(tmp_path):
    rec = slo.LatencyRecorder()
    for _ in range(30):
        rec.observe("steady", "healthy", 0.01)
        rec.observe("steady", "degraded", 0.03)
    verdict = slo.slo_verdict(rec, factor=5.0)
    assert verdict["ok"] and verdict["enough_samples"]
    assert verdict["ratio"] is not None and verdict["ratio"] < 5.0
    # degraded blows the budget -> not ok
    for _ in range(5):
        rec.observe("steady", "degraded", 3.0)
    assert not slo.slo_verdict(rec, factor=5.0)["ok"]
    # empty healthy side must yield None ratio (strict JSON), not Infinity
    empty = slo.LatencyRecorder()
    empty.observe("steady", "degraded", 0.1)
    v = slo.slo_verdict(empty)
    assert v["ratio"] is None and not v["ok"]
    json.dumps(v, allow_nan=False)  # must not raise
    # a mostly-FAILING degraded class must not certify the SLO off the
    # few reads that succeeded: the error-rate bound fails it
    erry = slo.LatencyRecorder()
    for _ in range(30):
        erry.observe("steady", "healthy", 0.01)
        erry.observe("steady", "degraded", 0.02)
    for _ in range(60):
        erry.error("steady", "degraded")
    ve = slo.slo_verdict(erry, factor=5.0)
    assert ve["ratio"] is not None and ve["ratio"] < 5.0
    assert ve["degraded_error_rate"] > 0.5 and not ve["ok"]

    report = slo.assemble_report(rec, workload={"rps": 1})
    for key in slo.REPORT_SCHEMA_KEYS:
        assert key in report
    out = tmp_path / "SLO_t.json"
    slo.write_report(str(out), report)
    again = json.loads(out.read_text())
    assert again["slo"]["target"].startswith("degraded_p99 < ")
    with pytest.raises(ValueError, match="missing required key"):
        slo.write_report(str(out), {"when": "x"})


# -- weedload smoke (tier-1 CI gate) ------------------------------------------


def test_weedload_smoke_schema_and_zero_loss(tmp_path):
    """The committed-artifact pipeline end to end on a tiny in-process
    cluster: weedload --smoke must finish inside the CI budget, write a
    schema-complete SLO artifact, observe all three traffic classes, and
    lose zero bytes."""
    weedload = _load_script("weedload")
    out = tmp_path / "SLO_smoke.json"
    t0 = time.monotonic()
    rc = weedload.main(["--smoke", "--out", str(out)])
    took = time.monotonic() - t0
    assert rc == 0, "weedload smoke lost bytes or crashed"
    # 30 s: the original 20 s load budget plus the tracing-overhead
    # gate's interleaved A/B phases (up to 3 damping attempts)
    assert took < 30.0, f"smoke run must stay under the 30 s CI budget ({took:.1f}s)"
    report = json.loads(out.read_text())
    for key in slo.REPORT_SCHEMA_KEYS:
        assert key in report, f"artifact missing {key}"
    assert report["lost"] == [] and report["ok"]
    assert report["workload"]["open_loop"] is True
    by_class = report["workload"]["objects_by_class"]
    assert by_class["healthy"] > 0 and by_class["degraded"] > 0
    # degraded traffic actually reconstructed server-side
    assert report["counters"]["weedtpu_degraded_read_seconds_count"] > 0
    merged_degraded = report["overall"]["degraded"]
    assert merged_degraded["count"] > 0 and merged_degraded["p99"] > 0
    # weedtrace rode along: per-stage tail attribution with stage sums
    # consistent with the observed end-to-end latencies (coverage is
    # exactly 1.0 by construction of attribute_stages), and the slowest
    # exemplar span trees retained
    attrib = report["trace_attribution"]
    for key in slo.TRACE_ATTRIB_SCHEMA_KEYS:
        assert key in attrib, f"trace attribution missing {key}"
    assert attrib["trace_count"] > 0
    for klass in ("healthy", "degraded"):
        cls = attrib["classes"][klass]
        assert cls["count"] > 0
        assert abs(cls["stage_coverage"] - 1.0) < 0.01, (klass, cls)
    assert len(attrib["slowest"]) >= 1
    assert all(t["root"].get("spans") is not None or t["kind"]
               for t in attrib["slowest"])
    # the leave-tracing-ON design claim, measured: trace-on healthy
    # p99/throughput within 5% of trace-off on the same live cluster, or
    # within the absolute per-read floor (loopback reads are so cheap
    # that tracing's fixed few-dozen-µs cost can exceed 5% relatively
    # while staying invisible against any real ms-scale read)
    overhead = report["trace_overhead"]
    assert overhead["ok"], f"tracing overhead gate failed: {overhead}"
    # hot-set serving: the decoded-interval cache must actually engage
    # under the zipf hot set (weedload itself exits 1 when hits == 0 —
    # these assertions pin the artifact evidence, not just the exit code)
    cache = report["cache"]
    assert cache["hits"] >= 1 and cache["hit_rate"] is not None
    assert cache["budget_mb"] > 0
    # the read-class header routed cache hits into their own class, so
    # `degraded` in this artifact means reads that actually decoded
    assert report["overall"]["cached"]["count"] > 0


def test_weedload_smoke_s3_front(tmp_path):
    """weedload --front s3: the same open-loop harness through the S3
    gateway (signed V4 requests -> s3api -> filer -> volume tier), with
    classes derived from the objects' chunk fids. The EC'd volume lives
    in the bucket's collection (`load_<vid>` on disk) — this smoke is
    what catches a harness that only handles the default collection."""
    weedload = _load_script("weedload")
    out = tmp_path / "SLO_smoke_s3.json"
    t0 = time.monotonic()
    rc = weedload.main(["--smoke", "--front", "s3", "--out", str(out)])
    took = time.monotonic() - t0
    assert rc == 0, "s3-front smoke lost bytes or crashed"
    assert took < 40.0, f"s3 smoke must stay inside the CI budget ({took:.1f}s)"
    report = json.loads(out.read_text())
    assert report["lost"] == [] and report["ok"]
    assert report["workload"]["front"] == "s3"
    by_class = report["workload"]["objects_by_class"]
    assert by_class["healthy"] > 0 and by_class["degraded"] > 0
    # degraded chunk reads reconstructed server-side through the gateway
    assert report["counters"]["weedtpu_degraded_read_seconds_count"] > 0
    assert report["overall"]["degraded"]["count"] > 0


# -- in-process cluster for server-side checks --------------------------------


def _build_ec_volume(dirpath: str, size: int = 400_000, seed: int = 3):
    base = os.path.join(dirpath, str(VID))
    rng = np.random.default_rng(seed)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    with open(base + ".idx", "wb"):
        pass
    stripe.write_ec_files(
        base, large_block_size=16384, small_block_size=4096, encoder=ENC
    )
    stripe.write_sorted_file_from_idx(base)
    golden = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            golden[s] = f.read()
    os.unlink(base + ".dat")
    return base, golden


@pytest.fixture
def mini_cluster(tmp_path):
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "srv0"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    yield master, vs
    vs.stop()
    master.stop()


def test_rebuild_admission_gate_counts_waits(tmp_path, monkeypatch):
    """With WEEDTPU_REBUILD_MAX_INFLIGHT=1, two concurrent slab streams
    serialize: the second waits for the token (counted) and both still
    deliver byte-correct CRC-framed data."""
    monkeypatch.setenv("WEEDTPU_REBUILD_MAX_INFLIGHT", "1")
    monkeypatch.setenv("WEEDTPU_REBUILD_YIELD_MS", "50")
    master = MasterServer(port=0, reap_interval=3600)
    master.start()
    d = tmp_path / "gated"
    d.mkdir()
    vs = VolumeServer([str(d)], master.address, heartbeat_interval=0.3)
    vs.start()
    try:
        base = vs._base_path_for(VID)
        _, golden = _build_ec_volume(str(d), size=3_000_000)
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": VID})
        waits0 = stats.RebuildAdmissionWaits.value
        results: dict[int, bytes] = {}

        def pull(i: int) -> None:
            with rpc.RpcClient(vs.grpc_address) as c:
                frames = c.stream(
                    VOLUME_SERVICE,
                    "VolumeEcShardSlabRead",
                    {
                        "volume_id": VID,
                        "shard_id": i,
                        "offset": 0,
                        "size": len(golden[i]),
                        # small chunks: each stream spans several frames, so
                        # the 50 ms inter-chunk yield keeps the token held
                        # long enough that the streams MUST overlap (a
                        # single-chunk stream can finish before the second
                        # thread is even scheduled — a coin-flip on 1 core)
                        "chunk_size": 64 * 1024,
                    },
                    timeout=60,
                )
                results[i] = b"".join(rpc.crc_unframe(f) for f in frames)

        threads = [threading.Thread(target=pull, args=(i,)) for i in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert results[1] == golden[1] and results[2] == golden[2]
        assert stats.RebuildAdmissionWaits.value - waits0 >= 1, (
            "second slab stream should have waited for the admission token"
        )
    finally:
        vs.stop()
        master.stop()


def test_degraded_read_maps_to_503_with_retry_after(mini_cluster):
    """A needle whose stripe lost too many shards must answer HTTP 503
    with a Retry-After hint and the typed error class — not a bare 500 —
    so load balancers/clients back off instead of hammering."""
    master, vs = mini_cluster
    client = MasterClient(master.address)
    try:
        fids = []
        for i in range(8):
            r = client.submit(os.urandom(12_000))
            fids.append(r.fid)
        vid = int(fids[0].split(",", 1)[0])
        with rpc.RpcClient(vs.grpc_address) as c:
            c.call(VOLUME_SERVICE, "VolumeMarkReadonly", {"volume_id": vid})
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsGenerate",
                {
                    "volume_id": vid,
                    "large_block_size": 16384,
                    "small_block_size": 4096,
                },
                timeout=120,
            )
            c.call(VOLUME_SERVICE, "VolumeEcShardsMount", {"volume_id": vid})
            c.call(VOLUME_SERVICE, "VolumeDelete", {"volume_id": vid})
            # lose 5 of 14: any reconstructing read is unservable
            c.call(
                VOLUME_SERVICE, "VolumeEcShardsDelete",
                {"volume_id": vid, "shard_ids": [0, 1, 2, 3, 4]},
            )
        errs0 = stats.DegradedReadErrors.labels("EcNoViableHolders").value
        saw_503 = 0
        for fid in fids:
            try:
                with urllib.request.urlopen(
                    f"http://{vs.url}/{fid}", timeout=30
                ) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                assert e.code == 503, f"expected 503, got {e.code}"
                assert e.headers.get("Retry-After") is not None
                body = json.loads(e.read().decode())
                assert body["class"] in (
                    "EcNoViableHolders", "EcDegradedReadTimeout"
                )
                assert "attempted" in body and "suspected" in body
                saw_503 += 1
        assert saw_503 > 0, "no needle hit the lost shards — fixture too small"
        assert stats.DegradedReadErrors.labels("EcNoViableHolders").value > errs0
    finally:
        client.close()


def test_lookup_retry_with_jitter_rides_out_transient_failures(
    mini_cluster, monkeypatch
):
    """The single-flight lookup leader retries transient master errors
    (WEEDTPU_LOOKUP_RETRIES) instead of failing every waiter on one
    hiccup; with retries disabled the old fail-fast behavior returns."""
    master, vs = mini_cluster
    master.topology.ec_locations[77] = {0: {"127.0.0.1:1"}}
    calls = {"n": 0}
    real_query = vs._master_query

    def flaky(method, req, timeout=5.0):
        if method == "LookupEcVolume":
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RuntimeError("transient master hiccup")
        return real_query(method, req, timeout)

    monkeypatch.setattr(vs, "_master_query", flaky)
    monkeypatch.setenv("WEEDTPU_LOOKUP_RETRIES", "2")
    locs = vs._lookup_shard_locations(77)
    assert calls["n"] == 3, "leader should have retried twice then succeeded"
    # the answer reached the caller (holders not on THIS node are filtered
    # out of the map, so emptiness is fine — no exception is the point)
    assert isinstance(locs, dict)

    vs._invalidate_shard_locations(77)
    calls["n"] = 0

    def always_down(method, req, timeout=5.0):
        if method == "LookupEcVolume":
            calls["n"] += 1
            raise RuntimeError("master down")
        return real_query(method, req, timeout)

    monkeypatch.setattr(vs, "_master_query", always_down)
    monkeypatch.setenv("WEEDTPU_LOOKUP_RETRIES", "0")
    with pytest.raises(RuntimeError, match="master down"):
        vs._lookup_shard_locations(77)
    assert calls["n"] == 1, "retries=0 must fail fast (pre-knob behavior)"
