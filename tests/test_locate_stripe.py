"""Stripe-engine tests: interval math validated through full encode->locate->
read round trips (the reference's ec_test.go golden pattern, SURVEY.md §4),
plus shard rebuild, decode-to-dat, and index sorting — all with scaled-down
block sizes so the large->small row transition is exercised cheaply."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.ec import locate, stripe
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ops.rs_codec import Encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle_map import MemDb

LARGE = 1024  # scaled-down ErasureCodingLargeBlockSize
SMALL = 64  # scaled-down ErasureCodingSmallBlockSize
BUF = 256

ENC = Encoder(10, 4, backend="numpy")


def make_dat(tmp_path, size, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    base = str(tmp_path / "v1")
    with open(base + ".dat", "wb") as f:
        f.write(data)
    return base, data


def encode(base):
    stripe.write_ec_files(base, large_block_size=LARGE, small_block_size=SMALL, buffer_size=BUF, encoder=ENC)


def read_via_intervals(base, data_len, offset, size):
    shard_size = os.path.getsize(stripe.shard_file_name(base, 0))
    dat_size_est = shard_size * DATA_SHARDS_COUNT
    ivs = locate.locate_data(LARGE, SMALL, dat_size_est, offset, size)
    out = b""
    for iv in ivs:
        sid, soff = iv.to_shard_id_and_offset(LARGE, SMALL)
        with open(stripe.shard_file_name(base, sid), "rb") as f:
            f.seek(soff)
            out += f.read(iv.size)
    return out


@pytest.mark.parametrize(
    "dat_size",
    [
        1,  # tiny: one small row
        SMALL * DATA_SHARDS_COUNT,  # exactly one small row
        SMALL * DATA_SHARDS_COUNT + 1,  # one small row + 1 byte
        LARGE * DATA_SHARDS_COUNT,  # exactly one large row -> encoded as small rows
        LARGE * DATA_SHARDS_COUNT + 1,  # one large row + tail
        2 * LARGE * DATA_SHARDS_COUNT + 3 * SMALL * DATA_SHARDS_COUNT + 17,  # mixed
    ],
)
def test_encode_layout_and_interval_roundtrip(tmp_path, dat_size):
    base, data = make_dat(tmp_path, dat_size)
    encode(base)
    sizes = {os.path.getsize(stripe.shard_file_name(base, s)) for s in range(TOTAL_SHARDS_COUNT)}
    assert len(sizes) == 1, "all shard files must be equal length"
    # every random sub-range reads back exactly via the interval math
    rng = np.random.default_rng(dat_size)
    probes = [(0, min(10, dat_size)), (max(0, dat_size - 7), min(7, dat_size))]
    for _ in range(20):
        off = int(rng.integers(0, dat_size))
        sz = int(rng.integers(1, min(3 * SMALL, dat_size - off) + 1))
        probes.append((off, sz))
    for off, sz in probes:
        if sz <= 0:
            continue
        got = read_via_intervals(base, dat_size, off, sz)
        assert got == data[off : off + sz], f"range ({off},{sz}) mismatch"


def test_parity_consistency(tmp_path):
    base, _ = make_dat(tmp_path, 3 * SMALL * DATA_SHARDS_COUNT + 5)
    encode(base)
    shard_size = os.path.getsize(stripe.shard_file_name(base, 0))
    shards = []
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            shards.append(np.frombuffer(f.read(), dtype=np.uint8))
    assert all(len(s) == shard_size for s in shards)
    assert ENC.verify(shards)


@pytest.mark.parametrize("lost", [[0], [13], [0, 5, 10, 13], [6, 7, 8, 9]])
def test_rebuild_roundtrip(tmp_path, lost):
    base, _ = make_dat(tmp_path, LARGE * DATA_SHARDS_COUNT + 2 * SMALL * DATA_SHARDS_COUNT + 9)
    encode(base)
    orig = {}
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            orig[s] = f.read()
    for s in lost:
        os.remove(stripe.shard_file_name(base, s))
    rebuilt = stripe.rebuild_ec_files(base, encoder=ENC, buffer_size=BUF)
    assert sorted(rebuilt) == sorted(lost)
    for s in range(TOTAL_SHARDS_COUNT):
        with open(stripe.shard_file_name(base, s), "rb") as f:
            assert f.read() == orig[s], f"shard {s} differs after rebuild"


def test_rebuild_too_few_shards(tmp_path):
    base, _ = make_dat(tmp_path, SMALL * DATA_SHARDS_COUNT)
    encode(base)
    for s in range(5):
        os.remove(stripe.shard_file_name(base, s))
    os.remove(stripe.shard_file_name(base, 13))
    with pytest.raises(ValueError, match="cannot rebuild"):
        stripe.rebuild_ec_files(base, encoder=ENC, buffer_size=BUF)


def test_decode_to_dat(tmp_path):
    size = LARGE * DATA_SHARDS_COUNT + SMALL * DATA_SHARDS_COUNT + 123
    base, data = make_dat(tmp_path, size)
    encode(base)
    os.rename(base + ".dat", base + ".dat.orig")
    stripe.write_dat_file(base, size, large_block_size=LARGE, small_block_size=SMALL)
    with open(base + ".dat", "rb") as f:
        assert f.read() == data


def test_sorted_ecx_from_idx(tmp_path):
    base = str(tmp_path / "v2")
    entries = [
        (5, 1, 100),
        (3, 2, 50),
        (9, 3, 10),
        (3, 4, 60),  # update of key 3 -> last wins
        (5, 0, types.TOMBSTONE_FILE_SIZE),  # delete of key 5
    ]
    idx_mod.write_entries(entries, base + ".idx")
    stripe.write_sorted_file_from_idx(base)
    with open(base + ".ecx", "rb") as f:
        got = list(idx_mod.walk_index_buffer(f.read()))
    assert got == [(3, 4, 60), (9, 3, 10)]


def test_idx_from_ec_index_with_deletions(tmp_path):
    base = str(tmp_path / "v3")
    idx_mod.write_entries([(1, 1, 10), (2, 2, 20)], base + ".idx")
    stripe.write_sorted_file_from_idx(base)
    stripe.append_ecj(base, 2)
    stripe.write_idx_file_from_ec_index(base)
    db = MemDb()
    db.load_from_idx(base + ".idx")
    assert db.get(1) == (1, 10)
    assert db.get(2) is None


def test_memdb_idx_replay(tmp_path):
    db = MemDb()
    p = str(tmp_path / "x.idx")
    idx_mod.write_entries([(7, 3, 40), (7, 0, types.TOMBSTONE_FILE_SIZE), (8, 9, 1)], p)
    db.load_from_idx(p)
    assert db.get(7) is None and db.get(8) == (9, 1)


def test_rebuild_rejects_truncated_survivor(tmp_path):
    base, _ = make_dat(tmp_path, 2 * SMALL * DATA_SHARDS_COUNT)
    encode(base)
    os.remove(stripe.shard_file_name(base, 13))
    p = stripe.shard_file_name(base, 3)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(IOError, match="disagree"):
        stripe.rebuild_ec_files(base, encoder=ENC, buffer_size=BUF)


def test_write_dat_file_stale_size_raises(tmp_path):
    base, data = make_dat(tmp_path, SMALL * DATA_SHARDS_COUNT)
    encode(base)
    with pytest.raises(IOError, match="exhausted"):
        stripe.write_dat_file(base, len(data) * 100, large_block_size=LARGE, small_block_size=SMALL)
