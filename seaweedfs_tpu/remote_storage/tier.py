"""Volume tiering — mirror of weed/shell/command_volume_tier_move.go /
command_volume_tier_upload/download + weed/storage/backend/s3_backend
volume tiering [VERIFY: mount empty; SURVEY.md §2.1 "Remote storage
tiering" row].

`tier_move` uploads a volume's .dat to a remote vendor and replaces it
with `<base>.tierinfo` (JSON: vendor location + key + size). The volume
engine (storage/volume.py) detects the tierinfo file on load and serves
needle reads through a RemoteDatFile. `tier_fetch` brings the .dat back
and removes the tierinfo.
"""

from __future__ import annotations

import json
import os

from seaweedfs_tpu.remote_storage import RemoteStorageClient, make_remote_client

TIER_EXT = ".tierinfo"


def tier_info_path(base_path: str) -> str:
    return base_path + TIER_EXT


def read_tier_info(base_path: str) -> dict:
    with open(tier_info_path(base_path), encoding="utf-8") as f:
        return json.load(f)


def tier_move(
    base_path: str,
    client: RemoteStorageClient,
    key_prefix: str = "volumes/",
    keep_local: bool = False,
) -> dict:
    """Upload <base>.dat to the vendor, write <base>.tierinfo, drop the
    local .dat (unless keep_local). Returns the tier info dict."""
    dat = base_path + ".dat"
    if os.path.exists(tier_info_path(base_path)):
        raise IOError(f"{base_path} is already tiered")
    size = os.path.getsize(dat)
    key = key_prefix + os.path.basename(dat)
    with open(dat, "rb") as f:
        client.write_stream(key, f, size)  # chunked: volumes are multi-GB
    # verify before dropping the only local copy
    if client.size(key) != size:
        client.delete(key)
        raise IOError(f"tier upload size mismatch for {dat}")
    info = {"location": client.location(), "key": key, "size": size}
    tmp = tier_info_path(base_path) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(info, f)
        f.flush()
        # the tierinfo is about to be the ONLY pointer to the moved volume
        # (local .dat removed below) — it must be durable first
        os.fsync(f.fileno())
    os.replace(tmp, tier_info_path(base_path))
    if not keep_local:
        os.remove(dat)
    return info


def tier_fetch(base_path: str) -> None:
    """Download the tiered .dat back (chunked) and remove the tierinfo."""
    info = read_tier_info(base_path)
    client = make_remote_client(info["location"])
    tmp = base_path + ".dat.fetch"
    client.read_to_file(info["key"], tmp, info["size"])
    if os.path.getsize(tmp) != info["size"]:
        os.remove(tmp)
        raise IOError(f"tier fetch size mismatch for {base_path}")
    os.replace(tmp, base_path + ".dat")
    os.remove(tier_info_path(base_path))


def open_tiered_dat(base_path: str):
    """RemoteDatFile for a tiered volume (used by Volume on load)."""
    from seaweedfs_tpu.storage.backend import RemoteDatFile

    info = read_tier_info(base_path)
    client = make_remote_client(info["location"])
    return RemoteDatFile(client, info["key"], size=info["size"])
