"""Remote storage — mirror of weed/remote_storage/ (the vendor wall:
s3, gcs, azure, ...) [VERIFY: mount empty; SURVEY.md §2.1 "Remote
storage tiering" row].

`RemoteStorageClient` is the vendor interface. Two concrete vendors fit
this image: a local-directory vendor (the reference also ships one for
dev/testing) and an S3 vendor that signs with this framework's own
SigV4 implementation — pointable at the in-tree S3 gateway or any
external endpoint.

Used by volume tiering (remote_storage.tier): a cold volume's .dat
moves to remote storage and reads flow back through `read_range`.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional


_STREAM_CHUNK = 16 * 1024 * 1024


class RemoteStorageClient:
    vendor = "abstract"

    def write_file(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def write_stream(self, key: str, reader, size: int) -> None:
        """Upload from a file-like without materializing it when the
        vendor can stream; the base impl buffers (single-PUT vendors)."""
        self.write_file(key, reader.read(size))

    def read_to_file(self, key: str, path: str, size: int) -> None:
        """Ranged download in chunks — never holds the object in RAM."""
        with open(path, "wb") as f:
            pos = 0
            while pos < size:
                n = min(_STREAM_CHUNK, size - pos)
                data = self.read_range(key, pos, n)
                if not data:
                    raise IOError(f"short remote read of {key} at {pos}")
                f.write(data)
                pos += len(data)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def size(self, key: str) -> int:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def location(self) -> dict:
        """Serializable description; make_remote_client(location) must
        reconstruct an equivalent client (stored in .tierinfo files)."""
        raise NotImplementedError


class LocalRemoteStorage(RemoteStorageClient):
    """Directory-backed vendor (the reference's remote_storage local dev
    vendor): key -> file under root."""

    vendor = "local"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.abspath(os.path.join(self.root, key.lstrip("/")))
        # separator-anchored check: '/srv/tier2' must not pass for root
        # '/srv/tier'
        if not (p == self.root or p.startswith(self.root + os.sep)):
            raise ValueError(f"key {key!r} escapes the vendor root")
        return p

    def write_file(self, key: str, data: bytes) -> None:
        import io

        self.write_stream(key, io.BytesIO(data), len(data))

    def write_stream(self, key: str, reader, size: int) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".part"
        with open(tmp, "wb") as f:
            remaining = size
            while remaining > 0:
                chunk = reader.read(min(_STREAM_CHUNK, remaining))
                if not chunk:
                    raise IOError(f"short reader for {key}")
                f.write(chunk)
                remaining -= len(chunk)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        with open(self._path(key), "rb") as f:
            f.seek(offset)
            return f.read(size)

    def size(self, key: str) -> int:
        return os.path.getsize(self._path(key))

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def location(self) -> dict:
        return {"vendor": "local", "root": self.root}


class S3RemoteStorage(RemoteStorageClient):
    """S3-endpoint vendor using the in-tree SigV4 signer."""

    vendor = "s3"

    def __init__(self, endpoint: str, bucket: str, access_key: str = "", secret_key: str = ""):
        self.endpoint = endpoint
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key

    def _request(
        self, method: str, key: str, data: bytes = b"", headers: Optional[dict] = None
    ):
        from seaweedfs_tpu.s3api.auth import sign_request

        url = f"http://{self.endpoint}/{self.bucket}/{urllib.parse.quote(key.lstrip('/'))}"
        signed = sign_request(
            self.access_key, self.secret_key, method, url, data,
            extra_headers=headers or {},
        )
        req = urllib.request.Request(
            url, data=data if data else None, method=method, headers=signed
        )
        return urllib.request.urlopen(req, timeout=60)

    def write_file(self, key: str, data: bytes) -> None:
        with self._request("PUT", key, data) as r:
            r.read()

    def read_range(self, key: str, offset: int, size: int) -> bytes:
        # Range is not part of the SigV4 signed headers set we emit, so
        # sign normally and add Range after
        from seaweedfs_tpu.s3api.auth import sign_request

        url = f"http://{self.endpoint}/{self.bucket}/{urllib.parse.quote(key.lstrip('/'))}"
        signed = sign_request(self.access_key, self.secret_key, "GET", url, b"")
        signed["Range"] = f"bytes={offset}-{offset + size - 1}"
        req = urllib.request.Request(url, headers=signed)
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def size(self, key: str) -> int:
        with self._request("HEAD", key) as r:
            return int(r.headers.get("Content-Length", 0))

    def delete(self, key: str) -> None:
        try:
            with self._request("DELETE", key) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def location(self) -> dict:
        return {
            "vendor": "s3",
            "endpoint": self.endpoint,
            "bucket": self.bucket,
            "access_key": self.access_key,
            "secret_key": self.secret_key,
        }


def make_remote_client(location: dict) -> RemoteStorageClient:
    vendor = location.get("vendor", "")
    if vendor == "local":
        return LocalRemoteStorage(location["root"])
    if vendor == "s3":
        return S3RemoteStorage(
            location["endpoint"],
            location["bucket"],
            location.get("access_key", ""),
            location.get("secret_key", ""),
        )
    raise ValueError(f"unknown remote storage vendor {vendor!r} (local|s3)")
