"""Wire contracts — the dataclass mirror of weed/pb/master.proto +
volume_server.proto [VERIFY: mount empty; SURVEY.md §2.1 "Protos" row].

Two wire formats share the contracts.proto schema: the default JSON
transport over seaweedfs_tpu.rpc's generic handlers, and a BINARY
PROTOBUF wire (WEEDTPU_WIRE=proto) built by pb/wire.py from a protoc
FileDescriptorSet at runtime — grpcio-tools codegen is absent from the
image, so message classes come from google.protobuf.message_factory
instead of generated _pb2 modules. Field names below match the
reference protos.

Services and methods (paths are /<service>/<method>):

  weedtpu.Master       — Assign, Lookup, LookupEcVolume, VolumeList,
                         Heartbeat (unary here: full-state report returning
                         config; the reference's bidi stream collapses to
                         periodic unaries), LeaveCluster, Statistics
  weedtpu.VolumeServer — WriteNeedle, ReadNeedle, DeleteNeedle (data path
                         also has HTTP); VolumeCreate, VolumeDelete,
                         VolumeMarkReadonly, VolumeMarkWritable,
                         VolumeCompact, VolumeStatus,
                         + the EC surface (SURVEY.md §2.4):
                         VolumeEcShardsGenerate, VolumeEcShardsCopy (stream),
                         VolumeEcShardsRebuild, VolumeEcShardsConvert,
                         VolumeEcShardsVerify,
                         VolumeEcShardsMount,
                         VolumeEcShardsUnmount, VolumeEcShardRead (stream),
                         VolumeEcBlobDelete, VolumeEcShardsToVolume,
                         VolumeEcShardsDelete
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Optional

MASTER_SERVICE = "weedtpu.Master"
VOLUME_SERVICE = "weedtpu.VolumeServer"
FILER_SERVICE = "weedtpu.Filer"
MQ_SERVICE = "weedtpu.MessageQueue"


@dataclass
class Location:
    url: str  # host:port of the volume server HTTP endpoint
    public_url: str = ""
    grpc_port: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Location":
        return cls(
            url=d["url"],
            public_url=d.get("public_url") or d["url"],
            grpc_port=int(d.get("grpc_port", 0)),
        )

    @property
    def grpc_address(self) -> str:
        host = self.url.rsplit(":", 1)[0]
        return f"{host}:{self.grpc_port}"


@dataclass
class VolumeInformation:
    """One volume's heartbeat row (VolumeInformationMessage analog)."""

    id: int
    size: int = 0
    collection: str = ""
    file_count: int = 0
    delete_count: int = 0
    read_only: bool = False
    replica_placement: str = "000"
    ttl: str = ""
    version: int = 3
    disk_type: str = ""
    garbage_ratio: float = 0.0  # dead fraction of .dat; auto-vacuum signal
    last_modified: int = 0      # unix secs of the last append (.dat mtime)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeInformation":
        return cls(
            id=int(d["id"]),
            size=int(d.get("size", 0)),
            collection=d.get("collection", ""),
            file_count=int(d.get("file_count", 0)),
            delete_count=int(d.get("delete_count", 0)),
            read_only=bool(d.get("read_only", False)),
            replica_placement=d.get("replica_placement", "000"),
            ttl=d.get("ttl", ""),
            version=int(d.get("version", 3)),
            disk_type=d.get("disk_type", ""),
            last_modified=int(d.get("last_modified", 0)),
            garbage_ratio=float(d.get("garbage_ratio", 0.0)),
        )


@dataclass
class Heartbeat:
    """Full-state volume-server report (HeartbeatMessage analog)."""

    ip: str
    port: int
    grpc_port: int
    public_url: str = ""
    data_center: str = "DefaultDataCenter"
    rack: str = "DefaultRack"
    max_volume_count: int = 8
    volumes: list[dict] = field(default_factory=list)  # VolumeInformation dicts
    ec_shards: list[dict] = field(default_factory=list)  # EcVolumeInfo dicts
    # peers (grpc host:port) this server repeatedly failed to reach on
    # the degraded-read/rebuild paths — the master's repair scheduler
    # cross-checks them against heartbeat silence to learn about dead
    # holders without waiting for the topology reaper
    unreachable_peers: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Heartbeat":
        return cls(
            ip=d["ip"],
            port=int(d["port"]),
            grpc_port=int(d["grpc_port"]),
            public_url=d.get("public_url", ""),
            data_center=d.get("data_center", "DefaultDataCenter"),
            rack=d.get("rack", "DefaultRack"),
            max_volume_count=int(d.get("max_volume_count", 8)),
            volumes=list(d.get("volumes", [])),
            ec_shards=list(d.get("ec_shards", [])),
            unreachable_peers=list(d.get("unreachable_peers", [])),
        )

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"


@dataclass
class AssignRequest:
    count: int = 1
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    data_center: str = ""

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class AssignResponse:
    fid: str = ""
    url: str = ""
    public_url: str = ""
    grpc_port: int = 0
    count: int = 0
    error: str = ""
    auth: str = ""  # JWT authorizing the write of fid (when security is on)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AssignResponse":
        return cls(
            fid=d.get("fid", ""),
            url=d.get("url", ""),
            public_url=d.get("public_url", ""),
            grpc_port=int(d.get("grpc_port", 0)),
            count=int(d.get("count", 0)),
            error=d.get("error", ""),
            auth=d.get("auth", ""),
        )
