"""Binary protobuf wire for the pinned contracts — the real protobuf
transport the reference speaks ([ref: weed/pb/*.proto — mount empty,
SURVEY.md §2.6]).

This image has protoc and the google.protobuf runtime but not
protoc-gen-python/grpcio-tools, so instead of generated _pb2 modules the
codec builds message classes AT RUNTIME from a FileDescriptorSet:
protoc compiles `contracts.proto` to `contracts.desc` (regenerated on
demand when protoc is present; the committed artifact serves
protoc-less deploys), and `message_factory` turns each descriptor into
a concrete class.

Handlers keep their dict-shaped requests/responses — the codec converts
strictly between dicts and messages:

  - field names match 1:1 (the dict key IS the proto field name);
    an unknown dict key raises instead of silently dropping data
  - 64-bit ints stay Python ints (proto3 JSON would stringify them)
  - `bytes` fields carry base64 strings in the dicts (the JSON wire's
    convention) and raw bytes on the wire
  - maps accept str keys for integer key types ({"7": ...}), matching
    how JSON object keys arrive today

Switch: WEEDTPU_WIRE=proto flips every unary JSON method whose
(service, method) pair exists in the schema to binary protobuf on BOTH
the server's generic handlers and the client stubs; streams keep their
raw byte frames. All processes of a cluster must agree (same env),
like a reference cluster agrees on its .proto version.

Measured (1-core host, 2026-08-02, after the plan-compiled converters —
see WireCodec): conversion-level roundtrip (dict -> message -> bytes ->
message -> dict vs json.dumps+loads) on the Assign request/response is
~123k/91k per second, 0.94-0.95x JSON — the r5 descriptor-walking codec
measured 0.32-0.41x, so the closures bought ~3x and flat control RPCs
are now at parity. DEEPLY NESTED dumps (LookupEcVolume's 14x2 location
tree, the topology dump) still run ~0.3x JSON: the remaining cost is
the pure-Python protobuf runtime building one message object per node,
which no converter layer can remove on this no-upb image. Verdict
unchanged in kind, sharpened in scope: the binary wire is CONTRACT-
PARITY-ONLY for nested topology dumps (see BASELINE.md measured table),
at-parity for flat control RPCs. JSON stays the default; bulk data
never rides either (raw byte frames).
"""

from __future__ import annotations

import base64
import functools
import os
import shutil
import subprocess
import threading

from seaweedfs_tpu.utils import config

_HERE = os.path.dirname(os.path.abspath(__file__))
PROTO_PATH = os.path.join(_HERE, "contracts.proto")
DESC_PATH = os.path.join(_HERE, "contracts.desc")

_lock = threading.Lock()


# Wrapper messages: proto map values cannot themselves be maps or
# repeated, so the schema wraps them (RackMap{racks}, UrlList{urls}, ...)
# while the dicts keep their natural bare shape ({rack: [nodes]}). The
# codec unwraps/rewraps EXACTLY these registered messages — inferring
# wrapperness from shape would misfire on real single-field messages
# like LookupRequest.
WRAPPER_FIELD = {
    "weedtpu.DataNodeList": "nodes",
    "weedtpu.RackMap": "racks",
    "weedtpu.UrlList": "urls",
    "weedtpu.ShardHolderMap": "shards",
}


def _is_repeated(fd) -> bool:
    rep = getattr(fd, "is_repeated", None)
    if rep is not None:
        return rep() if callable(rep) else bool(rep)
    return fd.label == fd.LABEL_REPEATED  # older protobuf runtimes


def _bytes_in(value):
    if isinstance(value, (bytes, bytearray)):
        return bytes(value)
    return base64.b64decode(value)  # dicts carry b64 strings


def _scalar_converter(fd):
    """dict value -> proto field value coercion, resolved ONCE per field
    at plan-build time (the old path re-dispatched on fd.type per value)."""
    t = fd.type
    if t in (fd.TYPE_INT32, fd.TYPE_INT64, fd.TYPE_UINT32, fd.TYPE_UINT64,
             fd.TYPE_SINT32, fd.TYPE_SINT64, fd.TYPE_FIXED32, fd.TYPE_FIXED64,
             fd.TYPE_SFIXED32, fd.TYPE_SFIXED64):
        return int  # str keys like {"7": ...} arrive from JSON habits
    if t in (fd.TYPE_FLOAT, fd.TYPE_DOUBLE):
        return float
    if t == fd.TYPE_BOOL:
        return bool
    if t == fd.TYPE_BYTES:
        return _bytes_in
    if t == fd.TYPE_STRING:
        name = fd.name

        def check_str(value, _n=name):
            if not isinstance(value, str):
                raise ValueError(
                    f"field {_n}: expected str, got {type(value).__name__}"
                )
            return value

        return check_str
    name = fd.name

    def unsupported(value, _n=name, _t=t):
        raise ValueError(f"field {_n}: unsupported proto type {_t}")

    return unsupported


def _scalar_out_converter(fd):
    """proto field value -> dict value; None means identity (the common
    case — the caller skips the call entirely)."""
    if fd.type == fd.TYPE_BYTES:
        return lambda v: base64.b64encode(bytes(v)).decode()
    return None


def wire_format() -> str:
    """'proto' or 'json' — the process-wide wire selection."""
    return config.env("WEEDTPU_WIRE")


def _descriptor_set_bytes() -> bytes:
    """Fresh descriptor set from protoc when available (keeps the wire in
    lockstep with contracts.proto), else the committed artifact."""
    protoc = shutil.which("protoc")
    if protoc is not None:
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".desc") as tmp:
            proc = subprocess.run(
                [
                    protoc,
                    f"--proto_path={_HERE}",
                    "--include_imports",
                    f"--descriptor_set_out={tmp.name}",
                    PROTO_PATH,
                ],
                capture_output=True,
                timeout=60,
            )
            if proc.returncode == 0:
                tmp.seek(0)
                raw = tmp.read()
                if raw:
                    return raw
    with open(DESC_PATH, "rb") as f:
        return f.read()


class WireCodec:
    """(service, method) -> request/response message classes + strict
    dict<->message conversion.

    Conversion is PLAN-COMPILED: the first encounter of each message type
    builds per-field converter closures (field kind, scalar coercion,
    wrapper/presence semantics all resolved ONCE from the descriptor) and
    every later call is a dict walk over prebound closures — no per-value
    descriptor inspection or type-dispatch if-chains on the hot path
    (~2.4-3x the conversion throughput of the descriptor-walking codec;
    see the measured note in the module docstring)."""

    def __init__(self) -> None:
        from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

        self._pool = descriptor_pool.DescriptorPool()
        fds = descriptor_pb2.FileDescriptorSet.FromString(_descriptor_set_bytes())
        for fdp in fds.file:
            self._pool.Add(fdp)
        self._factory = message_factory
        # compiled conversion plans, keyed by message full_name
        self._fill_plans: dict[str, dict] = {}
        self._read_plans: dict[str, list] = {}
        # service methods: (service_full_name, method) -> (req_cls, resp_cls)
        self._methods: dict[tuple[str, str], tuple] = {}
        for fdp in fds.file:
            for svc in fdp.service:
                full = f"{fdp.package}.{svc.name}" if fdp.package else svc.name
                sdesc = self._pool.FindServiceByName(full)
                for m in sdesc.methods:
                    self._methods[(full, m.name)] = (
                        message_factory.GetMessageClass(m.input_type),
                        message_factory.GetMessageClass(m.output_type),
                    )

    def has(self, service: str, method: str) -> bool:
        return (service, method) in self._methods

    def classes(self, service: str, method: str):
        return self._methods[(service, method)]

    # -- dict -> message ------------------------------------------------------

    def to_message(self, d: dict, cls):
        msg = cls()
        self._fill(msg, d or {})
        return msg

    def _fill(self, msg, d) -> None:
        desc = msg.DESCRIPTOR
        wrap = WRAPPER_FIELD.get(desc.full_name)
        if wrap is not None:
            # wrapper values arrive in their natural bare shape, always
            # (to_dict only ever emits bare; a rack literally named
            # "racks" must not flip the interpretation)
            d = {wrap: d}
        if not isinstance(d, dict):
            raise ValueError(
                f"{desc.full_name}: expected an object, got {type(d).__name__}"
            )
        plan = self._fill_plans.get(desc.full_name)
        if plan is None:
            plan = self._build_fill_plan(desc)
        for key, value in d.items():
            filler = plan.get(key)
            if filler is None:
                raise ValueError(
                    f"{desc.full_name}: dict key {key!r} is not a schema field"
                )
            if value is None:
                continue  # absent on the wire, like a missing JSON key
            filler(msg, value)

    def _build_fill_plan(self, desc) -> dict:
        """field name -> filler(msg, value) closure, every per-field
        decision (map/repeated/message/scalar kind, scalar coercion)
        resolved once from the descriptor."""
        plan: dict = {}
        fill = self._fill
        for fd in desc.fields:
            name = fd.name
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                key_conv = _scalar_converter(fd.message_type.fields_by_name["key"])
                val_fd = fd.message_type.fields_by_name["value"]
                if val_fd.message_type is not None:
                    def filler(msg, value, _n=name, _kc=key_conv):
                        tgt = getattr(msg, _n)
                        for k, v in value.items():
                            fill(tgt[_kc(k)], v)
                else:
                    val_conv = _scalar_converter(val_fd)
                    def filler(msg, value, _n=name, _kc=key_conv, _vc=val_conv):
                        tgt = getattr(msg, _n)
                        for k, v in value.items():
                            tgt[_kc(k)] = _vc(v)
            elif _is_repeated(fd):
                if fd.message_type is not None:
                    def filler(msg, value, _n=name):
                        tgt = getattr(msg, _n)
                        for item in value:
                            fill(tgt.add(), item)
                else:
                    conv = _scalar_converter(fd)
                    def filler(msg, value, _n=name, _c=conv):
                        getattr(msg, _n).extend(_c(item) for item in value)
            elif fd.message_type is not None:
                def filler(msg, value, _n=name):
                    fill(getattr(msg, _n), value)
            else:
                conv = _scalar_converter(fd)
                def filler(msg, value, _n=name, _c=conv):
                    setattr(msg, _n, _c(value))
            plan[name] = filler
        self._fill_plans[desc.full_name] = plan
        return plan

    # -- message -> dict ------------------------------------------------------

    def to_dict(self, msg):
        desc = msg.DESCRIPTOR
        wrap = WRAPPER_FIELD.get(desc.full_name)
        if wrap is not None:
            inner = self._to_dict_fields(msg)
            fd = desc.fields_by_name[wrap]
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                return inner.get(wrap, {})
            return inner.get(wrap, [])
        return self._to_dict_fields(msg)

    def _to_dict_fields(self, msg) -> dict:
        desc = msg.DESCRIPTOR
        plan = self._read_plans.get(desc.full_name)
        if plan is None:
            plan = self._build_read_plan(desc)
        out: dict = {}
        for extract in plan:
            extract(msg, out)
        return out

    def _build_read_plan(self, desc) -> list:
        """List of extract(msg, out) closures — one per field, each with
        its map/repeated/presence/bytes handling prebound."""
        plan: list = []
        to_dict = self.to_dict
        for fd in desc.fields:
            name = fd.name
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                # maps always emit (possibly {}): readers index resp["x"]
                val_fd = fd.message_type.fields_by_name["value"]
                if val_fd.message_type is not None:
                    def extract(msg, out, _n=name):
                        out[_n] = {k: to_dict(v) for k, v in getattr(msg, _n).items()}
                elif val_fd.type == val_fd.TYPE_BYTES:
                    def extract(msg, out, _n=name):
                        out[_n] = {
                            k: base64.b64encode(bytes(v)).decode()
                            for k, v in getattr(msg, _n).items()
                        }
                else:
                    # JSON object keys are strings; handlers already int()
                    # them — keep native ints for int-keyed maps (both
                    # sides accept them)
                    def extract(msg, out, _n=name):
                        out[_n] = dict(getattr(msg, _n).items())
            elif _is_repeated(fd):
                # repeated always emits (possibly []), same reason
                if fd.message_type is not None:
                    def extract(msg, out, _n=name):
                        out[_n] = [to_dict(v) for v in getattr(msg, _n)]
                elif fd.type == fd.TYPE_BYTES:
                    def extract(msg, out, _n=name):
                        out[_n] = [
                            base64.b64encode(bytes(v)).decode()
                            for v in getattr(msg, _n)
                        ]
                else:
                    def extract(msg, out, _n=name):
                        out[_n] = list(getattr(msg, _n))
            elif fd.message_type is not None:
                def extract(msg, out, _n=name):
                    if msg.HasField(_n):
                        out[_n] = to_dict(getattr(msg, _n))
            elif fd.has_presence:
                # `optional` scalar: absent and explicit-default differ on
                # the wire AND to handlers (.get(k, True) patterns —
                # copy_ecx_file / is_delete_data)
                conv = _scalar_out_converter(fd)
                if conv is None:
                    def extract(msg, out, _n=name):
                        if msg.HasField(_n):
                            out[_n] = getattr(msg, _n)
                else:
                    def extract(msg, out, _n=name, _c=conv):
                        if msg.HasField(_n):
                            out[_n] = _c(getattr(msg, _n))
            else:
                # plain proto3 scalar: zero == unset on the wire, so the
                # dict always carries the key (the codebase's dominant
                # pattern is req["volume_id"]-style indexing; the few
                # handlers with NON-zero defaults use `.get(k) or default`
                # or-defaulting, which treats explicit zero as unset —
                # exactly proto3's semantics)
                conv = _scalar_out_converter(fd)
                if conv is None:
                    def extract(msg, out, _n=name):
                        out[_n] = getattr(msg, _n)
                else:
                    def extract(msg, out, _n=name, _c=conv):
                        out[_n] = _c(getattr(msg, _n))
            plan.append(extract)
        self._read_plans[desc.full_name] = plan
        return plan

    # -- gRPC (de)serializers --------------------------------------------------

    def request_serdes(self, service: str, method: str):
        """(serializer, deserializer) for the REQUEST message."""
        req_cls, _ = self.classes(service, method)
        return (
            lambda d: self.to_message(d, req_cls).SerializeToString(),
            lambda raw: self.to_dict(req_cls.FromString(raw)),
        )

    def response_serdes(self, service: str, method: str):
        _, resp_cls = self.classes(service, method)
        return (
            lambda d: self.to_message(d, resp_cls).SerializeToString(),
            lambda raw: self.to_dict(resp_cls.FromString(raw)),
        )


@functools.lru_cache(maxsize=1)
def codec() -> WireCodec:
    with _lock:
        return WireCodec()


def regenerate_descriptor_artifact() -> bytes:
    """Write contracts.desc next to the proto (CI/commit-time helper; the
    drift test asserts the artifact matches what protoc emits)."""
    raw = _descriptor_set_bytes()
    with open(DESC_PATH, "wb") as f:
        f.write(raw)
    return raw
