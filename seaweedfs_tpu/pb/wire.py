"""Binary protobuf wire for the pinned contracts — the real protobuf
transport the reference speaks ([ref: weed/pb/*.proto — mount empty,
SURVEY.md §2.6]).

This image has protoc and the google.protobuf runtime but not
protoc-gen-python/grpcio-tools, so instead of generated _pb2 modules the
codec builds message classes AT RUNTIME from a FileDescriptorSet:
protoc compiles `contracts.proto` to `contracts.desc` (regenerated on
demand when protoc is present; the committed artifact serves
protoc-less deploys), and `message_factory` turns each descriptor into
a concrete class.

Handlers keep their dict-shaped requests/responses — the codec converts
strictly between dicts and messages:

  - field names match 1:1 (the dict key IS the proto field name);
    an unknown dict key raises instead of silently dropping data
  - 64-bit ints stay Python ints (proto3 JSON would stringify them)
  - `bytes` fields carry base64 strings in the dicts (the JSON wire's
    convention) and raw bytes on the wire
  - maps accept str keys for integer key types ({"7": ...}), matching
    how JSON object keys arrive today

Switch: WEEDTPU_WIRE=proto flips every unary JSON method whose
(service, method) pair exists in the schema to binary protobuf on BOTH
the server's generic handlers and the client stubs; streams keep their
raw byte frames. All processes of a cluster must agree (same env),
like a reference cluster agrees on its .proto version.

Measured (1-core host, loopback, 2026-07-30): Assign ~2.1k rpc/s JSON
vs ~2.0k proto; the topology dump ~2.2k vs ~1.7k — the dict<->message
walk is Python while json.dumps is C, so the binary wire buys contract
strictness and reference wire-shape parity, not speed. JSON stays the
default; bulk data never rides either (raw byte frames).
"""

from __future__ import annotations

import base64
import functools
import os
import shutil
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
PROTO_PATH = os.path.join(_HERE, "contracts.proto")
DESC_PATH = os.path.join(_HERE, "contracts.desc")

_lock = threading.Lock()


# Wrapper messages: proto map values cannot themselves be maps or
# repeated, so the schema wraps them (RackMap{racks}, UrlList{urls}, ...)
# while the dicts keep their natural bare shape ({rack: [nodes]}). The
# codec unwraps/rewraps EXACTLY these registered messages — inferring
# wrapperness from shape would misfire on real single-field messages
# like LookupRequest.
WRAPPER_FIELD = {
    "weedtpu.DataNodeList": "nodes",
    "weedtpu.RackMap": "racks",
    "weedtpu.UrlList": "urls",
    "weedtpu.ShardHolderMap": "shards",
}


def _is_repeated(fd) -> bool:
    rep = getattr(fd, "is_repeated", None)
    if rep is not None:
        return rep() if callable(rep) else bool(rep)
    return fd.label == fd.LABEL_REPEATED  # older protobuf runtimes


def wire_format() -> str:
    """'proto' or 'json' — the process-wide wire selection."""
    return "proto" if os.environ.get("WEEDTPU_WIRE", "") == "proto" else "json"


def _descriptor_set_bytes() -> bytes:
    """Fresh descriptor set from protoc when available (keeps the wire in
    lockstep with contracts.proto), else the committed artifact."""
    protoc = shutil.which("protoc")
    if protoc is not None:
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".desc") as tmp:
            proc = subprocess.run(
                [
                    protoc,
                    f"--proto_path={_HERE}",
                    "--include_imports",
                    f"--descriptor_set_out={tmp.name}",
                    PROTO_PATH,
                ],
                capture_output=True,
                timeout=60,
            )
            if proc.returncode == 0:
                tmp.seek(0)
                raw = tmp.read()
                if raw:
                    return raw
    with open(DESC_PATH, "rb") as f:
        return f.read()


class WireCodec:
    """(service, method) -> request/response message classes + strict
    dict<->message conversion."""

    def __init__(self) -> None:
        from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

        self._pool = descriptor_pool.DescriptorPool()
        fds = descriptor_pb2.FileDescriptorSet.FromString(_descriptor_set_bytes())
        for fdp in fds.file:
            self._pool.Add(fdp)
        self._factory = message_factory
        # service methods: (service_full_name, method) -> (req_cls, resp_cls)
        self._methods: dict[tuple[str, str], tuple] = {}
        for fdp in fds.file:
            for svc in fdp.service:
                full = f"{fdp.package}.{svc.name}" if fdp.package else svc.name
                sdesc = self._pool.FindServiceByName(full)
                for m in sdesc.methods:
                    self._methods[(full, m.name)] = (
                        message_factory.GetMessageClass(m.input_type),
                        message_factory.GetMessageClass(m.output_type),
                    )

    def has(self, service: str, method: str) -> bool:
        return (service, method) in self._methods

    def classes(self, service: str, method: str):
        return self._methods[(service, method)]

    # -- dict -> message ------------------------------------------------------

    def to_message(self, d: dict, cls):
        msg = cls()
        self._fill(msg, d or {})
        return msg

    def _fill(self, msg, d) -> None:
        desc = msg.DESCRIPTOR
        wrap = WRAPPER_FIELD.get(desc.full_name)
        if wrap is not None:
            # wrapper values arrive in their natural bare shape, always
            # (to_dict only ever emits bare; a rack literally named
            # "racks" must not flip the interpretation)
            d = {wrap: d}
        if not isinstance(d, dict):
            raise ValueError(
                f"{desc.full_name}: expected an object, got {type(d).__name__}"
            )
        fields = {f.name: f for f in desc.fields}
        for key, value in d.items():
            fd = fields.get(key)
            if fd is None:
                raise ValueError(
                    f"{desc.full_name}: dict key {key!r} is not a schema field"
                )
            if value is None:
                continue  # absent on the wire, like a missing JSON key
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                self._fill_map(msg, fd, value)
            elif _is_repeated(fd):
                tgt = getattr(msg, key)
                for item in value:
                    if fd.message_type is not None:
                        self._fill(tgt.add(), item)
                    else:
                        tgt.append(self._scalar(fd, item))
            elif fd.message_type is not None:
                self._fill(getattr(msg, key), value)
            else:
                setattr(msg, key, self._scalar(fd, value))


    def _fill_map(self, msg, fd, value: dict) -> None:
        key_fd = fd.message_type.fields_by_name["key"]
        val_fd = fd.message_type.fields_by_name["value"]
        tgt = getattr(msg, fd.name)
        for k, v in value.items():
            kk = self._scalar(key_fd, k)
            if val_fd.message_type is not None:
                self._fill(tgt[kk], v)
            else:
                tgt[kk] = self._scalar(val_fd, v)

    @staticmethod
    def _scalar(fd, value):
        t = fd.type
        if t in (fd.TYPE_INT32, fd.TYPE_INT64, fd.TYPE_UINT32, fd.TYPE_UINT64,
                 fd.TYPE_SINT32, fd.TYPE_SINT64, fd.TYPE_FIXED32, fd.TYPE_FIXED64,
                 fd.TYPE_SFIXED32, fd.TYPE_SFIXED64):
            return int(value)  # str keys like {"7": ...} arrive from JSON habits
        if t in (fd.TYPE_FLOAT, fd.TYPE_DOUBLE):
            return float(value)
        if t == fd.TYPE_BOOL:
            return bool(value)
        if t == fd.TYPE_BYTES:
            if isinstance(value, (bytes, bytearray)):
                return bytes(value)
            return base64.b64decode(value)  # dicts carry b64 strings
        if t == fd.TYPE_STRING:
            if not isinstance(value, str):
                raise ValueError(f"field {fd.name}: expected str, got {type(value).__name__}")
            return value
        raise ValueError(f"field {fd.name}: unsupported proto type {t}")

    # -- message -> dict ------------------------------------------------------

    def to_dict(self, msg):
        desc = msg.DESCRIPTOR
        wrap = WRAPPER_FIELD.get(desc.full_name)
        if wrap is not None:
            inner = self._to_dict_fields(msg)
            fd = desc.fields_by_name[wrap]
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                return inner.get(wrap, {})
            return inner.get(wrap, [])
        return self._to_dict_fields(msg)

    def _to_dict_fields(self, msg) -> dict:
        out = {}
        desc = msg.DESCRIPTOR
        for fd in desc.fields:
            if fd.message_type is not None and fd.message_type.GetOptions().map_entry:
                # maps always emit (possibly {}): readers index resp["x"]
                val_fd = fd.message_type.fields_by_name["value"]
                m = getattr(msg, fd.name)
                if val_fd.message_type is not None:
                    out[fd.name] = {
                        self._key_out(k): self.to_dict(v) for k, v in m.items()
                    }
                else:
                    out[fd.name] = {
                        self._key_out(k): self._scalar_out(val_fd, v)
                        for k, v in m.items()
                    }
            elif _is_repeated(fd):
                # repeated always emits (possibly []), same reason
                seq = getattr(msg, fd.name)
                if fd.message_type is not None:
                    out[fd.name] = [self.to_dict(v) for v in seq]
                else:
                    out[fd.name] = [self._scalar_out(fd, v) for v in seq]
            elif fd.message_type is not None:
                sub = getattr(msg, fd.name)
                if msg.HasField(fd.name):
                    out[fd.name] = self.to_dict(sub)
            elif fd.has_presence:
                # `optional` scalar: absent and explicit-default differ on
                # the wire AND to handlers (.get(k, True) patterns —
                # copy_ecx_file / is_delete_data)
                if msg.HasField(fd.name):
                    out[fd.name] = self._scalar_out(fd, getattr(msg, fd.name))
            else:
                # plain proto3 scalar: zero == unset on the wire, so the
                # dict always carries the key (the codebase's dominant
                # pattern is req["volume_id"]-style indexing; the few
                # handlers with NON-zero defaults use `.get(k) or default`
                # or-defaulting, which treats explicit zero as unset —
                # exactly proto3's semantics)
                out[fd.name] = self._scalar_out(fd, getattr(msg, fd.name))
        return out

    @staticmethod
    def _key_out(k):
        # JSON object keys are strings; handlers already int() them — keep
        # native ints for int-keyed maps (both sides accept them)
        return k

    @staticmethod
    def _scalar_out(fd, v):
        if fd.type == fd.TYPE_BYTES:
            return base64.b64encode(bytes(v)).decode()
        return v

    # -- gRPC (de)serializers --------------------------------------------------

    def request_serdes(self, service: str, method: str):
        """(serializer, deserializer) for the REQUEST message."""
        req_cls, _ = self.classes(service, method)
        return (
            lambda d: self.to_message(d, req_cls).SerializeToString(),
            lambda raw: self.to_dict(req_cls.FromString(raw)),
        )

    def response_serdes(self, service: str, method: str):
        _, resp_cls = self.classes(service, method)
        return (
            lambda d: self.to_message(d, resp_cls).SerializeToString(),
            lambda raw: self.to_dict(resp_cls.FromString(raw)),
        )


@functools.lru_cache(maxsize=1)
def codec() -> WireCodec:
    with _lock:
        return WireCodec()


def regenerate_descriptor_artifact() -> bytes:
    """Write contracts.desc next to the proto (CI/commit-time helper; the
    drift test asserts the artifact matches what protoc emits)."""
    raw = _descriptor_set_bytes()
    with open(DESC_PATH, "wb") as f:
        f.write(raw)
    return raw
