"""Mount — POSIX view of the filer, mirror of weed/mount/ (hanwen/go-fuse
v2 WFS + page_writer/) [VERIFY: mount empty; SURVEY.md §2.1 "FUSE mount"
row, §1 L6].

The core is FUSE-independent so it runs and tests anywhere:

  page_writer.py — write-back page cache: dirty interval list per open
                   file, merged on overlap (weed/mount/page_writer/)
  wfs.py         — WFS: the filesystem operation set (lookup/getattr/
                   read/write/mkdir/unlink/rename/...), entry cache,
                   flush-to-filer via chunk upload (weed/mount/wfs.go,
                   weedfs_file_*.go, weedfs_dir_*.go)
  fuse_adapter.py— optional kernel binding when a fusepy-compatible
                   module is importable (absent in this image; the
                   adapter degrades with a clear error)

Writes buffer in DirtyPages; flush uploads the dirty intervals as chunks
(assign+POST to the volume tier, discovered through the filer's
GetFilerConfiguration) and updates the entry chunk list over filer RPC —
the same write path shape as the reference's page_writer upload pipeline.
"""

from seaweedfs_tpu.mount.page_writer import DirtyPages
from seaweedfs_tpu.mount.wfs import WFS, FileHandle

__all__ = ["DirtyPages", "WFS", "FileHandle"]
