"""Write-back page cache — mirror of weed/mount/page_writer/ (the
UploadPipeline / ChunkedDirtyPages machinery, simplified to its
semantics) [VERIFY: mount empty; SURVEY.md §2.1 "FUSE mount" row].

DirtyPages holds the not-yet-uploaded byte intervals of one open file.
Overlapping/adjacent writes merge; `read` overlays dirty bytes on top of
what the store has; `drain` emits the merged intervals for upload.
"""

from __future__ import annotations

from typing import Optional


class DirtyPages:
    def __init__(self):
        # sorted, non-overlapping, non-adjacent [(offset, bytearray)]
        self._runs: list[tuple[int, bytearray]] = []

    @property
    def dirty(self) -> bool:
        return bool(self._runs)

    @property
    def byte_count(self) -> int:
        return sum(len(b) for _, b in self._runs)

    def write(self, offset: int, data: bytes) -> None:
        if not data:
            return
        new_lo, new_hi = offset, offset + len(data)
        merged = bytearray(data)
        keep: list[tuple[int, bytearray]] = []
        lo = new_lo
        for run_off, run_buf in self._runs:
            run_hi = run_off + len(run_buf)
            if run_hi < new_lo or run_off > new_hi:
                keep.append((run_off, run_buf))
                continue
            # overlap or adjacency: fold the old run around the new data
            # (new bytes win where they overlap)
            if run_off < lo:
                merged[0:0] = run_buf[: lo - run_off]
                lo = run_off
            if run_hi > new_hi:
                merged.extend(run_buf[len(run_buf) - (run_hi - new_hi) :])
                new_hi = run_hi
        keep.append((lo, merged))
        keep.sort(key=lambda r: r[0])
        self._runs = keep

    def read_overlay(self, offset: int, buf: bytearray) -> None:
        """Patch `buf` (file bytes starting at `offset`) with dirty data."""
        end = offset + len(buf)
        for run_off, run_buf in self._runs:
            lo = max(offset, run_off)
            hi = min(end, run_off + len(run_buf))
            if lo < hi:
                buf[lo - offset : hi - offset] = run_buf[lo - run_off : hi - run_off]

    def max_extent(self) -> int:
        """Highest dirty byte offset + 1 (0 when clean)."""
        if not self._runs:
            return 0
        off, buf = self._runs[-1]
        return off + len(buf)

    def drain(self) -> list[tuple[int, bytes]]:
        runs = [(off, bytes(buf)) for off, buf in self._runs]
        self._runs = []
        return runs

    def truncate(self, size: int) -> None:
        """Drop dirty bytes at or past `size`."""
        out = []
        for off, buf in self._runs:
            if off >= size:
                continue
            if off + len(buf) > size:
                buf = buf[: size - off]
            out.append((off, buf))
        self._runs = out
