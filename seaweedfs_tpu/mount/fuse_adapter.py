"""Kernel FUSE binding for WFS — the weed/mount/ <-> hanwen/go-fuse
equivalent seam [VERIFY: mount empty; SURVEY.md §2.1 "FUSE mount" row].

This image ships no fusepy/libfuse, so the binding is optional: import
`mount_and_serve` and it raises a clear error unless a fusepy-compatible
`fuse` module is importable. The WFS core (wfs.py) is fully exercised
without the kernel; this adapter is a thin translation layer from fusepy
Operations callbacks onto WFS ops.
"""

from __future__ import annotations

import errno
import os
import stat as stat_mod

from seaweedfs_tpu.mount.wfs import WFS


def fuse_available() -> bool:
    try:
        import fuse  # noqa: F401

        return hasattr(fuse, "FUSE") and hasattr(fuse, "Operations")
    except ImportError:
        return False


def mount_and_serve(filer_grpc_address: str, mountpoint: str, foreground: bool = True):
    """Block serving the FUSE mount (fusepy main loop)."""
    if not fuse_available():
        raise RuntimeError(
            "kernel FUSE needs the 'fusepy' module + /dev/fuse; neither is "
            "available in this image. The WFS API (seaweedfs_tpu.mount.WFS) "
            "offers the same operations in-process."
        )
    import fuse

    wfs = WFS(filer_grpc_address, watch=True)

    class _Ops(fuse.Operations):
        def __init__(self):
            import threading

            self._handles = {}
            self._next_fh = 0
            self._h_lock = threading.Lock()

        def _register(self, handle) -> int:
            # callbacks run concurrently (nothreads=False): allocation
            # must be atomic or two opens share an fh
            with self._h_lock:
                self._next_fh += 1
                self._handles[self._next_fh] = handle
                return self._next_fh

        def _attr_dict(self, a):
            mode = a.mode
            if a.is_dir:
                mode = stat_mod.S_IFDIR | (mode & 0o7777)
            else:
                mode = stat_mod.S_IFREG | (mode & 0o7777)
            return {
                "st_mode": mode,
                "st_size": a.size,
                "st_mtime": a.mtime,
                "st_ctime": a.crtime,
                "st_atime": a.mtime,
                "st_uid": a.uid or os.getuid(),
                "st_gid": a.gid or os.getgid(),
                "st_nlink": 1,
            }

        def getattr(self, path, fh=None):
            a = wfs.getattr(path)
            if a is None:
                raise fuse.FuseOSError(errno.ENOENT)
            return self._attr_dict(a)

        def readdir(self, path, fh):
            yield "."
            yield ".."
            for a in wfs.readdir(path):
                yield a.path.rsplit("/", 1)[-1]

        def mkdir(self, path, mode):
            wfs.mkdir(path, mode)

        def rmdir(self, path):
            wfs.rmdir(path)

        def unlink(self, path):
            wfs.unlink(path)

        def rename(self, old, new):
            wfs.rename(old, new)

        def create(self, path, mode, fi=None):
            return self._register(wfs.create(path, mode))

        def open(self, path, flags):
            return self._register(wfs.open(path))

        def read(self, path, size, offset, fh):
            return self._handles[fh].read(offset, size)

        def write(self, path, data, offset, fh):
            return self._handles[fh].write(offset, data)

        def truncate(self, path, length, fh=None):
            if fh and fh in self._handles:
                self._handles[fh].truncate(length)
            else:
                h = wfs.open(path)
                h.truncate(length)
                h.flush()

        def flush(self, path, fh):
            self._handles[fh].flush()

        def release(self, path, fh):
            with self._h_lock:
                h = self._handles.pop(fh, None)
            if h:
                h.release()

    return fuse.FUSE(_Ops(), mountpoint, foreground=foreground, nothreads=False)
