"""WFS — the mount filesystem core, mirror of weed/mount/wfs.go +
weedfs_file_io.go / weedfs_file_sync.go / weedfs_dir_*.go /
weedfs_attr.go [VERIFY: mount empty; SURVEY.md §2.1 "FUSE mount" row].

Path-keyed operation set (the kernel-facing inode table lives in the
FUSE adapter; keeping the core on paths makes it directly testable):

  lookup/getattr, readdir, mkdir, rmdir, create/open -> FileHandle,
  read/write/truncate/flush/release, unlink, rename, statfs.

Data path: reads go filer RPC ReadFileRange (only overlapping chunks are
touched) overlaid with local dirty pages; writes buffer in DirtyPages and
flush as chunk uploads straight to the volume tier (assign+POST through a
MasterClient discovered via GetFilerConfiguration), then an UpdateEntry
with the appended chunk list — the reference's page_writer upload
pipeline shape. Entry metadata is cached with a TTL and invalidated by
the filer's metadata subscription when `watch=True`.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.filer.chunks import ChunkIO
from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.filer.entry import Attributes, Entry, normalize_path
from seaweedfs_tpu.mount.page_writer import DirtyPages

_ATTR_TTL = 1.0


@dataclass
class Attr:
    """Stat-like view of an entry (FUSE attr analog)."""

    path: str
    is_dir: bool
    size: int
    mtime: float
    crtime: float
    mode: int
    uid: int
    gid: int


class FileHandle:
    def __init__(self, wfs: "WFS", entry: Entry):
        self.wfs = wfs
        self.entry = entry
        self.dirty = DirtyPages()
        self.lock = threading.Lock()
        self._truncated_to: Optional[int] = None

    @property
    def size(self) -> int:
        base = self.entry.size if self._truncated_to is None else self._truncated_to
        return max(base, self.dirty.max_extent())

    def read(self, offset: int, size: int) -> bytes:
        with self.lock:
            end = min(offset + size, self.size)
            if end <= offset:
                return b""
            buf = bytearray(end - offset)
            stored_end = self.entry.size
            if self._truncated_to is not None:
                stored_end = min(stored_end, self._truncated_to)
            want = min(end, stored_end) - offset
            if want > 0 and self.entry.chunks:
                data = self.wfs.filer.read_range(self.entry.path, offset, want)
                buf[: len(data)] = data
            self.dirty.read_overlay(offset, buf)
            return bytes(buf)

    def write(self, offset: int, data: bytes) -> int:
        with self.lock:
            self.dirty.write(offset, data)
            if (
                self.wfs.auto_flush_bytes
                and self.dirty.byte_count >= self.wfs.auto_flush_bytes
            ):
                self._flush_locked()
            return len(data)

    def truncate(self, size: int) -> None:
        with self.lock:
            self.dirty.truncate(size)
            self._truncated_to = size

    def flush(self) -> None:
        with self.lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        runs = self.dirty.drain()
        if not runs and self._truncated_to is None:
            return
        if self._truncated_to is not None:
            size = self._truncated_to
            # dropping chunks fully past the cut; the filer reclaims the
            # needles of chunks not carried into the updated entry
            self.entry.chunks = [
                c for c in self.entry.chunks if c.offset < size
            ]
            for c in self.entry.chunks:
                if c.offset + c.size > size:
                    c.size = size - c.offset
            self.entry.attributes.file_size = size
            self._truncated_to = None
        for off, data in runs:
            chunk = self.wfs.chunk_io.upload_chunk(
                data,
                off,
                collection=self.wfs.collection,
                replication=self.wfs.replication,
            )
            self.entry.chunks.append(chunk)
        self.entry.attributes.file_size = max(
            self.entry.attributes.file_size,
            max((c.offset + c.size for c in self.entry.chunks), default=0),
        )
        self.entry.attributes.mtime = time.time()
        self.entry.attributes.md5 = ""  # stale after partial rewrite
        self.wfs._put_entry(self.entry)

    def release(self) -> None:
        self.flush()


class WFS:
    def __init__(
        self,
        filer_grpc_address: str,
        auto_flush_bytes: int = 8 * 1024 * 1024,
        watch: bool = False,
        chunk_cache_bytes: int = 64 << 20,
    ):
        self.filer = FilerClient(filer_grpc_address)
        conf = self.filer.configuration()
        self.master = MasterClient(conf["masters"][0])
        from seaweedfs_tpu.utils.chunk_cache import ChunkCache

        # the mount is the reference's heaviest chunk_cache user: page
        # reads re-fetch the same chunks constantly; 0 disables
        cache = ChunkCache(memory_bytes=chunk_cache_bytes) if chunk_cache_bytes else None
        self.chunk_io = ChunkIO(
            self.master, chunk_size=int(conf["chunk_size"]), cache=cache
        )
        self.collection = conf.get("collection", "")
        self.replication = conf.get("replication", "")
        self.auto_flush_bytes = auto_flush_bytes
        self._attr_cache: dict[str, tuple[float, Entry]] = {}
        self._cache_lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None
        if watch:
            self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
            self._watcher.start()

    def close(self) -> None:
        self._stop.set()
        self.filer.close()
        self.master.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- metadata cache -------------------------------------------------------

    def _watch_loop(self) -> None:
        """Invalidate cached attrs when other clients mutate the tree."""
        while not self._stop.is_set():
            try:
                for ev in self.filer.subscribe(
                    since_ns=time.time_ns(), max_idle_s=2.0
                ):
                    for d in (ev.old_entry, ev.new_entry):
                        if d:
                            self._invalidate(d["path"])
            except Exception:  # noqa: BLE001 — filer restart; retry
                if self._stop.wait(0.5):
                    return

    def _invalidate(self, path: str) -> None:
        with self._cache_lock:
            self._attr_cache.pop(path, None)

    def _get_entry(self, path: str) -> Optional[Entry]:
        path = normalize_path(path)
        now = time.monotonic()
        with self._cache_lock:
            hit = self._attr_cache.get(path)
            if hit and now - hit[0] < _ATTR_TTL:
                return hit[1]
        e = self.filer.lookup(path)
        if e is not None:
            with self._cache_lock:
                self._attr_cache[path] = (now, e)
        return e

    def _put_entry(self, entry: Entry) -> None:
        self.filer.create(entry)
        with self._cache_lock:
            self._attr_cache[entry.path] = (time.monotonic(), entry)

    # -- operations -----------------------------------------------------------

    @staticmethod
    def _attr(e: Entry) -> Attr:
        return Attr(
            path=e.path,
            is_dir=e.is_directory,
            size=e.size,
            mtime=e.attributes.mtime,
            crtime=e.attributes.crtime,
            mode=e.attributes.mode,
            uid=e.attributes.uid,
            gid=e.attributes.gid,
        )

    def lookup(self, path: str) -> Optional[Attr]:
        e = self._get_entry(path)
        return self._attr(e) if e else None

    getattr = lookup

    def readdir(self, path: str) -> list[Attr]:
        out = []
        start = ""
        while True:
            batch = self.filer.list(path, start_from=start, limit=1024)
            if not batch:
                break
            out.extend(self._attr(e) for e in batch)
            start = batch[-1].name
            if len(batch) < 1024:
                break
        return out

    def mkdir(self, path: str, mode: int = 0o755) -> Attr:
        e = Entry(
            path=path,
            is_directory=True,
            attributes=Attributes(mtime=time.time(), mode=mode | 0o040000),
        )
        self._put_entry(e)
        return self._attr(e)

    def create(self, path: str, mode: int = 0o644) -> FileHandle:
        e = Entry(path=path, attributes=Attributes(mtime=time.time(), mode=mode))
        self._put_entry(e)
        return FileHandle(self, e)

    def open(self, path: str) -> FileHandle:
        e = self._get_entry(path)
        if e is None:
            raise FileNotFoundError(path)
        if e.is_directory:
            raise IsADirectoryError(path)
        return FileHandle(self, e)

    def unlink(self, path: str) -> None:
        self.filer.delete(path)
        self._invalidate(path)

    def rmdir(self, path: str) -> None:
        e = self._get_entry(path)
        if e is None:
            raise FileNotFoundError(path)
        if not e.is_directory:
            raise NotADirectoryError(path)
        if self.filer.list(path, limit=1):
            raise OSError(39, "directory not empty", path)  # ENOTEMPTY
        self.filer.delete(path, recursive=True)
        self._invalidate(path)

    def rename(self, old: str, new: str) -> None:
        self.filer.rename(old, new)
        self._invalidate(old)
        self._invalidate(new)

    def statfs(self) -> dict:
        try:
            return self.master.statistics()
        except Exception:  # noqa: BLE001
            return {}
