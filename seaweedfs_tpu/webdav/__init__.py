"""WebDAV gateway — mirror of weed/server/webdav_server.go (golang.org/x/
net/webdav backed by the filer) [VERIFY: mount empty; SURVEY.md §2.1
"Gateways" L6 row: "S3 REST, POSIX/FUSE, WebDAV"].

Class-2 WebDAV on the filer namespace: OPTIONS, PROPFIND (Depth 0/1),
MKCOL, GET/HEAD/PUT/DELETE, MOVE, COPY, LOCK/UNLOCK (exclusive depth-0
write locks with timeout/refresh — what Finder/Windows/Office require
to mount read-write). Data flows through the filer HTTP API; namespace
ops over filer RPC.
"""

from seaweedfs_tpu.webdav.server import WebDavServer

__all__ = ["WebDavServer"]
