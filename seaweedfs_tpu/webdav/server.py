"""WebDAV server on the filer — weed/server/webdav_server.go analog
[VERIFY: mount empty; SURVEY.md §2.1 "Gateways"]. See package docstring
for the supported method set."""

from __future__ import annotations

import posixpath
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Optional

from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.utils import httpd
from seaweedfs_tpu.security import tls

_DAV = "DAV:"


class WebDavServer:
    def __init__(
        self,
        filer_http_address: str,
        filer_grpc_address: str,
        port: int = 0,
        host: str = "127.0.0.1",
        root: str = "/",
    ):
        self.filer_http = filer_http_address
        self.filer = FilerClient(filer_grpc_address)
        self.root = root.rstrip("/") or ""
        self.host = host
        # class-2 write locks (RFC 4918 §6): path -> (token, owner, expiry).
        # Exclusive, depth-infinity: a collection lock protects its internal
        # members (enforced via lock_covering), and a member lock blocks
        # collection-level ops (lock_under) — what Finder, Windows, and
        # Office demand before they will mount read-write.
        self._locks: dict[str, tuple[str, str, float]] = {}
        self._locks_mu = threading.Lock()
        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.dav_server = self
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self.filer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def fpath(self, dav_path: str) -> str:
        p = posixpath.normpath("/" + dav_path.lstrip("/"))
        return (self.root + p) if p != "/" else (self.root or "/")

    # -- lock table -----------------------------------------------------------

    DEFAULT_LOCK_S = 600.0
    MAX_LOCK_S = 3600.0

    def lock_of(self, path: str):
        """(token, owner, expiry) or None; expired entries are dropped."""
        with self._locks_mu:
            entry = self._locks.get(path)
            if entry is not None and entry[2] < time.time():
                del self._locks[path]
                entry = None
            return entry

    def lock_under(self, path: str):
        """Any live lock AT or UNDER `path` (collection ops must honor
        child locks). Returns (locked_path, token) or None."""
        prefix = path.rstrip("/") + "/"
        now = time.time()
        with self._locks_mu:
            for p, (tok, _owner, exp) in list(self._locks.items()):
                if exp < now:
                    del self._locks[p]
                    continue
                if p == path or p.startswith(prefix):
                    return p, tok
            return None

    def lock_covering(self, path: str):
        """Any live lock at `path` or at an ANCESTOR of it (RFC 4918 §7:
        a write lock on a collection protects internal member creation,
        modification, and removal). Returns (locked_path, token) or None."""
        now = time.time()
        with self._locks_mu:
            cur = path.rstrip("/") or "/"
            while True:
                entry = self._locks.get(cur)
                if entry is not None:
                    if entry[2] < now:
                        del self._locks[cur]
                    else:
                        return cur, entry[0]
                if cur == "/" or "/" not in cur:
                    return None
                cur = cur.rsplit("/", 1)[0] or "/"

    def clear_under(self, path: str) -> None:
        """Drop every lock entry at/under `path` (the resources are gone —
        stale entries would 423-block whoever recreates the paths)."""
        prefix = path.rstrip("/") + "/"
        with self._locks_mu:
            for p in list(self._locks):
                if p == path or p.startswith(prefix):
                    del self._locks[p]

    def acquire_lock(self, path: str, owner: str, seconds: float, token: str = ""):
        """Grant (or refresh when `token` matches) the exclusive lock.
        Returns (token, seconds) or None when someone else holds it."""
        seconds = min(max(seconds, 1.0), self.MAX_LOCK_S)
        now = time.time()
        with self._locks_mu:
            # opportunistic sweep: expired entries must not accumulate for
            # the life of the gateway (clients lock every file they write)
            for p in [p for p, e in self._locks.items() if e[2] < now]:
                del self._locks[p]
            cur = self._locks.get(path)
            if cur is not None and cur[2] >= time.time() and cur[0] != token:
                return None
            if not token or cur is None or cur[0] != token:
                import uuid

                token = f"opaquelocktoken:{uuid.uuid4()}"
                owner = owner or (cur[1] if cur else "")
            else:
                owner = cur[1]
            self._locks[path] = (token, owner, time.time() + seconds)
            return token, seconds

    def release_lock(self, path: str, token: str) -> bool:
        with self._locks_mu:
            cur = self._locks.get(path)
            if cur is None or cur[0] != token:
                return False
            del self._locks[path]
            return True

    def filer_url(self, path: str) -> str:
        return f"{tls.scheme()}://{self.filer_http}{urllib.parse.quote(path)}"


class _ThreadingHTTPServer(httpd.ThreadingHTTPServer):
    dav_server: "WebDavServer"


def _http_date(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


class _Handler(httpd.QuietHandler):
    @property
    def dav(self) -> WebDavServer:
        return self.server.dav_server

    def _path(self) -> str:
        return urllib.parse.unquote(urllib.parse.urlparse(self.path).path) or "/"

    def _reply(self, code: int, body: bytes = b"", ctype="text/xml; charset=utf-8", headers=None):
        self.send_reply(code, body, ctype, headers=headers)

    # -- methods --------------------------------------------------------------

    # -- locking (RFC 4918 class 2) -------------------------------------------

    def _submitted_token(self) -> str:
        """Lock token from the If / Lock-Token headers (either form)."""
        import re as _re

        for h in (self.headers.get("If", ""), self.headers.get("Lock-Token", "")):
            m = _re.search(r"<(opaquelocktoken:[^>]+)>", h)
            if m:
                return m.group(1)
        return ""

    def _check_lock(self, path: str) -> bool:
        """True when `path` is writable by this request: unlocked, or the
        request submitted the covering lock's token. Both directions are
        enforced — a child lock blocks collection ops (lock_under), and a
        collection lock blocks tokenless writes to its members
        (lock_covering). Replies 423 otherwise."""
        hit = self.dav.lock_under(path) or self.dav.lock_covering(path)
        if hit is None or self._submitted_token() == hit[1]:
            return True
        self._reply(423, b"<?xml version=\"1.0\"?><D:error xmlns:D=\"DAV:\"/>")
        return False

    def _lock_seconds(self) -> float:
        t = self.headers.get("Timeout", "")
        for part in t.split(","):
            part = part.strip()
            if part.lower().startswith("second-"):
                try:
                    return float(part[len("second-"):])
                except ValueError:
                    break
        return self.dav.DEFAULT_LOCK_S

    def do_LOCK(self):
        path = self.dav.fpath(self._path())
        body = self.read_body()
        if body is None:  # chunked encoding: unread bytes would desync keep-alive
            self.reply_length_required()
            return
        owner = ""
        if body:
            try:
                root = ET.fromstring(body)
                o = root.find(f"{{{_DAV}}}owner")
                if o is not None:
                    owner = "".join(o.itertext()).strip()
            except ET.ParseError:
                self._reply(400, b"bad lockinfo")
                return
        token = "" if body else self._submitted_token()  # empty body = refresh
        # depth-infinity exclusivity: a new lock is refused while a DIFFERENT
        # lock exists anywhere on the path's subtree or its ancestors —
        # otherwise a child lock would tunnel through a collection lock
        conflict = self.dav.lock_under(path) or self.dav.lock_covering(path)
        if conflict is not None and conflict[1] != (token or self._submitted_token()):
            self._reply(423, b"<?xml version=\"1.0\"?><D:error xmlns:D=\"DAV:\"/>")
            return
        granted = self.dav.acquire_lock(path, owner, self._lock_seconds(), token=token)
        if granted is None:
            self._reply(423, b"<?xml version=\"1.0\"?><D:error xmlns:D=\"DAV:\"/>")
            return
        token, seconds = granted
        prop = ET.Element(f"{{{_DAV}}}prop")
        ld = ET.SubElement(prop, f"{{{_DAV}}}lockdiscovery")
        al = ET.SubElement(ld, f"{{{_DAV}}}activelock")
        ET.SubElement(ET.SubElement(al, f"{{{_DAV}}}locktype"), f"{{{_DAV}}}write")
        ET.SubElement(ET.SubElement(al, f"{{{_DAV}}}lockscope"), f"{{{_DAV}}}exclusive")
        ET.SubElement(al, f"{{{_DAV}}}depth").text = "infinity"
        if owner:
            ET.SubElement(al, f"{{{_DAV}}}owner").text = owner
        ET.SubElement(al, f"{{{_DAV}}}timeout").text = f"Second-{int(seconds)}"
        ET.SubElement(
            ET.SubElement(al, f"{{{_DAV}}}locktoken"), f"{{{_DAV}}}href"
        ).text = token
        out = ET.tostring(prop, xml_declaration=True, encoding="unicode").encode()
        self._reply(200, out, headers={"Lock-Token": f"<{token}>"})

    def do_UNLOCK(self):
        path = self.dav.fpath(self._path())
        if self.dav.release_lock(path, self._submitted_token()):
            self._reply(204)
        else:
            self._reply(409, b"no such lock")

    def do_OPTIONS(self):
        self._reply(
            200,
            headers={
                "DAV": "1,2",
                "Allow": "OPTIONS, PROPFIND, MKCOL, GET, HEAD, PUT, DELETE, "
                         "MOVE, COPY, LOCK, UNLOCK",
                "MS-Author-Via": "DAV",
            },
        )

    def _prop_response(self, ms: ET.Element, dav_path: str, entry: Entry) -> None:
        resp = ET.SubElement(ms, f"{{{_DAV}}}response")
        href = ET.SubElement(resp, f"{{{_DAV}}}href")
        href.text = urllib.parse.quote(dav_path + ("/" if entry.is_directory and dav_path != "/" else ""))
        propstat = ET.SubElement(resp, f"{{{_DAV}}}propstat")
        prop = ET.SubElement(propstat, f"{{{_DAV}}}prop")
        ET.SubElement(prop, f"{{{_DAV}}}displayname").text = (
            posixpath.basename(dav_path) or "/"
        )
        ET.SubElement(prop, f"{{{_DAV}}}getlastmodified").text = _http_date(
            entry.attributes.mtime
        )
        rt = ET.SubElement(prop, f"{{{_DAV}}}resourcetype")
        if entry.is_directory:
            ET.SubElement(rt, f"{{{_DAV}}}collection")
        else:
            ET.SubElement(prop, f"{{{_DAV}}}getcontentlength").text = str(entry.size)
            ET.SubElement(prop, f"{{{_DAV}}}getcontenttype").text = (
                entry.attributes.mime or "application/octet-stream"
            )
        status = ET.SubElement(propstat, f"{{{_DAV}}}status")
        status.text = "HTTP/1.1 200 OK"

    def do_PROPFIND(self):
        self.read_body()  # drain; we return the standard prop set regardless
        dav_path = self._path()
        fpath = self.dav.fpath(dav_path)
        entry = self.dav.filer.lookup(fpath)
        if entry is None:
            self._reply(404)
            return
        depth = self.headers.get("Depth", "1")
        ET.register_namespace("D", _DAV)
        ms = ET.Element(f"{{{_DAV}}}multistatus")
        self._prop_response(ms, dav_path, entry)
        if entry.is_directory and depth != "0":
            for child in self.dav.filer.list(fpath, limit=10000):
                self._prop_response(
                    ms, posixpath.join(dav_path, child.name), child
                )
        body = b'<?xml version="1.0" encoding="utf-8"?>\n' + ET.tostring(ms)
        self._reply(207, body)

    def do_MKCOL(self):
        if not self._check_lock(self.dav.fpath(self._path())):
            return
        fpath = self.dav.fpath(self._path())
        if self.dav.filer.lookup(fpath) is not None:
            self._reply(405)
            return
        self.dav.filer.create(Entry(path=fpath, is_directory=True))
        self._reply(201)

    def _serve_get(self, head: bool):
        fpath = self.dav.fpath(self._path())
        entry = self.dav.filer.lookup(fpath)
        if entry is None:
            self._reply(404)
            return
        if entry.is_directory:
            self._reply(405)
            return
        if head:
            self._reply(
                200,
                headers={
                    "Content-Length": str(entry.size),
                    "Last-Modified": _http_date(entry.attributes.mtime),
                },
            )
            return
        fwd = {}
        if self.headers.get("Range"):
            fwd["Range"] = self.headers["Range"]
        try:
            req = urllib.request.Request(self.dav.filer_url(fpath), headers=fwd)
            with tls.urlopen(req, timeout=60) as r:
                body = r.read()
                headers = {"Last-Modified": r.headers.get("Last-Modified", "")}
                if r.headers.get("Content-Range"):
                    headers["Content-Range"] = r.headers["Content-Range"]
                self._reply(
                    r.status, body,
                    r.headers.get("Content-Type", "application/octet-stream"),
                    headers=headers,
                )
        except urllib.error.URLError:
            self._reply(404)

    def do_GET(self):
        self._serve_get(head=False)

    def do_HEAD(self):
        self._serve_get(head=True)

    def do_PUT(self):
        fpath = self.dav.fpath(self._path())
        if not self._check_lock(fpath):
            return
        body = self.read_body()
        if body is None:
            self.reply_length_required()
            return
        req = urllib.request.Request(
            self.dav.filer_url(fpath),
            data=body,
            method="PUT",
            headers={"Content-Type": self.headers.get("Content-Type", "application/octet-stream")},
        )
        try:
            with tls.urlopen(req, timeout=60) as r:
                r.read()
        except urllib.error.URLError as e:
            self._reply(500, str(e).encode(), "text/plain")
            return
        self._reply(201)

    def do_DELETE(self):
        fpath = self.dav.fpath(self._path())
        if not self._check_lock(fpath):
            return
        if self.dav.filer.lookup(fpath) is None:
            self._reply(404)
            return
        self.dav.filer.delete(fpath, recursive=True)
        # RFC 4918: DELETE destroys the locks of everything it removed —
        # stale entries would 423-block whoever recreates the paths. The
        # request already passed _check_lock, so dropping them is safe.
        self.dav.clear_under(fpath)
        self._reply(204)

    def _dest_path(self) -> Optional[str]:
        dest = self.headers.get("Destination", "")
        if not dest:
            return None
        u = urllib.parse.urlparse(dest)
        return self.dav.fpath(urllib.parse.unquote(u.path))

    def do_MOVE(self):
        src = self.dav.fpath(self._path())
        dst = self._dest_path()
        if dst is None:
            self._reply(400)
            return
        if not self._check_lock(src):
            return
        if not self._check_lock(dst):
            return
        if self.dav.filer.lookup(src) is None:
            self._reply(404)
            return
        overwrote = self.dav.filer.lookup(dst) is not None
        if overwrote and self.headers.get("Overwrite", "T") == "F":
            self._reply(412)
            return
        try:
            self.dav.filer.rename(src, dst)
        except (IsADirectoryError, FileNotFoundError):
            self._reply(412)
            return
        # locks are URL-scoped and do not travel with the resource: clear
        # both subtrees so no path carries a stale 423
        self.dav.clear_under(src)
        self.dav.clear_under(dst)
        self._reply(204 if overwrote else 201)

    def do_COPY(self):
        src = self.dav.fpath(self._path())
        dst = self._dest_path()
        if dst is None:
            self._reply(400)
            return
        if not self._check_lock(dst):  # overwriting a locked target
            return
        entry = self.dav.filer.lookup(src)
        if entry is None:
            self._reply(404)
            return
        if entry.is_directory:
            self._reply(501)  # collection COPY not supported (reference parity gap)
            return
        overwrote = self.dav.filer.lookup(dst) is not None
        if overwrote and self.headers.get("Overwrite", "T") == "F":
            self._reply(412)
            return
        try:
            with tls.urlopen(self.dav.filer_url(src), timeout=60) as r:
                data = r.read()
                ctype = r.headers.get("Content-Type", "application/octet-stream")
            req = urllib.request.Request(
                self.dav.filer_url(dst), data=data, method="PUT",
                headers={"Content-Type": ctype},
            )
            with tls.urlopen(req, timeout=60) as r:
                r.read()
        except urllib.error.URLError as e:
            self._reply(500, str(e).encode(), "text/plain")
            return
        self._reply(204 if overwrote else 201)
