"""Guard — weed/security/guard.go analog [VERIFY: mount empty]: gate
HTTP handlers by IP white-list and/or JWT. The volume server wraps its
write/delete path with `guard.check_write(fid, auth_header)`; reads use a
separate optional key (the reference's jwt.signing.read)."""

from __future__ import annotations

from typing import Optional

from seaweedfs_tpu.security.jwt import check_file_token


def _parse_bearer(auth_header: str) -> str:
    if not auth_header:
        return ""
    parts = auth_header.split()
    if len(parts) == 2 and parts[0].lower() in ("bearer", "bear"):
        return parts[1]
    return auth_header.strip()


class Guard:
    def __init__(
        self,
        signing_key: Optional[bytes] = None,
        read_signing_key: Optional[bytes] = None,
        white_list: Optional[list[str]] = None,
        expires_seconds: int = 10,
    ):
        self.signing_key = signing_key or None
        self.read_signing_key = read_signing_key or None
        self.white_list = set(white_list or [])
        self.expires_seconds = expires_seconds

    @property
    def secured(self) -> bool:
        return bool(self.signing_key or self.white_list)

    def white_listed(self, remote_ip: str) -> bool:
        return bool(self.white_list) and remote_ip in self.white_list

    def check_write(self, fid: str, auth_header: str, remote_ip: str = "") -> bool:
        if self.white_listed(remote_ip):
            return True
        if self.signing_key:
            return check_file_token(self.signing_key, _parse_bearer(auth_header), fid)
        # whitelist-only mode: membership is the ONLY credential — a
        # non-member must be denied, not fall through to auth-disabled
        return not self.white_list

    def check_read(self, fid: str, auth_header: str, remote_ip: str = "") -> bool:
        if self.read_signing_key is None:
            return True
        if self.white_listed(remote_ip):
            return True
        return check_file_token(self.read_signing_key, _parse_bearer(auth_header), fid)
