"""TLS/mTLS for the gRPC control plane + HTTPS for the HTTP data path —
the weed/security/tls.go analog [VERIFY: mount empty; SURVEY.md §2.1
"Security" row, VERDICT r3 missing #4].

Configuration comes from `security.toml` (like every other key in the
reference's security config), loaded ONCE per process:

    [grpc]
    ca = "/etc/seaweedfs_tpu/ca.crt"          # trust anchor (mTLS)
    cert = "/etc/seaweedfs_tpu/node.crt"      # this process's identity
    key = "/etc/seaweedfs_tpu/node.key"
    require_client_auth = true                # mTLS (default when ca set)

    [https]
    enabled = true                            # serve the HTTP data path TLS
    # cert/key/ca default to the [grpc] values

Process-global state mirrors the reference's design: every RpcServer /
RpcClient / HTTP server in the process consults this module, so servers
and tools pick TLS up from the TOML without per-callsite plumbing.
`generate_self_signed()` creates a throwaway CA + leaf pair for tests
and the `security.toml` scaffold workflow.
"""

from __future__ import annotations

import datetime
import os
import ssl
import threading
import urllib.request
from dataclasses import dataclass
from typing import Optional

import grpc


@dataclass
class TlsState:
    ca_file: str
    cert_file: str
    key_file: str
    require_client_auth: bool = True
    https: bool = False
    # self-signed test certs are issued for a fixed name; gRPC needs the
    # target-name override to accept them when dialing by IP
    override_authority: Optional[str] = None


_state: Optional[TlsState] = None
_lock = threading.Lock()
# SSLContexts are immutable-config and thread-safe for wrapping: build them
# once per configure() — the data path calls urlopen per chunk, and a fresh
# context per request would re-read PEM files and forfeit TLS session reuse
_ctx_cache: dict = {}


def configure(
    ca_file: str,
    cert_file: str,
    key_file: str,
    require_client_auth: bool = True,
    https: bool = False,
    override_authority: Optional[str] = None,
) -> None:
    global _state
    # cert/key may be empty for pure clients of a require_client_auth=false
    # cluster; cluster nodes need all three
    for p in (ca_file, cert_file, key_file):
        if p and not os.path.exists(p):
            raise FileNotFoundError(f"tls file missing: {p}")
    if not ca_file:
        raise ValueError("tls: ca_file is required")
    if bool(cert_file) != bool(key_file):
        raise ValueError("tls: grpc.cert and grpc.key must be set together")
    with _lock:
        _state = TlsState(
            ca_file, cert_file, key_file, require_client_auth, https, override_authority
        )
        _ctx_cache.clear()


def configure_from_conf(conf: dict) -> bool:
    """Wire TLS up from a parsed security.toml. Returns True when enabled."""
    g = conf.get("grpc") or {}
    h = conf.get("https") or {}
    if not g.get("ca"):
        if h.get("enabled"):
            # fail CLOSED: the operator asked for an encrypted data path but
            # gave no trust anchor — silently serving plaintext would be a
            # security misconfiguration they can't see
            raise ValueError(
                "security.toml: [https] enabled=true requires [grpc] ca/cert/key"
            )
        return False
    configure(
        ca_file=g["ca"],
        cert_file=g.get("cert", ""),
        key_file=g.get("key", ""),
        require_client_auth=bool(g.get("require_client_auth", True)),
        https=bool(h.get("enabled", False)),
        override_authority=g.get("override_authority") or None,
    )
    return True


def reset() -> None:
    global _state
    with _lock:
        _state = None
        _ctx_cache.clear()


def enabled() -> bool:
    return _state is not None


def https_enabled() -> bool:
    return _state is not None and _state.https


def scheme() -> str:
    """URL scheme for the intra-cluster HTTP data path."""
    return "https" if https_enabled() else "http"


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


# -- gRPC credentials ---------------------------------------------------------


def server_credentials() -> Optional[grpc.ServerCredentials]:
    st = _state
    if st is None:
        return None
    if not st.cert_file or not st.key_file:
        raise ValueError("tls: servers need grpc.cert and grpc.key in security.toml")
    return grpc.ssl_server_credentials(
        [(_read(st.key_file), _read(st.cert_file))],
        root_certificates=_read(st.ca_file),
        require_client_auth=st.require_client_auth,
    )


def channel_credentials() -> Optional[grpc.ChannelCredentials]:
    st = _state
    if st is None:
        return None
    return grpc.ssl_channel_credentials(
        root_certificates=_read(st.ca_file),
        private_key=_read(st.key_file) if st.cert_file else None,
        certificate_chain=_read(st.cert_file) if st.cert_file else None,
    )


def channel_options() -> list:
    st = _state
    if st is None or not st.override_authority:
        return []
    return [("grpc.ssl_target_name_override", st.override_authority)]


# -- HTTPS (data path) --------------------------------------------------------


def https_server_context() -> Optional[ssl.SSLContext]:
    st = _state
    if st is None or not st.https:
        return None
    if not st.cert_file or not st.key_file:
        raise ValueError("tls: https servers need grpc.cert and grpc.key in security.toml")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(st.cert_file, st.key_file)
    # require_client_auth means mTLS on the data path too — CERT_REQUIRED,
    # actually enforced by the handshake. Deployments whose gateways face
    # browsers / presigned-URL clients set require_client_auth=false and
    # rely on the gateway's own auth (SigV4/JWT) instead.
    if st.require_client_auth:
        ctx.load_verify_locations(st.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


_HANDSHAKE_TIMEOUT = 10.0


def maybe_wrap_https(server) -> None:
    """Wrap a bound-but-not-yet-serving HTTP server's socket in TLS when
    https is configured; no-op otherwise.

    The handshake is deferred to the per-connection worker thread
    (do_handshake_on_connect=False + an explicit do_handshake in
    finish_request): with the default eager handshake it would run inside
    accept() on the single serve_forever thread, where one idle or
    plaintext client parks the whole server."""
    ctx = https_server_context()
    if ctx is None:
        return
    server.socket = ctx.wrap_socket(
        server.socket, server_side=True, do_handshake_on_connect=False
    )
    orig_finish = server.finish_request

    def finish_request(request, client_address):
        request.settimeout(_HANDSHAKE_TIMEOUT)
        try:
            request.do_handshake()
        except (OSError, ValueError):  # plaintext probe / handshake timeout
            try:
                request.close()
            except OSError:
                pass
            return
        request.settimeout(None)
        orig_finish(request, client_address)

    server.finish_request = finish_request


def _client_context() -> ssl.SSLContext:
    st = _state
    cached = _ctx_cache.get("client")
    if cached is not None:
        return cached
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if st is not None:
        ctx.load_verify_locations(st.ca_file)
        if st.cert_file:
            ctx.load_cert_chain(st.cert_file, st.key_file)
    else:
        ctx.load_default_certs()
    with _lock:
        _ctx_cache["client"] = ctx
    return ctx


def _relaxed_context() -> ssl.SSLContext:
    """CA-pinned but hostname-flexible: cluster nodes dial each other by
    IP:port while the shared cert names the cluster authority. The CA pin
    still authenticates the peer; only the name check is relaxed."""
    cached = _ctx_cache.get("relaxed")
    if cached is not None:
        return cached
    st = _state
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    if st is not None:
        ctx.load_verify_locations(st.ca_file)
        if st.cert_file:
            ctx.load_cert_chain(st.cert_file, st.key_file)
    with _lock:
        _ctx_cache["relaxed"] = ctx
    return ctx


def urlopen(req, timeout: float = 30.0):
    """Intra-cluster urlopen: plain HTTP when TLS is off; otherwise HTTPS
    with the cluster CA (and client cert, for data-path mTLS). Contexts
    are cached — this sits on the per-chunk hot path."""
    if not https_enabled():
        return urllib.request.urlopen(req, timeout=timeout)
    st = _state
    if st is not None and st.override_authority:
        # dials are by IP:port, certs name the cluster authority
        return urllib.request.urlopen(req, timeout=timeout, context=_relaxed_context())
    return urllib.request.urlopen(req, timeout=timeout, context=_client_context())


# -- self-signed material (tests / scaffold) ---------------------------------


def generate_self_signed(directory: str, common_name: str = "weedtpu-cluster") -> dict:
    """Issue a throwaway CA + one leaf cert/key signed by it (SANs cover
    localhost/127.0.0.1 so loopback clusters verify). Returns the paths:
    {"ca": ..., "cert": ..., "key": ...}."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(directory, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _write_key(key, path):
        with open(path, "wb") as f:
            f.write(
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.TraditionalOpenSSL,
                    serialization.NoEncryption(),
                )
            )

    def _write_cert(cert, path):
        with open(path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))

    ca_key = _key()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name + "-ca")])
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(ca_key, hashes.SHA256())
    )

    leaf_key = _key()
    leaf_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    import ipaddress

    leaf_cert = (
        x509.CertificateBuilder()
        .subject_name(leaf_name)
        .issuer_name(ca_name)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=365))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName(common_name),
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )

    paths = {
        "ca": os.path.join(directory, "ca.crt"),
        "cert": os.path.join(directory, "node.crt"),
        "key": os.path.join(directory, "node.key"),
    }
    _write_cert(ca_cert, paths["ca"])
    _write_cert(leaf_cert, paths["cert"])
    _write_key(leaf_key, paths["key"])
    return paths
