"""Security — mirror of weed/security (guard.go, jwt handling)
[VERIFY: mount empty; SURVEY.md §2.1 "Security" row]: HMAC-SHA256 JWTs
minted by the master on Assign and enforced by volume servers on the
write/delete data path; optional separate read key. Keys come from
`security.toml` (seaweedfs_tpu.utils.config)."""

from seaweedfs_tpu.security.guard import Guard
from seaweedfs_tpu.security.jwt import decode_jwt, encode_jwt

__all__ = ["Guard", "encode_jwt", "decode_jwt"]
