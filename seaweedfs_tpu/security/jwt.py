"""Minimal HS256 JWT — the only JWT shape the reference uses for its
volume-write tokens (SeaweedFileIdClaims: exp + fid) [VERIFY: mount empty;
weed/security/jwt.go]. Stdlib-only: hmac + sha256 + base64url."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _unb64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def encode_jwt(key: bytes, claims: dict, expires_seconds: int = 10) -> str:
    """Sign claims (adding exp) with HS256."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    body = dict(claims)
    if expires_seconds:
        body["exp"] = int(time.time()) + expires_seconds
    payload = _b64url(json.dumps(body, separators=(",", ":")).encode())
    signing_input = header + b"." + payload
    sig = _b64url(hmac.new(key, signing_input, hashlib.sha256).digest())
    return (signing_input + b"." + sig).decode()


def decode_jwt(key: bytes, token: str) -> dict:
    """Verify signature + expiry; returns the claims. Raises JwtError."""
    try:
        header_s, payload_s, sig_s = token.split(".")
    except ValueError:
        raise JwtError("malformed token") from None
    signing_input = (header_s + "." + payload_s).encode()
    expect = _b64url(hmac.new(key, signing_input, hashlib.sha256).digest()).decode()
    if not hmac.compare_digest(expect, sig_s):
        raise JwtError("bad signature")
    try:
        header = json.loads(_unb64url(header_s))
        claims = json.loads(_unb64url(payload_s))
    except (ValueError, json.JSONDecodeError):
        raise JwtError("malformed payload") from None
    if header.get("alg") != "HS256":
        raise JwtError(f"unsupported alg {header.get('alg')!r}")
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        raise JwtError("token expired")
    return claims


def mint_file_token(key: Optional[bytes], fid: str, expires_seconds: int = 10) -> str:
    """Token authorizing one write/delete of `fid` (SeaweedFileIdClaims
    analog). Empty string when no key is configured (auth disabled)."""
    if not key:
        return ""
    return encode_jwt(key, {"fid": fid}, expires_seconds=expires_seconds)


def check_file_token(key: Optional[bytes], token: str, fid: str) -> bool:
    """True iff auth is disabled, or `token` validly authorizes `fid`."""
    if not key:
        return True
    if not token:
        return False
    try:
        claims = decode_jwt(key, token)
    except JwtError:
        return False
    return claims.get("fid") == fid
