"""JSON-over-gRPC transport — the control-plane RPC layer.

The reference's control plane is gRPC with protobuf contracts
(weed/pb/*.proto [VERIFY: mount empty; SURVEY.md §2.6]). This image ships
grpcio but not grpcio-tools/protoc-gen-python, so instead of generated
stubs the framework registers methods on grpc's *generic handler* API with
two wire formats per method:

  "json"  — request/response are UTF-8 JSON objects (control messages)
  "bytes" — raw byte frames (bulk data: shard copy streams, interval reads);
            metadata rides in gRPC invocation metadata, not the payload

Method kinds: unary-unary, unary-stream (server streaming). That covers the
reference's EC surface (SURVEY.md §2.4): control RPCs are unary, shard
copy/read are server-streamed byte frames.

Errors: handlers raising RpcFault abort with that code/detail; anything
else maps to INTERNAL. Clients get grpc.RpcError as usual.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from concurrent import futures
from typing import Any, Callable, Iterator, Optional

import grpc

from seaweedfs_tpu import stats
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.security import tls
from seaweedfs_tpu.utils import glog


def _json_ser(obj: Any) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode()


def _json_de(data: bytes) -> Any:
    return json.loads(data.decode())


def _bytes_ser(b: bytes) -> bytes:
    return bytes(b)


def _bytes_de(b: bytes) -> bytes:
    return b


_FORMATS = {
    "json": (_json_ser, _json_de),
    "bytes": (_bytes_ser, _bytes_de),
}


def _resolve_serdes(service: str, method: str, req_format: str, resp_format: str):
    """(req_ser, req_de, resp_ser, resp_de) for one method, honoring the
    process-wide wire selection: WEEDTPU_WIRE=proto swaps every "json"
    side for binary protobuf built from pb/contracts.proto (pb/wire.py).
    "bytes" streams are already the reference's raw-frame shape and stay.

    Failures are LOUD by design: a process that silently fell back to
    JSON while its peers speak protobuf would corrupt every call — the
    operator asked for proto, so a missing schema entry or a codec load
    error must stop the process, not downgrade it."""
    req_ser, req_de = _FORMATS[req_format]
    resp_ser, resp_de = _FORMATS[resp_format]
    if "json" in (req_format, resp_format):
        from seaweedfs_tpu.pb import wire

        if wire.wire_format() == "proto":
            codec = wire.codec()
            # a (service, method) outside the schema (ad-hoc test services)
            # falls back to JSON on BOTH ends — every process derives the
            # decision from the same descriptor set, so the fallback is
            # symmetric and interoperable. A codec load failure still
            # raises: that CAN diverge between processes.
            if codec.has(service, method):
                if req_format == "json":
                    req_ser, req_de = codec.request_serdes(service, method)
                if resp_format == "json":
                    resp_ser, resp_de = codec.response_serdes(service, method)
    return req_ser, req_de, resp_ser, resp_de


def crc_frame(chunk: bytes) -> bytes:
    """Frame one bulk-stream chunk as 4-byte big-endian CRC32 + payload.

    The slab-read bulk stream (VolumeEcShardSlabRead) carries rebuild
    input across the network: a flipped bit there would decode into a
    silently-wrong shard on the rebuilder, so every chunk is integrity-
    checked at the transport seam rather than trusting TCP checksums
    across proxies/retries."""
    return zlib.crc32(chunk).to_bytes(4, "big") + chunk


def crc_unframe(frame: bytes) -> bytes:
    """Inverse of crc_frame; raises IOError on checksum mismatch."""
    if len(frame) < 4:
        raise IOError(f"short CRC frame: {len(frame)} bytes")
    want = int.from_bytes(frame[:4], "big")
    chunk = frame[4:]
    got = zlib.crc32(chunk)
    if got != want:
        raise IOError(f"bulk-stream chunk CRC mismatch: got {got:08x}, want {want:08x}")
    return chunk


class RpcFault(Exception):
    """Handler-raised fault with an explicit status code."""

    def __init__(self, detail: str, code: grpc.StatusCode = grpc.StatusCode.INTERNAL):
        super().__init__(detail)
        self.code = code
        self.detail = detail


class NotFoundFault(RpcFault):
    def __init__(self, detail: str):
        super().__init__(detail, grpc.StatusCode.NOT_FOUND)


class NotLeaderFault(RpcFault):
    """Raised by a raft follower for leader-only operations; carries the
    current leader so facades can point clients at it in a structured way
    instead of burying the address in free text."""

    def __init__(self, leader: str):
        detail = f"not the raft leader; leader is {leader}" if leader else (
            "not the raft leader; no leader elected yet"
        )
        super().__init__(detail, grpc.StatusCode.FAILED_PRECONDITION)
        self.leader = leader


class Method:
    def __init__(
        self,
        fn: Callable,
        kind: str = "unary_unary",
        req_format: str = "json",
        resp_format: str = "json",
    ):
        if kind not in ("unary_unary", "unary_stream", "stream_unary", "stream_stream"):
            raise ValueError(f"bad rpc kind {kind}")
        self.fn = fn
        self.kind = kind
        self.req_format = req_format
        self.resp_format = resp_format


class Service:
    """A named bag of methods. Handlers receive (request, context)."""

    def __init__(self, name: str):
        self.name = name
        self.methods: dict[str, Method] = {}

    def method(self, name: str, kind: str = "unary_unary", req_format: str = "json", resp_format: str = "json"):
        def deco(fn):
            self.methods[name] = Method(fn, kind, req_format, resp_format)
            return fn

        return deco

    def add(self, name: str, fn: Callable, **kw) -> None:
        self.methods[name] = Method(fn, **kw)


def _inbound_trace_id(context) -> Optional[str]:
    """Propagated trace id from gRPC invocation metadata, if any — the
    one reserved metadata field tracing rides, so the pinned proto
    contracts (and every JSON/bytes payload) stay untouched."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == trace_mod.MD_KEY:
                return v if isinstance(v, str) else None
    except Exception:  # noqa: BLE001 — metadata is best-effort context
        pass
    return None


def _wrap_unary(fn, method: str = ""):
    def handler(request, context):
        stats.RpcInflight.labels(method).inc()
        t0 = time.monotonic()
        try:
            with trace_mod.continue_trace(
                "rpc.server", _inbound_trace_id(context)
            ) as sp:
                if sp is not None:
                    sp.annotate(method=method)
                try:
                    return fn(request, context)
                except RpcFault as e:
                    glog.V(1).infof("rpc %s fault: %s", method, e.detail)
                    context.abort(e.code, e.detail)
                except Exception as e:  # noqa: BLE001 — map to INTERNAL for the peer
                    glog.error("rpc %s failed: %s: %s", method, type(e).__name__, e)
                    context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        finally:
            stats.RpcInflight.labels(method).dec()
            stats.RpcServerSeconds.labels(method).observe(time.monotonic() - t0)

    return handler


def _wrap_stream(fn, method: str = ""):
    def handler(request, context):
        stats.RpcInflight.labels(method).inc()
        t0 = time.monotonic()
        try:
            with trace_mod.continue_trace(
                "rpc.server", _inbound_trace_id(context)
            ) as sp:
                if sp is not None:
                    sp.annotate(method=method)
                try:
                    yield from fn(request, context)
                except RpcFault as e:
                    glog.V(1).infof("rpc %s fault: %s", method, e.detail)
                    context.abort(e.code, e.detail)
                except Exception as e:  # noqa: BLE001
                    glog.error("rpc %s failed: %s: %s", method, type(e).__name__, e)
                    context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
        finally:
            stats.RpcInflight.labels(method).dec()
            stats.RpcServerSeconds.labels(method).observe(time.monotonic() - t0)

    return handler


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, services: dict[str, Service]):
        self._services = services

    def service(self, handler_call_details):
        # method path: /<service>/<method>
        _, svc_name, m_name = handler_call_details.method.split("/", 2)
        svc = self._services.get(svc_name)
        if svc is None:
            return None
        m = svc.methods.get(m_name)
        if m is None:
            return None
        req_ser, req_de, resp_ser, resp_de = _resolve_serdes(
            svc_name, m_name, m.req_format, m.resp_format
        )
        if m.kind == "unary_unary":
            return grpc.unary_unary_rpc_method_handler(
                _wrap_unary(m.fn, m_name), request_deserializer=req_de, response_serializer=resp_ser
            )
        if m.kind == "unary_stream":
            return grpc.unary_stream_rpc_method_handler(
                _wrap_stream(m.fn, m_name), request_deserializer=req_de, response_serializer=resp_ser
            )
        if m.kind == "stream_unary":
            return grpc.stream_unary_rpc_method_handler(
                _wrap_unary(m.fn, m_name), request_deserializer=req_de, response_serializer=resp_ser
            )
        return grpc.stream_stream_rpc_method_handler(
            _wrap_stream(m.fn, m_name), request_deserializer=req_de, response_serializer=resp_ser
        )


class RpcServer:
    """grpc.server wrapper hosting Service objects on one port."""

    def __init__(self, port: int = 0, max_workers: int = 16, host: str = "127.0.0.1"):
        self._services: dict[str, Service] = {}
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 64 * 1024 * 1024),
                ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ],
        )
        self._server.add_generic_rpc_handlers((_GenericHandler(self._services),))
        # process-wide TLS (security.toml [grpc]) — mTLS when configured,
        # matching the reference's per-process grpc cert wiring
        creds = tls.server_credentials()
        if creds is not None:
            self.port = self._server.add_secure_port(f"{host}:{port}", creds)
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._started = False

    def add_service(self, svc: Service) -> None:
        self._services[svc.name] = svc

    def start(self) -> None:
        self._server.start()
        self._started = True

    def stop(self, grace: Optional[float] = 0.5) -> None:
        if self._started:
            self._server.stop(grace).wait()
            self._started = False


class RpcClient:
    """Channel wrapper: call(service, method, request) with lazy per-method
    callables, JSON by default."""

    def __init__(self, address: str):
        self.address = address
        options = [
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            *tls.channel_options(),
        ]
        creds = tls.channel_credentials()
        if creds is not None:
            self._channel = grpc.secure_channel(address, creds, options=options)
        else:
            self._channel = grpc.insecure_channel(address, options=options)
        self._lock = threading.Lock()
        self._stubs: dict[tuple, Callable] = {}

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _stub(self, service: str, method: str, kind: str, req_format: str, resp_format: str):
        key = (service, method, kind)
        with self._lock:
            stub = self._stubs.get(key)
            if stub is None:
                req_ser, _, _, resp_de = _resolve_serdes(
                    service, method, req_format, resp_format
                )
                path = f"/{service}/{method}"
                factory = getattr(self._channel, kind)
                stub = factory(path, request_serializer=req_ser, response_deserializer=resp_de)
                self._stubs[key] = stub
        return stub

    @staticmethod
    def _trace_metadata():
        """Invocation metadata carrying the ambient trace id, when one is
        active in this thread — the client half of cross-process trace
        propagation. None (no metadata at all) otherwise."""
        tid = trace_mod.current_trace_id()
        return ((trace_mod.MD_KEY, tid),) if tid else None

    def call(self, service: str, method: str, request: Any = None, timeout: float = 30.0) -> Any:
        """Unary-unary JSON call."""
        stub = self._stub(service, method, "unary_unary", "json", "json")
        return stub(
            request if request is not None else {}, timeout=timeout,
            metadata=self._trace_metadata(),
        )

    def stream(
        self, service: str, method: str, request: Any = None, timeout: float = 600.0,
        resp_format: str = "bytes",
    ) -> Iterator:
        """Unary-stream call; defaults to raw byte frames (bulk transfer)."""
        stub = self._stub(service, method, "unary_stream", "json", resp_format)
        return stub(
            request if request is not None else {}, timeout=timeout,
            metadata=self._trace_metadata(),
        )


class ClientPool:
    """Long-lived RpcClient per peer address — the degraded-read ladder and
    replication fan-out dial the same few holders over and over; a fresh
    channel per read costs a TCP+HTTP/2 setup on the latency-critical path
    ([ref: weed/storage/erasure_coding/ec_volume.go ShardLocations +
    grpc connection reuse in weed/operation — mount empty, SURVEY.md §3.2]).

    gRPC channels are thread-safe; the pool only guards the dict. A caller
    that sees a transport error should `invalidate(addr)` so the next use
    redials instead of reusing a broken channel.
    """

    def __init__(self) -> None:
        self._clients: dict[str, RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, address: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(address)
            if c is None:
                c = self._clients[address] = RpcClient(address)
            return c

    def invalidate(self, address: str) -> None:
        with self._lock:
            c = self._clients.pop(address, None)
        if c is not None:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def close_all(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
