"""Replicator — mirror of weed/replication/replicator.go + the offset
bookkeeping in command/filer_sync.go [VERIFY: mount empty; SURVEY.md
§2.1 "Replication/sync" row].

Tails the source filer's metadata subscription from the last checkpoint
and applies each event to the sink:

  new only            -> create (file data streamed from the source)
  old only            -> delete
  old+new, same path  -> overwrite
  old+new, moved      -> delete old + create new

The checkpoint (last applied ts_ns) lives in the SOURCE filer's KV store
under `replication.offset.<sink-id>`, so a restarted sync resumes where
it stopped (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import threading
from typing import Optional

from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.filer.filer import MetaEvent
from seaweedfs_tpu.replication.sinks import ReplicationSink


class Replicator:
    def __init__(
        self,
        source_grpc_address: str,
        sink: ReplicationSink,
        prefix: str = "/",
        sink_id: str = "",
    ):
        self.source = FilerClient(source_grpc_address)
        self.sink = sink
        self.prefix = "/" + prefix.strip("/") if prefix.strip("/") else "/"
        self.sink_id = sink_id or f"{sink.name}"
        self._offset_key = f"replication.offset.{self.sink_id}"

    def close(self) -> None:
        self.source.close()
        self.sink.close()

    # -- checkpoint -----------------------------------------------------------

    def load_offset(self) -> int:
        raw = self.source.kv_get(self._offset_key)
        return int(raw.decode()) if raw else 0

    def save_offset(self, ts_ns: int) -> None:
        self.source.kv_put(self._offset_key, str(ts_ns).encode())

    # -- apply ----------------------------------------------------------------

    def _key_of(self, path: str) -> Optional[str]:
        root = self.prefix.rstrip("/")
        if root and not (path == root or path.startswith(root + "/")):
            return None
        rel = path[len(root) :].lstrip("/")
        return rel or None

    def apply(self, ev: MetaEvent) -> None:
        import grpc

        old, new = ev.old_entry, ev.new_entry
        old_key = self._key_of(old["path"]) if old else None
        new_key = self._key_of(new["path"]) if new else None
        if old_key and (not new_key or new_key != old_key):
            self.sink.delete(old_key, is_dir=bool(old.get("is_directory")))
        if new_key:
            is_dir = bool(new.get("is_directory"))
            data = b""
            if not is_dir and new.get("chunks"):
                try:
                    data = self.source.read_file(new["path"])
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.NOT_FOUND:
                        # replaying history: the entry was renamed/deleted
                        # by a LATER event, which will reconcile the sink —
                        # don't let one vanished path poison the stream
                        return
                    raise
            mime = (new.get("attributes") or {}).get("mime", "")
            self.sink.create(new_key, data, mime=mime, is_dir=is_dir)

    # -- run loops ------------------------------------------------------------

    def run_once(self, max_idle_s: float = 1.0) -> int:
        """Drain events since the checkpoint until the stream idles;
        returns the number applied. (filer.backup shape)"""
        applied = 0
        last = self.load_offset()
        for ev in self.source.subscribe(
            since_ns=last, path_prefix=self.prefix, max_idle_s=max_idle_s
        ):
            self.apply(ev)
            last = ev.ts_ns
            self.save_offset(last)
            applied += 1
        return applied

    def run(self, stop: threading.Event, max_idle_s: float = 2.0) -> None:
        """Continuous sync until `stop` is set. (filer.sync shape)"""
        while not stop.is_set():
            try:
                self.run_once(max_idle_s=max_idle_s)
            except Exception:  # noqa: BLE001 — source hiccup; retry
                if stop.wait(1.0):
                    return
            stop.wait(0.2)
