"""Replication — mirror of weed/replication/ (Replicator + sink wall:
filer/s3/gcs/azure/b2/local) driven by the filer metadata event log
[VERIFY: mount empty; SURVEY.md §2.1 "Replication/sync" row, §5].

  sinks.py      — ReplicationSink interface + LocalSink (directory),
                  FilerSink (another filer), S3Sink (any S3 endpoint,
                  including this framework's own gateway)
  replicator.py — tails a source filer's metadata subscription and
                  applies each event to a sink; resumes from a
                  checkpoint stored in the source filer's KV store
                  (SURVEY.md §5 checkpoint/resume).

Drives `filer.sync` (continuous filer->filer) and `filer.backup`
(filer->local directory), the command/filer_sync.go / filer_backup.go
analogs.
"""

from seaweedfs_tpu.replication.sinks import (
    FilerSink,
    LocalSink,
    ReplicationSink,
    S3Sink,
)
from seaweedfs_tpu.replication.replicator import Replicator

__all__ = ["ReplicationSink", "LocalSink", "FilerSink", "S3Sink", "Replicator"]
