"""Replication sinks — mirror of weed/replication/sink/{localsink,
filersink,s3sink} [VERIFY: mount empty; SURVEY.md §2.1 "Replication/sync"
row]. A sink applies one entry mutation; the Replicator decides which.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from seaweedfs_tpu.s3api.auth import sign_request
from seaweedfs_tpu.security import tls


class ReplicationSink:
    """Keys are source-filer paths relative to the replication prefix
    (no leading slash)."""

    name = "abstract"

    def create(self, key: str, data: bytes, mime: str = "", is_dir: bool = False) -> None:
        raise NotImplementedError

    def delete(self, key: str, is_dir: bool = False) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalSink(ReplicationSink):
    """Mirror into a local directory tree (sink/localsink)."""

    name = "local"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.abspath(os.path.join(self.root, key))
        if not p.startswith(self.root + os.sep) and p != self.root:
            raise ValueError(f"key {key!r} escapes the sink root")
        return p

    def create(self, key: str, data: bytes, mime: str = "", is_dir: bool = False) -> None:
        p = self._path(key)
        if is_dir:
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".repl"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def delete(self, key: str, is_dir: bool = False) -> None:
        p = self._path(key)
        try:
            if is_dir:
                import shutil

                shutil.rmtree(p, ignore_errors=True)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


class FilerSink(ReplicationSink):
    """Replicate into another filer over its HTTP API (sink/filersink)."""

    name = "filer"

    def __init__(self, filer_http_address: str, target_root: str = "/"):
        self.filer_http = filer_http_address
        self.root = "/" + target_root.strip("/")

    def _url(self, key: str, query: str = "") -> str:
        path = (self.root.rstrip("/") + "/" + key).replace("//", "/")
        return f"{tls.scheme()}://{self.filer_http}{urllib.parse.quote(path)}" + (
            f"?{query}" if query else ""
        )

    def create(self, key: str, data: bytes, mime: str = "", is_dir: bool = False) -> None:
        if is_dir:
            req = urllib.request.Request(
                self._url(key) + "/?op=mkdir", data=b"", method="PUT"
            )
        else:
            req = urllib.request.Request(
                self._url(key),
                data=data,
                method="PUT",
                headers={"Content-Type": mime or "application/octet-stream"},
            )
        with tls.urlopen(req, timeout=60) as r:
            r.read()

    def delete(self, key: str, is_dir: bool = False) -> None:
        try:
            req = urllib.request.Request(
                self._url(key, "recursive=true"), method="DELETE"
            )
            with tls.urlopen(req, timeout=60) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class S3Sink(ReplicationSink):
    """Replicate into any S3 endpoint (sink/s3sink) — works against this
    framework's own gateway or an external one."""

    name = "s3"

    def __init__(
        self,
        endpoint: str,  # host:port
        bucket: str,
        access_key: str = "",
        secret_key: str = "",
        directory: str = "",
    ):
        self.endpoint = endpoint
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.prefix = directory.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def _request(self, method: str, key: str, data: bytes = b"", mime: str = ""):
        url = f"http://{self.endpoint}/{self.bucket}/{urllib.parse.quote(self._key(key))}"
        extra = {"Content-Type": mime} if mime else {}
        headers = sign_request(
            self.access_key, self.secret_key, method, url, data, extra_headers=extra
        )
        req = urllib.request.Request(
            url, data=data if data else None, method=method, headers=headers
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.read()

    def create(self, key: str, data: bytes, mime: str = "", is_dir: bool = False) -> None:
        if is_dir:
            return  # S3 has no directories
        self._request("PUT", key, data, mime)

    def delete(self, key: str, is_dir: bool = False) -> None:
        if is_dir:
            return
        try:
            self._request("DELETE", key)
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
