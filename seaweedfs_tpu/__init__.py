"""seaweedfs_tpu — a TPU-native framework with the capabilities of SeaweedFS's
warm-storage stack (reference: eliefly/seaweedfs).

The compute heart is GF(2^8) Reed-Solomon 10+4 erasure coding executed as batched
int8 matmuls on TPU MXUs (bit-plane / Cauchy-binary formulation), wrapped in the
same operational surface the reference exposes: volume striping (`.ec00..ec13`),
sorted needle indexes (`.ecx`), deletion journals (`.ecj`), interval math for
degraded reads, rebuild orchestration, and a cluster control plane.

Layout (mirrors SURVEY.md §2 component inventory, TPU-first design per §7):
  ops/      — GF(2^8) math core + JAX/Pallas RS kernels   (ref: klauspost/reedsolomon)
  ec/       — stripe engine, interval math, shard formats (ref: weed/storage/erasure_coding)
  storage/  — needle/volume engine, indexes, superblock   (ref: weed/storage)
  parallel/ — device mesh, shard_map multi-chip paths     (ref: goroutine/grpc parallelism)
  models/   — end-to-end pipelines (encode/rebuild/read)  (the "model families")
  cluster/  — master/volume/topology control plane        (ref: weed/server, weed/topology)
  utils/    — config, metrics, logging
"""

__version__ = "0.1.0"

from seaweedfs_tpu.ec.constants import (  # noqa: F401
    DATA_SHARDS_COUNT,
    PARITY_SHARDS_COUNT,
    TOTAL_SHARDS_COUNT,
)
