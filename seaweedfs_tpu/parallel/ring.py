"""Ring-pipelined multi-chip rebuild — the ring-attention analog for the
EC domain (SURVEY.md §5 "long-context" row; [ref: weed/shell/
command_ec_rebuild.go, mount empty — the reference copies every survivor
shard to ONE rebuilder node]).

`make_distributed_rebuild_fn` (parallel/sharded.py) flips shard-major
survivors to byte-major with one `all_to_all`, which materializes every
chip's full survivor working set at once. This module does the same
reconstruction as a RING: each chip keeps its resident survivor-shard
block and rotates it one hop per step with `lax.ppermute`, accumulating
that block's contribution to its own byte tile before passing it on.

    step k on chip c:
      block holds the survivor shards originally resident on chip c-k
      acc ^= decode_cols(owner[block]) x block[:, :, my_byte_tile]
      block -> ppermute -> chip c+1

After P steps every chip has seen every survivor exactly once. GF(2^8)
addition is XOR, so the per-owner partial outputs combine exactly.
Peak per-chip memory is ONE resident block (vs the all_to_all's full
regrouped survivor set) and each hop's transfer overlaps the matmul of
the block in hand — the same memory/latency trade ring attention makes
for KV blocks over ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from seaweedfs_tpu.ops import rs_jax
from seaweedfs_tpu.parallel import shard_map
from seaweedfs_tpu.parallel.sharded import matrix_bits, pad_survivor_matrix, place_survivors


def make_ring_rebuild_fn(mesh: Mesh, recon_m: np.ndarray, donate: bool = False):
    """Ring rebuild over the 'sp' mesh axis.

    recon_m: (L, S) GF(2^8) decode matrix (survivors -> lost shards). The
    survivor axis is zero-padded to a multiple of the ring size (zero
    matrix columns contribute nothing).

    Returns run(survivors (B, S, N) uint8) -> (B, L, N) device array with
    N sharded over 'sp' — the same contract as make_distributed_rebuild_fn,
    so the two are drop-in alternatives and directly comparable.
    donate=True releases the placed survivor buffer at dispatch-consume
    time (run() owns the device_put'ed copy; caller memory is never
    donated).
    """
    n_lost, n_surv = np.asarray(recon_m).shape
    sp = mesh.shape["sp"]
    padded = pad_survivor_matrix(recon_m, sp)
    s_pad = padded.shape[1]
    b_rec = matrix_bits(padded)
    l8 = n_lost * 8
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "sp", None),),
        out_specs=P("dp", None, "sp"),
    )
    def _ring_rebuild(survivors):
        # local block: (B/dp, s_pad/sp, N) — whole shards, full byte extent
        b_local, s_local, n = survivors.shape
        tile = n // sp
        cols_per = s_local * 8
        my = jax.lax.axis_index("sp")
        acc0 = jnp.zeros((b_local, n_lost, tile), dtype=jnp.uint8)
        # the loop carry varies per device (each chip accumulates its own
        # tile) — mark the unvarying zeros init accordingly or the scan
        # carry types mismatch under shard_map's varying-axes checks
        if hasattr(jax.lax, "pcast"):  # jax>=0.9 spelling
            acc0 = jax.lax.pcast(acc0, ("dp", "sp"), to="varying")
        elif hasattr(jax.lax, "pvary"):  # deprecated predecessor
            acc0 = jax.lax.pvary(acc0, ("dp", "sp"))

        def body(k, carry):
            block, acc = carry
            owner = (my - k) % sp  # whose shards this block holds
            cols = jax.lax.dynamic_slice(
                b_rec, (0, owner * cols_per), (l8, cols_per)
            )
            tile_block = jax.lax.dynamic_slice(
                block, (0, 0, my * tile), (b_local, s_local, tile)
            )
            acc = acc ^ rs_jax.gf_apply(cols, tile_block)
            block = jax.lax.ppermute(block, "sp", perm)
            return block, acc

        _, acc = jax.lax.fori_loop(0, sp, body, (survivors, acc0))
        return acc

    donate_argnums = (0,) if donate else ()
    rebuild = jax.jit(_ring_rebuild, donate_argnums=donate_argnums)

    def run(survivors: np.ndarray) -> jax.Array:
        return rebuild(place_survivors(mesh, survivors, n_surv, s_pad))

    return run
