"""Multi-chip EC paths: volume-batch (dp) x stripe (sp) sharding via
shard_map over a Mesh — the TPU-native analog of the reference's
shell-orchestrated fan-out of encode/rebuild over volume servers
(SURVEY.md §2.5 rows DP/TP/SP, §2.6).

Design: the coding kernel is elementwise over the volume-batch axis and over
the stripe (byte) axis, so both shard cleanly with zero communication; the
only collectives are global reductions (integrity checks, progress counters)
which ride ICI as psums. Shard-id redistribution (column regrouping across
chips) is an all_to_all and lives in the distributed rebuild model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from seaweedfs_tpu.ops import gf8, rs_jax


def _bits(m: np.ndarray) -> jax.Array:
    return jnp.asarray(gf8.gf_matrix_to_bits(np.asarray(m, dtype=np.uint8)), dtype=jnp.int8)


def make_encode_fn(mesh: Mesh, parity_m: np.ndarray):
    """Jitted sharded encode: (B, D, N) uint8 -> (B, D+P, N) uint8, with B on
    'dp' and N on 'sp' (either axis may be size 1)."""
    b_bits = _bits(parity_m)
    spec = P("dp", None, "sp")

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    def encode(data):
        parity = rs_jax.gf_apply(b_bits, data)
        return jnp.concatenate([data, parity], axis=1)

    return encode


def make_apply_fn(mesh: Mesh, matrix: np.ndarray):
    """Jitted sharded matrix application (reconstruction with a cached decode
    matrix): (B, C, N) -> (B, R, N)."""
    b_bits = _bits(matrix)
    spec = P("dp", None, "sp")

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec)
    def apply(survivors):
        return rs_jax.gf_apply(b_bits, survivors)

    return apply


def make_ec_cycle_fn(mesh: Mesh, parity_m: np.ndarray, recon_m: np.ndarray, lost_ids, survivor_ids):
    """The full-step function the driver dry-runs: encode -> lose shards ->
    reconstruct -> global integrity psum. Exercises dp x sp sharding plus an
    ICI collective, on one jit.

    Returns fn(data (B, D, N)) -> (shards (B, T, N), global_mismatches ())."""
    b_enc = _bits(parity_m)
    b_rec = _bits(recon_m)
    lost_ids = tuple(lost_ids)
    survivor_ids = tuple(survivor_ids)
    spec = P("dp", None, "sp")

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=(spec, P()),
    )
    def step(data):
        parity = rs_jax.gf_apply(b_enc, data)
        shards = jnp.concatenate([data, parity], axis=1)
        survivors = shards[:, survivor_ids, :]
        rebuilt = rs_jax.gf_apply(b_rec, survivors)
        want = shards[:, lost_ids, :]
        local_bad = jnp.sum(rebuilt != want)
        global_bad = jax.lax.psum(local_bad, ("dp", "sp"))
        return shards, global_bad

    return step


def shard_batch(mesh: Mesh, data: np.ndarray) -> jax.Array:
    """Place a (B, C, N) host array onto the mesh with B on dp, N on sp."""
    return jax.device_put(data, NamedSharding(mesh, P("dp", None, "sp")))
