"""Multi-chip EC paths: volume-batch (dp) x stripe (sp) sharding via
shard_map over a Mesh — the TPU-native analog of the reference's
shell-orchestrated fan-out of encode/rebuild over volume servers
(SURVEY.md §2.5 rows DP/TP/SP, §2.6).

Design: the coding kernel is elementwise over the volume-batch axis and over
the stripe (byte) axis, so both shard cleanly with zero communication; the
collectives are global reductions (integrity checks, progress counters)
riding ICI as psums, plus the shard-major -> byte-major layout flip in
`make_distributed_rebuild_fn` — one all_to_all over 'sp' that lets every
chip rebuild lost shards for its own byte tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from seaweedfs_tpu.ops import gf8, rs_jax
from seaweedfs_tpu.parallel import shard_map


def matrix_bits(m: np.ndarray) -> jax.Array:
    """Device int8 lift of a GF(2^8) matrix (shared by every sharded path)."""
    return jnp.asarray(gf8.gf_matrix_to_bits(np.asarray(m, dtype=np.uint8)), dtype=jnp.int8)


_bits = matrix_bits  # internal alias


def pad_survivor_matrix(recon_m: np.ndarray, sp: int) -> np.ndarray:
    """Zero-pad a (L, S) decode matrix's survivor axis to a multiple of the
    'sp' axis size (zero columns contribute nothing). Shared by the
    all_to_all and ring rebuild formulations."""
    recon_m = np.asarray(recon_m, dtype=np.uint8)
    n_lost, n_surv = recon_m.shape
    s_pad = -(-n_surv // sp) * sp
    padded = np.zeros((n_lost, s_pad), dtype=np.uint8)
    padded[:, :n_surv] = recon_m
    return padded


def place_survivors(
    mesh: Mesh, survivors: np.ndarray, n_surv: int, s_pad: int
) -> jax.Array:
    """Validate + zero-pad + device_put survivors SHARD-major for a
    distributed rebuild: B over 'dp', padded shard rows over 'sp'. The
    validation/padding contract is identical for the all_to_all and ring
    paths — one copy, so they can never drift."""
    b, s, n = survivors.shape
    if s != n_surv:
        raise ValueError(f"want {n_surv} survivor shards, got {s}")
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    if b % dp:
        raise ValueError(f"batch {b} must divide evenly over dp={dp}")
    if n % sp:
        raise ValueError(f"shard length {n} must divide evenly over sp={sp}")
    if s_pad != s:
        survivors = np.concatenate(
            [survivors, np.zeros((b, s_pad - s, n), dtype=np.uint8)], axis=1
        )
    return jax.device_put(survivors, NamedSharding(mesh, P("dp", "sp", None)))


def make_matrix_apply_fn(mesh: Mesh, matrix: np.ndarray, donate: bool = False):
    """Column-sharded GF(2^8) matrix apply over the FULL device set:
    (C, W) uint8 with W sharded across every mesh axis -> (R, W), zero
    communication (GF matmul is column-independent, so each chip's column
    tile is an independent matmul). This is the mesh backend's generic
    dispatch — parity encode, repair projections, and delta columns all
    ride it; W must divide evenly over the device count (the dispatcher
    zero-pads, which is exact: zero columns map to zero columns).

    donate=True releases the input's device buffer at dispatch-consume
    time (the mesh dispatcher always device_puts its own copy first, so
    the donated buffer is jax-owned, never caller memory — the same
    early-release contract as rs_jax.apply_matrix)."""
    b_bits = _bits(matrix)
    spec = P(None, tuple(mesh.axis_names))

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec)
    def apply(cols):
        return rs_jax.gf_apply(b_bits, cols)

    donate_argnums = (0,) if donate else ()
    return jax.jit(apply, donate_argnums=donate_argnums)


def make_encode_fn(mesh: Mesh, parity_m: np.ndarray):
    """Jitted sharded encode: (B, D, N) uint8 -> (B, D+P, N) uint8, with B on
    'dp' and N on 'sp' (either axis may be size 1)."""
    b_bits = _bits(parity_m)
    spec = P("dp", None, "sp")

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    def encode(data):
        parity = rs_jax.gf_apply(b_bits, data)
        return jnp.concatenate([data, parity], axis=1)

    return encode


def make_apply_fn(mesh: Mesh, matrix: np.ndarray):
    """Jitted sharded matrix application (reconstruction with a cached decode
    matrix): (B, C, N) -> (B, R, N)."""
    b_bits = _bits(matrix)
    spec = P("dp", None, "sp")

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec)
    def apply(survivors):
        return rs_jax.gf_apply(b_bits, survivors)

    return apply


def make_ec_cycle_fn(mesh: Mesh, parity_m: np.ndarray, recon_m: np.ndarray, lost_ids, survivor_ids):
    """The full-step function the driver dry-runs: encode -> lose shards ->
    reconstruct -> global integrity psum. Exercises dp x sp sharding plus an
    ICI collective, on one jit. On a mesh WITH a 'dcn' axis the batch also
    shards over it and the reduction is staged: intra-slice psum over ICI
    axes first, then one scalar psum across 'dcn' — the only thing that
    crosses DCN (SURVEY §2.6 pod↔pod).

    Returns fn(data (B, D, N)) -> (shards (B, T, N), global_mismatches ())."""
    b_enc = _bits(parity_m)
    b_rec = _bits(recon_m)
    lost_ids = tuple(lost_ids)
    survivor_ids = tuple(survivor_ids)
    has_dcn = "dcn" in mesh.axis_names
    spec = P(("dcn", "dp") if has_dcn else "dp", None, "sp")
    ici_axes = tuple(a for a in mesh.axis_names if a != "dcn")

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=(spec, P()),
    )
    def step(data):
        parity = rs_jax.gf_apply(b_enc, data)
        shards = jnp.concatenate([data, parity], axis=1)
        survivors = shards[:, survivor_ids, :]
        rebuilt = rs_jax.gf_apply(b_rec, survivors)
        want = shards[:, lost_ids, :]
        bad = jax.lax.psum(jnp.sum(rebuilt != want), ici_axes)
        if has_dcn:
            bad = jax.lax.psum(bad, "dcn")
        return shards, bad

    return step


def shard_batch(mesh: Mesh, data: np.ndarray) -> jax.Array:
    """Place a (B, C, N) host array onto the mesh with B on dp, N on sp."""
    return jax.device_put(data, NamedSharding(mesh, P("dp", None, "sp")))


def make_multislice_ec_cycle_fn(
    mesh: Mesh,
    parity_m: np.ndarray,
    recon_m: np.ndarray,
    lost_ids,
    survivor_ids,
):
    """Host-facing wrapper of make_ec_cycle_fn for a ('dcn', 'dp', 'sp')
    mesh (SURVEY §2.6 pod↔pod: jax multi-slice over DCN for rack-scale
    rebuild fan-out). Slices own disjoint volume sub-batches, heavy
    collectives ride ICI, one scalar crosses DCN — see make_ec_cycle_fn.
    On hardware, 'dcn' maps to slices (mesh_utils
    create_hybrid_device_mesh); the CPU test mesh simulates it with the
    outermost axis, exercising identical sharding/collective structure."""
    if "dcn" not in mesh.axis_names:
        raise ValueError(f"mesh {mesh.axis_names} has no 'dcn' axis")
    step = make_ec_cycle_fn(mesh, parity_m, recon_m, lost_ids, survivor_ids)
    spec = P(("dcn", "dp"), None, "sp")
    batch_div = mesh.shape["dcn"] * mesh.shape["dp"]
    sp = mesh.shape["sp"]

    def run(data: np.ndarray):
        b, _c, n = data.shape
        if b % batch_div:
            raise ValueError(f"batch {b} must divide evenly over dcn*dp={batch_div}")
        if n % sp:
            raise ValueError(f"shard length {n} must divide evenly over sp={sp}")
        x = jax.device_put(data, NamedSharding(mesh, spec))
        return step(x)

    return run


def make_distributed_rebuild_fn(mesh: Mesh, recon_m: np.ndarray, donate: bool = False):
    """Multi-chip distributed rebuild — the TPU-native analog of the
    reference's `ec.rebuild` fan-out of survivor-shard copies to one
    rebuilder node ([ref: weed/shell/command_ec_rebuild.go, mount empty —
    SURVEY.md §3.3]), except every chip participates instead of one node
    doing all the work.

    Storage hands survivors over SHARD-MAJOR (a node/chip holds whole
    shards — the on-disk `.ecNN` layout); the decode matmul wants
    BYTE-MAJOR (each chip needs the same byte range of ALL survivors).
    That layout flip is exactly one `all_to_all` over the mesh's 'sp'
    axis riding ICI; after it, reconstruction of the lost shards is a
    zero-communication matmul per chip on its byte tile, and the output
    comes back byte-sharded, ready for striped writes.

    recon_m: (L, S) GF(2^8) decode matrix mapping S survivors to L lost
    shards (from rs_codec._reconstruction_matrix). The survivor axis is
    zero-padded up to a multiple of the 'sp' axis size (zero matrix
    columns contribute nothing, so correctness is unaffected).

    Returns run(survivors (B, S, N) uint8) -> (B, L, N) device array.
    B must divide evenly over 'dp' and N over 'sp'. donate=True releases
    the placed survivor buffer at dispatch-consume time (run() owns the
    device_put'ed copy, so donation never touches caller memory).
    """
    n_surv = np.asarray(recon_m).shape[1]
    padded = pad_survivor_matrix(recon_m, mesh.shape["sp"])
    s_pad = padded.shape[1]
    b_rec = _bits(padded)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P("dp", "sp", None),),
        out_specs=P("dp", None, "sp"),
    )
    def _rebuild(survivors):
        # local view: (B/dp, s_pad/sp, N) whole-shard rows ->
        # (B/dp, s_pad, N/sp) full survivor set for this chip's byte tile
        regrouped = jax.lax.all_to_all(
            survivors, "sp", split_axis=2, concat_axis=1, tiled=True
        )
        return rs_jax.gf_apply(b_rec, regrouped)

    donate_argnums = (0,) if donate else ()
    rebuild = jax.jit(_rebuild, donate_argnums=donate_argnums)

    def run(survivors: np.ndarray) -> jax.Array:
        return rebuild(place_survivors(mesh, survivors, n_surv, s_pad))

    return run
