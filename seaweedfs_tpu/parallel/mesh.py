"""Device-mesh helpers — the ICI/DCN scaling seam (SURVEY.md §2.6).

The reference scales EC work by fanning goroutines/gRPC over volume servers;
the TPU-native design scales by laying volume batches and stripe tiles over a
`jax.sharding.Mesh` and letting XLA insert collectives over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_mesh(
    axis_names: Sequence[str] = ("dp",),
    shape: Optional[Sequence[int]] = None,
    devices=None,
) -> Mesh:
    """Build a mesh over available devices.

    axis_names: logical axes, e.g. ("dp",) for volume-batch parallelism or
    ("dp", "sp") for volume x stripe 2D sharding.
    shape: devices per axis; defaults to all devices on the first axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))
