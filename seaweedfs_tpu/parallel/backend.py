"""Mesh dispatch — the production adapter between the flat (shards, width)
forms the streaming pipelines in `ec/stripe` dispatch and the dp x sp
shard_map formulations in `parallel/sharded` + `parallel/ring`.

The streaming encode/rebuild pipelines stage every batch as ONE wide
(shards, W) host slab; GF(2^8) matmul is column-independent, so W *is*
the batch axis laid out flat. `MeshDispatch` shards it:

  * encode / generic apply — `sharded.make_matrix_apply_fn`: W splits
    over the FULL device set (zero communication), so host->device
    transfers of a staging batch land on all chips concurrently and each
    chip matmuls its own column tile.
  * distributed rebuild — the flat (S, W) survivor stack is viewed as dp
    column-slice "volumes" of width W/dp and handed SHARD-major to
    `ring.make_ring_rebuild_fn` (ppermute rotation, one resident block
    per chip — the measured-faster formulation, MULTICHIP_r05: 1.21 s vs
    1.54 s on 64 MiB shards) or `sharded.make_distributed_rebuild_fn`
    (one all_to_all layout flip), selected by `WEEDTPU_MESH_REBUILD`.

Byte-identity contract: a column partition never changes any output byte
(matmul columns are independent; zero pad columns map to zero columns and
are sliced off before the host sees them), so every mesh path is
byte-identical to the single-device encoder / `rebuild_ec_files_serial`.
Fully testable off-TPU via `--xla_force_host_platform_device_count=8`.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from seaweedfs_tpu.ops import rs_jax
from seaweedfs_tpu.parallel import mesh as mesh_mod
from seaweedfs_tpu.parallel import ring as ring_mod
from seaweedfs_tpu.parallel import sharded
from seaweedfs_tpu.utils import config

REBUILD_VARIANTS = ("ring", "alltoall")

#: cap on cached compiled dispatch functions per MeshDispatch. Decode
#: matrices churn with shard-loss patterns on a long-lived server (the
#: same churn WEEDTPU_DECODE_MATRIX_CACHE bounds for plain matrices), and
#: each entry here pins a compiled XLA executable — far heavier than a
#: matrix — so the cache must evict, not grow for the life of the process.
_COMPILED_CACHE_CAP = 64


def parse_mesh_shape(raw: str) -> Optional[Tuple[int, int]]:
    """`"4x2"` -> (4, 2); empty/`auto` -> None (resolve elsewhere).
    Malformed values raise — a typo'd shape must fail loudly, not fall
    back to a different mesh than the operator asked for."""
    s = str(raw or "").strip().lower()
    if not s or s == "auto":
        return None
    parts = s.split("x")
    if len(parts) != 2 or not all(p.isdigit() and int(p) > 0 for p in parts):
        raise ValueError(
            f"WEEDTPU_MESH_SHAPE must be `DPxSP` (e.g. 4x2) or auto, got {raw!r}"
        )
    return int(parts[0]), int(parts[1])


def default_mesh_shape(n_devices: int) -> Tuple[int, int]:
    """The dryrun's rule: (n/2 x 2) dp x sp for n >= 4, else (n x 1) —
    sp=2 keeps the ring/all_to_all collectives exercised while dp takes
    the bulk of the batch parallelism."""
    n = max(1, int(n_devices))
    if n >= 4:
        return n // 2, 2
    return n, 1


def _evidence_shape(n_devices: int) -> Optional[Tuple[int, int]]:
    """Best achievable mesh shape from committed MULTICHIP evidence, or
    None. Lazy rs_codec import: the evidence loader lives with the other
    artifact readers and must stay importable without jax."""
    try:
        from seaweedfs_tpu.ops import rs_codec

        ok, dec = rs_codec.pick_mesh_backend(n_devices)
        if ok:
            return parse_mesh_shape(dec["mesh_shape"])
    except Exception:  # noqa: BLE001 — unreadable evidence = no preference
        pass
    return None


class _LazyRestore:
    """An inflight mesh dispatch whose host form differs from the device
    layout: `np.asarray(handle)` (the pipelines' sync point) materializes
    the sharded device output and restores the flat column layout. Until
    then the dispatch stays async, exactly like a bare jax array."""

    def __init__(self, dev, restore, shape):
        self._dev = dev
        self._restore = restore
        #: host-facing shape (pad sliced off) — what np.asarray returns
        self.shape = tuple(shape)

    def __array__(self, dtype=None, copy=None):  # noqa: ARG002 — numpy 2.x kw
        out = self._restore(np.asarray(self._dev))
        if dtype is not None:
            out = out.astype(dtype, copy=False)
        return out


class MeshDispatch:
    """One encoder's mesh state: the `jax.sharding.Mesh`, the jitted
    shard_map'd apply/rebuild functions (cached per GF matrix), and the
    padding rules that keep every dispatch byte-identical to the
    single-device path."""

    def __init__(
        self,
        shape: Optional[Sequence[int]] = None,
        rebuild: Optional[str] = None,
        devices=None,
    ):
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if shape is None:
            shape = parse_mesh_shape(config.env("WEEDTPU_MESH_SHAPE"))
        if shape is None:
            shape = _evidence_shape(n) or default_mesh_shape(n)
        dp, sp = int(shape[0]), int(shape[1])
        if dp <= 0 or sp <= 0 or dp * sp > n:
            raise ValueError(
                f"mesh shape {dp}x{sp} needs {dp * sp} devices, have {n}"
            )
        self.mesh = mesh_mod.device_mesh(("dp", "sp"), shape=(dp, sp), devices=devices)
        self.dp, self.sp = dp, sp
        self.n_devices = dp * sp
        rebuild = rebuild or config.env("WEEDTPU_MESH_REBUILD")
        if rebuild not in REBUILD_VARIANTS:
            raise ValueError(
                f"mesh rebuild variant {rebuild!r} not in {REBUILD_VARIANTS}"
            )
        self.rebuild_variant = rebuild
        #: staging-width alignment: widths that are a multiple of dp*sp
        #: shard with zero padding (the streaming pipelines round their
        #: staging spans up to this so steady-state batches never pad)
        self.width_align = dp * sp
        self._donate = rs_jax.donation_supported()
        self._col_sharding = NamedSharding(self.mesh, P(None, ("dp", "sp")))
        self._apply_fns: dict = {}
        self._rebuild_fns: dict = {}
        self._lock = threading.Lock()
        try:
            from seaweedfs_tpu import stats

            stats.EcMeshDevices.set(self.n_devices)
        except Exception:  # noqa: BLE001 — metrics must never break dispatch
            pass

    def shape_str(self) -> str:
        return f"{self.dp}x{self.sp}"

    # -- cached compiled functions -------------------------------------------

    @staticmethod
    def _cache_get(cache: dict, key, build):
        """LRU-ish bounded memo: move hits to the end, evict the oldest
        entry past _COMPILED_CACHE_CAP (dict preserves insertion order).
        Caller holds the dispatch lock."""
        fn = cache.pop(key, None)
        if fn is None:
            fn = build()
            while len(cache) >= _COMPILED_CACHE_CAP:
                cache.pop(next(iter(cache)))
        cache[key] = fn
        return fn

    def _apply_fn(self, m: np.ndarray):
        key = (m.shape, m.tobytes())
        with self._lock:
            return self._cache_get(
                self._apply_fns,
                key,
                lambda: sharded.make_matrix_apply_fn(self.mesh, m, donate=self._donate),
            )

    def _rebuild_fn(self, recon_m: np.ndarray):
        key = (recon_m.shape, recon_m.tobytes(), self.rebuild_variant)
        make = (
            ring_mod.make_ring_rebuild_fn
            if self.rebuild_variant == "ring"
            else sharded.make_distributed_rebuild_fn
        )
        with self._lock:
            return self._cache_get(
                self._rebuild_fns,
                key,
                lambda: make(self.mesh, recon_m, donate=self._donate),
            )

    # -- layout helpers -------------------------------------------------------

    def _pad_cols(self, flat: np.ndarray, align: int) -> tuple[np.ndarray, int]:
        """Zero-pad the column axis to a multiple of `align` (exact: GF
        matmul maps zero columns to zero columns; the pad is sliced off
        on restore). Aligned inputs pass through untouched — the
        streaming pipelines stage aligned widths so this is the tail-
        batch/serving-path case only."""
        w = flat.shape[-1]
        pad = -w % align
        if pad == 0:
            return flat, w
        out = np.zeros(flat.shape[:-1] + (w + pad,), dtype=np.uint8)
        out[..., :w] = flat
        return out, w

    @staticmethod
    def _flatten_batch(shards: np.ndarray) -> tuple[np.ndarray, tuple]:
        """(B, C, N) -> (C, B*N): per-batch matmuls ARE column-wise
        concatenation, so the batched apply is the flat apply on the
        transposed layout."""
        b, c, n = shards.shape
        return np.ascontiguousarray(np.moveaxis(shards, 0, 1)).reshape(c, b * n), (b, n)

    # -- dispatches -----------------------------------------------------------

    def apply(self, m: np.ndarray, shards: np.ndarray, donate: bool = False):  # noqa: ARG002
        """Generic mesh apply: (C, W) -> lazy (R, W), or (B, C, N) ->
        lazy (B, R, N). Columns shard over the full device set, so every
        chip receives its host slice concurrently and computes its own
        tile. Donation is managed internally: the dispatcher always owns
        the device_put'ed copy, and releases it at dispatch-consume time
        on accelerator platforms regardless of the caller's hint."""
        m = np.ascontiguousarray(np.asarray(m, dtype=np.uint8))
        shards = np.asarray(shards, dtype=np.uint8)
        batched = shards.ndim == 3
        if batched:
            flat, (b, n) = self._flatten_batch(shards)
        else:
            flat = shards
        padded, w = self._pad_cols(flat, self.width_align)
        x = jax.device_put(padded, self._col_sharding)
        out = self._apply_fn(m)(x)
        r = m.shape[0]
        if batched:
            def restore(a, r=r, b=b, n=n):
                return np.ascontiguousarray(
                    np.moveaxis(a[:, : b * n].reshape(r, b, n), 1, 0)
                )

            shape = (b, r, n)
        else:
            def restore(a, w=w):
                return a[:, :w]

            shape = (r, w)
        return _LazyRestore(out, restore, shape)

    def reconstruct(self, recon_m: np.ndarray, stack: np.ndarray, donate: bool = False):  # noqa: ARG002
        """Distributed rebuild of a flat survivor stack: (S, W) -> lazy
        (L, W) (or (B, S, N) -> lazy (B, L, N)) through the selected
        ring/all_to_all formulation. The stack's byte axis is viewed as
        dp column-slice volumes of width W/dp placed SHARD-major
        (P(dp, sp, None)) — each chip holds whole survivor rows of its
        slice, the collective does the layout work, and the output comes
        back byte-sharded over sp."""
        recon_m = np.ascontiguousarray(np.asarray(recon_m, dtype=np.uint8))
        stack = np.asarray(stack, dtype=np.uint8)
        batched = stack.ndim == 3
        if batched:
            flat, (b, n) = self._flatten_batch(stack)
        else:
            flat = stack
        # W/dp must itself divide over sp, so align the flat width to dp*sp
        padded, w = self._pad_cols(flat, self.dp * self.sp)
        s, wp = padded.shape
        wd = wp // self.dp
        # (S, dp, wd) -> (dp, S, wd): volume k holds byte columns
        # [k*wd, (k+1)*wd) of every survivor — a pure column partition
        surv = padded.reshape(s, self.dp, wd).transpose(1, 0, 2)
        out = self._rebuild_fn(recon_m)(surv)  # (dp, L, wd) device, async
        rows = recon_m.shape[0]

        if batched:
            def restore(a, rows=rows, wp=wp, b=b, n=n):
                flat_out = a.transpose(1, 0, 2).reshape(rows, wp)[:, : b * n]
                return np.ascontiguousarray(
                    np.moveaxis(flat_out.reshape(rows, b, n), 1, 0)
                )

            shape = (b, rows, n)
        else:
            def restore(a, rows=rows, wp=wp, w=w):
                return np.ascontiguousarray(
                    a.transpose(1, 0, 2).reshape(rows, wp)[:, :w]
                )

            shape = (rows, w)
        return _LazyRestore(out, restore, shape)
