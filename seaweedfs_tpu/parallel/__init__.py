"""Multi-chip parallel paths (dp x sp shard_map encode/rebuild, ring
rebuild). `shard_map` is resolved here once: newer jax exports it as
`jax.shard_map`; this image's 0.4.x only has the experimental module —
without the fallback every sharded path dies at trace time on
`AttributeError: jax.shard_map` (the whole test_parallel suite failed at
the seed for exactly this)."""

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.6 spelling
    from jax.experimental.shard_map import shard_map  # noqa: F401
