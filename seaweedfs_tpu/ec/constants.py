"""EC constants — mirror of the reference's erasure_coding constants.

[VERIFY: weed/storage/erasure_coding/ec_encoder.go — reference mount empty,
values are upstream SeaweedFS's long-stable constants, see SURVEY.md §2.3.]
"""

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT

# Upper bound on shard ids ANY registered code geometry may use. The
# legacy layout above stays the wire/default geometry; geometry-flexible
# volumes (ec.convert targets such as 12+3 or the 10+4 -> 20+4 stripe
# merge) record their own (k, m) in the .eci sidecar. Discovery scans and
# ShardBits masks size to this bound, not to the legacy 14 — a uint32
# shard bitmask caps it at 32.
MAX_SHARD_COUNT = 32

# Two-tier striping: large rows first, then the tail as small rows.
ERASURE_CODING_LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1 GiB
ERASURE_CODING_SMALL_BLOCK_SIZE = 1024 * 1024  # 1 MiB

# Buffer granularity the reference encodes with (WriteEcFiles' bufferSize).
EC_BUFFER_SIZE = 256 * 1024
