"""EC constants — mirror of the reference's erasure_coding constants.

[VERIFY: weed/storage/erasure_coding/ec_encoder.go — reference mount empty,
values are upstream SeaweedFS's long-stable constants, see SURVEY.md §2.3.]
"""

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT

# Two-tier striping: large rows first, then the tail as small rows.
ERASURE_CODING_LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1 GiB
ERASURE_CODING_SMALL_BLOCK_SIZE = 1024 * 1024  # 1 MiB

# Buffer granularity the reference encodes with (WriteEcFiles' bufferSize).
EC_BUFFER_SIZE = 256 * 1024
