"""Failure-domain-aware EC shard placement — the pure planning half of
the fleet-repair story (ROADMAP open item 3; the policy layer
`command_ec_common.go`'s balancedEcDistribution gestures at but never
enforces).

THE INVARIANT: no failure domain (rack, and transitively DC) may hold
MORE THAN `m` (parity count) shards of any one stripe. Losing one whole
domain then costs at most m shards, which a (k, m) code survives by
construction — "survive a node, then a rack" is exactly this inequality.
A 10+4 stripe therefore needs >= ceil(14/4) = 4 racks for a compliant
spread; on smaller topologies the planner degrades to MINIMIZING the
per-domain maximum (and `placement_violations` reports what remains, so
the gap is visible in `ec.status` instead of silent).

Everything here is pure data -> data (node dicts in, assignments out):
the shell's `ec.encode` spread, `ec.balance -fixPlacement` migration,
the master scheduler's rebuild-target choice, and the inline-ingest
parity spreader all call through these functions, so there is ONE
definition of "legal placement" in the tree.
"""

from __future__ import annotations

from typing import Optional, Sequence

#: legacy default domain cap — callers pass the volume's real parity
#: count; this is only the fallback when geometry is unknown (10+4).
DEFAULT_PARITY = 4


def domain_of(node: dict) -> tuple[str, str]:
    """One node's failure-domain identity: (data_center, rack). Rack is
    the enforcement granularity; the DC component keeps two same-named
    racks in different DCs distinct."""
    return (str(node.get("data_center", "")), str(node.get("rack", "")))


def max_per_domain(parity: int, override: int = 0) -> int:
    """The domain cap: `m` shards, unless an operator override
    (WEEDTPU_PLACEMENT_MAX_PER_DOMAIN, passed in parsed) tightens or
    loosens it. Never below 1 — a cap of 0 would make every placement
    infeasible."""
    cap = int(override) if override else int(parity)
    return max(1, cap)


def plan_spread(
    nodes: Sequence[dict],
    total: int,
    parity: int,
    *,
    cap_override: int = 0,
    load_of=None,
) -> dict[str, list[int]]:
    """Assign shard ids 0..total-1 to nodes, load-balanced AND
    domain-capped: each shard goes to the least-loaded node whose rack
    still has headroom under the cap; when NO rack has headroom (fewer
    racks than ceil(total/cap) — small topologies), the cap relaxes by
    one and assignment continues, i.e. the planner minimizes the
    per-domain maximum instead of failing. Deterministic (ties break on
    url) so tests and re-runs agree.

    `load_of(node) -> int` supplies each node's existing shard load for
    balancing (default: count of ec_shards entries' shard bits is the
    caller's business — 0 when absent)."""
    if not nodes:
        raise ValueError("no volume servers available")
    cap = max_per_domain(parity, cap_override)
    if load_of is None:
        load_of = lambda n: 0  # noqa: E731 — trivial default
    assigned: dict[str, list[int]] = {n["url"]: [] for n in nodes}
    base_load = {n["url"]: int(load_of(n)) for n in nodes}
    dom_count: dict[tuple, int] = {}
    eff_cap = cap
    for sid in range(total):
        viable = [n for n in nodes if dom_count.get(domain_of(n), 0) < eff_cap]
        while not viable:
            # fewer domains than the cap demands: relax one notch and
            # keep the spread as even as the topology allows
            eff_cap += 1
            viable = [
                n for n in nodes if dom_count.get(domain_of(n), 0) < eff_cap
            ]
        best = min(
            viable,
            key=lambda n: (
                len(assigned[n["url"]]) + base_load[n["url"]],
                dom_count.get(domain_of(n), 0),
                n["url"],
            ),
        )
        assigned[best["url"]].append(sid)
        dom_count[domain_of(best)] = dom_count.get(domain_of(best), 0) + 1
    return {u: s for u, s in assigned.items() if s}


def domain_shard_counts(
    holders: dict[int, Sequence[str]], domains: dict[str, tuple]
) -> dict[tuple, set[int]]:
    """{domain: set(shard ids present there)} for one stripe. A shard
    replicated inside one domain still counts ONCE — the invariant is
    about distinct stripe positions a domain failure removes, and a
    second copy of the same shard elsewhere keeps that position alive."""
    out: dict[tuple, set[int]] = {}
    for sid, urls in holders.items():
        for u in urls:
            dom = domains.get(u)
            if dom is None:
                continue
            out.setdefault(dom, set()).add(sid)
    # a shard ONLY held inside one domain is what that domain's failure
    # actually costs; shards replicated across domains survive. Keep the
    # conservative full count (presence), which upper-bounds the loss —
    # operators reading the audit want the worst case.
    return out


def stripe_violations(
    holders: dict[int, Sequence[str]],
    domains: dict[str, tuple],
    parity: int,
    cap_override: int = 0,
) -> list[tuple[tuple, list[int]]]:
    """Domains holding more than the cap's worth of one stripe's shards:
    [(domain, sorted shard ids)] — the positions whose ONLY copies live
    in the offending domain are the actual exposure, so shards that also
    exist elsewhere are excluded before comparing against the cap."""
    cap = max_per_domain(parity, cap_override)
    per_dom = domain_shard_counts(holders, domains)
    out: list[tuple[tuple, list[int]]] = []
    for dom, sids in sorted(per_dom.items()):
        exclusive = sorted(
            s
            for s in sids
            if not any(
                domains.get(u) is not None and domains[u] != dom
                for u in holders.get(s, ())
            )
        )
        if len(exclusive) > cap:
            out.append((dom, exclusive))
    return out


def domain_exposure(
    holders: dict[int, Sequence[str]], domains: dict[str, tuple]
) -> int:
    """The stripe's worst-case single-domain loss: how many shard
    positions the failure of its most-loaded domain would remove. The
    repair scheduler uses it as a ranking tiebreak — equal-redundancy
    stripes with higher exposure are one correlated failure closer to
    data loss."""
    per_dom = domain_shard_counts(holders, domains)
    worst = 0
    for dom, sids in per_dom.items():
        exclusive = sum(
            1
            for s in sids
            if not any(
                domains.get(u) is not None and domains[u] != dom
                for u in holders.get(s, ())
            )
        )
        worst = max(worst, exclusive)
    return worst


def pick_rebuild_target(
    nodes: Sequence[dict],
    holders: dict[int, Sequence[str]],
    domains: dict[str, tuple],
    missing: Sequence[int],
    parity: int,
    *,
    cap_override: int = 0,
    addr_of=None,
    strict: bool = False,
) -> Optional[dict]:
    """Choose the node a whole-stripe rebuild should land on. Rebuilt
    shards all materialize on the target, so the constraint is
    (shards the target's rack already holds) + |missing| <= cap;
    among compliant nodes prefer the one already holding the MOST of
    this stripe's shards (fewest survivor slabs over the wire), then
    the least EC-loaded, then url. Falls back to the least-loaded
    compliant-less node when no rack has headroom (small topologies) —
    repairing with a violation beats not repairing — unless `strict`,
    which returns None instead of violating (used when probing whether
    a SPECIFIC node can legally join a batch; the caller has other
    candidates, so there is no repair-or-nothing tradeoff).

    `addr_of(node) -> str` maps a node dict to the url key used in
    `holders` (defaults to node["url"])."""
    if not nodes:
        return None
    if addr_of is None:
        addr_of = lambda n: n["url"]  # noqa: E731
    cap = max_per_domain(parity, cap_override)
    per_dom = domain_shard_counts(holders, domains)

    def local_shards(n: dict) -> int:
        u = addr_of(n)
        return sum(1 for sids in holders.values() for h in sids if h == u)

    def key(n: dict):
        # most of THIS stripe's shards first (fewest survivor slabs over
        # the wire), then the node's cluster-wide EC load when the caller
        # supplies it (`ec_load` on the node dict), then url
        return (-local_shards(n), int(n.get("ec_load", 0)), n["url"])

    compliant = [
        n
        for n in nodes
        if len(per_dom.get(domain_of(n), set()) | set(missing)) <= cap
    ]
    if strict and not compliant:
        return None
    pool = compliant or list(nodes)
    return min(pool, key=key)


def plan_parity_targets(
    nodes: Sequence[dict],
    owner_url: str,
    data_shards: int,
    total_shards: int,
    *,
    cap_override: int = 0,
    load_of=None,
) -> dict[int, dict]:
    """Inline-ingest spread plan: which node should host each PARITY
    shard of a volume being encoded on `owner_url`. The owner keeps the
    k data shards (they are views of its local .dat), so parity rows
    stream to nodes OUTSIDE the owner's domain first, spread so no
    other domain accumulates more than the cap. Returns
    {parity shard id: node dict} — possibly empty (single-node cluster:
    nothing to spread to, seal keeps everything local)."""
    parity = total_shards - data_shards
    others = [n for n in nodes if n["url"] != owner_url]
    if not others or parity <= 0:
        return {}
    owner_dom = next(
        (domain_of(n) for n in nodes if n["url"] == owner_url), None
    )
    # prefer non-owner-domain nodes; same-domain nodes only when there is
    # nowhere else (still better than the owner hosting all 14)
    preferred = [n for n in others if domain_of(n) != owner_dom] or others
    alloc = plan_spread(
        preferred,
        parity,
        parity,
        cap_override=cap_override,
        load_of=load_of,
    )
    by_url = {n["url"]: n for n in preferred}
    out: dict[int, dict] = {}
    for url, sids in alloc.items():
        for rel in sids:
            out[data_shards + rel] = by_url[url]
    return out
