"""ReadPlanner — the per-interval decision tree behind EcVolume reads.

One object owns everything the read path decides per interval: which rung
serves it (local -> decoded-interval cache -> remote -> reconstruct), how
remote fetches are capped and blamed (per-holder wedge caps, the suspicion
ladder, EWMA-driven hedging), how concurrent decodes of the same interval
coalesce into one survivor fan-out, and how quarantined shards reroute.
Historically this logic grew interleaved through `ec_volume.py`; the
extraction gives it a single seam so serving tiers can be layered behind
it without re-threading `ec_volume.py` each time.

The planner holds a back-reference to its EcVolume and reads the volume's
mutable collaborators (`remote_reader`, `encoder`, the `recover_*` knobs,
the suspicion registry) dynamically — swapping a reader or encoder on the
volume mid-life keeps working exactly as before the extraction.

The first tier behind the planner is the DECODED-INTERVAL CACHE: degraded
traffic is wire-dominated (TRACE_ATTRIB_r01: fetch.holder 0.67 of the
tail vs decode 0.22), so a hot degraded interval that is reconstructed
once per *request* wastes a full survivor fan-out every time. The cache
makes it once per epoch instead — see `DecodedIntervalCache`.
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from concurrent.futures import TimeoutError as _FutureTimeout  # 3.10: not builtins.TimeoutError
from typing import Optional

import numpy as np

from seaweedfs_tpu import stats
from seaweedfs_tpu.obs import trace as trace_mod

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.utils import config


class EcDegradedReadError(IOError):
    """A degraded read could not be served. Typed (instead of a bare
    IOError/None bubble) so the volume server can answer 503 with a
    Retry-After hint and operators can count failure classes apart.
    Carries WHO was attempted and what the suspicion registry thought at
    failure time — the difference between "the cluster lost the stripe"
    and "one wedged peer is poisoning the ladder"."""

    #: seconds a client should back off before retrying; subclasses pick
    #: a default matched to their failure mode, callers may override
    retry_after: float = 1.0

    def __init__(
        self,
        msg: str,
        shard_id: Optional[int] = None,
        attempted: tuple = (),
        suspected: tuple = (),
        retry_after: Optional[float] = None,
    ):
        super().__init__(msg)
        self.shard_id = shard_id
        #: holder keys (peer addrs when the reader names peers, else
        #: (volume, shard) tuples) the read actually tried
        self.attempted = list(attempted)
        #: holder keys sitting in a suspicion window when the read failed
        self.suspected = list(suspected)
        if retry_after is not None:
            self.retry_after = retry_after


class EcNoViableHolders(EcDegradedReadError):
    """Too few survivors reachable and no attempt still pending: every
    candidate answered a miss, erred, or sat suspected. Retrying sooner
    than the suspicion backoff mostly re-fails, hence the longer hint."""

    retry_after = 5.0


class EcDegradedReadTimeout(EcDegradedReadError):
    """The overall recover deadline expired with fetches still in flight —
    survivors exist but answered too slowly; a prompt retry may win."""

    retry_after = 1.0


class EcShardCorrupt(EcDegradedReadError):
    """The read failed AND this volume has shards quarantined for failed
    integrity verification — no clean copy could serve the interval. The
    scrubber's auto-repair is (or will be) rebuilding the quarantined
    shards, so the retry hint matches the repair timescale, and the
    operator-facing class says 'corruption', not 'holders down'."""

    retry_after = 5.0

    def __init__(self, msg: str, quarantined: Optional[dict] = None, **kw):
        super().__init__(msg, **kw)
        #: {shard_id: reason} snapshot of the volume's quarantine registry
        self.quarantined = dict(quarantined or {})


class _CoalesceSlot:
    """One in-flight degraded decode: the leader publishes its result (or
    error) here and sets the event; waiters read it instead of decoding."""

    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class DecodedIntervalCache:
    """Process-wide bounded LRU of DECODED shard intervals, keyed like a
    `_CoalesceSlot` plus the owning volume: (base, shard, offset, size) ->
    bytes. Only real reconstructions publish (the coalesce leader, or the
    batch decoder per item), so a hot degraded interval costs one survivor
    fan-out + decode per WEEDTPU_READ_CACHE_TTL_S epoch instead of one per
    request. Capped by WEEDTPU_READ_CACHE_MB (MiB; 0 disables lookups and
    publishes entirely).

    Byte safety over hit rate: every event that can change what a shard
    interval SHOULD read as — quarantine, shard remount after rebuild,
    inline-ingest delta update, unmount / convert cut-over — flushes the
    volume's entries AND bumps its generation. Publishers snapshot the
    generation BEFORE gathering survivors and `put` refuses a stale
    snapshot, so a decode that straddles an invalidation can never install
    pre-event bytes. Generations are kept for every base ever invalidated
    (one int each): forgetting one would let an in-flight decode from
    before the flush publish against a fresh generation counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()  # leaf: guards maps only, no I/O
        # key -> (payload, publish time); OrderedDict insertion order is
        # the LRU order (get() re-ends the key)
        self._entries: "OrderedDict[tuple, tuple[bytes, float]]" = OrderedDict()
        self._bytes = 0
        self._by_volume: dict[str, set] = {}
        self._gen: dict[str, int] = {}

    @staticmethod
    def _cap_bytes() -> int:
        return int(float(config.env("WEEDTPU_READ_CACHE_MB")) * (1 << 20))

    def enabled(self) -> bool:
        return self._cap_bytes() > 0

    def generation(self, base: str) -> int:
        """Snapshot BEFORE gathering survivors; pass to put()."""
        with self._lock:
            return self._gen.get(base, 0)

    def get(self, base: str, shard_id: int, offset: int, size: int) -> Optional[bytes]:
        if not self.enabled():
            return None
        key = (base, shard_id, offset, size)
        ttl = float(config.env("WEEDTPU_READ_CACHE_TTL_S"))
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ttl > 0 and _time.monotonic() - ent[1] >= ttl:
                # the epoch boundary: age out and let the read re-decode
                self._drop_locked(key)
                stats.ReadCacheEvictions.inc()
                ent = None
            if ent is None:
                stats.ReadCacheMisses.inc()
                return None
            self._entries.move_to_end(key)
            stats.ReadCacheHits.inc()
            return ent[0]

    def put(
        self, base: str, shard_id: int, offset: int, size: int,
        data: bytes, gen: int,
    ) -> bool:
        cap = self._cap_bytes()
        if cap <= 0 or len(data) > cap:
            return False
        key = (base, shard_id, offset, size)
        with self._lock:
            if self._gen.get(base, 0) != gen:
                # the volume was invalidated while this decode ran: its
                # survivors may predate the event — refuse the publish
                return False
            if key in self._entries:
                self._drop_locked(key)
            self._entries[key] = (bytes(data), _time.monotonic())
            self._bytes += len(data)
            self._by_volume.setdefault(base, set()).add(key)
            while self._bytes > cap and self._entries:
                self._drop_locked(next(iter(self._entries)))
                stats.ReadCacheEvictions.inc()
            stats.ReadCacheBytes.set(float(self._bytes))
        return True

    def _drop_locked(self, key: tuple) -> None:
        data, _ = self._entries.pop(key)
        self._bytes -= len(data)
        stats.ReadCacheBytes.set(float(self._bytes))
        keys = self._by_volume.get(key[0])
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_volume[key[0]]

    def invalidate_volume(self, base: str) -> int:
        """Flush every cached interval of `base` and bump its generation
        (quarantine / delta update / unmount / convert cut-over)."""
        with self._lock:
            self._gen[base] = self._gen.get(base, 0) + 1
            dropped = list(self._by_volume.get(base, ()))
            for key in dropped:
                self._drop_locked(key)
            if dropped:
                stats.ReadCacheInvalidations.inc(len(dropped))
            return len(dropped)

    def invalidate_shard(self, base: str, shard_id: int) -> int:
        """Flush one shard's cached intervals (remount after rebuild).
        The generation still bumps per-volume: an in-flight decode OF THIS
        SHARD must not publish pre-remount bytes, and over-invalidating a
        sibling shard's in-flight publish merely costs one re-decode."""
        with self._lock:
            self._gen[base] = self._gen.get(base, 0) + 1
            dropped = [
                key for key in self._by_volume.get(base, ())
                if key[1] == shard_id
            ]
            for key in dropped:
                self._drop_locked(key)
            if dropped:
                stats.ReadCacheInvalidations.inc(len(dropped))
            return len(dropped)

    def clear(self) -> None:
        """Full reset (tests): entries, volume index, AND generations."""
        with self._lock:
            self._entries.clear()
            self._by_volume.clear()
            self._gen.clear()
            self._bytes = 0
            stats.ReadCacheBytes.set(0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}


#: the process-wide cache every planner publishes into — one byte budget
#: shared by all mounted volumes, same scope as the suspicion registry
CACHE = DecodedIntervalCache()


class ReadPlanner:
    """Owns the per-interval read decision tree for ONE EcVolume.

    The volume keeps the storage-shaped state (index, shard handles,
    quarantine registry, geometry); the planner keeps the serving-shaped
    state (fetch pool, coalesce map) and every policy decision. Volume
    attributes are read through properties at call time, never copied:
    tests and the volume server mutate `remote_reader`/`encoder` on the
    volume after construction and the planner must follow."""

    def __init__(self, volume) -> None:
        self.v = volume
        # degraded-read survivor fan-out pool (lazily built: most volumes
        # never take a reconstructing read, and a pool per mount would
        # leak threads)
        self._fetch_pool: Optional[ThreadPoolExecutor] = None
        self._fetch_pool_lock = threading.Lock()
        # single-flight coalescing of concurrent degraded decodes of the
        # SAME (shard, offset, size): key -> _CoalesceSlot. The lock is
        # leaf-level (never held across another acquisition or any I/O).
        self._coalesce: dict[tuple[int, int, int], "_CoalesceSlot"] = {}
        self._coalesce_lock = threading.Lock()

    # -- volume views (live, never cached) -----------------------------------

    @property
    def base(self) -> str:
        return self.v.base

    @property
    def remote_reader(self):
        return self.v.remote_reader

    @property
    def encoder(self):
        return self.v.encoder

    @property
    def total_shards(self) -> int:
        return self.v.total_shards

    @property
    def data_shards(self) -> int:
        return self.v.data_shards

    @property
    def quarantined(self) -> dict:
        return self.v.quarantined

    @property
    def _suspicion(self):
        return self.v._suspicion

    @property
    def recover_fetch_parallelism(self) -> int:
        return self.v.recover_fetch_parallelism

    @property
    def recover_fetch_deadline(self) -> float:
        return self.v.recover_fetch_deadline

    @property
    def recover_holder_timeout(self) -> float:
        return self.v.recover_holder_timeout

    @property
    def recover_holder_backoff(self) -> float:
        return self.v.recover_holder_backoff

    @property
    def recover_suspect_after(self) -> float:
        return self.v.recover_suspect_after

    # -- lifecycle ------------------------------------------------------------

    def shutdown(self) -> None:
        with self._fetch_pool_lock:
            pool, self._fetch_pool = self._fetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def _fetch_executor(self) -> ThreadPoolExecutor:
        with self._fetch_pool_lock:
            if self._fetch_pool is None:
                self._fetch_pool = ThreadPoolExecutor(
                    max_workers=self.recover_fetch_parallelism,
                    thread_name_prefix=f"ec-fetch-{os.path.basename(self.base)}",
                )
            return self._fetch_pool

    # -- suspicion ladder ------------------------------------------------------

    def _holder_key(self, shard_id: int) -> tuple:
        """Suspicion key for the holder behind `shard_id`. When the
        injected reader can name the peer (the volume server's closures
        carry a cache-only `peer_for` attribute), the key IS the peer
        identity — suspicion then applies to every shard of every volume
        that peer serves, so one wedged peer costs one capped attempt
        process-wide. Readers without peer identity fall back to a
        (volume, shard) key: the old per-volume scope, never wrong, just
        narrower."""
        peer_for = getattr(self.remote_reader, "peer_for", None)
        if peer_for is not None:
            try:
                peer = peer_for(shard_id)
            except Exception:  # noqa: BLE001 — identity is best-effort
                peer = None
            if peer:
                return ("peer", peer)
        return ("volume-shard", self.base, shard_id)

    def holder_suspected(self, shard_id: int) -> bool:
        return self._suspicion.suspected(self._holder_key(shard_id))

    def mark_holder_suspect(self, shard_id: int) -> None:
        self._suspicion.mark(self._holder_key(shard_id), self.recover_holder_backoff)

    def _track_wedged(self, shard_id: int, fut) -> None:
        """Remember that `fut` is a call into a wedged holder whose pool
        thread is still blocked; the holder reads as suspected until the
        call finally returns (SIGCONT, TCP reset, ...)."""
        self._suspicion.track_wedged(self._holder_key(shard_id), fut)

    # -- the read ladder -------------------------------------------------------

    def read_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """One interval: local -> cache -> remote -> reconstruct."""
        data = self.read_present(shard_id, offset, size)
        if data is not None:
            return data
        return self.recover_interval(shard_id, offset, size)

    def read_present(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        """The non-reconstructing rungs of the read ladder (local ->
        decoded-interval cache -> remote), or None when only
        reconstruction can serve the interval. The cache sits BEFORE the
        remote rung: the degraded tail is wire-dominated (fetch.holder
        0.67 in TRACE_ATTRIB_r01), and a hit must skip RTTs, not just the
        GF math. Local disk still wins — it is never stale."""
        data = self.v._read_local(shard_id, offset, size)
        if data is not None:
            return data
        data = self.cache_lookup(shard_id, offset, size)
        if data is not None:
            return data
        return self._remote_fetch_capped(shard_id, offset, size)

    def cache_lookup(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        """Decoded-interval cache rung. A hit classifies the request
        "cached" (unless a sibling interval already went degraded — the
        slower class tells the truer story) and, critically, returns
        BEFORE any fan-out, hedge, or reconstruct-histogram observation:
        only real decodes may feed the EWMA/suspicion statistics."""
        if not CACHE.enabled():
            return None
        raw = CACHE.get(self.base, shard_id, offset, size)
        if raw is None:
            with trace_mod.span("cache.miss", shard=shard_id):
                pass
            return None
        with trace_mod.span("cache.hit", shard=shard_id, size=size):
            if trace_mod.current_class() in ("healthy", "ec_intact"):
                trace_mod.set_class("cached")
            return np.frombuffer(raw, dtype=np.uint8).copy()

    def _remote_fetch_capped(
        self, shard_id: int, offset: int, size: int
    ) -> Optional[np.ndarray]:
        """One remote attempt under the per-holder cap: the call runs on
        the fetch pool and is abandoned once it has RUN for
        `recover_holder_timeout` — a SIGSTOPped/wedged holder (answers
        nothing, errors nothing) costs exactly one capped wait, gets
        marked suspect for the backoff window, and later reads skip it.
        The cap is measured from the call's ACTUAL start, same rule as
        the fan-out: an attempt stuck in the pool queue is the pool's
        fault, not the holder's, and must never suspect a healthy peer
        (the read gives up after ~2x the cap either way)."""
        if self.remote_reader is None or self.holder_suspected(shard_id):
            return None
        started: list[float] = []
        parent = trace_mod.current()

        def _call():
            started.append(_time.monotonic())
            with trace_mod.attach(parent), trace_mod.span(
                "ec.fetch", shard=shard_id
            ):
                return self.remote_reader(shard_id, offset, size)

        cap = self.recover_holder_timeout
        fut = self._fetch_executor().submit(_call)
        try:
            raw = fut.result(timeout=cap)
        except _FutureTimeout:
            if not started:
                # never left the queue: saturated pool, holder unproven —
                # a miss for this read, no suspicion
                stripe._abandon_future(fut)
                return None
            remaining = cap - (_time.monotonic() - started[0])
            raw = None
            if remaining > 0:
                try:
                    raw = fut.result(timeout=remaining)
                except _FutureTimeout:
                    remaining = 0.0
                except Exception:  # noqa: BLE001 — a down holder is a miss
                    return None
            if remaining <= 0:
                self.mark_holder_suspect(shard_id)
                self._track_wedged(shard_id, fut)
                stripe._abandon_future(fut)
                return None
        except Exception:  # noqa: BLE001 — a down holder is a miss,
            return None  # not a failed read: survivors can still serve it
        if raw is None:
            # a long-running NOTHING is the wedge signature when the
            # reader has its own internal transport timeout (it swallows
            # the stall and reports a miss): suspect without re-probing
            if (
                started
                and _time.monotonic() - started[0] >= self.recover_suspect_after
            ):
                self.mark_holder_suspect(shard_id)
            return None
        if started:
            # completed answers feed the per-peer latency EWMA the hedge
            # delay derives from; misses/wedges never do (see suspicion)
            self._suspicion.observe_latency(
                self._holder_key(shard_id), _time.monotonic() - started[0]
            )
        return np.frombuffer(raw, dtype=np.uint8).copy()

    # -- reconstruction --------------------------------------------------------

    def recover_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """recoverOneRemoteEcShardInterval: read the same interval from every
        other shard and reconstruct the wanted one. Concurrent recovers of
        the SAME interval are single-flight coalesced (WEEDTPU_COALESCE_READS):
        a hot needle on a lost shard costs one survivor fan-out + decode,
        with every waiter handed a byte-identical copy."""
        t0 = _time.monotonic()
        trace_mod.set_class("degraded")
        try:
            with trace_mod.span("ec.recover", shard=shard_id, size=size):
                if not config.env("WEEDTPU_COALESCE_READS"):
                    return self._decode_once(shard_id, offset, size)
                return self._recover_interval_coalesced(shard_id, offset, size)
        finally:
            # DegradedReadSeconds is the CLIENT-facing latency (waiters
            # included); EcReconstructSeconds counts actual decodes and is
            # observed in _recover_interval_inner, else N coalesced waiters
            # would inflate the reconstruct histogram N-fold
            stats.DegradedReadSeconds.observe(_time.monotonic() - t0)

    def _recover_interval_coalesced(
        self, shard_id: int, offset: int, size: int
    ) -> np.ndarray:
        key = (shard_id, offset, size)
        with self._coalesce_lock:
            slot = self._coalesce.get(key)
            leader = slot is None
            if leader:
                slot = self._coalesce[key] = _CoalesceSlot()
        if not leader:
            stats.CoalescedReads.inc()
            # generous bound: the leader's decode is itself bounded by the
            # fetch deadline + one holder cap; a vanished leader (killed
            # thread) must not strand waiters forever
            budget = self.recover_fetch_deadline + self.recover_holder_timeout + 5.0
            with trace_mod.span("ec.coalesce.wait", shard=shard_id) as sp:
                won = slot.event.wait(timeout=budget)
                if sp is not None:
                    sp.annotate(served_by_leader=won)
            if won:
                if slot.error is not None:
                    raise slot.error
                assert slot.result is not None
                return slot.result.copy()
            return self._decode_once(shard_id, offset, size)
        try:
            out = self._decode_once(shard_id, offset, size)
            slot.result = out
            return out
        except BaseException as e:
            slot.error = e
            raise
        finally:
            # unpublish BEFORE waking waiters: a brand-new reader arriving
            # after the event must elect a fresh leader, never read a slot
            # that is mid-teardown
            with self._coalesce_lock:
                self._coalesce.pop(key, None)
            slot.event.set()

    def _decode_once(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """One real reconstruction, published into the decoded-interval
        cache under the generation snapshotted BEFORE the survivor gather:
        an invalidation (quarantine/remount/delta/cut-over) landing while
        this decode runs bumps the generation and the publish is refused —
        pre-event bytes can never be installed."""
        gen = CACHE.generation(self.base) if CACHE.enabled() else 0
        out = self._recover_interval_inner(shard_id, offset, size)
        if CACHE.enabled():
            CACHE.put(self.base, shard_id, offset, size, out.tobytes(), gen)
        return out

    def _recover_interval_inner(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        t0 = _time.monotonic()
        try:
            shards = self._gather_survivors(shard_id, offset, size)
            with trace_mod.span(
                "ec.decode",
                backend=getattr(self.encoder, "backend", "?"),
                width=size,
            ):
                rec = self.encoder.reconstruct(shards, wanted=[shard_id])
            return rec[shard_id]
        finally:
            stats.EcReconstructSeconds.observe(_time.monotonic() - t0)

    def _gather_survivors(
        self, shard_id: int, offset: int, size: int
    ) -> list[Optional[np.ndarray]]:
        """Collect >= DATA_SHARDS survivor copies of one interval (local
        first, then a parallel remote fan-out). Raises IOError when too few
        survivors are reachable."""
        with trace_mod.span("ec.gather", shard=shard_id):
            return self._gather_survivors_fanout(shard_id, offset, size)

    def _gather_survivors_fanout(
        self, shard_id: int, offset: int, size: int
    ) -> list[Optional[np.ndarray]]:
        shards: list[Optional[np.ndarray]] = [None] * self.total_shards
        have = 0
        # local shards first — remote reads cost RTTs on the p50-critical path
        for s in range(self.total_shards):
            if s == shard_id or have >= self.data_shards:
                continue
            buf = self.v._read_local(s, offset, size)
            if buf is not None:
                shards[s] = buf
                have += 1
        need = self.data_shards - have
        attempted: tuple = ()
        deadline_expired = False
        if need > 0 and self.remote_reader is not None:
            # Fan out to ALL remaining survivors at once and take the first
            # `need` arrivals — the reference reads the same interval from
            # >=10 shards with parallel goroutines
            # (recoverOneRemoteEcShardInterval [ref: weed/storage/
            # store_ec.go — mount empty, SURVEY.md §3.2]); serial fetches
            # cost one RTT per survivor and dominated the reconstruct p50.
            # Late arrivals beyond `need` are ignored; a hung peer is cut by
            # the overall deadline rather than stalling the read forever.
            # suspected-wedged holders are skipped outright: the fan-out
            # needs only `need` of the remaining survivors, and a holder
            # inside its backoff window would just burn a pool thread
            candidates = []
            skipped_suspected = []
            for s in range(self.total_shards):
                if s == shard_id or shards[s] is not None:
                    continue
                if self.holder_suspected(s):
                    skipped_suspected.append(s)
                else:
                    candidates.append(s)
            trace_mod.annotate(
                local=have, need=need,
                **({"skipped_suspected": skipped_suspected}
                   if skipped_suspected else {}),
            )
            fan_parent = trace_mod.current()
            pool = self._fetch_executor()
            # per-holder cap is measured from each call's ACTUAL start (a
            # queued attempt waiting for a pool slot is not the holder's
            # fault): the worker records its entry time, and the wait loop
            # cuts any holder that has been RUNNING past the cap — wedged,
            # not merely slow — marking it suspect. The OVERALL read is
            # still bounded by `recover_fetch_deadline`, unchanged.
            started: dict[int, float] = {}
            attempted = tuple(self._holder_key(s) for s in candidates)

            def _attempt(s: int):
                started[s] = _time.monotonic()
                with trace_mod.attach(fan_parent), trace_mod.span(
                    "ec.fetch", shard=s
                ):
                    return self.remote_reader(s, offset, size)

            futs = {pool.submit(_attempt, s): s for s in candidates}
            primaries = {sid: fut for fut, sid in futs.items()}
            pending = set(futs)
            # hedging (WEEDTPU_HEDGE_READS): once a primary fetch has RUN
            # past the peer's EWMA-derived tail, launch ONE backup against
            # a different holder; first success wins, the loser is
            # cancelled/drained, and both results must be byte-identical.
            hedge_on = bool(config.env("WEEDTPU_HEDGE_READS"))
            hedge_started: dict[int, float] = {}
            # sid -> backup future, or None when a submit attempt found no
            # second holder (memoized: retrying every loop tick would spin
            # the wait budget down to 5 ms for the rest of the read)
            hedges: dict[int, object] = {}
            hedge_targets: dict[int, Optional[str]] = {}
            hedge_futs: set = set()
            hedge_wins: list[int] = []
            winners: dict[int, bytes] = {}
            deadline = _time.monotonic() + self.recover_fetch_deadline
            cap = self.recover_holder_timeout
            try:
                while pending and have < self.data_shards:
                    now = _time.monotonic()
                    for fut in list(pending):
                        sid = futs[fut]
                        is_hedge = fut in hedge_futs
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        if t0s is None or fut.done():
                            continue
                        if now - t0s >= cap:
                            # running past the per-holder cap: wedged.
                            # Suspect it, remember the blocked thread, and
                            # stop waiting on it (the read may still
                            # complete from the other survivors). A wedged
                            # BACKUP blames the alternate holder it was
                            # pinned at — never the primary's key (which
                            # names a different, possibly healthy peer).
                            pending.discard(fut)
                            if is_hedge:
                                self._suspect_hedge_target(
                                    hedge_targets.get(sid), fut
                                )
                            else:
                                self.mark_holder_suspect(sid)
                                self._track_wedged(sid, fut)
                            stripe._abandon_future(fut)
                        elif (
                            hedge_on
                            and not is_hedge
                            and sid not in hedges
                            and now - t0s >= self.hedge_delay(sid)
                        ):
                            # memoize the outcome either way: None means
                            # "no second holder", and must not be retried
                            # (and re-pay peer lookups) every loop tick
                            hedges[sid] = self._submit_hedge(
                                pool, sid, offset, size,
                                hedge_started, hedge_targets,
                            )
                            backup = hedges[sid]
                            if backup is not None:
                                hedge_futs.add(backup)
                                futs[backup] = sid
                                pending.add(backup)
                    if not pending:
                        break
                    budget = deadline - now
                    if budget <= 0:
                        deadline_expired = True
                        break
                    # wake at the earliest per-holder cap OR pending hedge
                    # fire time, whichever comes first
                    wake: list[float] = []
                    for f in pending:
                        sid = futs[f]
                        is_hedge = f in hedge_futs
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        if t0s is None:
                            continue
                        wake.append(t0s + cap - now)
                        if hedge_on and not is_hedge and sid not in hedges:
                            wake.append(t0s + self.hedge_delay(sid) - now)
                    if wake:
                        budget = min(budget, max(min(wake), 0.005))
                    done, pending = wait(
                        pending, timeout=budget, return_when=FIRST_COMPLETED
                    )
                    for fut in done:
                        sid = futs[fut]
                        is_hedge = fut in hedge_futs
                        try:
                            raw = fut.result()
                        except Exception:  # noqa: BLE001 — a failed peer is a miss
                            raw = None
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        now2 = _time.monotonic()
                        if raw is not None and len(raw) == size:
                            if t0s is not None and not is_hedge:
                                # primaries only: a hedge's fast answer is
                                # the OTHER holder's latency and would drag
                                # the slow peer's estimate down
                                self._suspicion.observe_latency(
                                    self._holder_key(sid), now2 - t0s
                                )
                            want = winners.get(sid)
                            if want is not None:
                                # the hedged pair's LOSER also answered:
                                # first-success already won, but the bytes
                                # must agree — a divergence is survivor
                                # corruption, not a race to tolerate
                                if bytes(raw) != want:
                                    stats.DegradedReadErrors.labels(
                                        "HedgeMismatch"
                                    ).inc()
                                    raise IOError(
                                        f"shard {sid}: hedged fetch returned "
                                        "bytes differing from the primary's"
                                    )
                                continue
                            winners[sid] = bytes(raw)
                            shards[sid] = np.frombuffer(
                                raw, dtype=np.uint8
                            ).copy()
                            have += 1
                            if is_hedge:
                                stats.HedgeWon.inc()
                                hedge_wins.append(sid)
                            other = (
                                primaries.get(sid) if is_hedge else hedges.get(sid)
                            )
                            if other is not None and other in pending:
                                pending.discard(other)
                                self._settle_hedge_loser(other, winners[sid])
                        else:
                            # slow NOTHING = internally-timed-out wedge
                            # (see _remote_fetch_capped); fast None is a
                            # plain miss and never suspects. Same blame
                            # rule as the cap: a slow-missing BACKUP names
                            # its own alternate holder, not the primary.
                            if (
                                t0s is not None
                                and now2 - t0s >= self.recover_suspect_after
                            ):
                                if is_hedge:
                                    self._suspect_hedge_target(
                                        hedge_targets.get(sid), None
                                    )
                                else:
                                    self.mark_holder_suspect(sid)
            finally:
                fired = sorted(s for s, f in hedges.items() if f is not None)
                trace_mod.annotate(
                    gathered=have,
                    **({"hedges_fired": fired} if fired else {}),
                    **({"hedges_won": hedge_wins} if hedge_wins else {}),
                    **({"deadline_expired": True} if deadline_expired else {}),
                )
                # EVERY exit (normal, deadline, or an exception raised
                # mid-loop) cancels what never started and drains what did:
                # the discard callback drops a late result/exception on the
                # floor so a hung peer's thread never outlives the read with
                # a reference to its buffer (or an unobserved error).
                for fut in pending:
                    stripe._abandon_future(fut)
        if have < self.data_shards:
            suspected = tuple(
                self._holder_key(s)
                for s in range(self.total_shards)
                if s != shard_id and self.holder_suspected(s)
            )
            # the corruption class applies only when quarantine is actually
            # RELEVANT to this failure: the wanted shard itself sits
            # quarantined, or the quarantined shards are what kept the
            # survivor count short (with them clean the read would have had
            # enough). An unrelated quarantined shard during a plain
            # holder outage must still classify as holders-down.
            quarantine_blocked = bool(self.quarantined) and (
                shard_id in self.quarantined
                or (
                    not deadline_expired
                    and have + len(self.quarantined) >= self.data_shards
                )
            )
            if quarantine_blocked:
                # local shards sit quarantined for failed verification and
                # the stripe still couldn't be served: this is CORRUPTION
                # awaiting repair, not holders being down — a distinct
                # class (and retry hint) for clients and dashboards
                stats.DegradedReadErrors.labels(EcShardCorrupt.__name__).inc()
                raise EcShardCorrupt(
                    f"shard {shard_id}: only {have} clean surviving shards "
                    f"reachable, need {self.data_shards}; local shards "
                    f"{sorted(self.quarantined)} quarantined "
                    f"({self.quarantined}) — repair pending",
                    quarantined=self.quarantined,
                    shard_id=shard_id,
                    attempted=attempted,
                    suspected=suspected,
                )
            cls = EcDegradedReadTimeout if deadline_expired else EcNoViableHolders
            stats.DegradedReadErrors.labels(cls.__name__).inc()
            raise cls(
                f"shard {shard_id}: only {have} surviving shards reachable, "
                f"need {self.data_shards}"
                + (" (recover deadline expired)" if deadline_expired else ""),
                shard_id=shard_id,
                attempted=attempted,
                suspected=suspected,
            )
        return shards

    # -- hedging ---------------------------------------------------------------

    def hedge_delay(self, shard_id: int) -> float:
        """Seconds a survivor fetch may run before its backup launches.
        WEEDTPU_HEDGE_DELAY_MS pins it; otherwise the per-peer latency
        EWMA (mean + 4*dev, a live high-quantile tracker) decides, with a
        cold-start default of half the slow-miss threshold. Never later
        than half the per-holder cap — past that the wedge machinery owns
        the fetch, not the hedge."""
        fixed = float(config.env("WEEDTPU_HEDGE_DELAY_MS"))
        if fixed > 0:
            return fixed / 1e3
        d = self._suspicion.hedge_delay(self._holder_key(shard_id))
        if d is None:
            d = max(0.05, self.recover_suspect_after / 2.0)
        return min(d, self.recover_holder_timeout / 2.0)

    def _submit_hedge(
        self, pool, shard_id: int, offset: int, size: int,
        hedge_started: dict[int, float],
        hedge_targets: dict[int, Optional[str]],
    ):
        """Launch the backup fetch for one survivor. Readers that expose
        holder addressing (`via` + `holders_for`, the volume server's
        closures) are steered at a DIFFERENT holder than the one the
        primary is inside; a reader without addressing re-runs its own
        holder ladder. None when there is no second holder to try.

        The backup rides the same bounded fetch pool as the primaries, so
        under heavy wedging it can queue before it runs — HedgeFired is
        therefore counted (and the per-holder cap armed) from the worker's
        ACTUAL start, never at submit."""
        reader = self.remote_reader
        if reader is None:
            return None
        via = getattr(reader, "via", None)
        holders_for = getattr(reader, "holders_for", None)
        target = None
        if via is not None and holders_for is not None:
            primary = None
            peer_for = getattr(reader, "peer_for", None)
            if peer_for is not None:
                try:
                    primary = peer_for(shard_id)
                except Exception:  # noqa: BLE001 — identity is best-effort
                    primary = None
            try:
                holders = list(holders_for(shard_id) or ())
            except Exception:  # noqa: BLE001 — no holder list, no hedge
                return None
            # skip holders already inside a suspicion window: pinning the
            # ONE backup at a known-wedged peer would spend the hedge on
            # exactly the holder it exists to route around
            alts = [
                a for a in holders
                if a != primary and not self._suspicion.suspected(("peer", a))
            ]
            if not alts:
                return None
            target = alts[0]
        hedge_targets[shard_id] = target
        parent = trace_mod.current()

        def _backup():
            hedge_started[shard_id] = _time.monotonic()
            stats.HedgeFired.inc()
            with trace_mod.attach(parent), trace_mod.span(
                "ec.hedge", shard=shard_id, **({"addr": target} if target else {})
            ):
                if target is not None:
                    return via(target, shard_id, offset, size)
                return reader(shard_id, offset, size)

        return pool.submit(_backup)

    def _suspect_hedge_target(self, target: Optional[str], fut) -> None:
        """Suspicion for a wedged/slow-missing BACKUP fetch: the blame key
        is the alternate holder the backup was pinned at (the peer-scoped
        key the registry shares process-wide). A backup without addressing
        (generic reader re-run) names no one — better unsuspected than the
        primary's key mis-marked for a different peer's wedge."""
        if not target:
            return
        key = ("peer", target)
        self._suspicion.mark(key, self.recover_holder_backoff)
        if fut is not None:
            self._suspicion.track_wedged(key, fut)

    def _settle_hedge_loser(self, fut, want: bytes) -> None:
        """First-success-wins settlement: cancel the loser if it never
        started; if running, drain it in the background and verify its
        late result byte-identical to the winner's (a mismatch is counted
        as HedgeMismatch — the read already returned the winner)."""
        if fut.cancel():
            return

        def _check(f):
            try:
                raw = f.result()
            except Exception:  # noqa: BLE001 — loser erred; winner served
                return
            if raw is not None and len(raw) == len(want) and bytes(raw) != want:
                stats.DegradedReadErrors.labels("HedgeMismatch").inc()

        fut.add_done_callback(_check)

    # -- batched reconstruction ------------------------------------------------

    def recover_intervals_batch(
        self, shard_id: int, items: list[tuple[int, int]]
    ) -> list[np.ndarray]:
        """Recover several (offset, size) intervals that all miss the SAME
        shard in one bucketed device call: survivors are gathered per
        interval (the same local -> remote ladder as the single path),
        grouped by which shards actually answered, zero-padded to a shared
        bucket length, and decoded as a (B, survivors, bucket) stack with
        ONE fused matrix per group — instead of one dispatch (and one
        decode-matrix application) per interval. Zero padding is exact and
        trimmed per interval before returning."""
        if len(items) == 1:
            off, size = items[0]
            return [self.recover_interval(shard_id, off, size)]
        t0 = _time.monotonic()
        trace_mod.set_class("degraded")
        try:
            with trace_mod.span(
                "ec.recover", shard=shard_id, batch=len(items)
            ):
                return self._recover_intervals_batch_inner(shard_id, items)
        finally:
            dt = _time.monotonic() - t0
            stats.EcReconstructSeconds.observe(dt)
            stats.DegradedReadSeconds.observe(dt)

    def _recover_intervals_batch_inner(
        self, shard_id: int, items: list[tuple[int, int]]
    ) -> list[np.ndarray]:
        # one generation snapshot covers the whole batch: every gather
        # below starts after it, so the publish race check stays sound
        publish = CACHE.enabled()
        gen = CACHE.generation(self.base) if publish else 0
        gathered = [
            self._gather_survivors(shard_id, off, size) for off, size in items
        ]
        results: list[Optional[np.ndarray]] = [None] * len(items)
        # distinct survivor sets decode with distinct matrices; in the
        # common case (stable shard availability) there is ONE group
        groups: dict[tuple, list[int]] = {}
        for idx, shards in enumerate(gathered):
            present = tuple(
                i for i, s in enumerate(shards) if s is not None
            )[: self.data_shards]
            groups.setdefault(present, []).append(idx)
        for survivors, idxs in groups.items():
            nmax = max(items[i][1] for i in idxs)
            stack = np.zeros(
                (len(idxs), self.data_shards, nmax), dtype=np.uint8
            )
            for bi, i in enumerate(idxs):
                for di, s in enumerate(survivors):
                    arr = gathered[i][s]
                    stack[bi, di, : arr.shape[0]] = arr
            # bucketed: the encoder's own serving-path shape buckets,
            # so odd interval sizes never pay a fresh XLA compile
            with trace_mod.span(
                "ec.decode",
                backend=getattr(self.encoder, "backend", "?"),
                batch=len(idxs),
                width=nmax,
            ):
                out = self.encoder.reconstruct_batch(
                    stack, survivors, [shard_id], bucketed=True
                )
            for bi, i in enumerate(idxs):
                results[i] = np.ascontiguousarray(out[bi, 0, : items[i][1]])
        if publish:
            for (off, size), arr in zip(items, results):
                CACHE.put(self.base, shard_id, off, size, arr.tobytes(), gen)
        return results
