"""ShardBits + EcVolumeInfo — mirror of weed/storage/erasure_coding/
ec_volume_info.go [VERIFY: mount empty]. A uint32 bitmask of which shards a
node holds (sized to MAX_SHARD_COUNT so geometry-flexible volumes register
shards past the legacy 14); exchanged in heartbeats and kept in the master's
EcShardLocations registry."""

from __future__ import annotations

from dataclasses import dataclass, field

from seaweedfs_tpu.ec.constants import MAX_SHARD_COUNT


class ShardBits(int):
    def add_shard_id(self, shard_id: int) -> "ShardBits":
        return ShardBits(self | (1 << shard_id))

    def remove_shard_id(self, shard_id: int) -> "ShardBits":
        return ShardBits(self & ~(1 << shard_id))

    def has_shard_id(self, shard_id: int) -> bool:
        return bool(self & (1 << shard_id))

    def shard_ids(self) -> list[int]:
        # sized to the registry-wide shard-id bound, not the legacy 14:
        # a converted 20+4 volume heartbeats shards 14..23 through the
        # same mask (bits above any volume's actual geometry are never set)
        return [i for i in range(MAX_SHARD_COUNT) if self.has_shard_id(i)]

    def shard_id_count(self) -> int:
        return bin(self & ((1 << MAX_SHARD_COUNT) - 1)).count("1")

    def plus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self | other)

    def minus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self & ~other)

    @classmethod
    def from_ids(cls, ids) -> "ShardBits":
        b = cls(0)
        for i in ids:
            b = b.add_shard_id(i)
        return b


@dataclass
class EcVolumeInfo:
    volume_id: int
    collection: str = ""
    shard_bits: ShardBits = field(default_factory=lambda: ShardBits(0))
    # code geometry + per-shard byte size, heartbeat-propagated so the
    # master's repair scheduler can compute missing counts against the
    # volume's real (k, k+m) and rank stripes by bytes at risk. 0 = an
    # old reporter: consumers fall back to the legacy 10+4 defaults.
    shard_size: int = 0
    data_shards: int = 0
    total_shards: int = 0

    def to_dict(self) -> dict:
        return {
            "volume_id": self.volume_id,
            "collection": self.collection,
            "shard_bits": int(self.shard_bits),
            "shard_size": int(self.shard_size),
            "data_shards": int(self.data_shards),
            "total_shards": int(self.total_shards),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EcVolumeInfo":
        return cls(
            d["volume_id"],
            d.get("collection", ""),
            ShardBits(d.get("shard_bits", 0)),
            shard_size=int(d.get("shard_size", 0) or 0),
            data_shards=int(d.get("data_shards", 0) or 0),
            total_shards=int(d.get("total_shards", 0) or 0),
        )
