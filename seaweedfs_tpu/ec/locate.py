"""Interval math — the exact semantics of weed/storage/erasure_coding/
ec_locate.go [VERIFY: mount empty; upstream semantics, SURVEY.md §2.3].

A volume's .dat is striped row-major: large rows (DATA_SHARDS x 1 GiB blocks)
first, then the tail as small rows (DATA_SHARDS x 1 MiB). A shard file is one
column of that grid, so a logical .dat range maps to a list of
(shard_id, offset_in_shard) intervals; the large->small transition makes this
non-trivial and is the part the reference's tests pin down.
"""

from __future__ import annotations

from dataclasses import dataclass

from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT


@dataclass(frozen=True)
class Interval:
    block_index: int  # index into the row-major grid of blocks of one tier
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int
    #: row width in blocks — geometry-flexible volumes carry their own k
    #: (the legacy default keeps every existing caller byte-identical)
    data_shards: int = DATA_SHARDS_COUNT

    def to_shard_id_and_offset(self, large_block_size: int, small_block_size: int) -> tuple[int, int]:
        ec_file_offset = self.inner_block_offset
        row_index = self.block_index // self.data_shards
        if self.is_large_block:
            ec_file_offset += row_index * large_block_size
        else:
            ec_file_offset += (
                self.large_block_rows_count * large_block_size + row_index * small_block_size
            )
        shard_id = self.block_index % self.data_shards
        return shard_id, ec_file_offset


def large_row_count(
    dat_size: int, large_block_length: int, data_shards: int = DATA_SHARDS_COUNT
) -> int:
    """Number of large rows the encoder emitted for a .dat of this size.

    Matches the encode loop's strictly-greater condition: a volume of exactly
    one large-row is encoded entirely as small rows."""
    large_row_size = large_block_length * data_shards
    if dat_size <= 0:
        return 0
    return (dat_size - 1) // large_row_size


def _locate_offset_within_blocks(block_length: int, offset: int) -> tuple[int, int]:
    return offset // block_length, offset % block_length


def locate_offset(
    large_block_length: int,
    small_block_length: int,
    dat_size: int,
    offset: int,
    data_shards: int = DATA_SHARDS_COUNT,
) -> tuple[int, bool, int, int]:
    """-> (block_index, is_large_block, n_large_block_rows, inner_block_offset)."""
    large_row_size = large_block_length * data_shards
    n_large_rows = large_row_count(dat_size, large_block_length, data_shards)
    if offset < n_large_rows * large_row_size:
        block_index, inner = _locate_offset_within_blocks(large_block_length, offset)
        return block_index, True, n_large_rows, inner
    offset -= n_large_rows * large_row_size
    block_index, inner = _locate_offset_within_blocks(small_block_length, offset)
    return block_index, False, n_large_rows, inner


def locate_data(
    large_block_length: int,
    small_block_length: int,
    dat_size: int,
    offset: int,
    size: int,
    data_shards: int = DATA_SHARDS_COUNT,
) -> list[Interval]:
    """Split a logical .dat byte range into per-block intervals."""
    block_index, is_large, n_large_rows, inner = locate_offset(
        large_block_length, small_block_length, dat_size, offset, data_shards
    )
    intervals: list[Interval] = []
    while size > 0:
        block_len = large_block_length if is_large else small_block_length
        block_remaining = block_len - inner
        take = min(size, block_remaining)
        intervals.append(
            Interval(
                block_index=block_index,
                inner_block_offset=inner,
                size=take,
                is_large_block=is_large,
                large_block_rows_count=n_large_rows,
                data_shards=data_shards,
            )
        )
        size -= take
        if size <= 0:
            break
        block_index += 1
        if is_large and block_index == n_large_rows * data_shards:
            is_large = False
            block_index = 0
        inner = 0
    return intervals
